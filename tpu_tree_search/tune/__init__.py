"""Adaptive dispatch: offline autotuning + measured defaults.

- `defaults` — the per-shape-class measured-defaults table (the single
  source config/bench/serve read chunk/balance_period from, and the
  tuner's fallback tier)
- `TuningCache` — fingerprint-checked, CRC-stamped persistent cache of
  probed optima (cache.py)
- `ProbeHarness` / `measure_balance_periods` — the warmed same-state
  measurement method every knob sweep shares (probe.py)
- `Autotuner` — cache → probe → defaults resolution (tuner.py)

This ``__init__`` stays import-light (utils/config imports
``defaults`` at module load): the heavy members resolve lazily.
"""

from . import defaults
from .defaults import Params

__all__ = ["Autotuner", "Params", "ProbeError", "ProbeHarness",
           "TuningCache", "defaults", "measure_balance_periods"]

_LAZY = {
    "Autotuner": ("tuner", "Autotuner"),
    "TuningCache": ("cache", "TuningCache"),
    "ProbeHarness": ("probe", "ProbeHarness"),
    "ProbeError": ("probe", "ProbeError"),
    "measure_balance_periods": ("probe", "measure_balance_periods"),
}


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib
    mod = importlib.import_module(f".{mod_name}", __name__)
    return getattr(mod, attr)
