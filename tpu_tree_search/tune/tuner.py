"""Offline autotuner: probe the dispatch ladder once, replay forever.

Every perf round so far re-tuned the engine's dispatch knobs BY HAND:
chunk went 256 → 32768 → 65536 when the bf16 matmul changed the cost
structure (ROUND5_NOTES.md), and balance_period=4 came from a one-off
tools/bench_balance_period.py sweep the ROADMAP warns cannot be
re-derived on the virtual mesh. The Autotuner retires that ritual:

- **Probe**: per (J×M shape family, lb kind, worker count), run short
  warmed probes (tune/probe.ProbeHarness — the validated same-state
  method) over a candidate chunk ladder, then a balance-period sweep
  at the winning chunk, and pick the best node-evals/s.
- **Persist**: the winner lands in the fingerprint-checked, CRC-stamped
  tuning cache (tune/cache.TuningCache) keyed by shape/bound/topology —
  a restarted server replays it with ZERO probe executions
  (``resolve(...)`` source="cache"; the probe ledger stays empty).
- **Fall back**: with no cache entry and probing not allowed (the
  request hot path), resolution returns the measured-defaults table
  (tune/defaults.py) — the tier that used to be three drifting
  hardcoded constants.

Consumption points: ``distributed.search(chunk=None, tuner=...)``,
``SearchServer(tune_cache_dir=...)`` (+ ``serve --tune-cache/--tune``),
``bench.py`` (TTS_BENCH_TUNED=1), and ``serve --prewarm`` (tune at
boot, warm the tuned shapes).

Observability: ``tts_tuner_probes_total``,
``tts_tuner_cache_{hits,misses}_total`` and ``tts_tuner_probe_seconds``
when a registry is supplied; ``snapshot()`` rides the server's
``/status`` under the ``tuner`` key; ``tools/tune_report.py`` renders
the cache directory.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..obs import tracelog
from . import defaults
from .cache import TuningCache
from .defaults import Params
from .probe import ProbeError, ProbeHarness

__all__ = ["Autotuner"]

# default candidate ladder for the chunk sweep (pow2 keeps every rung
# lane-aligned; TTS_TUNE_CHUNKS overrides, e.g. "64,256,1024" for the
# CPU CI smoke). The production span covers the serving default through
# the round-5 single-chip optimum.
CHUNK_CANDIDATES_DEFAULT = (256, 1024, 4096, 16384, 65536)
# balance periods swept at the winning chunk (the old
# bench_balance_period default set, trimmed to the plausible range)
PERIOD_CANDIDATES_DEFAULT = (1, 4, 16)


class Autotuner:
    """Cache → probe → defaults resolution of the dispatch knobs.

    `cache_dir` (or the TTS_TUNE_CACHE env) enables the persistent
    tier; without it the tuner still probes (results memoized
    in-process) and still falls back to the defaults table. All probe
    knobs have CI-friendly env overrides (TTS_TUNE_CHUNKS,
    TTS_TUNE_PERIODS, TTS_TUNE_WINDOW, TTS_TUNE_WARM)."""

    def __init__(self, cache_dir: str | os.PathLike | None = None,
                 registry=None, fingerprint_extra: dict | None = None,
                 chunks: tuple | None = None, periods: tuple | None = None,
                 window_iters: int | None = None,
                 warm_iters: int | None = None,
                 capacity: int | None = None, repeats: int = 2):
        self.cache = (TuningCache(cache_dir, registry=registry,
                                  fingerprint_extra=fingerprint_extra)
                      if cache_dir else None)
        from ..utils import config as _cfg
        self.chunks = tuple(chunks) if chunks else _cfg.env_ints(
            "TTS_TUNE_CHUNKS", CHUNK_CANDIDATES_DEFAULT)
        self.periods = tuple(periods) if periods else _cfg.env_ints(
            "TTS_TUNE_PERIODS", PERIOD_CANDIDATES_DEFAULT)
        self.window_iters = int(window_iters
                                or _cfg.env_int("TTS_TUNE_WINDOW")
                                or _cfg.TUNE_WINDOW_ITERS_DEFAULT)
        self.warm_iters = int(warm_iters
                              or _cfg.env_int("TTS_TUNE_WARM")
                              or _cfg.TUNE_WARM_ITERS_DEFAULT)
        self.capacity = int(capacity or 1 << 18)
        self.repeats = int(repeats)
        self.probes_run = 0          # guarded-by: self._lock
        #                              (probe executions this lifetime —
        #                              the zero-probe warm-boot assertion)
        self.ledger: list[dict] = []  # guarded-by: self._lock
        #                               (one record per probe execution)
        self._memo: dict[tuple, Params] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._probes_c = self._probe_h = None
        if registry is not None:
            self._probes_c = registry.counter(
                "tts_tuner_probes_total",
                "warmed probe executions (candidate measurements)")
            self._probe_h = registry.histogram(
                "tts_tuner_probe_seconds",
                "wall seconds per tuning sweep (all candidates of one "
                "shape)")

    # ------------------------------------------------------------- keys

    @staticmethod
    def key(jobs: int, machines: int, lb_kind: int,
            n_workers: int, problem: str = "pfsp",
            batch: int | None = None) -> tuple:
        # the problem name LEADS the key (PFSP entries keep their
        # pre-plugin cache identity — persisted caches stay valid).
        # A megabatched dispatch (batch > 1) appends a ("batch", B)
        # suffix: solo keys keep their exact persisted layout, and a
        # batched optimum can never be served from — or clobber — the
        # solo entry of the same shape. The resolved fused mode joins
        # the same way (only when ON, and only for problems whose
        # step HAS a fused pipeline — Problem.supports_fused): the
        # sweep picks its chunk winner on the probing boot's pipeline
        # rates, so an optimum probed under TTS_FUSED=1 must never be
        # replayed by a matmul boot of the same shape (or vice versa)
        # — each mode probes and persists its own entry, unfused
        # entries keep their pre-fused identity. A problem without a
        # fused pipeline measures identical rates either way:
        # suffixing it would split one optimum across two keys and
        # re-probe the same sweep at the next boot.
        base = (str(problem), int(jobs), int(machines), int(lb_kind),
                int(n_workers))
        if batch is not None and int(batch) > 1:
            base = base + ("batch", int(batch))
        from ..ops import pallas_fused
        mode = pallas_fused.resolve_mode(None)
        if mode != "off":
            from ..problems import get as _get_problem
            try:
                fused_capable = getattr(_get_problem(str(problem)),
                                        "supports_fused", False)
            except KeyError:
                fused_capable = False
            if fused_capable:
                base = base + ("fused", mode)
        return base

    # --------------------------------------------------------- resolve

    def resolve(self, jobs: int, machines: int, lb_kind: int = 1,
                n_workers: int = 1, allow_probe: bool = False,
                p_times: np.ndarray | None = None,
                context: str = "serving",
                problem: str = "pfsp",
                batch: int | None = None) -> Params:
        """The three-tier lookup. ``allow_probe=False`` is the request
        hot path (cache else defaults — never seconds of probing while
        a client waits); ``allow_probe=True`` is the boot/bench path
        (cache else probe+persist else defaults). The probe harness is
        problem-generic (tune/probe.ProbeHarness drives the plugin's
        own step pipeline), so any registered problem probes when a
        table is supplied; a probe without one is PFSP-only (the
        synthetic-table fallback is a PFSP generator) and other
        problems fall through to defaults.

        ``batch`` (a megabatch dispatch's instance-axis width) rides
        the cache key and the defaults lookup: batched optima are their
        own entries, and the fallback is the batched defaults row —
        never the solo serving row (the probe harness is solo-only, so
        batched keys resolve cache-else-batched-defaults)."""
        key = self.key(jobs, machines, lb_kind, n_workers, problem,
                       batch=batch)
        if batch is not None and batch > 1:
            allow_probe = False
        with self._lock:
            memo = self._memo.get(key)
        if memo is not None:
            return memo
        if self.cache is not None:
            entry = self.cache.load(key)
            if entry is not None:
                rm = entry.get("rung_modes")
                params = Params(chunk=int(entry["chunk"]),
                                balance_period=int(entry["balance_period"]),
                                transfer_cap=entry.get("transfer_cap"),
                                source="cache",
                                evals_per_s=entry.get("evals_per_s"),
                                rung_modes=tuple(rm) if rm else None)
                with self._lock:
                    self._memo[key] = params
                return params
        if allow_probe:
            try:
                return self.tune(jobs, machines, lb_kind=lb_kind,
                                 n_workers=n_workers, p_times=p_times,
                                 problem=problem)
            except ProbeError as e:
                tracelog.event("tuner.probe_failed", jobs=jobs,
                               machines=machines, lb_kind=lb_kind,
                               problem=problem, error=repr(e))
        return defaults.params_for(context, jobs, machines,
                                   problem=problem, batch=batch)

    # ------------------------------------------------------------ tune

    def tune(self, jobs: int, machines: int, lb_kind: int = 1,
             n_workers: int = 1,
             p_times: np.ndarray | None = None,
             problem: str = "pfsp") -> Params:
        """Run the sweep for one shape family and persist the winner.

        Only the SHAPE of `p_times` matters (a synthetic table in the
        Taillard value range probes the same compiled program every
        real instance of the class runs); pass a real table to probe
        on committed traffic — REQUIRED for non-PFSP problems (the
        synthetic fallback is a PFSP generator). Raises ProbeError
        when no steady measurement state exists (callers fall back to
        defaults).

        After the chunk/period winner is picked, the winning chunk's
        LADDER rungs are probed too — each rung once per available
        step pipeline (fused kernel vs the matmul path,
        ops/pallas_fused) and BELOW the static rung floor — producing
        the per-rung profitability mask (`Params.rung_modes`) that
        engine/ladder consumes for measured rung admission and
        per-rung fused selection."""
        key = self.key(jobs, machines, lb_kind, n_workers, problem)
        if p_times is None:
            if problem != "pfsp":
                raise ProbeError(
                    f"probing problem {problem!r} needs its instance "
                    "table (the synthetic fallback generates PFSP "
                    "tables only)")
            from ..problems.pfsp import PFSPInstance
            p_times = PFSPInstance.synthetic(jobs=jobs,
                                             machines=machines,
                                             seed=0).p_times
        t0 = time.perf_counter()
        # the harness capacity must make EVERY candidate measurable:
        # a chunk's scratch margin (chunk*jobs) plus its balance
        # headroom must fit under the pool, or the top rungs of the
        # production ladder (65536 at 20 jobs needs ~2.6M rows) would
        # silently drop out of the sweep and the tuner could never
        # select the documented optimum — grow past the configured
        # floor as the candidate set demands
        capacity = self.capacity
        while capacity < 2 * max(self.chunks) * max(int(jobs), 4):
            capacity *= 2
        harness = ProbeHarness(
            p_times, lb_kind=lb_kind, capacity=capacity,
            warm_chunk=min(self.chunks), warm_iters=self.warm_iters,
            window_iters=self.window_iters, repeats=self.repeats,
            problem=problem)
        # the boot's step pipeline decides what the sweep must
        # measure: when the fused route resolves on, every candidate
        # is probed on BOTH pipelines and judged by the better rate —
        # the chunk winner must be chosen on rates the serving boot
        # can actually run (the same rule rung admission applies one
        # level down, ladder._selected_ms), and fused_for will route
        # the winner chunk to its measured winner pipeline at serve
        # time. Probes stay PFSP-only (the fused kernels are the PFSP
        # fast path) and interpret admits every shape; when the hw
        # route returns (on-chip round), this gate must also consult
        # pallas_fused.fused_ok per shape so a kernel-rejected shape
        # never pays fused probes the step would silently run unfused.
        from ..engine import ladder as _ladder
        from ..ops import pallas_fused
        from ..problems import get as _get_problem
        from ..utils import config as _cfg
        fused_mode = pallas_fused.resolve_mode(None)
        probe_fused = (fused_mode != "off" and lb_kind in (1, 2)
                       and getattr(_get_problem(problem),
                                   "supports_fused", False))
        with tracelog.span("tuner.sweep", jobs=jobs, machines=machines,
                           lb_kind=lb_kind, n_workers=n_workers) as sp:
            results = []
            fused_results = {}
            for c in self.chunks:
                try:
                    results.append(self._probe(
                        harness, c, defaults.BALANCE_PERIOD_DEFAULT))
                except ProbeError as e:
                    # a dropped candidate must be LOUD in the sweep
                    # record — a silent continue here once cost the
                    # whole top of the ladder
                    tracelog.event("tuner.candidate_dropped", chunk=c,
                                   error=repr(e))
                    continue
                if probe_fused:
                    try:
                        fused_results[c] = self._probe(
                            harness, c, defaults.BALANCE_PERIOD_DEFAULT,
                            fused=fused_mode)
                    except ProbeError as e:
                        tracelog.event("tuner.candidate_dropped",
                                       chunk=c, fused=fused_mode,
                                       error=repr(e))
            if not results:
                raise ProbeError(
                    f"no chunk candidate of {self.chunks} is "
                    f"measurable at capacity {capacity}")

            def best_rate(r):
                f = fused_results.get(r.chunk)
                return max(r.evals_per_s,
                           f.evals_per_s if f is not None else 0.0)

            # steady-state rates outrank ramp rates: an underfilled
            # candidate (pool < chunk at the window start) only wins
            # when every candidate is underfilled
            filled = [r for r in results if not r.underfilled]
            best_chunk = max(filled or results, key=best_rate)
            # the period sweep runs on the winner chunk's WINNING
            # pipeline — the one the boot will serve on
            win_fm, base = "off", best_chunk
            fbest = fused_results.get(best_chunk.chunk)
            if fbest is not None \
                    and fbest.evals_per_s > best_chunk.evals_per_s:
                win_fm, base = fused_mode, fbest
            period_results = [base]
            for b in self.periods:
                if b == base.balance_period:
                    continue
                try:
                    period_results.append(self._probe(
                        harness, best_chunk.chunk, b, fused=win_fm))
                except ProbeError as e:
                    tracelog.event("tuner.candidate_dropped",
                                   balance_period=b, error=repr(e))
                    continue
            winner = max(period_results, key=lambda r: r.evals_per_s)
            sp.set(chunk=winner.chunk,
                   balance_period=winner.balance_period,
                   evals_per_s=winner.evals_per_s,
                   probes=len(results) + len(fused_results)
                   + len(period_results) - 1)

            # --- per-rung kernel-vs-matmul profitability mask: probe
            # the winning chunk's LADDER rungs — below the static rung
            # floor too (min_chunk=1), since measured admission
            # (engine/ladder.rungs_from_profile) subsumes the floor —
            # once per available step pipeline on the same warmed
            # state. The mask persists with the winner and decides
            # each rung's fused-vs-matmul dispatch at serve time.
            # Probed only when there is a pipeline CHOICE to record
            # (the fused route resolves on) or the operator asks
            # (TTS_TUNE_RUNGS) — each rung is an extra compile, and a
            # matmul-only boot gains nothing from paying several of
            # them per shape (ladder admission then uses the static
            # floors, exactly the pre-mask behavior).
            rung_modes = []
            memo = {(r.chunk, r.balance_period, r.fused): r
                    for r in results + list(fused_results.values())
                    + period_results}
            rungs = (_ladder.rungs_for(winner.chunk, min_chunk=1)
                     if probe_fused or _cfg.env_flag("TTS_TUNE_RUNGS")
                     else ())
            for c in rungs:
                rows = {}
                for fm in ("off",) + ((fused_mode,) if probe_fused
                                      else ()):
                    k = (c, winner.balance_period, fm)
                    try:
                        rows[fm] = memo.get(k) or self._probe(
                            harness, c, winner.balance_period,
                            fused=fm)
                    except ProbeError as e:
                        tracelog.event("tuner.candidate_dropped",
                                       chunk=c, fused=fm,
                                       error=repr(e))
                if "off" not in rows:
                    continue
                ru = rows["off"]
                rf = rows.get(fused_mode) if probe_fused else None
                win = ("fused" if rf is not None
                       and rf.evals_per_s > ru.evals_per_s
                       else "unfused")
                best_r = rf if win == "fused" else ru
                rung_modes.append({
                    "chunk": int(c), "winner": win,
                    "ms_per_iter": best_r.ms_per_iter,
                    # per-pipeline rates too: rung ADMISSION must judge
                    # the pipeline a consuming boot actually runs
                    # (ladder._selected_ms) — a fused-won rung read by
                    # a TTS_FUSED=0 boot runs its unfused rate
                    "ms_per_iter_unfused": ru.ms_per_iter,
                    "ms_per_iter_fused":
                        rf.ms_per_iter if rf is not None else None,
                    "evals_per_s_unfused": ru.evals_per_s,
                    "evals_per_s_fused":
                        rf.evals_per_s if rf is not None else None,
                })
        sweep_s = time.perf_counter() - t0
        if self._probe_h is not None:
            self._probe_h.observe(sweep_s)
        payload = {
            "chunk": winner.chunk,
            "balance_period": winner.balance_period,
            "transfer_cap": None,    # derived from chunk at run time
            #   (the byte-budget rule prices it per topology; a probed
            #   1-worker cap would mis-size a production submesh)
            "evals_per_s": winner.evals_per_s,
            "sweep_seconds": round(sweep_s, 3),
            "rung_modes": rung_modes,
            "probes": [r.to_json()
                       for r in results + list(fused_results.values())
                       + period_results[1:]],
        }
        if self.cache is not None:
            self.cache.store(key, payload,
                             key_repr="/".join(str(k) for k in key))
        params = Params(chunk=winner.chunk,
                        balance_period=winner.balance_period,
                        source="probe", evals_per_s=winner.evals_per_s,
                        rung_modes=(tuple(rung_modes) if rung_modes
                                    else None))
        with self._lock:
            self._memo[key] = params
        return params

    def _probe(self, harness: ProbeHarness, chunk: int,
               balance_period: int, fused: str = "off"):
        r = harness.measure(chunk, balance_period, fused=fused)
        with self._lock:
            self.probes_run += 1
            self.ledger.append(r.to_json())
        if self._probes_c is not None:
            self._probes_c.inc()
        return r

    # ------------------------------------------------------------ read

    def snapshot(self) -> dict:
        """JSON-safe stats — status_snapshot()'s `tuner` key."""
        with self._lock:
            return {
                "probes_run": self.probes_run,
                "tuned_shapes": len(self._memo),
                "chunk_candidates": list(self.chunks),
                "period_candidates": list(self.periods),
                "cache": (self.cache.snapshot()
                          if self.cache is not None else None),
            }
