"""Persistent tuning cache: probe once per (shape, bound, topology).

The Autotuner's probes cost real device time (warmed measurement
windows over a candidate ladder); this cache makes them a once-per-key
cost ACROSS process lifetimes, exactly like service/aot_cache.py makes
compiles one: a restarted/autoscaled server replays its tuned dispatch
knobs from disk with ZERO probe executions.

Same safety model as the AOT cache, scaled to JSON-sized entries:

- **Key**: the file name is a digest of the tuning key (problem kind,
  jobs, machines, lb kind, worker count) — everything the optimum
  specializes on besides the runtime.
- **Fingerprint**: each entry's header embeds the device
  platform/topology fingerprint (:func:`tuning_fingerprint`); a
  wrong-runtime entry (a TPU optimum read on the CPU mesh, a topology
  change) is IGNORED — and overwritten by the next probe — but never
  consumed. The chunk optimum moved 256 → 32768 → 65536 across
  hardware/kernel changes (ROUND5_NOTES.md); a cache that served a
  stale platform's winner would silently re-introduce exactly the
  drift the tuner exists to kill.
- **Integrity**: entries are written temp + fsync + atomic rename with
  a CRC32 stamp over the payload; a corrupt/truncated entry is
  QUARANTINED (renamed ``*.corrupt``, never loaded, counted) and
  re-probed — the checkpoint/AOT discipline.

Writes are synchronous (entries are a few hundred bytes and happen
once per cold shape — no writer thread needed); loads never raise.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import struct
import threading
import time
import zlib

from ..obs import tracelog

__all__ = ["TuningCache", "tuning_fingerprint"]

MAGIC = b"TTSTUNE1\n"
_HDR_LEN = struct.Struct("<Q")
QUARANTINE_SUFFIX = ".corrupt"


def tuning_fingerprint(extra: dict | None = None) -> dict:
    """The device platform/topology identity a tuned optimum is only
    valid on. Narrower than the AOT cache's runtime fingerprint on
    purpose: serialized executables break on a jax/jaxlib bump, but a
    measured chunk optimum survives one — it breaks when the HARDWARE
    (or the mesh shape) changes."""
    import jax

    devices = jax.devices()
    fp = {
        "platform": jax.default_backend(),
        "device_count": len(devices),
        "device_kinds": sorted({d.device_kind for d in devices}),
        "process_count": jax.process_count(),
    }
    if extra:
        fp.update(extra)
    return fp


def _key_digest(key: tuple) -> str:
    """Stable digest of a tuning key (tuples of scalars). The
    fingerprint stays OUT of the name so a runtime change overwrites
    stale entries in place instead of stranding them (the aot_cache
    rule)."""
    raw = json.dumps([str(k) for k in key]).encode()
    return hashlib.sha256(raw).hexdigest()[:32]


class TuningCache:
    """Disk tier under the Autotuner. ``load(key)`` returns the stored
    payload dict (or None — absent, wrong-fingerprint, or corrupt);
    ``store(key, payload)`` persists atomically."""

    ENTRIES_TTL_S = 5.0   # entries() rescans the dir at most this often

    def __init__(self, root: str | os.PathLike, registry=None,
                 fingerprint_extra: dict | None = None):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.fingerprint = tuning_fingerprint(fingerprint_extra)
        self.hits = 0            # guarded-by: self._lock
        self.misses = 0          # guarded-by: self._lock
        self.mismatches = 0      # guarded-by: self._lock
        self.errors = 0          # guarded-by: self._lock
        self.quarantined = 0     # guarded-by: self._lock
        self.writes = 0          # guarded-by: self._lock
        # deliberately UNguarded (atomic tuple swap; staleness is fine
        # for a stats field): see entries()
        self._entries_cache: tuple | None = None
        self._lock = threading.Lock()
        self._hits_c = self._misses_c = None
        if registry is not None:
            self._hits_c = registry.counter(
                "tts_tuner_cache_hits_total",
                "tuned dispatch params replayed from the tuning cache "
                "(zero probes paid)")
            self._misses_c = registry.counter(
                "tts_tuner_cache_misses_total",
                "tuning-cache lookups with no loadable entry (absent, "
                "wrong-fingerprint, or quarantined corrupt)")

    # ---------------------------------------------------------- paths

    def path_for(self, key: tuple) -> pathlib.Path:
        return self.root / f"{_key_digest(key)}.tune"

    # ----------------------------------------------------------- load

    def load(self, key: tuple) -> dict | None:
        """The stored payload for `key`, or None. Never raises: corrupt
        entries quarantine, wrong-fingerprint entries are ignored (the
        next probe overwrites them), and the caller probes as if the
        cache were empty."""
        path = self.path_for(key)
        try:
            blob = path.read_bytes()
        except FileNotFoundError:
            self._count("_misses_c", "misses")
            return None
        except OSError as e:
            self._count("_misses_c", "errors")
            tracelog.event("tuner_cache.read_error", path=path.name,
                           error=repr(e))
            return None
        try:
            if blob[:len(MAGIC)] != MAGIC:
                raise ValueError("bad magic")
            off = len(MAGIC)
            (hdr_len,) = _HDR_LEN.unpack_from(blob, off)
            off += _HDR_LEN.size
            header = json.loads(blob[off:off + hdr_len].decode())
            off += hdr_len
            payload_raw = blob[off:]
            if len(payload_raw) != int(header["payload_len"]):
                raise ValueError("truncated payload")
            if zlib.crc32(payload_raw) != int(header["payload_crc32"]):
                raise ValueError("payload CRC mismatch")
            payload = json.loads(payload_raw.decode())
        except Exception as e:  # noqa: BLE001 — torn/truncated/garbled
            self._quarantine(path, repr(e))
            return None
        if header.get("fingerprint") != self.fingerprint:
            with self._lock:
                self.mismatches += 1
            self._count("_misses_c", "misses")
            tracelog.event("tuner_cache.mismatch", path=path.name,
                           theirs=header.get("fingerprint"),
                           ours=self.fingerprint)
            return None
        self._count("_hits_c", "hits")
        tracelog.event("tuner_cache.hit", path=path.name,
                       key=header.get("key"))
        return payload

    def _quarantine(self, path: pathlib.Path, error: str) -> None:
        self._count("_misses_c", "errors")
        # per-writer unique target (same discipline as store()'s temp
        # name): N processes quarantining corrupt incarnations of the
        # SAME entry must not os.replace over each other's forensic
        # copy — the suffix stays last so sweeps/tests keep matching.
        # The existence loop is raceless: only THIS thread mints names
        # under this pid-tid prefix
        base = f"{path.name}.{os.getpid()}-{threading.get_ident()}"
        qpath = str(path.with_name(base + QUARANTINE_SUFFIX))
        n = 0
        while os.path.exists(qpath):
            n += 1
            qpath = str(path.with_name(f"{base}.{n}{QUARANTINE_SUFFIX}"))
        try:
            os.replace(path, qpath)
            with self._lock:
                self.quarantined += 1
            self._entries_cache = None   # one fewer .tune on disk
        except OSError:
            qpath = None
        tracelog.event("tuner_cache.quarantine", path=path.name,
                       quarantined_to=qpath, error=error)

    # ---------------------------------------------------------- store

    def store(self, key: tuple, payload: dict, key_repr: str = "") -> None:
        """Persist `payload` for `key`: CRC stamp, temp + fsync +
        atomic rename (readers see old bytes or new, never torn).
        Synchronous — entries are a few hundred bytes, written once
        per cold shape."""
        payload_raw = json.dumps(payload, sort_keys=True).encode()
        header = json.dumps({
            "v": 1, "fingerprint": self.fingerprint, "key": key_repr,
            "created_unix": time.time(),
            "payload_len": len(payload_raw),
            "payload_crc32": zlib.crc32(payload_raw),
        }).encode()
        path = self.path_for(key)
        tmp = path.with_name(
            f".{path.name}.{os.getpid()}-{threading.get_ident()}.tmp")
        try:
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                f.write(_HDR_LEN.pack(len(header)))
                f.write(header)
                f.write(payload_raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            self.writes += 1
        self._entries_cache = None       # count may have changed
        tracelog.event("tuner_cache.store", path=path.name,
                       key=key_repr, bytes=len(payload_raw))

    # ----------------------------------------------------------- read

    def _count(self, counter_attr: str, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)
        c = getattr(self, counter_attr)
        if c is not None:
            c.inc()

    def entries(self) -> int:
        """Entry-file count, rescanned at most every ENTRIES_TTL_S —
        status_snapshot() reaches here at poll frequency and must not
        pay a directory scan per tick on slow fleet storage (the
        aot_cache rule; invalidated on write/quarantine)."""
        now = time.monotonic()
        cached = self._entries_cache
        if cached is not None and now - cached[0] < self.ENTRIES_TTL_S:
            return cached[1]
        try:
            n = sum(1 for p in self.root.iterdir()
                    if p.suffix == ".tune")
        except OSError:
            n = 0
        self._entries_cache = (now, n)
        return n

    def snapshot(self) -> dict:
        """JSON-safe stats — status_snapshot()'s `tuner` cache view."""
        n = self.entries()
        with self._lock:
            return {"dir": str(self.root), "entries": n,
                    "hits": self.hits, "misses": self.misses,
                    "mismatches": self.mismatches,
                    "errors": self.errors,
                    "quarantined": self.quarantined,
                    "writes": self.writes}
