"""Warmed probe runner: one harness for every dispatch-knob sweep.

The methodology is the one tools/bench_balance_period.py validated
on-chip (and the two earlier methodologies it documents as garbage):
warm a REAL pool past the ramp once, then time the full SPMD program
(engine/distributed.build_dist_loop) for each candidate configuration
on IDENTICAL warmed state and identical iteration windows — same
state, same window, best-of-N wall time. The chunk sweep and the
balance-period sweep (previously two bespoke tools) are both thin
loops over :meth:`ProbeHarness.measure`; the Autotuner drives the same
entry points, so the offline tuner and the hand-run sweep tools can
never measure different things.

The score is node-evals/s (bound evaluations per wall second): the
north-star unit, and the one that stays comparable across chunk
candidates — different chunks do different amounts of work per
iteration, so ms/iter only ranks candidates at a FIXED chunk
(balance-period sweeps report it too, for continuity with the old
tool's output).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

__all__ = ["ProbeHarness", "ProbeResult", "ProbeError",
           "measure_balance_periods"]


class ProbeError(RuntimeError):
    """The harness could not produce a steady measurement state (the
    instance exhausted or overflowed inside the warm-up). Callers fall
    back to the defaults tier — a failed probe must never fail a boot."""


@dataclasses.dataclass
class ProbeResult:
    """One candidate's measurement."""

    chunk: int
    balance_period: int
    transfer_cap: int
    evals_per_s: float
    ms_per_iter: float
    window_iters: int
    evals: int
    seconds: float          # best-of-repeats wall time of the window
    pool_start: int         # live rows when the window began
    underfilled: bool       # pool < chunk at window start: the rate is
    #                         a ramp rate, not a steady-state one —
    #                         the tuner deprioritizes these
    fused: str = "off"      # fused-kernel mode the candidate ran under
    #                         (ops/pallas_fused: "off"|"hw"|"interpret")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class ProbeHarness:
    """Warm ONCE per (instance, bound), measure MANY candidates on the
    identical state. Single-device mesh by construction (the same-state
    method needs one canonical pool; the per-worker program cost is
    what the knobs move — spread effects are documented separately in
    BENCHMARKS.md's sensitivity table).

    `problem` (registry name or plugin object, default "pfsp")
    generalizes the harness to every registered workload: the pool is
    seeded from the plugin's root/seed_aux, the warm-up and every
    measured candidate run the plugin's own step pipeline
    (Problem.make_step — the fast-path hook for PFSP, generic_step for
    the rest), so TSP/knapsack shapes get MEASURED chunk optima
    instead of the serving fallback row (ROADMAP item 2c). `table` is
    the problem's 2-D instance table; the historical ``p_times`` name
    is kept for the PFSP callers."""

    def __init__(self, p_times: np.ndarray, lb_kind: int = 1,
                 init_ub: int | None = None, capacity: int = 1 << 18,
                 warm_chunk: int | None = None, warm_iters: int = 200,
                 window_iters: int = 24, repeats: int = 2,
                 problem="pfsp"):
        from ..engine import device

        if isinstance(problem, str):
            from .. import problems as problems_pkg
            problem = problems_pkg.get(problem)
        self.problem = problem
        self.p_times = np.asarray(p_times)
        self.jobs = int(problem.slots(self.p_times))
        self.machines = int(problem.aux_rows(self.p_times))
        self.lb_kind = int(lb_kind)
        self.capacity = int(capacity)
        self.window_iters = int(window_iters)
        self.repeats = max(1, int(repeats))
        self.tables = problem.make_tables(self.p_times)
        self._adt = np.dtype(problem.aux_dtype(self.p_times))

        warm_chunk = int(warm_chunk or 64)
        prmu0, depth0 = problem.root(self.p_times)
        state = device.init_state(
            self.jobs, self.capacity, init_ub, prmu0=prmu0,
            depth0=depth0,
            aux0=problem.seed_aux(self.p_times, prmu0, depth0))
        state = device.run_problem(problem, self.tables, state,
                                   self.lb_kind, warm_chunk,
                                   max_iters=warm_iters, fused="off")
        state.size.block_until_ready()
        if bool(state.overflow) or int(state.size) == 0:
            raise ProbeError(
                f"warm-up left no steady state to measure "
                f"(pool={int(state.size)}, "
                f"overflow={bool(state.overflow)}) — instance "
                "exhausts or overflows within the warm-up window")
        self.pool = int(state.size)
        self.iters0 = int(state.iters)
        self._evals0 = int(state.evals)
        # DEVICE-resident, exactly like the validated tool this
        # harness replaces: a host-numpy pool would re-upload tens of
        # MB inside every timed window and rank candidates by
        # transfer noise instead of program cost
        self._stacked = tuple(x[None] for x in state)

    def measure(self, chunk: int, balance_period: int,
                transfer_cap: int | None = None,
                min_transfer: int | None = None,
                fused: str = "off") -> ProbeResult:
        """Time one candidate configuration on the warmed state.
        `fused` selects the step pipeline the candidate runs
        (ops/pallas_fused mode string) — the kernel-vs-matmul
        profitability probes measure the same rung twice, once per
        mode, on identical state."""
        import jax
        import jax.numpy as jnp

        from ..engine import distributed
        from ..parallel.mesh import worker_mesh

        chunk = int(chunk)
        balance_period = int(balance_period)
        if transfer_cap is None:
            transfer_cap = distributed.default_transfer_cap(
                chunk, self.jobs, self.machines, 1,
                aux_itemsize=self._adt.itemsize)
        min_transfer = int(min_transfer or 2 * chunk)
        limit = min(self.problem.usable_rows(self.capacity, chunk,
                                             self.jobs),
                    self.capacity - transfer_cap)
        if limit < 1:
            raise ProbeError(
                f"chunk {chunk} leaves no usable rows at capacity "
                f"{self.capacity} (limit={limit}); raise the harness "
                "capacity or drop the candidate")

        def mls(t, lim):
            return self.problem.make_step(t, self.lb_kind, chunk, 1024,
                                          lim, fused=fused)

        loop = distributed.build_dist_loop(
            worker_mesh(1), self.tables, mls, balance_period,
            transfer_cap, min_transfer, limit)
        target = jnp.asarray(self.iters0 + self.window_iters, jnp.int64)
        cap = jnp.asarray(distributed.I32_MAX, jnp.int32)

        def call():
            out = loop(self.tables, target, cap, *self._stacked)
            jax.block_until_ready(out)
            return out

        out = call()                 # compile + warm at the final sig
        best = float("inf")
        for _ in range(self.repeats):
            t0 = time.perf_counter()
            out = call()
            best = min(best, time.perf_counter() - t0)
        from ..engine.device import SearchState
        res = SearchState(*out)
        evals = int(np.asarray(res.evals).sum()) - self._evals0
        iters = int(np.asarray(res.iters).max()) - self.iters0
        return ProbeResult(
            chunk=chunk, balance_period=balance_period,
            transfer_cap=int(transfer_cap),
            evals_per_s=round(evals / best, 1) if best > 0 else 0.0,
            ms_per_iter=round(best / max(iters, 1) * 1e3, 4),
            window_iters=iters, evals=evals, seconds=round(best, 6),
            pool_start=self.pool,
            underfilled=self.pool < chunk, fused=fused)


def measure_balance_periods(p_times: np.ndarray, lb_kind: int,
                            chunk: int, periods, capacity: int = 1 << 22,
                            warm_iters: int = 500,
                            window_iters: int = 256,
                            repeats: int = 3,
                            init_ub: int | None = None) -> list[dict]:
    """The balance-period sweep (the old tools/bench_balance_period.py
    body, now a loop over the shared harness — its CLI is a thin
    wrapper around this). Returns one dict per period with the legacy
    ``ms_per_iter`` field plus the harness's evals/s."""
    h = ProbeHarness(p_times, lb_kind=lb_kind, init_ub=init_ub,
                     capacity=capacity, warm_chunk=chunk,
                     warm_iters=warm_iters, window_iters=window_iters,
                     repeats=repeats)
    rows = []
    for period in periods:
        r = h.measure(chunk, period)
        rows.append({"balance_period": int(period),
                     "ms_per_iter": r.ms_per_iter,
                     "evals_per_s": r.evals_per_s})
    return rows
