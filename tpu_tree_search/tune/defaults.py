"""Measured dispatch defaults — the autotuner's fallback tier.

Before this table existed, the engine's dispatch knobs lived in three
places that drifted independently: `utils/config.PFSPConfig` shipped
`chunk=256 / balance_period=4` (the round-1 CLI defaults), bench.py
hardcoded `chunk=65536` (the round-5 single-chip retune after the bf16
one-hot matmul changed the cost structure), and the serving layer's
`SearchRequest` defaulted to `chunk=64` (sized for preemption latency
on shared submeshes). This module is the ONE table all three consume —
and the tier the Autotuner (tune/tuner.py) falls back to when no
probed entry exists for a shape.

Provenance of the measured rows (do not "clean up" these numbers
without a measurement — each was a perf round):

- ``bench`` 20x20 chunk 65536: ROUND5_NOTES.md — 73.5M evals/s at
  65536 vs 67.8M at 32768 on v5e after the bf16 act matmul made the
  pair sweeps ~4x cheaper (81920/98304/131072 regress; pow2 keeps the
  lanes aligned).
- ``balance_period=4`` everywhere: tools/bench_balance_period.py
  on-chip — 6.40 ms/iter at period 4 vs 6.64 at 1 and 6.53 at 16 on
  identical ta021 state (±2% noise), so the period is chosen for
  SPREAD (per-worker tree CV 0.16 at 4 vs 0.20 at 16, BENCHMARKS.md).
  The CPU mesh's preference for sparse periods is a host-serialized-
  collectives artifact; never retune this knob on the virtual mesh.
- ``serving`` chunk 64: the service's preemption/deadline reaction
  granularity — stop flags land at segment boundaries, and a
  65536-wide chunk on a small submesh makes every boundary (and every
  ramp/drain step) pay for parents that are not there.
- ``cli`` chunk 256: the reference-parity default
  (PFSP_lib.c:175-185's -M family), kept for command-line
  compatibility.

This module must stay import-light (stdlib only): utils/config imports
it at module load.
"""

from __future__ import annotations

import dataclasses

# the knob every context shares — measured on-chip, see provenance above
BALANCE_PERIOD_DEFAULT = 4

# per-context chunk defaults (the fallback row of the table below)
CLI_CHUNK_DEFAULT = 256
SERVING_CHUNK_DEFAULT = 64
BENCH_CHUNK_DEFAULT = 65536


@dataclasses.dataclass(frozen=True)
class Params:
    """One resolved dispatch configuration. ``transfer_cap`` None means
    "derive from chunk via distributed.default_transfer_cap" (the byte-
    budgeted rule); ``source`` records which tier produced it:
    ``default`` (this table), ``cache`` (a persisted tuned entry) or
    ``probe`` (freshly measured)."""

    chunk: int
    balance_period: int = BALANCE_PERIOD_DEFAULT
    transfer_cap: int | None = None
    source: str = "default"
    evals_per_s: float | None = None   # the winning probe's rate, when
    #                                    source is cache/probe
    rung_modes: tuple | None = None    # per-rung kernel-vs-matmul
    #   profitability mask (source cache/probe only): a tuple of
    #   {"chunk", "winner": "fused"|"unfused", "ms_per_iter",
    #   "evals_per_s_fused", "evals_per_s_unfused"} rows for the
    #   winning chunk's ladder rungs, probed below the static rung
    #   floor too — engine/ladder.rungs_from_profile admits rungs from
    #   it (subsuming the static LB2 floor) and ladder.fused_for picks
    #   each rung's pipeline (ops/pallas_fused vs the matmul path)


def shape_class(jobs: int, machines: int, problem: str = "pfsp",
                batch: int | None = None) -> str:
    """The shape-class label table rows key on. PFSP keeps the legacy
    Taillard-style ``JxM`` label (persisted tuning caches and the
    MEASURED rows predate the problem prefix); every other problem is
    namespaced ``problem:JxM`` so two workloads can never alias one
    measured row. A megabatched dispatch (``batch`` = the instance-axis
    width B > 1) appends ``@bB``: the batched loop's cost structure is
    its own (every member pops a chunk per iteration, so the effective
    parallel width is B x chunk), and a batched optimum must never
    alias — or silently fall back to — the solo row of the same
    shape."""
    label = f"{int(jobs)}x{int(machines)}"
    if problem != "pfsp":
        label = f"{problem}:{label}"
    if batch is not None and int(batch) > 1:
        label = f"{label}@b{int(batch)}"
    return label


# (context, shape_class) -> Params. Contexts: "bench" (single-chip
# throughput bench), "serving" (SearchServer request default), "cli"
# (reference-parity one-shot runs). Only MEASURED rows belong here;
# everything else resolves through _FALLBACK.
MEASURED: dict[tuple[str, str], Params] = {
    # ROUND5: the bf16-matmul retune, measured on ta021 (20x20) — the
    # whole 20-job family shares the cost structure (the pair sweep is
    # machine-count-bound, not job-count-bound)
    ("bench", "20x5"): Params(chunk=BENCH_CHUNK_DEFAULT),
    ("bench", "20x10"): Params(chunk=BENCH_CHUNK_DEFAULT),
    ("bench", "20x20"): Params(chunk=BENCH_CHUNK_DEFAULT),
    # MEGABATCH round (this PR, 8-dev CPU mesh, bench.py
    # pfsp_serve_rps): the small-instance serving mix the batch-former
    # targets — per-member chunk 64 at B=4/8/16 beat 128/256 (lockstep
    # ramp dominates; every member pays the widest member's underfilled
    # steps) and matched the solo row's reaction latency. Explicit rows
    # so the batched hot path never probes and never silently reads
    # the solo serving row.
    ("serving", "8x5@b4"): Params(chunk=SERVING_CHUNK_DEFAULT),
    ("serving", "8x5@b8"): Params(chunk=SERVING_CHUNK_DEFAULT),
    ("serving", "8x5@b16"): Params(chunk=SERVING_CHUNK_DEFAULT),
}

# megabatched serving (TTS_MEGABATCH): the per-member chunk of a
# batched dispatch. MEASURED on the 8-dev CPU mesh (this PR's
# megabatch round): at B=8 small instances per submesh the batched
# loop's effective parallel width is B x chunk, so the solo serving
# chunk (64) already saturates each member's shallow pools — larger
# per-member chunks only inflate the lockstep ramp (every member pays
# the widest member's underfilled steps). Re-measure on hardware
# before trusting this for big-B TPU batches.
SERVING_BATCH_CHUNK_DEFAULT = 64

_FALLBACK: dict[str, Params] = {
    "bench": Params(chunk=BENCH_CHUNK_DEFAULT),
    "serving": Params(chunk=SERVING_CHUNK_DEFAULT),
    "cli": Params(chunk=CLI_CHUNK_DEFAULT),
}

# the BATCHED serving fallback is its own explicit row: a batched
# dispatch that finds no measured/tuned entry must land on a value
# chosen FOR batched execution — falling through to the solo serving
# row silently would let a solo retune change every megabatch's cost
# structure without anyone measuring it
_FALLBACK_BATCHED = Params(chunk=SERVING_BATCH_CHUNK_DEFAULT)


def params_for(context: str, jobs: int | None = None,
               machines: int | None = None,
               problem: str = "pfsp",
               batch: int | None = None) -> Params:
    """Resolve the default dispatch params for a context, problem and
    shape — the tuner's fallback tier and the single source
    config/bench/serve read their chunk/balance_period defaults from.
    Only PFSP has measured rows today; other problems resolve through
    the per-context fallback until their own perf rounds land.

    ``batch`` (the megabatch instance-axis width) keys batched rows via
    :func:`shape_class`'s ``@bB`` suffix; with no batched row measured
    the resolution falls to the explicit batched serving fallback
    (``_FALLBACK_BATCHED``), NEVER silently to the solo serving row."""
    if context not in _FALLBACK:
        raise ValueError(f"unknown defaults context {context!r} "
                         f"(want one of {sorted(_FALLBACK)})")
    if jobs is not None and machines is not None:
        row = MEASURED.get((context, shape_class(jobs, machines,
                                                 problem, batch=batch)))
        if row is not None:
            return row
    if batch is not None and int(batch) > 1:
        return _FALLBACK_BATCHED
    return _FALLBACK[context]
