"""Command-line interface.

Mirrors the reference's flag vocabulary (reference: PFSP_lib.c:173-320 for
PFSP, nqueens_multigpu_cuda.cu:25-89 for N-Queens) and its settings/results
report format (PFSP_lib.c:133-170), so reference users can re-run their
command lines against the TPU engine:

    python -m tpu_tree_search pfsp -i 14 -l 1 -u 1 -D 1
    python -m tpu_tree_search nqueens -N 13 -g 1
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from .utils.config import NQueensConfig, PFSPConfig


def _pfsp_parser(sub):
    p = sub.add_parser("pfsp", help="Taillard PFSP B&B")
    d = PFSPConfig()
    p.add_argument("-i", "--inst", type=int, default=d.inst)
    p.add_argument("-l", "--lb", type=int, default=d.lb, choices=(0, 1, 2))
    p.add_argument("-u", "--ub", type=int, default=d.ub, choices=(0, 1))
    p.add_argument("-m", type=int, default=d.m)
    p.add_argument("-M", type=int, default=d.M)
    p.add_argument("-T", type=int, default=d.T)
    p.add_argument("-D", type=int, default=d.D)
    p.add_argument("-C", type=int, default=d.C)
    p.add_argument("-w", "--ws", type=int, default=d.ws)
    p.add_argument("-L", type=int, default=d.L)
    p.add_argument("-p", "--perc", type=float, default=d.perc)
    p.add_argument("--chunk", type=int, default=d.chunk)
    p.add_argument("--capacity", type=int, default=d.capacity)
    p.add_argument("--balance-period", type=int, default=d.balance_period)
    p.add_argument("--csv", type=str, default=None)
    p.add_argument("--max-iters", type=int, default=None,
                   help="truncate the search (debugging)")


def _nq_parser(sub):
    p = sub.add_parser("nqueens", help="N-Queens backtracking")
    d = NQueensConfig()
    p.add_argument("-N", type=int, default=d.N)
    p.add_argument("-g", type=int, default=d.g)
    p.add_argument("-D", type=int, default=d.D)
    p.add_argument("--chunk", type=int, default=d.chunk)
    p.add_argument("--capacity", type=int, default=d.capacity)


def _print_pfsp_settings(args, machines, jobs, n_dev):
    print("=" * 49)
    print(f"TPU B&B ({n_dev} device(s) - balancing [{int(args.ws or args.L)}])")
    print(f"Resolution of PFSP Taillard's instance: ta{args.inst} "
          f"(m = {machines}, n = {jobs})")
    print("Initial upper bound: " + ("opt" if args.ub == 1 else "inf"))
    print("Lower bound function: " + {0: "lb1_d", 1: "lb1", 2: "lb2"}[args.lb])
    print("Branching rule: fwd")
    print("=" * 49)


def _print_results(optimum, tree, sol, elapsed):
    print("=" * 49)
    print(f"Size of the explored tree: {tree}")
    print(f"Number of explored solutions: {sol}")
    print(f"Optimal makespan: {optimum}")
    print(f"Elapsed time: {elapsed:.4f} [s]")
    print("=" * 49)


def run_pfsp(args) -> int:
    import jax

    from .engine import device, distributed
    from .problems import taillard
    from .utils import csv_stats

    p = taillard.processing_times(args.inst)
    jobs, machines = p.shape[1], p.shape[0]
    init_ub = taillard.optimal_makespan(args.inst) if args.ub == 1 else None
    n_dev = args.D if args.D > 0 else len(jax.devices())
    _print_pfsp_settings(args, machines, jobs, n_dev)

    t0 = time.perf_counter()
    if n_dev == 1:
        out = device.search(p, lb_kind=args.lb, init_ub=init_ub,
                            chunk=args.chunk, capacity=args.capacity,
                            max_iters=args.max_iters)
        tree, sol, best = out.explored_tree, out.explored_sol, out.best
        per_device = {"tree": [tree], "sol": [sol], "evals": [out.evals],
                      "steals": [0], "recv": [0]}
    else:
        res = distributed.search(
            p, lb_kind=args.lb, init_ub=init_ub, n_devices=n_dev,
            chunk=args.chunk, capacity=args.capacity,
            balance_period=(args.balance_period if (args.ws or args.L)
                            else 1 << 30),
            min_seed=args.m,
            max_rounds=args.max_iters)
        tree, sol, best = res.explored_tree, res.explored_sol, res.best
        per_device = {k: list(v) for k, v in res.per_device.items()}
    elapsed = time.perf_counter() - t0

    _print_results(best, tree, sol, elapsed)
    if args.csv:
        if n_dev == 1:
            csv_stats.write_single(args.csv, args.inst, args.lb, best, args.m,
                                   args.M, elapsed, elapsed, tree, sol)
        else:
            csv_stats.write_dist(args.csv, args.inst, args.lb, n_dev, args.C,
                                 args.L, 1, best, args.m, args.M, args.T,
                                 elapsed, tree, sol, per_device)
    return 0


def run_nqueens(args) -> int:
    import jax

    from .engine import nqueens_device

    n_dev = args.D if args.D > 0 else len(jax.devices())
    print("=" * 49)
    print(f"TPU N-Queens ({n_dev} device(s))")
    print(f"Resolution of the {args.N}-Queens instance")
    print(f"  with {args.g} safety check(s) per evaluation")
    print("=" * 49)
    t0 = time.perf_counter()
    if n_dev == 1:
        out = nqueens_device.search(args.N, g=args.g, chunk=args.chunk,
                                    capacity=args.capacity)
    else:
        out = nqueens_device.search_distributed(
            args.N, g=args.g, n_devices=n_dev, chunk=args.chunk,
            capacity=args.capacity)
    elapsed = time.perf_counter() - t0
    print("=" * 49)
    print(f"Size of the explored tree: {out.explored_tree}")
    print(f"Number of explored solutions: {out.explored_sol}")
    print(f"Elapsed time: {elapsed:.4f} [s]")
    print("=" * 49)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu_tree_search")
    sub = ap.add_subparsers(dest="cmd", required=True)
    _pfsp_parser(sub)
    _nq_parser(sub)
    args = ap.parse_args(argv)
    if args.cmd == "pfsp":
        return run_pfsp(args)
    return run_nqueens(args)


if __name__ == "__main__":
    sys.exit(main())
