"""Command-line interface.

Mirrors the reference's flag vocabulary (reference: PFSP_lib.c:173-320 for
PFSP, nqueens_multigpu_cuda.cu:25-89 for N-Queens) and its settings/results
report format (PFSP_lib.c:133-170), so reference users can re-run their
command lines against the TPU engine:

    python -m tpu_tree_search pfsp -i 14 -l 1 -u 1 -D 1
    python -m tpu_tree_search nqueens -N 13 -g 1

Beyond the reference's one-shot runs, `serve` starts the long-lived
search service (tpu_tree_search/service/) over a file spool and
`client` submits requests to it:

    python -m tpu_tree_search serve --spool /tmp/tts-spool --submeshes 2
    python -m tpu_tree_search client --spool /tmp/tts-spool -i 21 -l 1
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .utils.config import NQueensConfig, PFSPConfig


def _pfsp_parser(sub):
    p = sub.add_parser("pfsp", help="Taillard PFSP B&B")
    d = PFSPConfig()
    p.add_argument("-i", "--inst", type=int, default=d.inst)
    p.add_argument("-l", "--lb", type=int, default=d.lb, choices=(0, 1, 2))
    p.add_argument("-u", "--ub", type=int, default=d.ub, choices=(0, 1))
    p.add_argument("-m", type=int, default=d.m)
    p.add_argument("-M", type=int, default=d.M)
    p.add_argument("-T", type=int, default=d.T,
                   help="reference CPU bulk-pop size; accepted for "
                        "command-line and CSV-schema compatibility but "
                        "inert here, like -p (the host tier's native DFS "
                        "pops per node; PFSP_lib.c:175-185)")
    p.add_argument("-D", type=int, default=d.D)
    p.add_argument("-C", type=int, default=d.C)
    p.add_argument("--host-fraction", type=int, default=None,
                   help="with -C 1: seed the native host tier with every "
                        "k-th warm-up node (default 8; 0 disables the "
                        "concurrent tier)")
    p.add_argument("--host-threads", type=int, default=None,
                   help="with -C 1: native host worker threads "
                        "(default: host cores / device count, the "
                        "reference's num_procs/deviceCount rule, "
                        "pfsp_multigpu_cuda.c:61-69)")
    p.add_argument("-w", "--ws", type=int, default=d.ws)
    p.add_argument("-L", type=int, default=d.L)
    p.add_argument("-p", "--perc", type=float, default=d.perc)
    p.add_argument("--chunk", type=int, default=d.chunk)
    p.add_argument("--capacity", type=int, default=None,
                   help=f"pool rows (default: sized by instance class, "
                        f"at least {d.capacity}; weak-bound classes "
                        "like 50x5 pre-size large — device."
                        "default_capacity)")
    p.add_argument("--balance-period", type=int, default=d.balance_period)
    p.add_argument("--csv", type=str, default=None)
    p.add_argument("--max-iters", type=int, default=None,
                   help="truncate the search (debugging)")
    p.add_argument("--segment-iters", type=int, default=None,
                   help="run in bounded segments with heartbeat reports "
                        "(enables checkpointing; any -D)")
    p.add_argument("--checkpoint", type=str, default=None,
                   help="checkpoint path; if the file exists the search "
                        "resumes from it")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="write the checkpoint every N segments (the "
                        "compressed pool snapshot costs seconds at "
                        "production sizes; amortize it on long runs)")
    p.add_argument("--grow-capacity", type=int, default=None,
                   help="re-home a resumed checkpoint into a larger pool "
                        "(recovery after an overflow abort)")
    from .utils import config as _cfg
    p.add_argument("--retry-attempts", type=int, default=None,
                   help="transient-error retries per segment operation "
                        f"(default {_cfg.RETRY_ATTEMPTS_DEFAULT}; "
                        "exponential backoff base "
                        f"{_cfg.RETRY_BASE_S_DEFAULT}s — also via "
                        "TTS_RETRY_ATTEMPTS / TTS_RETRY_BASE_S)")
    p.add_argument("--segment-timeout", type=float, default=None,
                   help="per-segment wall-clock watchdog in seconds "
                        "(0/default: off; a hung device dispatch raises "
                        "instead of waiting forever — also via "
                        "TTS_SEG_TIMEOUT_S)")
    p.add_argument("--faults", type=str, default=None,
                   help="deterministic fault-injection spec for "
                        "resilience drills, e.g. "
                        "'kill_after_segment=3,fail_host_fetch=1' "
                        "(utils/faults.py; also via TTS_FAULTS)")
    p.add_argument("--search-telemetry", action="store_true",
                   help="compile the on-device search-telemetry block "
                        "into the loop (engine/telemetry.py: depth-"
                        "bucketed pruning counts, bound histograms, "
                        "pool high-water, steal flow, incumbent ring; "
                        "also via TTS_SEARCH_TELEMETRY=1). Node counts "
                        "stay bit-identical; segmented runs emit per-"
                        "segment search.telemetry trace events "
                        "(tools/search_report.py renders them)")


def _serve_parser(sub):
    from .utils import config as _cfg
    p = sub.add_parser(
        "serve",
        help="run the in-process search service over a file spool "
             "(service/: submesh scheduling, priority preemption, "
             "executable reuse)")
    p.add_argument("--spool", type=str, required=True,
                   help="directory watched for <id>.req.json request "
                        "files; results land beside them as "
                        "<id>.res.json (see service/spool.py for the "
                        "payload schema)")
    p.add_argument("--submeshes", type=int,
                   default=_cfg.env_int("TTS_SUBMESHES"),
                   help="partition the device mesh into this many equal "
                        "submeshes, one concurrent request each "
                        "(must divide the device count; TTS_SUBMESHES "
                        "sets the default — the campaign respawn "
                        "channel)")
    p.add_argument("--workdir", type=str, default=None,
                   help="checkpoint directory for preempted/deadline "
                        "requests (default: a fresh temp dir)")
    p.add_argument("--queue-depth", type=int,
                   default=_cfg.env_int("TTS_QUEUE_DEPTH"),
                   help="admission bound: requests beyond this are "
                        "rejected with a reason, not buffered")
    p.add_argument("--segment-iters", type=int,
                   default=_cfg.SERVICE_SEGMENT_ITERS_DEFAULT,
                   help="segment length between stop-flag checks — the "
                        "preemption/deadline reaction granularity")
    p.add_argument("--idle-exit", type=float, default=None,
                   help="exit after this many seconds with no queued or "
                        "running work (default: serve forever)")
    p.add_argument("--status-every", type=float, default=30.0,
                   help="print a JSON status snapshot every N seconds "
                        "(0 disables)")
    p.add_argument("--http-port", type=int, default=None,
                   help="start the observability HTTP front-end "
                        "(obs/httpd: /healthz /metrics /status /trace) "
                        "on this port (0 = ephemeral, printed at "
                        "startup; default: off)")
    p.add_argument("--http-host", type=str, default="127.0.0.1",
                   help="bind address for --http-port (default "
                        "loopback; 0.0.0.0 exposes it)")
    p.add_argument("--trace-file", type=str, default=None,
                   help="append the flight recorder's span/event log "
                        "to this JSONL file (also via TTS_TRACE_FILE; "
                        "convert with tools/trace_summary.py or the "
                        "/trace endpoint)")
    p.add_argument("--phase-metrics", action="store_true",
                   help="measure per-phase unit costs once per request "
                        "shape and publish live per-worker "
                        "kernel/genchild/balance/idle attribution as "
                        "tts_phase_seconds gauges (adds seconds of "
                        "profiling to each shape's first dispatch)")
    p.add_argument("--search-telemetry", action="store_true",
                   help="compile the on-device search-telemetry block "
                        "into every served loop (also via "
                        "TTS_SEARCH_TELEMETRY=1): per-request pruning "
                        "efficiency on /metrics (tts_search_* gauges), "
                        "search.telemetry trace events, Perfetto "
                        "counter tracks on /trace")
    p.add_argument("--otel-endpoint", type=str, default=None,
                   help="export the session's flight-recorder ring as "
                        "OTLP spans to this OTLP/HTTP traces URL at "
                        "shutdown (obs/otel.py; requires the "
                        "opentelemetry SDK — a clean no-op warning "
                        "when it is not installed)")
    p.add_argument("--otel-interval-s", type=float, default=0.0,
                   help="also flush the flight-recorder ring to "
                        "--otel-endpoint every N seconds while serving "
                        "(seq-watermarked: each flush ships only new "
                        "records, so a crashed server has exported "
                        "everything up to its last interval; <= 0 "
                        "keeps the shutdown-only behavior)")
    p.add_argument("--profile-dir", type=str, default=None,
                   help="artifact root for POST /profile captures "
                        "(obs/profiler; one subdirectory per capture; "
                        "default: <workdir>/profiles)")
    p.add_argument("--resource-sample-s", type=float, default=None,
                   help="device-memory/host-RSS sampler cadence in "
                        "seconds (obs/resource: tts_device_bytes_* "
                        "gauges + Perfetto memory lanes; default "
                        "1.0, also via TTS_RESOURCE_SAMPLE_S; <= 0 "
                        "disables)")
    p.add_argument("--health-interval-s", type=float, default=None,
                   help="health rules-engine evaluation cadence in "
                        "seconds (obs/health: /alerts, /dashboard, "
                        "tts_alerts gauges; default "
                        f"{_cfg.OBS_HEALTH_INTERVAL_S_DEFAULT}, also "
                        "via TTS_HEALTH_INTERVAL_S; <= 0 disables "
                        "the daemon — thresholds via TTS_HEALTH_*)")
    p.add_argument("--overlap", action="store_true",
                   help="pipeline segmented execution (also via "
                        "TTS_OVERLAP=1): the next segment dispatches "
                        "before the previous segment's counters are "
                        "fetched (donated carries) and checkpoint "
                        "serialization moves to a writer thread — "
                        "device-idle gap between segments -> ~0 "
                        "(tts_segment_gap_seconds), bit-identical "
                        "node accounting")
    p.add_argument("--share-incumbent", action="store_true",
                   help="share best-makespan incumbents across "
                        "concurrent same-instance requests (also via "
                        "TTS_SHARE_INCUMBENT=1): each segment boundary "
                        "publishes the submesh's best and folds the "
                        "global best in as the next pruning ceiling "
                        "(monotone-only, audited; "
                        "tts_incumbent_folds_total)")
    p.add_argument("--aot-cache", type=str, default=None,
                   help="disk directory for persisted AOT executables "
                        "(also via TTS_AOT_CACHE): a restarted server "
                        "deserializes previously-compiled loops from "
                        "it (~0.2 s, ledger source=disk) instead of "
                        "re-tracing+compiling; entries are CRC-"
                        "stamped, fingerprinted against the runtime, "
                        "corrupt ones quarantined (service/"
                        "aot_cache.py). Default: off (in-memory "
                        "executor cache only)")
    p.add_argument("--tune-cache", type=str, default=None,
                   help="persistent tuning-cache directory (also via "
                        "TTS_TUNE_CACHE): requests submitted with open "
                        "knobs ({'tuned': true} spool payloads / "
                        "chunk=None) resolve chunk/balance_period from "
                        "probed optima instead of the defaults table "
                        "(tune/: fingerprint-checked, CRC-stamped, "
                        "corrupt entries quarantined). Default: off")
    p.add_argument("--tune", action="store_true",
                   help="with --prewarm: PROBE cold shapes at boot "
                        "(short warmed measurement sweeps, winners "
                        "persisted to --tune-cache; also via "
                        "TTS_TUNE=1). A warm cache replays with zero "
                        "probe executions either way")
    p.add_argument("--ladder", action="store_true",
                   help="chunk-ladder execution (also via "
                        "TTS_LADDER=1): pre-build 2-3 chunk rungs per "
                        "served shape and switch at segment "
                        "boundaries from the pool-occupancy signal, "
                        "so ramp/drain run small-chunk steps "
                        "(engine/ladder.py; off-mode is bit-identical "
                        "to the fixed-chunk driver)")
    p.add_argument("--megabatch", action="store_true",
                   help="request megabatching (also via "
                        "TTS_MEGABATCH=1; engine/megabatch.py): the "
                        "admission queue becomes a batch-former — "
                        "same-shape-class requests stack into ONE "
                        "vmapped compiled loop per submesh (close on "
                        "size TTS_BATCH_MAX or age TTS_BATCH_AGE_S; a "
                        "lone request age-closes onto the solo path). "
                        "Every batched request's counts/optimum/"
                        "telemetry are bit-identical to its solo run; "
                        "default off = the solo scheduler exactly")
    p.add_argument("--batch-max", type=int, default=None,
                   help="megabatch: close a forming batch at this "
                        "many members (also via TTS_BATCH_MAX, "
                        f"default {_cfg.BATCH_MAX_DEFAULT})")
    p.add_argument("--batch-age-s", type=float, default=None,
                   help="megabatch: close a forming batch once its "
                        "oldest member has waited this long (also via "
                        "TTS_BATCH_AGE_S, default "
                        f"{_cfg.BATCH_AGE_S_DEFAULT:g})")
    p.add_argument("--remediate", action="store_true",
                   help="EXECUTE the self-healing policy table (also "
                        "via TTS_REMEDIATE=1; service/remediate.py): "
                        "stall alerts auto-preempt + requeue with the "
                        "offending submesh excluded, failures "
                        "localized to one submesh quarantine it "
                        "(drain, canary-probe, readmit), failures "
                        "following a request across submeshes "
                        "dead-letter it with a full failure_log, "
                        "compile storms pause admission (429), audit "
                        "failures quarantine the bad checkpoint. "
                        "Default: observe-only — the controller logs "
                        "the action it WOULD take and touches nothing")
    p.add_argument("--ledger", type=str, default=None,
                   help="durable request-ledger directory (also via "
                        "TTS_LEDGER; service/ledger.py): every request "
                        "state transition is journaled (fsync'd, "
                        "CRC-stamped JSONL) BEFORE it is acknowledged "
                        "— a POST /submit 200 becomes a durability "
                        "promise — and a restarted server REPLAYS the "
                        "ledger at boot: queued/active requests "
                        "re-admit with budgets/exclusions/failure "
                        "logs intact and resume from their "
                        "checkpoints, terminal results re-serve "
                        "idempotently, quarantines and admission "
                        "pauses are restored. Pairs with a persistent "
                        "--workdir (default with --ledger: "
                        "<ledger>/workdir). Default: off")
    p.add_argument("--fleet-dir", type=str, default=None,
                   help="shared fleet root for high availability (also "
                        "via TTS_FLEET_DIR; service/lease.py + "
                        "failover.py): the server takes an fsync'd, "
                        "CRC-stamped LEASE on its --ledger dir (owner "
                        "id, fencing epoch, TTL TTS_LEASE_TTL_S) and "
                        "renews it from a daemon thread; every ledger "
                        "append and checkpoint save is stamped with "
                        "the epoch, and a FailoverWatcher scans the "
                        "fleet root for peer leases that expired "
                        "without release. Requires --ledger. Default: "
                        "off (single-server PR-12 behavior)")
    p.add_argument("--failover", action="store_true",
                   help="ARM peer-ledger takeover (also via "
                        "TTS_FAILOVER=1): when a peer's lease expires, "
                        "CAS-bump its epoch, adopt its ledger — "
                        "re-admit queued/active requests here with "
                        "budgets/exclusions/spool ids intact, re-serve "
                        "done tags idempotently — and keep its lease "
                        "so the stale owner boots fenced. Default: "
                        "observe-only — peer-down detection and "
                        "journaling only, zero takeovers, behavior "
                        "bit-identical to a fleet-less server")
    p.add_argument("--drain-timeout", type=float, default=None,
                   help="graceful SIGTERM/SIGINT drain budget in "
                        "seconds (also via TTS_DRAIN_TIMEOUT_S, "
                        f"default {_cfg.DRAIN_TIMEOUT_S_DEFAULT:g}): "
                        "stop admission, preempt running requests at "
                        "segment boundaries (checkpointed), drain the "
                        "checkpoint/AOT/ledger writers, exit 0; past "
                        "the budget the process checkpoint-and-aborts "
                        "(nonzero exit — with --ledger the abort is "
                        "itself recoverable)")
    p.add_argument("--prewarm", type=str, nargs="?", const="",
                   default=None, metavar="SPEC",
                   help="boot pre-warm: ready compiled loops BEFORE "
                        "the first request (also via TTS_PREWARM). "
                        "SPEC is comma-separated 'taillard' (the "
                        "standard shape families), 'spool' (shapes in "
                        "the backlog) and/or explicit JxM entries; "
                        "bare --prewarm means 'spool,taillard' "
                        "(backlog shapes first). With "
                        "--aot-cache, a warm dir makes this a burst "
                        "of disk loads and a cold dir pays each "
                        "compile exactly once across lifetimes")


def _problem_instance_args(p, require_inst: bool = False):
    """Shared instance-selection flags for `solve` and `client`: a
    problem name plus ONE instance source — a Taillard id (PFSP only),
    a synthetic --size/--seed, or a raw table from a JSON file."""
    p.add_argument("--problem", type=str, default="pfsp",
                   help="workload plugin (problems/base.py): pfsp | "
                        "nqueens | tsp | knapsack")
    p.add_argument("-i", "--inst", type=int,
                   required=require_inst, default=None,
                   help="Taillard instance id (PFSP only)")
    p.add_argument("--size", type=int, default=None,
                   help="synthetic instance size: jobs (pfsp), board "
                        "n (nqueens), cities (tsp), items (knapsack)")
    p.add_argument("--machines", type=int, default=5,
                   help="machines for a synthetic PFSP --size instance")
    p.add_argument("--seed", type=int, default=0,
                   help="synthetic instance seed")
    p.add_argument("--instance-json", type=str, default=None,
                   help="path to a JSON 2-D instance table (the "
                        "problem's p_times format, problems/base.py)")


def _solve_instance_table(args):
    """Resolve the instance table for `solve`/`client` from the flags
    (--inst > --instance-json > --size synthetic)."""
    import numpy as _np

    if args.inst is not None:
        if args.problem != "pfsp":
            raise SystemExit("--inst (a Taillard id) is PFSP-only; "
                             "use --size or --instance-json")
        from .problems import taillard
        return taillard.processing_times(args.inst)
    if args.instance_json:
        import json as _json
        return _np.asarray(
            _json.load(open(args.instance_json)), _np.int32)
    if args.size is None:
        raise SystemExit("pick an instance: -i (pfsp), --size or "
                         "--instance-json")
    n, seed = args.size, args.seed
    if args.problem == "pfsp":
        from .problems.pfsp import PFSPInstance
        return PFSPInstance.synthetic(jobs=n, machines=args.machines,
                                      seed=seed).p_times
    if args.problem == "nqueens":
        from .problems import nqueens as nq
        return nq.table(n)
    if args.problem == "tsp":
        from .problems.tsp import TSPInstance
        return TSPInstance.synthetic(n, seed).d
    if args.problem == "knapsack":
        from .problems.knapsack import KnapsackInstance
        return KnapsackInstance.synthetic(n, seed).table
    raise SystemExit(f"no synthetic builder for problem "
                     f"{args.problem!r}; use --instance-json")


def _solve_parser(sub):
    p = sub.add_parser(
        "solve",
        help="one-shot solve of ANY registered problem through the "
             "generic plugin engine (single-device or distributed)")
    _problem_instance_args(p)
    p.add_argument("-l", "--lb", type=int, default=None,
                   help="bound kind (default: the problem's default)")
    p.add_argument("-u", "--ub", type=int, default=None,
                   help="seed incumbent value (objective units)")
    p.add_argument("-D", type=int, default=1,
                   help="devices (1 = single-device engine)")
    p.add_argument("--chunk", type=int, default=64)
    p.add_argument("--capacity", type=int, default=None)
    p.add_argument("--max-iters", type=int, default=None,
                   help="truncate the search (debugging)")


def run_solve(args) -> int:
    import json

    from . import problems
    from .engine import device, distributed

    try:
        prob = problems.get(args.problem)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    table = _solve_instance_table(args)
    reason = prob.validate(table)
    if reason is not None:
        print(f"error: invalid instance: {reason}", file=sys.stderr)
        return 2
    lb = prob.default_lb if args.lb is None else args.lb
    # --ub is in OBJECTIVE units; the engine's incumbent lives in the
    # minimized domain (knapsack: -value)
    init_ub = (None if args.ub is None
               else prob.engine_objective(args.ub))
    print("=" * 49)
    print(f"TPU B&B problem={prob.name} shape="
          f"{'x'.join(map(str, table.shape))} lb={lb} D={args.D}")
    print("=" * 49)
    t0 = time.perf_counter()
    if args.D == 1:
        out = device.solve(prob, table, lb_kind=lb, init_ub=init_ub,
                           chunk=args.chunk, capacity=args.capacity,
                           max_iters=args.max_iters)
        tree, sol, best = out.explored_tree, out.explored_sol, out.best
        complete = out.complete
    else:
        res = distributed.search(
            table, problem=prob, lb_kind=lb, init_ub=init_ub,
            n_devices=args.D, chunk=args.chunk,
            capacity=args.capacity or prob.default_capacity(table),
            max_rounds=args.max_iters)
        tree, sol, best = (res.explored_tree, res.explored_sol,
                           res.best)
        complete = res.complete
    elapsed = time.perf_counter() - t0
    print(json.dumps({
        "problem": prob.name, "explored_tree": tree,
        "explored_sol": sol, "best": int(best),
        "objective": prob.display_objective(best),
        "complete": bool(complete), "elapsed_s": round(elapsed, 4)}))
    return 0


def _client_parser(sub):
    p = sub.add_parser(
        "client",
        help="submit one request to a running `serve` spool and wait")
    p.add_argument("--spool", type=str, required=True)
    _problem_instance_args(p)
    p.add_argument("-l", "--lb", type=int, default=None,
                   help="bound kind (default: the problem's default)")
    p.add_argument("-u", "--ub", type=int, default=1, choices=(0, 1),
                   help="1: seed the incumbent with the known optimum "
                        "(applies to Taillard -i instances only)")
    p.add_argument("--priority", type=int, default=0,
                   help="higher preempts lower on a full mesh")
    p.add_argument("--deadline", type=float, default=None,
                   help="compute budget in seconds (accumulated "
                        "execution time, not queue wait)")
    p.add_argument("--chunk", type=int, default=None)
    p.add_argument("--capacity", type=int, default=None)
    p.add_argument("--tag", type=str, default=None,
                   help="checkpoint tag; resubmitting a DEADLINE "
                        "request's tag with a larger budget extends it")
    p.add_argument("--portfolio", type=int, default=None, metavar="K",
                   help="bound-portfolio racing: fan out as K sibling "
                        "configs (bound tiers, tuned chunk plans) "
                        "sharing one incumbent board; first proof "
                        "wins, losers cancel (service/portfolio.py)")
    p.add_argument("--timeout", type=float, default=None,
                   help="give up waiting for the result after N seconds")


# exit code of the drain-timeout escalation (checkpoint-and-abort):
# distinct from clean drains (0), tracebacks (1) and the injected hard
# kill (137) so a supervisor's restart policy can tell them apart
DRAIN_ESCALATE_EXIT_CODE = 70


def _install_drain_handlers(drain_evt, timeout_s: float):
    """SIGTERM/SIGINT -> graceful drain: set `drain_evt` (the serve
    loop exits, the server close() preempts at segment boundaries and
    drains every writer) and arm the escalation watchdog — a drain
    that cannot finish inside `timeout_s` checkpoint-and-aborts
    instead of hanging the pod's termination grace period. A second
    signal escalates immediately. Returns False when handlers cannot
    be installed (not the main thread — in-process tests)."""
    import os as _os
    import signal
    import threading

    def _escalate():
        from .obs import tracelog
        tracelog.event("server.drain_escalated", timeout_s=timeout_s)
        print(f"drain exceeded {timeout_s:g}s: checkpoint-and-abort",
              flush=True)
        _os._exit(DRAIN_ESCALATE_EXIT_CODE)

    def _handler(signum, frame):
        if drain_evt.is_set():
            _os._exit(DRAIN_ESCALATE_EXIT_CODE)
        print(f"signal {signum}: draining (budget {timeout_s:g}s)",
              flush=True)
        drain_evt.set()
        t = threading.Timer(timeout_s, _escalate)
        t.daemon = True
        t.start()
        drain_evt.watchdog = t

    try:
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
    except ValueError:      # not the main thread
        return False
    return True


def run_serve(args) -> int:
    import threading

    from .obs import tracelog
    from .service import SearchServer, spool
    from .utils import config as _cfg

    if args.search_telemetry:
        # static compile-in flag, read at each request's state init
        _cfg.set_env("TTS_SEARCH_TELEMETRY", "1")
    if args.overlap:
        # env too, not just the server knob: campaign-style respawns
        # and in-process tools must see the same static flag
        _cfg.set_env("TTS_OVERLAP", "1")
    if args.share_incumbent:
        _cfg.set_env("TTS_SHARE_INCUMBENT", "1")
    if args.ladder:
        # static flag: every engine entry (serve dispatches, prewarm's
        # rung warms, in-process tools) must see the same ladder mode
        _cfg.set_env(_cfg.LADDER_FLAG, "1")
    if args.remediate:
        _cfg.set_env(_cfg.REMEDIATE_FLAG, "1")
    if args.megabatch:
        _cfg.set_env(_cfg.MEGABATCH_FLAG, "1")
    if args.fleet_dir:
        # env too: worker respawns and the lease/watcher layers all
        # resolve TTS_FLEET_DIR at one site (the server constructor)
        _cfg.set_env(_cfg.FLEET_DIR_ENV, args.fleet_dir)
    if args.failover:
        _cfg.set_env(_cfg.FAILOVER_FLAG, "1")
    if args.trace_file:
        tracelog.get().set_sink(args.trace_file)
        print(f"flight recorder: {args.trace_file}", flush=True)
    # --ledger passes straight through: SearchServer resolves the
    # TTS_LEDGER env fallback itself (one resolution site) and, with a
    # ledger and no explicit --workdir, defaults the workdir to
    # <ledger>/workdir — checkpoints must survive the restart the
    # ledger exists for
    drain_evt = threading.Event()
    drain_timeout = (args.drain_timeout if args.drain_timeout is not None
                     else _cfg.env_float("TTS_DRAIN_TIMEOUT_S"))
    _install_drain_handlers(drain_evt, drain_timeout)
    httpd = None
    otel_exp = None
    otel_stop = None
    if args.otel_endpoint:
        from .obs import otel
        # ONE exporter for interval flushes AND the shutdown flush: its
        # seq watermark is what keeps a record from shipping twice
        otel_exp = otel.IncrementalExporter(endpoint=args.otel_endpoint)
        if args.otel_interval_s and args.otel_interval_s > 0:
            otel_stop = threading.Event()

            def _otel_tick():
                while not otel_stop.wait(args.otel_interval_s):
                    try:
                        otel_exp.flush(tracelog.get().records())
                    except Exception:  # noqa: BLE001 — a flaky
                        # collector must not kill the flusher; the next
                        # tick (same watermark) retries the same tail
                        pass
            threading.Thread(target=_otel_tick, name="otel-flush",
                             daemon=True).start()
            print(f"otel: flushing to {args.otel_endpoint} every "
                  f"{args.otel_interval_s:g}s", flush=True)
    try:
        with SearchServer(n_submeshes=args.submeshes,
                          workdir=args.workdir,
                          max_queue_depth=args.queue_depth,
                          segment_iters=args.segment_iters,
                          phase_profile=(True if args.phase_metrics
                                         else None),
                          resource_sample_s=args.resource_sample_s,
                          health_interval_s=args.health_interval_s,
                          overlap=(True if args.overlap else None),
                          share_incumbent=(True if args.share_incumbent
                                           else None),
                          aot_cache_dir=args.aot_cache,
                          tune_cache_dir=args.tune_cache,
                          tune_at_boot=(True if args.tune else None),
                          remediate=(True if args.remediate else None),
                          ledger_dir=args.ledger,
                          megabatch=(True if args.megabatch else None),
                          batch_max=args.batch_max,
                          batch_age_s=args.batch_age_s
                          ) as srv:
            if srv.megabatch:
                print(f"megabatch: ON (max {srv.former.max_size}, "
                      f"age {srv.former.age_s:g}s)", flush=True)
            print(f"remediation: "
                  f"{'ACT' if srv.remediation.enabled else 'observe'}"
                  f"-mode (TTS_REMEDIATE)", flush=True)
            if srv.ledger is not None:
                led = srv.ledger.snapshot()
                rec = srv._recovered
                print(f"ledger: {led['dir']} (restart "
                      f"#{led['restarts']}, replayed "
                      f"{led['replayed']} record(s), recovered "
                      f"{rec['queued']}q/{rec['active']}a/"
                      f"{rec['held']}h/{rec['terminal']}t, "
                      f"truncated {led['truncated']})", flush=True)
            if srv.lease is not None or srv.fenced:
                mode = ("FENCED" if srv.fenced else
                        ("ACT" if srv.watcher is not None
                         and srv.watcher.act else "observe"))
                epoch = srv.lease.epoch if srv.lease is not None else "-"
                print(f"failover: {mode}-mode, lease epoch {epoch}, "
                      f"ttl {_cfg.env_float('TTS_LEASE_TTL_S'):g}s "
                      f"(TTS_FLEET_DIR/TTS_FAILOVER)", flush=True)
            if srv.aot is not None:
                print(f"aot cache: {srv.aot.root} "
                      f"({srv.aot.entries()} entr(y/ies))", flush=True)
            if srv.tuner is not None and srv.tuner.cache is not None:
                print(f"tune cache: {srv.tuner.cache.root} "
                      f"({srv.tuner.cache.entries()} entr(y/ies), "
                      f"probe-at-boot={srv.tune_at_boot})", flush=True)
            if args.http_port is not None:
                # BEFORE pre-warm: a cold-dir warm of the full shape
                # family list is minutes of compiles at production
                # shapes, and a readiness probe (or the doctor) that
                # cannot reach /healthz during it would restart the
                # server into the same warm — the crash-loop the
                # feature exists to prevent
                from .obs.httpd import start_http_server
                httpd = start_http_server(srv, host=args.http_host,
                                          port=args.http_port,
                                          profile_dir=args.profile_dir)
                print(f"observability: {httpd.url}/healthz /metrics "
                      "/status /trace /alerts /dashboard; "
                      "POST /submit /cancel /profile?duration_s=N",
                      flush=True)
            env_spec = _cfg.env_str(_cfg.PREWARM_ENV)
            prewarm_spec = (args.prewarm if args.prewarm is not None
                            else env_spec)
            if env_spec is not None and env_spec.strip().lower() in (
                    "0", "off", "no"):
                # the env kill-switch wins even over the CLI flag: an
                # operator must be able to disable a unit file's
                # --prewarm during an incident without editing it
                prewarm_spec = None
            if prewarm_spec is not None \
                    and prewarm_spec.strip().lower() not in ("0", "off",
                                                             "no"):
                try:
                    summary = srv.prewarm_boot(prewarm_spec,
                                               spool_dir=args.spool)
                except Exception as e:  # noqa: BLE001 — pre-warm is
                    # an optimization: a typo'd TTS_PREWARM spec in a
                    # fleet unit file must degrade to a cold boot, not
                    # crash-loop every server (the first request pays
                    # its compile as before)
                    print(f"prewarm SKIPPED: {e}", flush=True)
                else:
                    print(f"prewarm: {summary['warms']} "
                          f"executable(s) for "
                          f"{summary['shapes']} shape(s) in "
                          f"{summary['seconds']}s "
                          f"(disk={summary['by']['disk']} "
                          f"compile={summary['by']['compile']} "
                          f"warm={summary['by']['warm']} "
                          f"skipped={summary['by']['skipped']} "
                          f"errors={summary['errors']})", flush=True)
            print(f"serving: {args.submeshes} submesh(es) x "
                  f"{srv.slots[0].mesh.devices.size} device(s), "
                  f"spool {args.spool}", flush=True)
            served = spool.serve_spool(
                srv, args.spool, idle_exit_s=args.idle_exit,
                status_every_s=args.status_every or None,
                emit=lambda s: print(s, flush=True),
                # a FENCED server (lease lost to an adopter) must stop
                # serving the spool too: its requests now live on the
                # peer, and a fenced loop polling forever would shadow
                # the adopter's results
                should_exit=lambda: drain_evt.is_set() or srv.fenced)
            # the `with` close() below IS the drain: stop at segment
            # boundaries, checkpoint, flush the async checkpoint/AOT/
            # ledger writers — the watchdog escalates if it wedges
    finally:
        if httpd is not None:
            httpd.close()
        if otel_stop is not None:
            otel_stop.set()
        if otel_exp is not None:
            # same instance as the interval flusher: only the tail past
            # its watermark ships, never a duplicate of a prior flush
            n = otel_exp.flush(tracelog.get().records())
            print(f"otel: exported {n} span(s) at shutdown "
                  f"({otel_exp.spans} total) to "
                  f"{args.otel_endpoint}", flush=True)
    watchdog = getattr(drain_evt, "watchdog", None)
    if watchdog is not None:
        watchdog.cancel()       # drained inside the budget: exit 0
    if drain_evt.is_set():
        print("drained cleanly", flush=True)
    if srv.fenced:
        # clean exit 0 ON PURPOSE: a fenced server did the right thing
        # (zero commits past the fence) — a nonzero exit would make a
        # supervisor restart-loop a host whose ledger now lives on a
        # peer
        print(f"fenced: {srv._fence_reason or 'lease lost'} — a peer "
              "owns this ledger now; exited without commits",
              flush=True)
    print(f"served {served} request(s)", flush=True)
    return 0


def run_client(args) -> int:
    import json

    from .service import spool

    payload = {"problem": args.problem,
               "priority": args.priority, "deadline_s": args.deadline,
               "chunk": args.chunk, "capacity": args.capacity,
               "tag": args.tag}
    if args.lb is not None:
        payload["lb"] = args.lb
    if args.portfolio is not None:
        payload["portfolio"] = args.portfolio
    if args.problem == "pfsp" and args.inst is not None:
        payload["inst"] = args.inst
        payload["ub"] = "opt" if args.ub == 1 else None
    else:
        payload["p_times"] = _solve_instance_table(args).tolist()
    sid = spool.submit_file(args.spool, payload)
    print(f"submitted {sid}", flush=True)
    try:
        res = spool.wait_result(args.spool, sid, timeout=args.timeout)
    except TimeoutError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(res, indent=1))
    return 0 if res.get("state") == "DONE" else 1


def _profile_parser(sub):
    p = sub.add_parser(
        "profile",
        help="standalone capture-on-demand: warm the single-device "
             "engine past its ramp, capture an XLA profiler trace of "
             "a steady-state window (obs/profiler — same session as "
             "POST /profile), and print the self-time attribution")
    p.add_argument("-i", "--inst", type=int, default=21,
                   help="Taillard instance id")
    p.add_argument("-l", "--lb", type=int, default=1, choices=(0, 1, 2))
    p.add_argument("--chunk", type=int, default=256)
    p.add_argument("--capacity", type=int, default=1 << 18)
    p.add_argument("--warm", type=int, default=50,
                   help="warm-up iterations before the traced window")
    p.add_argument("--iters", type=int, default=20,
                   help="traced-window iterations")
    p.add_argument("--out", type=str, default=None,
                   help="artifact root (default: a fresh temp dir); "
                        "each capture gets its own subdirectory")
    p.add_argument("--top", type=int, default=15,
                   help="ops to list in the self-time table")


def run_profile(args) -> int:
    import json
    import tempfile

    from .engine import device
    from .obs import chrome_trace, profiler
    from .ops import batched
    from .problems import taillard

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    tables = batched.make_tables(p)
    state = device.init_state(p.shape[1], args.capacity, ub, p_times=p)
    state = device.run(tables, state, args.lb, args.chunk,
                       max_iters=args.warm)
    state.size.block_until_ready()
    print(f"# warmed: iters={int(state.iters)} pool={int(state.size)}",
          file=sys.stderr)

    sess = profiler.session()
    root = args.out or tempfile.mkdtemp(prefix="tts_profile_")
    log_dir = sess.fresh_dir(root)
    with sess.trace(log_dir):
        out = device.run(tables, state, args.lb, args.chunk,
                         max_iters=args.warm + args.iters)
        out.size.block_until_ready()

    self_us, counts = chrome_trace.self_times(
        chrome_trace.load_xla_trace(log_dir))
    total = sum(self_us.values())
    buckets = chrome_trace.bucketed_self_times(self_us)
    print(json.dumps({
        "artifact": log_dir, "inst": args.inst, "lb": args.lb,
        "iters": int(out.iters) - int(state.iters),
        "evals": int(out.evals) - int(state.evals),
        "device_self_ms": round(total / 1e3, 2),
        "buckets_ms": {k: round(v / 1e3, 2)
                       for k, v in buckets.most_common()},
    }))
    print("\n# top ops by device self-time "
          "(tools/search_report.py renders the same table):")
    for name, d in self_us.most_common(args.top):
        print(f"{d / 1e3:10.2f} ms  x{counts[name]:<6} "
              f"[{chrome_trace.bucket_of(name):>15}]  {name[:90]}")
    print(f"\n# artifact: {log_dir}")
    return 0


def _doctor_parser(sub):
    p = sub.add_parser(
        "doctor",
        help="one-shot fleet health verdict: scrape N servers' "
             "/healthz /status /metrics /alerts (obs/aggregate), "
             "print the judgment, exit nonzero on any unreachable "
             "server or firing alert")
    p.add_argument("urls", nargs="+", metavar="URL",
                   help="server base URLs (http://host:port)")
    p.add_argument("--json", action="store_true",
                   help="print the merged fleet view as JSON instead "
                        "of the human table")
    p.add_argument("--dashboard", type=str, default=None,
                   help="also render the fleet dashboard HTML here "
                        "(obs/dashboard; self-contained, no external "
                        "assets — CI uploads it as an artifact)")
    p.add_argument("--metrics-out", type=str, default=None,
                   help="also write the merged, origin-labeled "
                        "Prometheus exposition here (one aggregated "
                        "scrape target for the fleet)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-endpoint scrape timeout in seconds")
    p.add_argument("--fleet-dir", type=str, default=None,
                   help="shared fleet root (TTS_FLEET_DIR): also read "
                        "every peer's LEASE file straight off storage, "
                        "so a DOWN server splits DOWN-with-lease-held "
                        "(exit 1: wait out the TTL) from "
                        "DOWN-lease-expired (exit 2: requests "
                        "orphaned, takeover needed)")


# doctor exit codes: 0 healthy; 1 unhealthy (unreachable/firing/
# degraded — or DOWN-with-lease-held: wait out the TTL); 2 an expired
# unreleased lease sits in --fleet-dir (orphaned ledger: page/arm
# takeover NOW). Distinct codes so a supervisor can wait on 1 and act
# on 2.
DOCTOR_TAKEOVER_EXIT_CODE = 2


def run_doctor(args) -> int:
    import json

    from .obs import aggregate, dashboard

    fleet = aggregate.scrape(args.urls, timeout=args.timeout)
    merged = aggregate.merge(fleet)
    lease_report = (aggregate.fleet_lease_report(args.fleet_dir)
                    if args.fleet_dir else None)
    healthy, reasons = aggregate.verdict(merged,
                                         lease_report=lease_report)
    if args.dashboard:
        with open(args.dashboard, "w") as f:
            f.write(dashboard.render_fleet(merged))
        print(f"# wrote {args.dashboard}", file=sys.stderr)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(aggregate.fleet_to_prometheus(merged))
        print(f"# wrote {args.metrics_out}", file=sys.stderr)
    if args.json:
        print(json.dumps({"healthy": healthy, "reasons": reasons,
                          **({"leases": lease_report}
                             if lease_report is not None else {}),
                          **{k: v for k, v in merged.items()
                             if k != "metrics"}}, indent=1))
    else:
        for s in merged["servers"]:
            degraded = bool(s.get("quarantined"))
            mark = ("ok" if s["ok"] and s["healthz"] == "ok"
                    and not s.get("firing") and not degraded
                    else ("DEGRADED" if degraded and s["ok"]
                          and s["healthz"] == "ok"
                          and not s.get("firing") else "UNHEALTHY"))
            aot = s.get("aot_cache")
            aot_col = (f" aot={aot['hits']}h/{aot['misses']}m"
                       f"/{aot['entries']}e" if aot else "")
            paused = s.get("admission_paused")
            rem_col = (f" quarantined={s.get('quarantined')}"
                       if s.get("quarantined") else "") + (
                       f" PAUSED({paused})" if paused else "")
            led_col = ""
            if s.get("restarts") is not None:
                led_col = (f" restarts={s.get('restarts')}"
                           f" recovered={s.get('recovered_requests')}"
                           f" ledger_lag_s={s.get('ledger_lag_s')}")
            pf = s.get("portfolio")
            pf_col = (f" portfolio={pf['active']}a/{pf['won']}w"
                      f"/{pf['cancelled_members']}cxl" if pf else "")
            # the predictive columns (obs/estimate): absent while no
            # request publishes an estimate (warmup / TTS_PROGRESS=0)
            eta_col = ""
            if s.get("progress_mean") is not None:
                eta_col = f" progress={s['progress_mean'] * 100:.1f}%"
            if s.get("eta_max_s") is not None:
                eta_col += f" eta_s={s['eta_max_s']:g}"
            # the capacity columns (obs/capacity): absent with
            # TTS_CAPACITY=0 or before a service-time estimate exists
            cap_col = ""
            if s.get("utilization") is not None:
                cap_col = (f" rho={s['utilization']:.2f}"
                           f" headroom={s['capacity_headroom']:.2f}")
            fo_col = ""
            if s.get("failover_mode") is not None or s.get("fenced"):
                fo_col = (f" failover={s.get('failover_mode')}"
                          f" epoch={s.get('lease_epoch')}"
                          f" peers_down={s.get('peers_down')}"
                          f" takeovers={s.get('takeovers')}") + (
                          " FENCED" if s.get("fenced") else "")
            print(f"{s['origin']:<24} {mark:<10} "
                  f"firing={s.get('firing')} "
                  f"queue={s.get('queue_depth')} "
                  f"busy={s.get('submeshes_busy')}/{s.get('submeshes')} "
                  f"requests={s.get('requests')}{eta_col}{cap_col}"
                  f"{aot_col}{rem_col}{pf_col}{led_col}{fo_col}")
        for r in lease_report or []:
            state = ("released" if r["released"] else
                     "EXPIRED" if r["expired"] else "live")
            print(f"lease {r['dir']}: {state} owner={r['owner']} "
                  f"epoch={r['epoch']} age={r['age_s']:g}s"
                  f"/ttl={r['ttl_s']:g}s")
        print("healthy" if healthy else
              "UNHEALTHY:\n  " + "\n  ".join(reasons))
    if healthy:
        return 0
    if lease_report and aggregate.needs_takeover(lease_report):
        return DOCTOR_TAKEOVER_EXIT_CODE
    return 1


def _capacity_parser(sub):
    p = sub.add_parser(
        "capacity",
        help="fleet capacity & utilization report (obs/capacity): "
             "scrape N servers' GET /capacity and print per-lane "
             "state/utilization, per-shape-class demand vs capacity "
             "(ρ, headroom, predicted queue wait) and the what-if "
             "submesh-partition advisor")
    p.add_argument("urls", nargs="+", metavar="URL",
                   help="server base URLs (http://host:port)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable documents instead of the "
                        "human tables")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-endpoint scrape timeout in seconds")


def run_capacity(args) -> int:
    import json

    from .obs import aggregate

    docs, rc = [], 0
    for url in args.urls:
        base = url.rstrip("/")
        origin = base.split("://", 1)[-1]
        try:
            _, body = aggregate._get(base + "/capacity", args.timeout)
            docs.append({"origin": origin, **json.loads(body)})
        except (OSError, ValueError) as e:
            docs.append({"origin": origin, "error": str(e)})
            rc = 1
    if args.json:
        print(json.dumps(docs, indent=1))
        return rc
    for doc in docs:
        if doc.get("error"):
            print(f"{doc['origin']}: UNREACHABLE ({doc['error']})")
            continue
        if not doc.get("enabled"):
            print(f"{doc['origin']}: capacity layer off "
                  "(TTS_CAPACITY=0)")
            continue
        rho = doc.get("utilization")
        print(f"{doc['origin']}: lanes={doc.get('healthy_lanes')}"
              f"/{doc.get('lanes')} devices={doc.get('devices')} "
              f"arrivals={doc.get('arrival_per_s', 0):.3f}/s "
              + (f"rho={rho:.2f} headroom={doc.get('headroom'):.2f}"
                 if rho is not None else "rho=— (no service estimate)")
              + (f" pred_wait_s={doc['predicted_wait_s']:.3f}"
                 if doc.get("predicted_wait_s") is not None else "")
              + (f" pred_req_per_s={doc['predicted_req_per_s']:.3f}"
                 if doc.get("predicted_req_per_s") is not None else ""))
        for ln in doc.get("lanes_detail") or []:
            secs = ln.get("seconds") or {}
            top = ", ".join(f"{k}={secs[k]:.1f}s" for k in sorted(
                secs, key=lambda k: -secs[k])[:3])
            print(f"  lane {ln.get('lane')}: {ln.get('state'):<13} "
                  f"exec={ln.get('utilization', 0) * 100:5.1f}%  "
                  f"[{top}]  conservation_err="
                  f"{ln.get('conservation_error_s'):.2e}s")
        for c in doc.get("classes") or []:
            srv_s = c.get("service_s")
            print(f"  class {c.get('shape')} tenant={c.get('tenant')}: "
                  f"lambda={c.get('arrival_per_s', 0):.3f}/s "
                  + (f"E[S]={srv_s:.3f}s rho={c.get('utilization'):.2f}"
                     if srv_s is not None else "E[S]=— (warming up)"))
        wi = doc.get("what_if") or []
        if wi:
            print("  what-if (same devices, n equal lanes):")
            for row in wi:
                cur = "  <- current" if row.get("current") else ""
                wait = row.get("predicted_wait_s")
                print(f"    {row['lanes']} lane(s) x "
                      f"{row['devices_per_lane']} dev: "
                      f"req/s={row['predicted_req_per_s']:.3f} "
                      f"rho={row['utilization']:.2f} "
                      + (f"wait_s={wait:.3f}" if wait is not None
                         else "wait_s=inf (saturated)") + cur)
    return rc


def _journey_parser(sub):
    p = sub.add_parser(
        "journey",
        help="reconstruct request journeys from durable state "
             "(obs/journey): one stitched cross-lifetime timeline per "
             "logical request, chained through ledger admits, "
             "failover takeovers and portfolio fan-outs — reads "
             "ledger/fleet dirs and the flight-recorder store "
             "straight off storage, no server required")
    p.add_argument("--ledger", action="append", default=[],
                   metavar="DIR",
                   help="request-ledger directory (repeatable)")
    p.add_argument("--fleet-dir", type=str, default=None,
                   help="shared fleet root (TTS_FLEET_DIR): read EVERY "
                        "peer ledger under it")
    p.add_argument("--store", type=str, default=None,
                   help="flight-recorder store directory "
                        "(TTS_OBS_STORE): fold its trace events into "
                        "each journey's timeline")
    p.add_argument("--tag", type=str, default=None,
                   help="only journeys whose tag (or any member rid) "
                        "matches")
    p.add_argument("--json", action="store_true",
                   help="machine-readable journeys instead of the "
                        "human report")


def run_journey(args) -> int:
    from .obs import journey as journey_mod

    if not args.ledger and not args.fleet_dir:
        print("journey: need --ledger and/or --fleet-dir",
              file=sys.stderr)
        return 2
    journeys = journey_mod.find_journeys(
        ledger_dirs=args.ledger or None, fleet_dir=args.fleet_dir,
        store=args.store, tag=args.tag)
    if args.json:
        print(journey_mod.to_json(journeys))
    elif not journeys:
        print("no journeys"
              + (f" matching tag {args.tag!r}" if args.tag else ""))
    else:
        for j in journeys:
            print(journey_mod.render_journey(j))
    # tag given but nothing matched: nonzero, so the CI leg's
    # one-journey assertion can't silently pass on an empty answer
    return 0 if journeys or not args.tag else 1


def _nq_parser(sub):
    p = sub.add_parser("nqueens", help="N-Queens backtracking")
    d = NQueensConfig()
    p.add_argument("-N", type=int, default=d.N)
    p.add_argument("-g", type=int, default=d.g)
    p.add_argument("-D", type=int, default=d.D)
    p.add_argument("--chunk", type=int, default=d.chunk)
    p.add_argument("--capacity", type=int, default=d.capacity)


def _print_pfsp_settings(args, machines, jobs, n_dev):
    print("=" * 49)
    print(f"TPU B&B ({n_dev} device(s) - balancing [{int(args.ws or args.L)}])")
    print(f"Resolution of PFSP Taillard's instance: ta{args.inst} "
          f"(m = {machines}, n = {jobs})")
    print("Initial upper bound: " + ("opt" if args.ub == 1 else "inf"))
    print("Lower bound function: " + {0: "lb1_d", 1: "lb1", 2: "lb2"}[args.lb])
    print("Branching rule: fwd")
    print("=" * 49)


def _print_results(optimum, tree, sol, elapsed, complete=True):
    print("=" * 49)
    print(f"Size of the explored tree: {tree}")
    print(f"Number of explored solutions: {sol}")
    label = "Optimal makespan" if complete else "Best makespan found (truncated run)"
    print(f"{label}: {optimum}")
    print(f"Elapsed time: {elapsed:.4f} [s]")
    print("=" * 49)


def run_pfsp(args) -> int:
    import jax

    from .engine import device, distributed
    from .problems import taillard
    from .utils import csv_stats

    p = taillard.processing_times(args.inst)
    jobs, machines = p.shape[1], p.shape[0]
    if args.capacity is None:
        args.capacity = device.default_capacity(jobs, machines)
    init_ub = taillard.optimal_makespan(args.inst) if args.ub == 1 else None
    n_dev = args.D if args.D > 0 else len(jax.devices())
    # resilience knobs travel as env so every run_segmented in the call
    # tree (direct, distributed.search's, a respawned campaign worker's)
    # sees the same policy
    from .utils import config as _cfg
    if getattr(args, "retry_attempts", None) is not None:
        _cfg.set_env("TTS_RETRY_ATTEMPTS", args.retry_attempts)
    if getattr(args, "segment_timeout", None) is not None:
        _cfg.set_env("TTS_SEG_TIMEOUT_S", args.segment_timeout)
    if getattr(args, "search_telemetry", False):
        # env, not a Python knob: init_state reads it at state
        # creation, and respawned campaign workers must inherit it
        _cfg.set_env("TTS_SEARCH_TELEMETRY", "1")
    if getattr(args, "faults", None):
        from .utils import faults
        faults.configure(args.faults)
    # -C composes with EVERY tier: single-device (hybrid.search),
    # single-device segmented (_run_pfsp_segmented's host session),
    # multi-device and the segmented/checkpointed flagship
    # (distributed.search host_fraction) — the reference runs CPU
    # workers beside both its multi-GPU and distributed engines.
    # --host-fraction/--host-threads make the tier a measured knob;
    # threads default to the reference's num_procs/deviceCount rule
    # (pfsp_multigpu_cuda.c:61-69).
    if args.C:
        host_fraction = (8 if args.host_fraction is None
                         else max(args.host_fraction, 0))
        host_threads = (max(1, (os.cpu_count() or 1) // max(n_dev, 1))
                        if args.host_threads is None
                        else max(args.host_threads, 1))
    else:
        host_fraction, host_threads = 0, 0
    _print_pfsp_settings(args, machines, jobs, n_dev)

    t0 = time.perf_counter()
    if args.segment_iters is not None or args.checkpoint is not None:
        if n_dev == 1:
            try:
                out, extras = _run_pfsp_segmented(args, p, init_ub,
                                                  host_fraction,
                                                  host_threads)
            except (RuntimeError, ValueError, OSError) as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
            tree = int(out.tree) + extras["tree"]
            sol = int(out.sol) + extras["sol"]
            best = int(out.best)
            if extras["best"] is not None:
                best = min(best, extras["best"])
            complete = int(np.asarray(out.size).sum()) == 0
            per_device = {"tree": [int(out.tree)], "sol": [int(out.sol)],
                          "evals": [int(out.evals)],
                          "iters": [int(out.iters)],
                          "steals": [0], "recv": [0],
                          **extras["host"]}
        else:
            # distributed durability: segmented SPMD loop with stacked
            # checkpoint/resume and per-worker heartbeat
            def heartbeat(r):
                pw = (f" sizes={r.per_worker['size']}"
                      f" steals={r.per_worker['steals']}"
                      if r.per_worker else "")
                print(f"[segment {r.segment}] iters={r.iters} "
                      f"tree={r.tree} sol={r.sol} best={r.best} "
                      f"pool={r.pool_size}{pw} t={r.elapsed:.2f}s")

            try:
                res = distributed.search(
                    p, lb_kind=args.lb, init_ub=init_ub, n_devices=n_dev,
                    chunk=args.chunk, capacity=args.capacity,
                    balance_period=args.balance_period,
                    # balancing off (-w 0 -L 0): an unreachable transfer
                    # threshold keeps every plan empty (the cond-gated
                    # exchange then costs one all_gather) while the
                    # while-cond — termination, ceiling, segment checks —
                    # still runs every period
                    min_transfer=(None if (args.ws or args.L)
                                  else 2**30),
                    min_seed=args.m, max_rounds=args.max_iters,
                    segment_iters=args.segment_iters,
                    checkpoint_path=args.checkpoint, heartbeat=heartbeat,
                    checkpoint_every=getattr(args, "checkpoint_every", 1),
                    host_fraction=host_fraction,
                    host_threads=host_threads)
            except (RuntimeError, ValueError, OSError) as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
            tree, sol, best = (res.explored_tree, res.explored_sol,
                               res.best)
            complete = res.complete
            per_device = {k: list(v) for k, v in res.per_device.items()}
    elif n_dev == 1 and args.C:
        # heterogeneous co-processing (-C 1): native host warm-up + the
        # compiled device loop while the pool feeds >= m parents (the
        # reference's -m offload threshold) + native multi-threaded drain
        # of the residue (reference: the CPU-worker tier and final drain
        # of pfsp_multigpu_cuda.c)
        from .engine import hybrid

        if args.max_iters is not None:
            print("error: --max-iters is not supported with -C 1",
                  file=sys.stderr)
            return 2
        res = hybrid.search(p, lb_kind=args.lb, init_ub=init_ub,
                            chunk=args.chunk, capacity=args.capacity,
                            drain_min=max(args.m, 1),
                            host_fraction=host_fraction,
                            host_threads=host_threads)
        tree, sol, best = res.explored_tree, res.explored_sol, res.best
        complete = res.complete
        per_device = {k: list(v) for k, v in res.per_device.items()}
    elif n_dev == 1:
        out = device.search(p, lb_kind=args.lb, init_ub=init_ub,
                            chunk=args.chunk, capacity=args.capacity,
                            max_iters=args.max_iters)
        tree, sol, best = out.explored_tree, out.explored_sol, out.best
        complete = out.complete
        per_device = {"tree": [tree], "sol": [sol], "evals": [out.evals],
                      "iters": [out.iters], "steals": [0], "recv": [0]}
    else:
        res = distributed.search(
            p, lb_kind=args.lb, init_ub=init_ub, n_devices=n_dev,
            chunk=args.chunk, capacity=args.capacity,
            balance_period=args.balance_period,
            min_transfer=(None if (args.ws or args.L) else 2**30),
            min_seed=args.m,
            max_rounds=args.max_iters,
            host_fraction=host_fraction,
            host_threads=host_threads)
        tree, sol, best = res.explored_tree, res.explored_sol, res.best
        complete = res.complete
        per_device = {k: list(v) for k, v in res.per_device.items()}
    elapsed = time.perf_counter() - t0

    _print_results(best, tree, sol, elapsed, complete=complete)
    if args.csv:
        _write_csv_with_phases(args, p, init_ub, n_dev, elapsed, tree, sol,
                               best, per_device, csv_stats)
    return 0


def _write_csv_with_phases(args, p, init_ub, n_dev, elapsed, tree, sol,
                           best, per_device, csv_stats):
    """CSV row with MEASURED phase-time attributions (utils/phase_timing):
    unit costs of the bound kernel / compaction / balance exchange timed
    on the real shapes, scaled by the run's counters — the reference's
    per-PU breakdown (PFSP_statistic.c:69-112) with real data, not the
    structural zeros of round 1."""
    import numpy as np

    from .engine import device as dev
    from .ops import batched
    from .problems import taillard
    from .utils import phase_timing

    jobs, machines = p.shape[1], p.shape[0]
    att = {}
    try:
        tables = batched.make_tables(p)
        pstate = dev.init_state(jobs, args.capacity, init_ub, p_times=p)
        prof = phase_timing.profile_phases(tables, pstate, args.lb,
                                           args.chunk)
        evals = per_device.get("evals", [0] * n_dev)
        iters = per_device.get("iters",
                               [max(1, int(e)) // (args.chunk * jobs)
                                for e in evals])
        t_bal = 0.0
        rounds = 0
        if n_dev > 1 and (args.ws or args.L):
            from .engine import distributed as dist
            from .ops import reference as ref
            from .parallel.mesh import worker_mesh

            adt = dev.aux_dtype(p)
            transfer_cap = dist.default_transfer_cap(
                args.chunk, jobs, machines, n_dev,
                aux_itemsize=adt.itemsize)
            min_transfer = 2 * args.chunk
            # the profiled round must honor _balance_round's contract
            # limit <= capacity - D*transfer_cap with limit >= 1; a
            # too-small capacity is GROWN (the same pre-grow rule as
            # _DistDriver.seed) rather than clamped — a clamped limit
            # times a degenerate exchange whose writes land on live rows
            cap = args.capacity

            def _limit(c):
                return min(dev.row_limit(c, args.chunk, jobs),
                           c - n_dev * transfer_cap)

            while _limit(cap) < 1:
                cap *= 2
            limit = _limit(cap)
            fr = dist.Frontier(
                prmu=np.arange(jobs, dtype=np.int16)[None, :],
                depth=np.zeros(1, np.int16), tree=0, sol=0,
                best=best)
            fr.aux = ref.prefix_front_remain(
                p, fr.prmu, fr.depth)[:, :machines].astype(adt)
            leaves = dist._shard_frontier(fr, n_dev, cap, jobs,
                                          best, limit=limit)
            t_bal = phase_timing.profile_balance(
                worker_mesh(n_dev), leaves, transfer_cap, min_transfer,
                limit)
            rounds = int(np.max(iters)) // max(1, args.balance_period)
        att = phase_timing.attribute(prof, elapsed, evals, iters,
                                     balance_rounds=rounds,
                                     t_balance=t_bal)
        # the same numbers land in the global metrics registry, so a
        # co-running /metrics endpoint and the CSV row cannot disagree
        phase_timing.publish_attribution(att, inst=args.inst, lb=args.lb)
        per_device = dict(per_device)
        per_device.update({k: list(v) for k, v in att.items()})
    except Exception as e:  # profiling must never eat the results row
        print(f"warning: phase profiling failed ({e}); writing "
              "zero timing columns", file=sys.stderr)

    if n_dev == 1:
        csv_stats.write_single(
            args.csv, args.inst, args.lb, best, args.m, args.M, elapsed,
            float(att["kernel_time"][0]) if att else elapsed, tree, sol,
            gen_child_time=float(att["gen_child_time"][0]) if att else 0.0)
    elif getattr(args, "multihost", False):
        # the DCN tier writes the reference's dist_multigpu.csv schema
        # (PFSP_statistic.c:123-167)
        csv_stats.write_dist(args.csv, args.inst, args.lb, n_dev, args.C,
                             args.L, 1, best, args.m, args.M, args.T,
                             elapsed, tree, sol, per_device)
    else:
        # single-controller multi-device runs are the intra-node tier:
        # the reference's multigpu.csv schema (PFSP_statistic.c:69-112),
        # which its analysis scripts distinguish from the dist schema
        csv_stats.write_multi(args.csv, args.inst, args.lb, n_dev, args.C,
                              args.ws, best, args.m, args.M, args.T,
                              elapsed, tree, sol, per_device)


def _run_pfsp_segmented(args, p, init_ub, host_fraction: int = 0,
                        host_threads: int = 0):
    """Segmented single-device search with heartbeat + checkpoint/resume
    (the durability layer the reference lacks, SURVEY.md §5). With
    `host_fraction > 0` a native `-C` host session runs beside the
    segments — seeded from a warm-up share (fresh) or rows carved off
    the checkpointed pool (resume) — with incumbents merged at every
    segment boundary (engine/hybrid.HostSession).

    Returns (state, extras): host-tier tree/sol/counters to add to the
    device totals (all zero without a host tier)."""
    import os

    from .engine import checkpoint, device, distributed, hybrid
    from .ops import batched

    jobs = p.shape[1]
    tables = batched.make_tables(p)
    session = None
    warm_tree = warm_sol = 0
    h_prmu = np.zeros((0, jobs), np.int16)
    h_depth = np.zeros(0, np.int16)
    if args.checkpoint and checkpoint.resume_path(args.checkpoint):
        # load_resilient: a torn snapshot rolls back to its rotating
        # last-good sibling; a stacked (distributed) snapshot collapses
        # onto this single device via the same elastic reshard a
        # mesh-size change uses
        state, meta, _ = checkpoint.load_resilient(args.checkpoint,
                                                   p_times=p)
        state = checkpoint.collapse_to_single_device(state, args.chunk,
                                                     jobs)
        if args.grow_capacity:
            state = checkpoint.grow(state, args.grow_capacity)
        warm_tree = int(meta.get("warmup_tree", 0))
        warm_sol = int(meta.get("warmup_sol", 0))
        # a -C checkpoint carries the host tier's carved seed nodes;
        # resume re-seeds the session from them (or pushes them back
        # into the pool when resuming without -C) — see
        # engine/distributed.search for the same invariant
        saved_p = np.asarray(meta.get("host_prmu",
                                      np.zeros((0, jobs))), np.int16)
        saved_d = np.asarray(meta.get("host_depth", np.zeros(0)),
                             np.int16)
        if host_fraction > 0:
            if len(saved_d):
                h_prmu, h_depth = saved_p, saved_d
            else:
                state, h_prmu, h_depth = hybrid.pop_host_share(
                    state, host_fraction)
            if len(h_depth):
                session = hybrid.HostSession(
                    p, h_prmu, h_depth, args.lb, int(state.best),
                    n_threads=host_threads)
        elif len(saved_d):
            state = hybrid.restore_host_share(state, saved_p, saved_d, p)
        print(f"Resumed from {args.checkpoint} "
              f"(segment {int(meta.get('segment', 0))}, "
              f"iters {int(np.asarray(state.iters).max())}, "
              f"pool {int(np.asarray(state.size).sum())})")
    elif host_fraction > 0:
        # a host tier needs real nodes to seed: native warm-up frontier,
        # stride-split exactly like hybrid.search
        fr = distributed.bfs_warmup(p, args.lb, init_ub,
                                    target=4 * host_fraction)
        best0 = fr.best if init_ub is None else min(fr.best, int(init_ub))
        warm_tree, warm_sol = fr.tree, fr.sol
        dmask, h_prmu, h_depth = hybrid.split_host_share(
            fr.prmu, fr.depth, host_fraction)
        if len(h_depth):
            session = hybrid.HostSession(p, h_prmu, h_depth, args.lb,
                                         best0, n_threads=host_threads)
        state = device.init_state(jobs, args.grow_capacity or args.capacity,
                                  best0, prmu0=fr.prmu[dmask],
                                  depth0=fr.depth[dmask], p_times=p)
    else:
        state = device.init_state(jobs, args.grow_capacity or args.capacity,
                                  init_ub, p_times=p)

    seg_iters = args.segment_iters or 2048

    def run_fn(s, target):
        return device.run(tables, s, args.lb, args.chunk, max_iters=target)

    def heartbeat(r):
        print(f"[segment {r.segment}] iters={r.iters} tree={r.tree} "
              f"sol={r.sol} best={r.best} pool={r.pool_size} "
              f"t={r.elapsed:.2f}s")

    out = checkpoint.run_segmented(
        run_fn, state, segment_iters=seg_iters,
        checkpoint_path=args.checkpoint, heartbeat=heartbeat,
        checkpoint_every=getattr(args, "checkpoint_every", 1),
        max_total_iters=args.max_iters,
        checkpoint_meta={"warmup_tree": warm_tree, "warmup_sol": warm_sol,
                         "host_prmu": (h_prmu if session else
                                       np.zeros((0, jobs), np.int16)),
                         "host_depth": (h_depth if session else
                                        np.zeros(0, np.int16))},
        post_segment=(session.post_segment if session else None))

    extras = {"tree": warm_tree, "sol": warm_sol, "best": None,
              "host": {}}
    if session is not None:
        session.offer(int(np.asarray(out.best).min()))
        h_tree, h_sol, h_best, h_expanded = session.join()
        extras["tree"] += h_tree
        extras["sol"] += h_sol
        extras["best"] = h_best
        extras["host"] = {"host_tree": [h_tree], "host_sol": [h_sol],
                          "host_expanded": [h_expanded],
                          "exchanges": [session.exchanges],
                          "host_improved": [session.host_improved],
                          "dev_improved": [session.dev_improved]}
    return out, extras


def run_nqueens(args) -> int:
    import jax

    from .problems import nqueens as nq

    n_dev = args.D if args.D > 0 else len(jax.devices())
    print("=" * 49)
    print(f"TPU N-Queens ({n_dev} device(s))")
    print(f"Resolution of the {args.N}-Queens instance")
    print(f"  with {args.g} safety check(s) per evaluation")
    print("=" * 49)
    t0 = time.perf_counter()
    if n_dev == 1:
        out = nq.search(args.N, g=args.g, chunk=args.chunk,
                        capacity=args.capacity)
    else:
        out = nq.search_distributed(
            args.N, g=args.g, n_devices=n_dev, chunk=args.chunk,
            capacity=args.capacity)
    elapsed = time.perf_counter() - t0
    print("=" * 49)
    print(f"Size of the explored tree: {out.explored_tree}")
    print(f"Number of explored solutions: {out.explored_sol}")
    print(f"Elapsed time: {elapsed:.4f} [s]")
    print("=" * 49)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu_tree_search")
    ap.add_argument("--platform", type=str, default=None,
                    help="override the JAX platform (e.g. cpu for "
                         "debugging); must precede the subcommand")
    ap.add_argument("--multihost", action="store_true",
                    help="join a multi-host mesh via "
                         "jax.distributed.initialize() (coordinator/rank "
                         "discovered from the cluster env, e.g. SLURM); "
                         "the reference needs a separate MPI executable "
                         "for this tier (pfsp_dist_multigpu_cuda.c) — "
                         "here the same program runs, the mesh just "
                         "spans every host's devices over ICI + DCN")
    sub = ap.add_subparsers(dest="cmd", required=True)
    _pfsp_parser(sub)
    _nq_parser(sub)
    _solve_parser(sub)
    _serve_parser(sub)
    _client_parser(sub)
    _profile_parser(sub)
    _doctor_parser(sub)
    _capacity_parser(sub)
    _journey_parser(sub)
    sub.add_parser("devices",
                   help="describe attached devices (the reference's "
                        "gpu_info, common/gpu_util.cu:5-17)")
    rp = sub.add_parser("roofline",
                        help="analytic FLOP/byte bound-kernel model "
                             "(the reference's flop_lb*/bytes_per_inv_*, "
                             "PFSP_gpu_lib.cu:213-267)")
    rp.add_argument("-i", "--inst", type=int, default=21)
    rp.add_argument("-l", "--lb", type=int, default=1, choices=(0, 1, 2))
    rp.add_argument("--rate", type=float, default=None,
                    help="measured node-evals/s to compare to the ceiling")
    args = ap.parse_args(argv)
    if args.cmd == "doctor":
        # pure scraper: skip the compile cache / backend bootstrap —
        # the doctor must never touch (or wait for) an accelerator
        return run_doctor(args)
    if args.cmd == "capacity":
        # pure scraper, same stance as doctor
        return run_capacity(args)
    if args.cmd == "journey":
        # pure storage reader (stdlib-only, same stance as doctor)
        return run_journey(args)
    if args.platform:
        # Env vars alone are read too early (the environment preloads jax
        # via sitecustomize); flip the platform through jax.config.
        import os

        import jax
        os.environ["JAX_PLATFORMS"] = args.platform
        jax.config.update("jax_platforms", args.platform)
    if args.multihost:
        import jax
        jax.distributed.initialize()
    # persistent compile cache: the reference's binaries are AOT-compiled
    # at build time; this is the JIT-world equivalent (first run compiles
    # ~45 s and caches to disk, every later process loads in ~1 s)
    from .utils import compile_cache
    compile_cache.enable()
    if args.cmd == "pfsp":
        return run_pfsp(args)
    if args.cmd == "solve":
        return run_solve(args)
    if args.cmd == "serve":
        return run_serve(args)
    if args.cmd == "client":
        return run_client(args)
    if args.cmd == "profile":
        return run_profile(args)
    if args.cmd == "devices":
        from .utils.device_info import print_device_info
        print_device_info()
        return 0
    if args.cmd == "roofline":
        from .problems import taillard
        from .utils import roofline
        jobs = taillard.nb_jobs(args.inst)
        machines = taillard.nb_machines(args.inst)
        print(roofline.report(args.lb, jobs, machines,
                              measured_rate=args.rate))
        return 0
    return run_nqueens(args)


if __name__ == "__main__":
    sys.exit(main())
