"""Device-mesh construction.

The reference binds parallel workers explicitly — OpenMP thread ids to
CUDA devices intra-node (pfsp_multigpu_cuda.c:159-160) and MPI ranks to
nodes inter-node (pfsp_dist_multigpu_cuda.c:910). The TPU equivalent is a
`jax.sharding.Mesh` with a single `"workers"` axis laid over all chips:
ICI inside a slice, DCN across hosts, with no code distinction between
the two tiers — growing the mesh is the only change for multi-host
(`jax.distributed.initialize` + the same program).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

WORKER_AXIS = "workers"


def worker_mesh(n_devices: int | None = None,
                devices: list | None = None) -> Mesh:
    """1-D mesh over all (or the first n) addressable devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        assert len(devices) >= n_devices, (
            f"need {n_devices} devices, have {len(devices)}"
        )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (WORKER_AXIS,))


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-tolerant shard_map wrapper.

    check_vma is disabled: the engine's scan/while carries are seeded from
    unvarying constants but updated from worker-varying pool data, which
    the varying-manual-axes checker rejects even though the program is a
    correct SPMD computation (collectives appear only at the balance and
    termination points, by construction).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)
