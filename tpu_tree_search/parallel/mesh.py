"""Device-mesh construction.

The reference binds parallel workers explicitly — OpenMP thread ids to
CUDA devices intra-node (pfsp_multigpu_cuda.c:159-160) and MPI ranks to
nodes inter-node (pfsp_dist_multigpu_cuda.c:910). The TPU equivalent is a
`jax.sharding.Mesh` with a single `"workers"` axis laid over all chips:
ICI inside a slice, DCN across hosts, with no code distinction between
the two tiers — growing the mesh is the only change for multi-host
(`jax.distributed.initialize` + the same program).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

WORKER_AXIS = "workers"


def worker_mesh(n_devices: int | None = None,
                devices: list | None = None) -> Mesh:
    """1-D mesh over all (or the first n) addressable devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        assert len(devices) >= n_devices, (
            f"need {n_devices} devices, have {len(devices)}"
        )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (WORKER_AXIS,))


def partition_submeshes(n_submeshes: int,
                        devices: list | None = None) -> list[Mesh]:
    """Partition the device set into `n_submeshes` equal, disjoint 1-D
    worker meshes (8 devices -> 2 submeshes of 4, 4 of 2, ...).

    The search service schedules one request per submesh, so a
    submesh is exactly the worker_mesh() shape the engines already
    compile against — a request served on a submesh runs the same SPMD
    program a standalone `n_devices=len(submesh)` run would, with
    bit-identical node counts (device identity never enters the search;
    only the worker count does).

    Devices are split contiguously so each submesh keeps the locality
    of the underlying topology (on real hardware, neighbouring chips on
    the ICI torus; the platform's device order is already
    locality-sorted). The device count must divide evenly: silently
    dropping a remainder would strand capacity the operator believes is
    serving.
    """
    if devices is None:
        devices = jax.devices()
    if n_submeshes < 1:
        raise ValueError(f"n_submeshes must be >= 1, got {n_submeshes}")
    if len(devices) % n_submeshes:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_submeshes} "
            f"equal submeshes; pick a divisor of the device count")
    per = len(devices) // n_submeshes
    return [worker_mesh(devices=list(devices[i * per:(i + 1) * per]))
            for i in range(n_submeshes)]


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-tolerant shard_map wrapper.

    check_vma is disabled: the engine's scan/while carries are seeded from
    unvarying constants but updated from worker-varying pool data, which
    the varying-manual-axes checker rejects even though the program is a
    correct SPMD computation (collectives appear only at the balance and
    termination points, by construction).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)
