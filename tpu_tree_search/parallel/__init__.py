from . import mesh, balance

__all__ = ["mesh", "balance"]
