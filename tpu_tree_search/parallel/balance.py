"""Collective load balancing: work stealing as an all_to_all exchange.

The reference has two dynamic load-balancing tiers: intra-node randomized
steal-half work stealing with CAS spin-locks (WS0/WS1 loops,
pfsp_multigpu_cuda.c:347-431) and inter-node collective redistribution
driven by a dedicated communicator thread (Allgather of needs + donor pops
+ Allgatherv scatter, pfsp_dist_multigpu_cuda.c:380-465). On a TPU mesh
both collapse into one synchronous exchange executed by every worker
inside the compiled loop:

1. `all_gather` the pool sizes (every worker sees the global picture —
   the analogue of the Allgather of `local_need`).
2. Compute a deterministic exchange plan, identically on every worker:
   workers above the mean donate half their surplus, workers below fill
   their deficit, matched by interval overlap so one donor can feed many
   receivers (steal-half, the reference's `ratio=2` semantics from
   popBackBulk, Pool_atom.c:154-178), capped by the static
   transfer-buffer size.
3. Donors pop from the top of their stack (deepest nodes — preserving the
   DFS locality the reference's popBack stealing keeps), pack into a
   (workers, cap, ...) buffer, `all_to_all` it, receivers push valid rows.

No locks, no victim retries, no communicator thread: the plan is a pure
function of the gathered sizes, so every worker agrees on it by
construction. Empty-handed workers keep looping (their local steps are
masked no-ops) until the exchange refills them or global termination —
the reference's idle-spin + reawaken protocol (dist:652-686) with the
spin replaced by the loop's own cadence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def waterfill_counts(total: int, m: int) -> np.ndarray:
    """(m,) per-worker pool sizes for an m-way water-filled split of
    `total` nodes: the terminal fixed point exchange_plan's
    surplus/deficit flow converges to (max-min difference <= 1, lower
    worker ids carry the remainder — exactly the counts a round-robin
    stripe `d::m` produces, matching the warm-up seeding's
    roundRobin_distribution idiom).

    Host-side numpy on purpose: this is the elastic-resume half of the
    water-filling machinery (engine/checkpoint.reshard_state re-splits
    an N-worker snapshot across M workers with it), which runs on the
    host between segments, not inside the compiled loop."""
    if m < 1:
        raise ValueError(f"m must be >= 1, got {m}")
    if total < 0:
        raise ValueError(f"total must be >= 0, got {total}")
    return (total // m
            + (np.arange(m) < total % m).astype(np.int64))


def exchange_plan(sizes: jax.Array, cap: int, min_transfer: int) -> jax.Array:
    """(D, D) flow matrix: plan[d, e] nodes move d -> e this round.

    Pure function of the globally-known sizes vector, so every worker
    computes the same plan. Water-filling: workers above the mean donate
    half their surplus (steal-half, the reference's `ratio=2` semantics
    from popBackBulk, Pool_atom.c:154-178, and its `size >= 2m` threshold
    via `min_transfer`), workers below the mean fill their deficit. Donor
    surpluses and receiver deficits are laid out as consecutive intervals
    on one shared flow axis; plan[d, e] is the overlap of donor d's and
    receiver e's intervals — so one hot worker feeds MANY starving
    workers in a single round (the r-th-fullest/r-th-emptiest pairing it
    replaces moved work to exactly one receiver per donor per round,
    which converges D× slower on wide meshes). Per-pair flow is capped
    at `cap`, the static width of the all_to_all transfer buffer.
    """
    D = sizes.shape[0]
    sizes = sizes.astype(jnp.int32)
    mean = sizes.sum() // D
    surplus = jnp.where(sizes - mean >= min_transfer,
                        (sizes - mean) // 2, 0)              # donors
    deficit = jnp.clip(mean - sizes, 0, None)                # receivers
    d_lo = (jnp.cumsum(surplus) - surplus)[:, None]          # (D, 1)
    d_hi = d_lo + surplus[:, None]
    r_lo = (jnp.cumsum(deficit) - deficit)[None, :]          # (1, D)
    r_hi = r_lo + deficit[None, :]
    overlap = jnp.minimum(d_hi, r_hi) - jnp.maximum(d_lo, r_lo)
    return jnp.clip(overlap, 0, cap)
