"""Collective load balancing: work stealing as an all_to_all exchange.

The reference has two dynamic load-balancing tiers: intra-node randomized
steal-half work stealing with CAS spin-locks (WS0/WS1 loops,
pfsp_multigpu_cuda.c:347-431) and inter-node collective redistribution
driven by a dedicated communicator thread (Allgather of needs + donor pops
+ Allgatherv scatter, pfsp_dist_multigpu_cuda.c:380-465). On a TPU mesh
both collapse into one synchronous exchange executed by every worker
inside the compiled loop:

1. `all_gather` the pool sizes (every worker sees the global picture —
   the analogue of the Allgather of `local_need`).
2. Compute a deterministic exchange plan, identically on every worker:
   rank workers by size; the r-th fullest donates to the r-th emptiest
   half of their difference (steal-half, the reference's `ratio=2`
   semantics from popBackBulk, Pool_atom.c:154-178), capped by the static
   transfer-buffer size.
3. Donors pop from the top of their stack (deepest nodes — preserving the
   DFS locality the reference's popBack stealing keeps), pack into a
   (workers, cap, ...) buffer, `all_to_all` it, receivers push valid rows.

No locks, no victim retries, no communicator thread: the plan is a pure
function of the gathered sizes, so every worker agrees on it by
construction. Empty-handed workers keep looping (their local steps are
masked no-ops) until the exchange refills them or global termination —
the reference's idle-spin + reawaken protocol (dist:652-686) with the
spin replaced by the loop's own cadence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def exchange_plan(sizes: jax.Array, cap: int, min_transfer: int) -> jax.Array:
    """(D, D) flow matrix: plan[d, e] nodes move d -> e this round.

    Pure function of the globally-known sizes vector, so every worker
    computes the same plan. Pairing: r-th largest donates to r-th
    smallest `min(cap, (diff)//2)` when diff >= min_transfer (steal-half
    with the reference's `size >= 2m` steal threshold, Pool_atom.c:154-178).
    """
    D = sizes.shape[0]
    sizes = sizes.astype(jnp.int32)
    order_desc = jnp.argsort(-sizes)            # stable: ties by worker id
    order_asc = jnp.argsort(sizes)
    donors = order_desc                          # (D,)
    receivers = order_asc
    diff = sizes[donors] - sizes[receivers]
    amount = jnp.clip(diff // 2, 0, cap)
    amount = jnp.where(diff >= min_transfer, amount, 0)
    amount = jnp.where(donors == receivers, 0, amount)
    plan = jnp.zeros((D, D), jnp.int32).at[donors, receivers].add(amount)
    return plan
