"""tpu-tree-search: a TPU-native distributed Branch-and-Bound tree-search framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
C+CUDA+OpenMP+MPI engine `ivantag13/dist-GPU-accelerated-tree-search`
(see SURVEY.md for the structural map). Node pools live in HBM, bound
evaluation is vectorized/Pallas kernels over node batches, the
pop->bound->prune->branch cycle is a compiled `lax.while_loop`, and the
reference's OpenMP work stealing + MPI load balancing collapse into
`jax.lax` collectives over the device mesh.

Layout
------
problems/  problem definitions: Taillard PFSP instances, N-Queens
           (reference: pfsp/lib/c_taillard.c, pfsp/lib/PFSP_node.h,
            nqueens/lib/NQueens_node.h)
ops/       lower-bound kernels LB1 / LB1_d / LB2, numpy oracle + batched JAX
           (+ Pallas) versions (reference: pfsp/lib/c_bound_simple.c,
            c_bound_johnson.c, bounds_gpu.cu)
engine/    device-resident pool + search loops: sequential oracle,
           single-device, multi-device (reference: Pool_atom.c, pfsp_c.c,
            pfsp_multigpu_cuda.c, pfsp_dist_multigpu_cuda.c)
parallel/  mesh construction, load-balance collectives, termination
           (reference: the MPI layer of pfsp_dist_multigpu_cuda.c:56-137)
utils/     statistics, CSV writers, config (reference: common/util.c,
           pfsp/lib/PFSP_statistic.c)
native/    C++ host runtime (fast sequential oracle / host drain), bound
           via ctypes (the TPU-native analogue of the reference's C core)
"""

import jax

# Tree/solution counters overflow int32 on large instances (the reference
# uses unsigned long long, pfsp/lib/PFSP_lib.c:8). Enable 64-bit mode so
# device-side counters can be int64; all hot-path arrays declare explicit
# narrow dtypes (int16/int32) so this only affects the scalar counters.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
