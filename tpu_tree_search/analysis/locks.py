"""Lock-discipline checker: guarded-attribute annotations + a
lock-acquisition-order graph.

The threaded classes (AOTCache, ExecutorCache, TuningCache,
IncumbentBoard, the metrics Registry, TraceLog, HealthMonitor, the
async checkpoint writer) each learned their race fixes the hard way in
review passes. This checker makes the resulting discipline declarative:

**Annotation grammar** (trailing comments — they survive formatting and
need no runtime import):

- ``self._best = {}   # guarded-by: self._lock`` — declares the
  attribute guarded: every MUTATION of it anywhere in the class must
  sit lexically inside ``with self._lock:`` (or in a method annotated
  as holding it). Reads are not checked — the repo's snapshot-read
  idiom is deliberate.
- ``_FINDINGS = deque()   # guarded-by: _LOCK`` — same, for
  module-level shared state.
- ``def _rotate_locked(self):   # holds: self._lock`` — declares a
  helper only ever called with the lock held; its mutations count as
  guarded and lock acquisitions inside it order AFTER the held lock.
- ``__init__`` is exempt (the object is not yet shared).

**Lock-order graph**: every ``with <lock>`` acquisition nested (again
lexically, plus one call-resolution hop computed to fixpoint over the
repo-local call graph) inside another lock's scope adds an edge
``outer -> inner``; locks are identified class-granularly
(``ClassName.attr`` / ``module:NAME``). A cycle in that graph is a
potential deadlock ordering and is reported as one finding per strongly
connected component. Class-granular identity can alias distinct
instances (two metrics' ``_lock`` are different objects) — that is the
usual static-analysis over-approximation; waive such a finding with the
aliasing argument written down.
"""

from __future__ import annotations

import ast

from .core import Finding, parse_many

__all__ = ["check", "LOCK_DIRS"]

LOCK_DIRS = ("tpu_tree_search/service", "tpu_tree_search/obs",
             "tpu_tree_search/tune", "tpu_tree_search/engine/checkpoint.py",
             "tpu_tree_search/engine/incumbent.py")

_MUTATORS = {"append", "appendleft", "add", "clear", "discard", "extend",
             "insert", "pop", "popleft", "popitem", "remove",
             "setdefault", "update", "sort", "reverse"}

_GUARD_TAG = "guarded-by:"
_HOLDS_TAG = "holds:"


def _unparse(expr) -> str:
    try:
        return ast.unparse(expr)
    except Exception:  # noqa: BLE001 — display-only
        return "<expr>"


def _tag_value(comment: str, tag: str) -> str | None:
    if tag not in comment:
        return None
    return comment.split(tag, 1)[1].strip().split()[0].rstrip(",;")


def _stmt_comment(src, node) -> str:
    end = getattr(node, "end_lineno", node.lineno)
    for line in range(node.lineno, end + 1):
        c = src.comment_at(line)
        if c:
            return c
    return ""


class _Class:
    def __init__(self, name: str, node: ast.ClassDef, src):
        self.name = name
        self.node = node
        self.src = src
        self.guarded: dict = {}     # attr -> lock expr string
        self.methods: dict = {}     # name -> FunctionDef
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        # guarded-by annotations anywhere in the class body
        for stmt in ast.walk(node):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            guard = _tag_value(_stmt_comment(src, stmt), _GUARD_TAG)
            if not guard:
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    self.guarded[t.attr] = guard


def _method_holds(src, fn) -> set:
    """`# holds:` annotations for a function: the line above the def,
    any line of the (possibly multi-line) signature, or a standalone
    comment line between the header and the first body statement."""
    held = set()
    first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
    for line in range(fn.lineno - 1, first_body):
        v = _tag_value(src.comment_at(line), _HOLDS_TAG)
        if v:
            held.add(v)
    return held


def _self_attr(expr):
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def _mutations(node):
    """Yield (attr_or_name, line, kind, selfish) mutations at `node`
    (one AST statement/expression level, not recursive). `selfish`
    distinguishes `self.X` mutations (class-attribute discipline) from
    bare-name mutations (module-level state discipline) — a local
    variable that happens to share a guarded attribute's name must not
    trip the class check."""
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr:
                yield attr, node.lineno, "assign", True
            elif isinstance(base, ast.Name) and base is not t:
                # NAME[...] = v  (container store through a bare name)
                yield base.id, node.lineno, "assign", False
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr:
                yield attr, node.lineno, "delete", True
            elif isinstance(base, ast.Name):
                yield base.id, node.lineno, "delete", False
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATORS:
        recv = node.func.value
        attr = _self_attr(recv)
        if attr:
            yield attr, node.lineno, f".{node.func.attr}()", True
        elif isinstance(recv, ast.Name):
            yield recv.id, node.lineno, f".{node.func.attr}()", False


def _walk_with_locks(fn, base_held: frozenset, visit):
    """Depth-first walk calling visit(node, held_lock_strings) on every
    node; `with X:` scopes extend the held set for their bodies."""

    def go(node, held):
        visit(node, held)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | {_unparse(i.context_expr)
                            for i in node.items}
            for i in node.items:
                go(i.context_expr, held)
            for child in node.body:
                go(child, inner)
            return
        for child in ast.iter_child_nodes(node):
            go(child, held)

    for stmt in fn.body:
        go(stmt, frozenset(base_held))


# ------------------------------------------------------------ the checker


def check(root=None) -> list:
    sources, findings = parse_many(root, LOCK_DIRS)
    out: list = list(findings)

    classes: list = []          # (_Class, src)
    module_guarded: dict = {}   # (rel, name) -> lock str
    module_locks: dict = {}     # per rel: {name} of module-level locks
    for src in sources:
        locks_here = set()
        for stmt in src.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call) and \
                    _unparse(stmt.value.func).split(".")[-1] in (
                        "Lock", "RLock", "Condition", "Semaphore"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        locks_here.add(t.id)
            guard = _tag_value(_stmt_comment(src, stmt), _GUARD_TAG) \
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)) else None
            if guard and isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        module_guarded[(src.rel, t.id)] = guard
        module_locks[src.rel] = locks_here
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                classes.append(_Class(node.name, node, src))

    # ---- guarded-mutation verification (classes)
    for cls in classes:
        if not cls.guarded:
            continue
        for mname, fn in cls.methods.items():
            if mname == "__init__":
                continue
            held0 = _method_holds(cls.src, fn)

            def visit(node, held, _cls=cls, _m=mname):
                for attr, line, kind, selfish in _mutations(node):
                    if not selfish:
                        continue   # bare local names shadow attr names
                    lock = _cls.guarded.get(attr)
                    if lock is None:
                        continue
                    if lock in held:
                        continue
                    out.append(Finding(
                        checker="locks", rule="unguarded_mutation",
                        path=_cls.src.rel, line=line,
                        symbol=f"{_cls.name}.{attr}@{_m}",
                        message=f"mutation ({kind}) of "
                                f"self.{attr} in {_cls.name}.{_m} "
                                f"outside 'with {lock}' (declared "
                                f"guarded-by {lock})"))

            _walk_with_locks(fn, frozenset(held0), visit)

    # ---- guarded-mutation verification (module-level state)
    for src in sources:
        names = {n for (rel, n) in module_guarded if rel == src.rel}
        if not names:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            held0 = _method_holds(src, node)

            def visit(n, held, _src=src, _fn=node, _names=names):
                for name, line, kind, selfish in _mutations(n):
                    if selfish or name not in _names:
                        continue
                    lock = module_guarded[(_src.rel, name)]
                    if lock in held:
                        continue
                    out.append(Finding(
                        checker="locks", rule="unguarded_mutation",
                        path=_src.rel, line=line,
                        symbol=f"{name}@{_fn.name}",
                        message=f"mutation ({kind}) of module-level "
                                f"{name} in {_fn.name}() outside "
                                f"'with {lock}' (declared guarded-by "
                                f"{lock})"))

            _walk_with_locks(node, frozenset(held0), visit)

    # ---- lock-order graph
    out.extend(_lock_order(sources, classes, module_locks))
    return out


# ----------------------------------------------------- acquisition order


def _lock_id(expr_str: str, cls_name: str | None, rel: str,
             module_locks: dict) -> str | None:
    """Normalize a with-expression to a lock node id, or None when it
    is not a known lock."""
    if expr_str.startswith("self.") and cls_name:
        return f"{cls_name}.{expr_str[5:]}"
    if expr_str in module_locks.get(rel, ()):
        mod = rel.rsplit("/", 1)[-1]
        return f"{mod}:{expr_str}"
    return None


def _lock_order(sources, classes, module_locks) -> list:
    # function registry: (rel, qualname) -> (fn node, cls or None, src)
    funcs: dict = {}
    by_bare: dict = {}         # bare name -> [(rel, qual)]
    by_method: dict = {}       # method name -> [(rel, qual)]
    cls_of: dict = {}
    for src in sources:
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (src.rel, node.name)
                funcs[key] = (node, None, src)
                by_bare.setdefault(node.name, []).append(key)
    for cls in classes:
        for mname, fn in cls.methods.items():
            key = (cls.src.rel, f"{cls.name}.{mname}")
            funcs[key] = (fn, cls, cls.src)
            by_method.setdefault(mname, []).append(key)
            cls_of[key] = cls

    def resolve_call(call, cls, src) -> list:
        func = call.func
        if isinstance(func, ast.Name):
            # bare name: same module first, else unique across repo
            same = [(r, q) for (r, q) in by_bare.get(func.id, ())
                    if r == src.rel]
            if same:
                return same
            allb = by_bare.get(func.id, [])
            return allb if len(allb) == 1 else []
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self" and cls is not None:
                key = (src.rel, f"{cls.name}.{func.attr}")
                return [key] if key in funcs else []
            # module alias: resolve a top-level function in that module
            if isinstance(func.value, ast.Name):
                cand = [(r, q) for (r, q) in by_bare.get(func.attr, ())
                        if r.rsplit("/", 1)[-1].startswith(
                            func.value.id + ".")]
                if len(cand) == 1:
                    return cand
            # unique method name across analyzed classes
            meths = by_method.get(func.attr, [])
            return meths if len(meths) == 1 else []
        return []

    # direct acquisitions + call lists per function
    direct: dict = {k: set() for k in funcs}
    calls: dict = {k: [] for k in funcs}
    for key, (fn, cls, src) in funcs.items():
        cls_name = cls.name if cls else None
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_id(_unparse(item.context_expr), cls_name,
                                   src.rel, module_locks)
                    if lid:
                        direct[key].add(lid)
            elif isinstance(node, ast.Call):
                calls[key].append(node)

    # fixpoint: may-acquire set per function through repo-local calls
    acq = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key, (fn, cls, src) in funcs.items():
            for call in calls[key]:
                for tgt in resolve_call(call, cls, src):
                    extra = acq.get(tgt, set()) - acq[key]
                    if extra:
                        acq[key] |= extra
                        changed = True

    # edges: for every with-lock scope, inner acquisitions (lexical
    # with + calls inside the body, transitively) order after it
    edges: dict = {}

    def note_edge(a, b, rel, line):
        if a != b:
            edges.setdefault((a, b), (rel, line))

    for key, (fn, cls, src) in funcs.items():
        cls_name = cls.name if cls else None
        held0 = set()
        for h in _method_holds(src, fn):
            lid = _lock_id(h, cls_name, src.rel, module_locks)
            if lid:
                held0.add(lid)

        def visit(node, held, _cls=cls_name, _src=src):
            ids = set()
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _lock_id(_unparse(item.context_expr), _cls,
                                   _src.rel, module_locks)
                    if lid:
                        ids.add(lid)
            elif isinstance(node, ast.Call):
                for tgt in resolve_call(node, cls_of.get(key), _src):
                    ids |= acq.get(tgt, set())
            for h in held:
                hid = _lock_id(h, _cls, _src.rel, module_locks)
                if hid:
                    for lid in ids:
                        note_edge(hid, lid, _src.rel, node.lineno)
            for hid in held0:
                for lid in ids:
                    note_edge(hid, lid, _src.rel, node.lineno)

        _walk_with_locks(fn, frozenset(), visit)

    # cycle detection (iterative Tarjan SCC)
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    out = []
    for comp in sccs:
        cyclic = len(comp) > 1 or (comp[0] in graph.get(comp[0], ()))
        if not cyclic:
            continue
        nodes = sorted(comp)
        witness = [f"{a} -> {b} ({edges[(a, b)][0]}:{edges[(a, b)][1]})"
                   for (a, b) in sorted(edges)
                   if a in comp and b in comp]
        out.append(Finding(
            checker="locks", rule="lock_cycle",
            path=edges[next((e for e in sorted(edges)
                             if e[0] in comp and e[1] in comp))][0],
            line=0, symbol="<->".join(nodes),
            message="lock-acquisition-order cycle between "
                    f"{', '.join(nodes)}: " + "; ".join(witness)))
    return out
