"""Trace-safety checker: host-sync and nondeterminism hazards inside
traced code.

The engine's hot loops are ``jit`` / ``shard_map`` programs built from
``lax.{while_loop, cond, switch, scan}`` callables. Host-sync calls
inside them (``.item()``, ``jax.device_get``, ``np.asarray`` on a traced
value) either fail at trace time in the best case or silently serialize
the device against the host in the worst; trace-time reads of ambient
state (``time.time()``, ``os.environ`` / the config accessors) bake a
value into the executable — a static flag read inside a traced function
is a silent retrace-or-stale hazard (the executable keeps the value the
FIRST trace saw; flipping the env var later does nothing, or worse,
retraces mid-serve).

Method: per analyzed module, index every function (including nested
defs), mark TRACED ROOTS — functions passed to the jit family
(``jit``/``pjit``/``vmap``/``pmap``/``shard_map``/``remat``), used as
decorators from that family, or passed as callables to ``lax`` control
flow — then walk the call graph (bare-name and imported-module
resolution, repo-local only) and scan every reachable function for the
hazard patterns. Lambdas passed to control flow are scanned in their
enclosing function's context.

Precision stance: ``np``/``float()``/``int()`` are ONLY flagged when
applied directly to a parameter of the traced function (parameters are
traced values by construction; np use on static shape math at trace
time is idiomatic and fine). Everything here is best-effort static
analysis — the waiver file exists for the rare justified exception, and
the fixture tests pin both directions.
"""

from __future__ import annotations

import ast

from .core import Finding, parse_many

__all__ = ["check", "TRACED_DIRS", "PLUGIN_JITTABLE"]

# the subtrees whose jit entry points are the engine's compiled surface
# (problems/ holds the plugin protocol's jittable branch/bound
# callables — traced code reached through a dynamic problem object the
# call-graph walk cannot resolve, hence the explicit root rule below)
TRACED_DIRS = ("tpu_tree_search/engine", "tpu_tree_search/ops",
               "tpu_tree_search/problems")

# every registered problem's jittable protocol methods
# (problems/base.Problem): the generic step invokes them through a
# plugin OBJECT (`problem.branch(...)`), which bare-name/module
# resolution cannot see — so any function with one of these names
# defined under problems/ is a traced root by rule. The conformance
# suite (tests/test_problem_plugins.py) pins that each registered
# plugin's methods are actually covered by this walk.
PLUGIN_JITTABLE = ("branch", "bound", "is_leaf_cols")
_PLUGIN_PKG = "tpu_tree_search.problems"

_JIT_WRAPPERS = {"jit", "pjit", "vmap", "pmap", "shard_map", "remat",
                 "named_call", "custom_jvp", "custom_vjp"}
_LAX_CTRL = {"cond", "switch", "scan", "while_loop", "fori_loop",
             "associative_scan"}

# host-sync calls by terminal attribute / bare name
_HOST_SYNC_ATTRS = {"device_get", "block_until_ready", "copy_to_host_async"}
_TIME_FNS = {"time", "monotonic", "perf_counter", "process_time",
             "time_ns", "monotonic_ns", "perf_counter_ns"}
_ENV_READERS = {"getenv", "env_flag", "env_str", "env_int", "env_float",
                "env_ints"}
_CASTS = {"float", "int", "bool", "complex"}
_NP_MATERIALIZERS = {"asarray", "array", "ascontiguousarray"}


def _terminal_attr(func) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _dotted(expr) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts))


class _ModuleIndex:
    """Per-module function table + import map for repo-local call
    resolution."""

    def __init__(self, src, pkg_key: str):
        self.src = src
        self.key = pkg_key                 # dotted module key
        self.functions: dict = {}          # qualname -> FunctionDef
        self.by_name: dict = {}            # bare name -> [qualname]
        self.import_alias: dict = {}       # local alias -> module key
        self.from_func: dict = {}          # local name -> (mod key, name)
        self._index()

    def _index(self) -> None:
        stack: list = []

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = ".".join(stack + [child.name])
                    self.functions[qual] = child
                    self.by_name.setdefault(child.name, []).append(qual)
                    stack.append(child.name)
                    visit(child)
                    stack.pop()
                elif isinstance(child, ast.ClassDef):
                    stack.append(child.name)
                    visit(child)
                    stack.pop()
                else:
                    visit(child)

        visit(self.src.tree)
        pkg_parts = self.key.split(".")
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or
                                      a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:-node.level]
                    mod = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    local = a.asname or a.name
                    self.from_func[local] = (mod, a.name)
                    # `from . import device` style: the name is a module
                    self.import_alias.setdefault(
                        local, f"{mod}.{a.name}" if mod else a.name)


def _module_key(rel: str) -> str:
    return rel[:-3].replace("/", ".")      # strip .py


def _func_args(fn) -> set:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _callable_args(call: ast.Call) -> list:
    """Expressions passed to a jit-family / lax-control call that may
    be callables: names, attributes, lambdas, partial(...) first args,
    list/tuple elements (switch branches)."""
    out = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, (ast.Name, ast.Attribute, ast.Lambda)):
            out.append(arg)
        elif isinstance(arg, (ast.List, ast.Tuple)):
            out.extend(e for e in arg.elts
                       if isinstance(e, (ast.Name, ast.Attribute,
                                         ast.Lambda)))
        elif isinstance(arg, ast.Call) and \
                _terminal_attr(arg.func) == "partial" and arg.args:
            out.append(arg.args[0])
    return out


def _is_wrapper_call(call: ast.Call) -> bool:
    name = _terminal_attr(call.func)
    return name in _JIT_WRAPPERS or name in _LAX_CTRL


def _resolve(expr, mod: _ModuleIndex, modules: dict) -> list:
    """Resolve a callable expression to [(module, qualname)] within the
    analyzed set. Best effort; unresolvable -> []."""
    if isinstance(expr, ast.Name):
        name = expr.id
        if name in mod.by_name:
            return [(mod, q) for q in mod.by_name[name]]
        if name in mod.from_func:
            mkey, orig = mod.from_func[name]
            target = modules.get(mkey)
            if target and orig in target.by_name:
                return [(target, q) for q in target.by_name[orig]]
        return []
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            mkey = mod.import_alias.get(base.id)
            target = modules.get(mkey) if mkey else None
            if target and expr.attr in target.by_name:
                return [(target, q) for q in target.by_name[expr.attr]]
        return []
    return []


def _body_calls(fn):
    """Call nodes in a function's own body, excluding nested defs (they
    are separate call-graph nodes); lambda bodies stay included."""
    skip: set = set()
    for node in ast.walk(fn):
        if node is not fn and isinstance(node, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)):
            skip.update(ast.walk(node))
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and node not in skip:
            yield node


def _lambda_sites(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Lambda):
            yield node


def check(root=None) -> list:
    sources, findings = parse_many(root, TRACED_DIRS)
    modules = {_module_key(s.rel): _ModuleIndex(s, _module_key(s.rel))
               for s in sources}

    # --- traced roots
    roots: set = set()     # (module key, qualname)
    for key, mod in modules.items():
        # decorator roots
        for qual, fn in mod.functions.items():
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _terminal_attr(target) in _JIT_WRAPPERS:
                    roots.add((key, qual))
                elif isinstance(dec, ast.Call) and \
                        _terminal_attr(dec.func) == "partial" and \
                        dec.args and \
                        _terminal_attr(dec.args[0]) in _JIT_WRAPPERS:
                    roots.add((key, qual))
        # call-site roots: jit(f), lax.while_loop(cond, body, ...)
        for node in ast.walk(mod.src.tree):
            if isinstance(node, ast.Call) and _is_wrapper_call(node):
                for expr in _callable_args(node):
                    if isinstance(expr, ast.Lambda):
                        continue       # scanned with its enclosing fn
                    for tgt_mod, qual in _resolve(expr, mod, modules):
                        roots.add((tgt_mod.key, qual))
        # plugin roots: the problem protocol's jittable callables are
        # invoked through a dynamic plugin object inside the generic
        # step — every definition of one under problems/ is traced
        if key.startswith(_PLUGIN_PKG):
            for qual in mod.functions:
                if qual.split(".")[-1] in PLUGIN_JITTABLE:
                    roots.add((key, qual))

    # --- reachability over repo-local calls
    reachable: set = set()
    work = sorted(roots)
    while work:
        key, qual = work.pop()
        if (key, qual) in reachable:
            continue
        reachable.add((key, qual))
        mod = modules[key]
        fn = mod.functions.get(qual)
        if fn is None:
            continue
        for call in _body_calls(fn):
            for tgt_mod, tgt_qual in _resolve(call.func, mod, modules):
                if (tgt_mod.key, tgt_qual) not in reachable:
                    work.append((tgt_mod.key, tgt_qual))
            # partial(f, ...) built inside traced code: f executes in
            # the trace when the partial is invoked
            if _terminal_attr(call.func) == "partial" and call.args:
                for tgt_mod, tgt_qual in _resolve(call.args[0], mod,
                                                  modules):
                    if (tgt_mod.key, tgt_qual) not in reachable:
                        work.append((tgt_mod.key, tgt_qual))
            # callables handed onward to nested control flow
            if _is_wrapper_call(call):
                for expr in _callable_args(call):
                    if isinstance(expr, ast.Lambda):
                        continue
                    for tgt_mod, tgt_qual in _resolve(expr, mod,
                                                      modules):
                        if (tgt_mod.key, tgt_qual) not in reachable:
                            work.append((tgt_mod.key, tgt_qual))

    # --- hazard scan
    seen_fp: set = set()

    def emit(mod, qual, token, rule, line, what):
        f = Finding(checker="trace_safety", rule=rule, path=mod.src.rel,
                    line=line, symbol=f"{qual}:{token}",
                    message=f"{what} inside traced function {qual!r}")
        if f.fingerprint() not in seen_fp:
            seen_fp.add(f.fingerprint())
            out.append(f)

    out: list = []
    for key, qual in sorted(reachable):
        mod = modules[key]
        fn = mod.functions.get(qual)
        if fn is None:
            continue
        params = _func_args(fn)
        for lam in _lambda_sites(fn):
            params |= _func_args(lam)
        for call in _body_calls(fn):
            name = _terminal_attr(call.func)
            dotted = _dotted(call.func)
            base = dotted.split(".")[0] if dotted else ""
            if name == "item" and isinstance(call.func, ast.Attribute):
                emit(mod, qual, "item", "host_sync", call.lineno,
                     ".item() (device->host sync)")
            elif name in _HOST_SYNC_ATTRS:
                emit(mod, qual, name, "host_sync", call.lineno,
                     f"{dotted}() (device->host sync)")
            elif base in ("time",) and name in _TIME_FNS:
                emit(mod, qual, f"time.{name}", "nondeterminism",
                     call.lineno,
                     f"{dotted}() (trace-time clock read bakes a "
                     "constant into the executable)")
            elif base in ("random",) or dotted.startswith("np.random") \
                    or dotted.startswith("numpy.random"):
                emit(mod, qual, dotted or "random", "nondeterminism",
                     call.lineno,
                     f"{dotted}() (trace-time randomness: every trace "
                     "bakes a different program)")
            elif name in _ENV_READERS or dotted.endswith("environ.get"):
                emit(mod, qual, name or dotted, "env_read", call.lineno,
                     f"{dotted}() (static flag read in traced code: "
                     "silent retrace/stale-value hazard — read it at "
                     "state init and pass the value in)")
            elif name in _CASTS and isinstance(call.func, ast.Name) \
                    and len(call.args) == 1 \
                    and isinstance(call.args[0], ast.Name) \
                    and call.args[0].id in params:
                emit(mod, qual, f"{name}({call.args[0].id})",
                     "host_sync", call.lineno,
                     f"{name}() applied to traced parameter "
                     f"{call.args[0].id!r} (forces a concrete value)")
            elif base in ("np", "numpy") and name in _NP_MATERIALIZERS \
                    and call.args \
                    and isinstance(call.args[0], ast.Name) \
                    and call.args[0].id in params:
                emit(mod, qual, f"np.{name}({call.args[0].id})",
                     "host_sync", call.lineno,
                     f"{dotted}() on traced parameter "
                     f"{call.args[0].id!r} (materializes on host)")
        # env reads via subscript: os.environ["TTS_X"]
        skip: set = set()
        for node in ast.walk(fn):
            if node is not fn and isinstance(node, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef)):
                skip.update(ast.walk(node))
        for node in ast.walk(fn):
            if node in skip or not isinstance(node, ast.Subscript):
                continue
            if _dotted(node.value).endswith("environ"):
                emit(mod, qual, "os.environ[]", "env_read", node.lineno,
                     "os.environ[...] (static flag read in traced code)")
    return findings + out
