"""Shared machinery for the tts-lint checkers: findings, fingerprints,
parsed-source caching, the waiver file, and report assembly.

Design rules the four checkers follow:

- **Stable fingerprints.** A finding's fingerprint hashes its checker,
  rule, repo-relative path and the SYMBOL it anchors to (class.attr,
  function qualname, knob/metric name) — never the line number — so a
  waiver survives unrelated edits to the file but dies with the symbol
  it excused.
- **Parse with stdlib.** The checkers themselves use only ``ast`` +
  ``tokenize``. Loading the registries (``utils/config.KNOBS``,
  ``obs/metric_names.REGISTRY``) does import the package — and the
  package ``__init__`` imports jax — so running the linter needs the
  repo installed, accelerator stack included (the CI lint leg
  ``pip install -e .`` first). Fixture trees without a registry module
  exercise the site-side rules with no registry import at all.
- **Never crash on bad input.** A file that fails to parse becomes a
  ``parse_error`` finding, not a traceback — the linter is a gate, and
  a gate that dies open is not a gate.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import pathlib
import tokenize

__all__ = ["Finding", "Waivers", "LintReport", "SourceFile", "parse_file",
           "repo_root", "repo_files", "load_waivers", "WAIVER_FILE"]

WAIVER_FILE = ".tts-lint-waivers.json"

# directories never scanned (vendored/derived/VCS trees)
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build",
              "dist", ".eggs"}


@dataclasses.dataclass
class Finding:
    """One invariant violation.

    `symbol` is the stable anchor (see the fingerprint rule above);
    `message` is the human sentence; `line` is advisory (it moves with
    edits and is deliberately NOT part of the fingerprint)."""

    checker: str
    rule: str
    path: str           # repo-relative, POSIX separators
    line: int
    symbol: str
    message: str

    def fingerprint(self) -> str:
        raw = f"{self.checker}:{self.rule}:{self.path}:{self.symbol}"
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {"checker": self.checker, "rule": self.rule,
                "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "fingerprint": self.fingerprint()}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}/{self.rule}] "
                f"{self.message} (fingerprint {self.fingerprint()})")


@dataclasses.dataclass
class Waivers:
    """The checked-in triage file: fingerprint -> written reason. A
    waiver without a reason is refused at load time — the file exists
    to make deferrals EXPLICIT, and an empty reason is not a triage."""

    by_fingerprint: dict
    path: str | None = None

    def reason_for(self, finding: Finding) -> str | None:
        return self.by_fingerprint.get(finding.fingerprint())

    @classmethod
    def empty(cls) -> "Waivers":
        return cls(by_fingerprint={})


def load_waivers(root) -> Waivers:
    path = pathlib.Path(repo_root(root)) / WAIVER_FILE
    if not path.exists():
        return Waivers.empty()
    data = json.loads(path.read_text())
    table = {}
    for entry in data.get("waivers", []):
        fp = entry.get("fingerprint", "")
        reason = (entry.get("reason") or "").strip()
        if not fp:
            raise ValueError(f"{path}: waiver entry missing fingerprint: "
                             f"{entry}")
        if not reason:
            raise ValueError(f"{path}: waiver {fp} has no reason — a "
                             "waiver is a written triage, not a mute")
        table[fp] = reason
    return Waivers(by_fingerprint=table, path=str(path))


@dataclasses.dataclass
class LintReport:
    """The run's outcome: surviving findings, waived findings (with
    their reasons) and waivers that matched nothing (stale triage —
    reported so the file stays honest, but not failing)."""

    findings: list          # unwaived, the gate input
    waived: list            # (Finding, reason)
    unused_waivers: list    # fingerprints with no matching finding

    @property
    def ok(self) -> bool:
        return not self.findings

    @classmethod
    def build(cls, findings: list, waivers: Waivers) -> "LintReport":
        live, waived, used = [], [], set()
        for f in findings:
            reason = waivers.reason_for(f)
            if reason is None:
                live.append(f)
            else:
                waived.append((f, reason))
                used.add(f.fingerprint())
        unused = sorted(set(waivers.by_fingerprint) - used)
        order = {"trace_safety": 0, "locks": 1, "knobs": 2, "metrics": 3}
        live.sort(key=lambda f: (order.get(f.checker, 9), f.path, f.line))
        return cls(findings=live, waived=waived, unused_waivers=unused)

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "counts": {"findings": len(self.findings),
                       "waived": len(self.waived),
                       "unused_waivers": len(self.unused_waivers)},
            "findings": [f.to_json() for f in self.findings],
            "waived": [{**f.to_json(), "reason": r}
                       for f, r in self.waived],
            "unused_waivers": self.unused_waivers,
        }

    def render(self) -> str:
        lines = []
        if self.findings:
            lines.append(f"{len(self.findings)} unwaived finding(s):")
            lines.extend("  " + f.render() for f in self.findings)
        else:
            lines.append("no unwaived findings")
        if self.waived:
            lines.append(f"{len(self.waived)} waived:")
            lines.extend(f"  {f.path}: [{f.checker}/{f.rule}] "
                         f"{f.symbol} — {r}" for f, r in self.waived)
        if self.unused_waivers:
            lines.append(f"{len(self.unused_waivers)} stale waiver(s) "
                         "matched nothing (prune them):")
            lines.extend(f"  {fp}" for fp in self.unused_waivers)
        return "\n".join(lines)


# ------------------------------------------------------------ source files


@dataclasses.dataclass
class SourceFile:
    """A parsed module plus the comment map the annotation grammars
    need (``# guarded-by:`` / ``# holds:`` live in comments, which ast
    drops — tokenize recovers them per line)."""

    path: pathlib.Path       # absolute
    rel: str                 # repo-relative POSIX
    tree: ast.Module
    source: str
    comments: dict           # line -> comment text (without '#')

    def comment_at(self, line: int) -> str:
        return self.comments.get(line, "")


# parsed-source cache shared by the four checkers: run_all() has them
# scan overlapping subtrees, so without it most of the package is
# ast.parse+tokenize'd several times per lint run. Keyed on
# (path, mtime_ns, size) so an edited file re-parses — a long pytest
# session linting many fixture trees stays correct.
_PARSE_CACHE: dict = {}


def parse_file(path: pathlib.Path, root: pathlib.Path
               ) -> SourceFile | Finding:
    rel = path.relative_to(root).as_posix()
    try:
        st = path.stat()
        cache_key = (str(path), st.st_mtime_ns, st.st_size)
        hit = _PARSE_CACHE.get(cache_key)
        if hit is not None and hit.rel == rel:
            return hit
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return Finding(checker="core", rule="parse_error", path=rel,
                       line=getattr(e, "lineno", 0) or 0, symbol=rel,
                       message=f"cannot parse: {e!r}")
    comments: dict = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string.lstrip("#").strip()
    except tokenize.TokenError:
        pass   # comments stay partial; the AST already parsed
    sf = SourceFile(path=path, rel=rel, tree=tree, source=source,
                    comments=comments)
    if len(_PARSE_CACHE) > 4096:   # fixture-tree churn bound
        _PARSE_CACHE.clear()
    _PARSE_CACHE[cache_key] = sf
    return sf


def repo_root(root=None) -> pathlib.Path:
    """Resolve the tree to lint: an explicit root, else the repo this
    package is installed from (three parents up: analysis/ -> package
    -> checkout)."""
    if root is not None:
        return pathlib.Path(root).resolve()
    return pathlib.Path(__file__).resolve().parents[2]


def repo_files(root, subdirs=None) -> list:
    """Every .py file under `root` (or just `subdirs` of it), sorted,
    skipping derived trees. `subdirs` entries may be files."""
    root = repo_root(root)
    paths = []
    bases = ([root / s for s in subdirs] if subdirs else [root])
    for base in bases:
        if base.is_file():
            paths.append(base)
            continue
        if not base.exists():
            continue
        for p in sorted(base.rglob("*.py")):
            if not any(part in _SKIP_DIRS for part in p.parts):
                paths.append(p)
    return paths


def parse_many(root, subdirs=None):
    """(sources, findings) over the selected files."""
    root = repo_root(root)
    sources, findings = [], []
    for p in repo_files(root, subdirs):
        got = parse_file(p, root)
        if isinstance(got, Finding):
            findings.append(got)
        else:
            sources.append(got)
    return sources, findings
