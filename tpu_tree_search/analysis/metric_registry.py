"""Metric-registry checker: ``tts_*`` metric names cannot drift.

``obs/metric_names.REGISTRY`` is the one checked-in table of every
series the stack emits. This checker reconciles it against the code:

- **unregistered_metric** — a literal ``tts_*`` name at an emit site
  (``counter()`` / ``gauge()`` / ``histogram()``) or a reference site
  (``gauge_samples()`` / ``remove_matching()``, the health rules' and
  aggregator's read paths) with no registry row. Constant indirection
  (``DROPPED = "tts_metrics_dropped_total"``) is resolved.
- **unemitted_metric** — a registry row with no emit site inside
  ``tpu_tree_search/`` (dead rows are how a README table starts lying).
- **kind_mismatch** — an emit site whose accessor (counter vs gauge vs
  histogram) disagrees with the registered kind; the runtime Registry
  raises on this too, but only when both sites actually execute in one
  process — the lint catches it across processes and test gaps.

Registry-side rules run only against this repo (fixture trees exercise
the site-side rules).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, parse_many, repo_root

__all__ = ["check", "METRIC_DIRS"]

METRIC_DIRS = ("tpu_tree_search", "tools", "bench.py")

_EMIT = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}
_REFERENCE = {"gauge_samples", "remove_matching"}
_NAME_RE = re.compile(r"^tts_[a-z0-9_]+$")
_REGISTRY_REL = "tpu_tree_search/obs/metric_names.py"
_ANALYSIS_PREFIX = "tpu_tree_search/analysis/"


def _literal_metric(expr) -> str | None:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
            and _NAME_RE.match(expr.value):
        return expr.value
    return None


def check(root=None) -> list:
    root = repo_root(root)
    sources, findings = parse_many(root, METRIC_DIRS)
    out: list = list(findings)

    const_map: dict = {}
    for src in sources:
        if src.rel == _REGISTRY_REL:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and _literal_metric(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        const_map[t.id] = node.value.value
                    elif isinstance(t, ast.Attribute):
                        const_map[t.attr] = node.value.value

    def resolve(expr) -> str | None:
        lit = _literal_metric(expr)
        if lit:
            return lit
        if isinstance(expr, ast.Name):
            return const_map.get(expr.id)
        if isinstance(expr, ast.Attribute):
            return const_map.get(expr.attr)
        return None

    emit_sites: list = []     # (name, kind, src, line, in_package)
    ref_sites: list = []
    mentions: set = set()     # literal tts_* names anywhere in the pkg
    for src in sources:
        if src.rel == _REGISTRY_REL or \
                src.rel.startswith(_ANALYSIS_PREFIX):
            continue
        in_pkg = src.rel.startswith("tpu_tree_search/")
        # local aliases of the emit accessors (`g = registry.gauge`)
        aliases: dict = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr in _EMIT:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = _EMIT[node.value.attr]
        for node in ast.walk(src.tree):
            if in_pkg and isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _NAME_RE.match(node.value):
                mentions.add(node.value)
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in aliases:
                attr = None
                name = resolve(node.args[0])
                if name:
                    emit_sites.append((name, aliases[node.func.id],
                                       src, node.lineno, in_pkg))
                continue
            else:
                continue
            if attr in _EMIT:
                name = resolve(node.args[0])
                if name:
                    emit_sites.append((name, _EMIT[attr], src,
                                       node.lineno, in_pkg))
            elif attr in _REFERENCE:
                name = resolve(node.args[0])
                if name:
                    ref_sites.append((name, src, node.lineno))

    real_repo = (root / _REGISTRY_REL).exists()
    if not real_repo:
        # fixture tree: judge sites against an empty registry is wrong;
        # only surface obviously malformed emissions (none detectable
        # without a registry) — return parse findings only
        return out
    from ..obs.metric_names import REGISTRY

    for name, kind, src, line, _ in emit_sites:
        m = REGISTRY.get(name)
        if m is None:
            out.append(Finding(
                checker="metrics", rule="unregistered_metric",
                path=src.rel, line=line, symbol=name,
                message=f"emit site for {name} has no "
                        "obs/metric_names.REGISTRY row"))
        elif m.kind != kind:
            out.append(Finding(
                checker="metrics", rule="kind_mismatch",
                path=src.rel, line=line, symbol=name,
                message=f"{name} emitted as {kind} but registered as "
                        f"{m.kind}"))
    for name, src, line in ref_sites:
        if name not in REGISTRY:
            out.append(Finding(
                checker="metrics", rule="unregistered_metric",
                path=src.rel, line=line, symbol=name,
                message=f"reference site for {name} has no "
                        "obs/metric_names.REGISTRY row (health rule / "
                        "aggregator reading a series nobody emits?)"))
    # the unemitted rule accepts any in-package MENTION as evidence of
    # life: several emitters build names from tuples/dicts (telemetry's
    # SERIES table) where the literal and the emit call are separated
    emitted_in_pkg = {n for n, _, _, _, in_pkg in emit_sites if in_pkg}
    emitted_in_pkg |= mentions
    for name in sorted(set(REGISTRY) - emitted_in_pkg):
        out.append(Finding(
            checker="metrics", rule="unemitted_metric",
            path=_REGISTRY_REL, line=0, symbol=name,
            message=f"REGISTRY lists {name} but no emit site exists in "
                    "tpu_tree_search/ — delete the row or restore the "
                    "series"))
    from . import docs
    out.extend(docs.check_block(root, "tts-metric-registry"))
    return out
