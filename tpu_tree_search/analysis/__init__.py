"""tts-lint: repo-native static invariant analysis.

The runtime stack's correctness rests on conventions that nine PRs of
review passes kept re-teaching by hand: static flags stay OUT of traced
code (bit-identical off-modes, no silent retraces), shared state is
touched only under its documented lock (the AOTCache / ExecutorCache /
IncumbentBoard / HealthMonitor race fixes), every ``TTS_*`` knob is
single-sourced in ``utils/config.py``, and every ``tts_*`` metric name
matches one checked-in registry. This package turns those conventions
into machine-checked invariants at COMMIT time — the same move
``obs/audit.py`` made for node conservation at runtime.

Four checkers (one module each):

- :mod:`trace_safety` — walks functions reachable from jit / shard_map /
  ``lax.{cond,switch,scan,while_loop}`` entry points in ``engine/`` and
  ``ops/`` and flags host-sync + nondeterminism hazards inside traced
  code (``.item()``, ``np.asarray`` on traced values, ``time.time()``,
  env reads — a static flag read inside a traced function is a silent
  retrace hazard);
- :mod:`locks` — a ``# guarded-by: self._lock`` annotation grammar on
  shared attributes of the threaded classes, verifying every mutation
  site sits inside the matching ``with`` block, plus a
  lock-acquisition-order graph that reports cycles;
- :mod:`knobs` — ``TTS_*`` env reads outside ``utils/config.py`` are
  findings; every knob needs a ``config.KNOBS`` row and a README
  mention;
- :mod:`metric_registry` — every ``tts_*`` metric name at an emit or
  reference site must appear in ``obs/metric_names.REGISTRY`` (and
  vice versa), catching name drift between emit sites, README tables,
  health rules and dashboards.

Findings are :class:`core.Finding` records with stable fingerprints; a
checked-in waiver file (``.tts-lint-waivers.json``: fingerprint +
written reason) triages pre-existing true-but-deferred violations
explicitly. ``tools/tts_lint.py`` is the CLI; the CI ``lint`` leg runs
it blocking — any unwaived finding fails the build.
"""

from __future__ import annotations

from . import docs, knobs, locks, metric_registry, trace_safety
from .core import (Finding, LintReport, Waivers, load_waivers, repo_files,
                   repo_root)

__all__ = ["Finding", "LintReport", "Waivers", "run_all", "repo_root",
           "repo_files", "load_waivers", "docs", "knobs", "locks",
           "metric_registry", "trace_safety"]

CHECKERS = {
    "trace_safety": trace_safety.check,
    "locks": locks.check,
    "knobs": knobs.check,
    "metrics": metric_registry.check,
}


def run_all(root=None, checkers=None, waivers: Waivers | None = None
            ) -> LintReport:
    """Run the requested checkers (all by default) over the repo at
    `root` and fold in the waiver file. Returns a :class:`LintReport`
    whose ``ok`` is True iff no unwaived finding survived."""
    root = repo_root(root)
    findings: list[Finding] = []
    for name in (checkers or CHECKERS):
        findings.extend(CHECKERS[name](root))
    if waivers is None:
        waivers = load_waivers(root)
    return LintReport.build(findings, waivers)
