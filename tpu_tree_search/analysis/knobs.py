"""Knob-registry checker: every TTS_* env knob is single-sourced.

``utils/config.py`` owns the knob registry (``config.KNOBS``) and the
typed accessors (``env_flag`` / ``env_str`` / ``env_int`` /
``env_float`` / ``env_ints`` / ``set_env``). This checker enforces the
single-sourcing three ways:

- **scattered_env_read / scattered_env_write** — any raw
  ``os.environ`` / ``os.getenv`` access of a ``TTS_*`` literal outside
  ``utils/config.py`` is a finding. (The two legitimate exceptions in
  the tree — reads that must happen BEFORE the package, and therefore
  jax, can be imported — carry explicit waivers.)
- **unregistered_knob** — a ``TTS_*`` name used at any accessor or raw
  site that has no ``config.KNOBS`` row. The accessors also refuse
  these at runtime; the checker catches the ones runtime never reaches.
- **unreferenced_knob / knob_undocumented** — registry rows no code
  references (dead knobs drift into lies) and rows README never
  mentions (the generated registry table normally satisfies this —
  see :mod:`docs`).

Constant indirection is resolved: ``AOT_CACHE_ENV = "TTS_AOT_CACHE"``
in config (or ``ENV_FLAG = ...`` in telemetry) makes
``env_str(cfg.AOT_CACHE_ENV)`` count as a reference to the underlying
knob.

The registry-side rules run only when the scanned root IS this repo
(it contains ``tpu_tree_search/utils/config.py``); fixture trees in
tests exercise just the site-side rules.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, parse_many, repo_root

__all__ = ["check", "KNOB_DIRS"]

KNOB_DIRS = ("tpu_tree_search", "tools", "tests", "bench.py",
             "__graft_entry__.py")

_ACCESSORS = {"env_flag", "env_str", "env_int", "env_float", "env_ints",
              "set_env"}
_KNOB_RE = re.compile(r"^TTS_[A-Z0-9_]+$")
_CONFIG_REL = "tpu_tree_search/utils/config.py"
_ANALYSIS_PREFIX = "tpu_tree_search/analysis/"


def _dotted(expr) -> str:
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    elif isinstance(expr, ast.Call):
        parts.append("()")
    return ".".join(reversed(parts))


def _literal_knob(expr) -> str | None:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
            and _KNOB_RE.match(expr.value):
        return expr.value
    return None


def check(root=None) -> list:
    root = repo_root(root)
    sources, findings = parse_many(root, KNOB_DIRS)
    out: list = list(findings)

    # ---- constant indirection: NAME = "TTS_X" at module/class level
    const_map: dict = {}
    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and \
                    _literal_knob(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        const_map[t.id] = node.value.value

    def resolve_name(expr) -> str | None:
        lit = _literal_knob(expr)
        if lit:
            return lit
        if isinstance(expr, ast.Name):
            return const_map.get(expr.id)
        if isinstance(expr, ast.Attribute):
            return const_map.get(expr.attr)
        return None

    referenced: set = set()

    for src in sources:
        in_config = src.rel == _CONFIG_REL
        if src.rel.startswith(_ANALYSIS_PREFIX):
            continue          # the linter's own pattern tables
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                fd = _dotted(node.func)
                tail = fd.split(".")[-1]
                if tail in ("get", "getenv", "pop", "setdefault") and \
                        ("environ" in fd or tail == "getenv"):
                    knob = resolve_name(node.args[0]) if node.args \
                        else None
                    if knob:
                        referenced.add(knob)
                        if not in_config:
                            # pop/setdefault MUTATE the environment —
                            # misfiling them as reads would point the
                            # fix at the read accessors (and stamp the
                            # wrong rule into the waiver fingerprint)
                            write = tail in ("pop", "setdefault")
                            remedy = ("config.set_env (tests: "
                                      "monkeypatch.setenv/delenv)"
                                      if write
                                      else "the config env_* accessors")
                            out.append(Finding(
                                checker="knobs",
                                rule=("scattered_env_write" if write
                                      else "scattered_env_read"),
                                path=src.rel, line=node.lineno,
                                symbol=knob,
                                message=f"raw {fd}({knob!r}) outside "
                                        f"utils/config.py — use "
                                        f"{remedy}"))
                elif tail in _ACCESSORS:
                    knob = resolve_name(node.args[0]) if node.args \
                        else None
                    if knob:
                        referenced.add(knob)
            elif isinstance(node, ast.Subscript):
                if not _dotted(node.value).endswith("environ"):
                    continue
                knob = resolve_name(node.slice)
                if not knob:
                    continue
                referenced.add(knob)
                if in_config:
                    continue
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                out.append(Finding(
                    checker="knobs",
                    rule=("scattered_env_write" if write
                          else "scattered_env_read"),
                    path=src.rel, line=node.lineno, symbol=knob,
                    message=(f"raw os.environ[{knob!r}] "
                             f"{'write' if write else 'read'} outside "
                             "utils/config.py — use config.set_env / "
                             "the env_* accessors")))

    # every knob literal seen ANYWHERE (incl. const defs) counts as a
    # reference for the dead-knob rule. Registration is only REQUIRED
    # for names seen outside tests/ — the linter's own test fixtures
    # use synthetic TTS_* names on purpose (and a test typo'ing a real
    # knob still fails at runtime: the accessors refuse unregistered
    # names).
    required: set = set()
    for src in sources:
        if src.rel.startswith(_ANALYSIS_PREFIX):
            continue
        for node in ast.walk(src.tree):
            lit = _literal_knob(node) if isinstance(node, ast.Constant) \
                else None
            if lit:
                referenced.add(lit)
                if not src.rel.startswith("tests/"):
                    required.add(lit)

    # ---- registry-side rules (real repo only)
    if not (root / _CONFIG_REL).exists():
        return out
    from ..utils.config import KNOBS
    for knob in sorted(required):
        if knob not in KNOBS:
            # anchor to the first site that used it
            site = next((f for f in out if f.symbol == knob), None)
            out.append(Finding(
                checker="knobs", rule="unregistered_knob",
                path=site.path if site else _CONFIG_REL,
                line=site.line if site else 0, symbol=knob,
                message=f"{knob} is used but has no config.KNOBS row "
                        "(every knob needs a registered default + doc "
                        "line)"))
    for knob in sorted(set(KNOBS) - referenced):
        out.append(Finding(
            checker="knobs", rule="unreferenced_knob",
            path=_CONFIG_REL, line=0, symbol=knob,
            message=f"config.KNOBS registers {knob} but no code "
                    "references it — dead registry rows drift into "
                    "lies; delete the row or wire the knob"))
    readme = root / "README.md"
    if readme.exists():
        text = readme.read_text(encoding="utf-8")
        for knob in sorted(KNOBS):
            if knob not in text:
                out.append(Finding(
                    checker="knobs", rule="knob_undocumented",
                    path="README.md", line=0, symbol=knob,
                    message=f"registered knob {knob} is not mentioned "
                            "in README.md (regenerate the registry "
                            "table: tools/tts_lint.py --write-docs)"))
    from . import docs
    out.extend(docs.check_block(root, "tts-knob-registry"))
    return out
