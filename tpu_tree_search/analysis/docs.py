"""Generated registry documentation: the README knob/metric tables.

The hand-maintained README knob and metric lists were exactly the drift
surface the registries exist to kill, so they are GENERATED here from
``utils/config.KNOBS`` and ``obs/metric_names.REGISTRY`` and spliced
between HTML-comment markers in README.md::

    <!-- BEGIN GENERATED: tts-knob-registry -->
    ... (do not edit by hand) ...
    <!-- END GENERATED: tts-knob-registry -->

``tools/tts_lint.py --write-docs`` rewrites the blocks;
:func:`check_block` (run by the knob and metric checkers) reports a
``docs_drift`` finding when a block is missing or stale, so CI fails a
registry edit that forgot to regenerate the docs.
"""

from __future__ import annotations

from .core import Finding, repo_root

__all__ = ["render_block", "write_docs", "check_block", "BLOCKS"]

_SCOPE_TITLES = (("runtime", "Runtime"), ("bench", "bench.py"),
                 ("tool", "tools/ drivers"), ("test", "Test suite"))


def _fmt_default(v) -> str:
    if v is None:
        return "unset"
    if v is True:
        return "on"
    if v is False:
        return "off"
    return f"`{v}`"


def render_knob_table() -> str:
    from ..utils.config import KNOBS
    lines = ["_Generated from `utils/config.KNOBS` by "
             "`tools/tts_lint.py --write-docs`; edit the registry, "
             "not this table._", ""]
    for scope, title in _SCOPE_TITLES:
        rows = [k for k in KNOBS.values() if k.scope == scope]
        if not rows:
            continue
        lines += [f"**{title}**", "",
                  "| knob | type | default | what it does |",
                  "|---|---|---|---|"]
        lines += [f"| `{k.name}` | {k.kind} | {_fmt_default(k.default)} "
                  f"| {k.doc} |" for k in rows]
        lines.append("")
    return "\n".join(lines).rstrip()


def render_metric_table() -> str:
    from ..obs.metric_names import REGISTRY
    lines = ["_Generated from `obs/metric_names.REGISTRY` by "
             "`tools/tts_lint.py --write-docs`; edit the registry, "
             "not this table._", "",
             "| metric | type | labels | meaning |", "|---|---|---|---|"]
    for m in sorted(REGISTRY.values(), key=lambda m: m.name):
        labels = f"`{m.labels}`" if m.labels else "—"
        lines.append(f"| `{m.name}` | {m.kind} | {labels} | {m.doc} |")
    return "\n".join(lines)


BLOCKS = {
    "tts-knob-registry": render_knob_table,
    "tts-metric-registry": render_metric_table,
}


def _markers(block: str) -> tuple:
    return (f"<!-- BEGIN GENERATED: {block} -->",
            f"<!-- END GENERATED: {block} -->")


def _splice(text: str, block: str, body: str) -> str | None:
    begin, end = _markers(block)
    i = text.find(begin)
    j = text.find(end)
    if i < 0 or j < 0 or j < i:
        return None
    return text[:i + len(begin)] + "\n" + body + "\n" + text[j:]


def write_docs(root=None) -> list:
    """Regenerate every marked README block; returns the block names
    that changed. Blocks whose markers are absent are left alone (the
    drift check reports them)."""
    root = repo_root(root)
    path = root / "README.md"
    text = path.read_text(encoding="utf-8")
    changed = []
    for block, render in BLOCKS.items():
        new = _splice(text, block, render())
        if new is not None and new != text:
            text = new
            changed.append(block)
    if changed:
        path.write_text(text, encoding="utf-8")
    return changed


def check_block(root, block: str) -> list:
    """``docs_drift`` findings for one generated README block (run by
    the checker that owns the corresponding registry)."""
    root = repo_root(root)
    path = root / "README.md"
    if not path.exists():
        return []
    text = path.read_text(encoding="utf-8")
    begin, end = _markers(block)
    i, j = text.find(begin), text.find(end)
    if i < 0 or j < 0 or j < i:
        return [Finding(
            checker="metrics" if "metric" in block else "knobs",
            rule="docs_drift", path="README.md", line=0, symbol=block,
            message=f"README.md is missing the generated {block} block "
                    f"(add the {begin} / {end} markers and run "
                    "tools/tts_lint.py --write-docs)")]
    current = text[i + len(begin):j].strip("\n")
    want = BLOCKS[block]().strip("\n")
    if current != want:
        return [Finding(
            checker="metrics" if "metric" in block else "knobs",
            rule="docs_drift", path="README.md",
            line=text[:i].count("\n") + 1, symbol=block,
            message=f"generated {block} block is stale — run "
                    "tools/tts_lint.py --write-docs")]
    return []
