"""ctypes bindings to the native host runtime (libtreesearch_host.so).

Builds the shared library on first use with the system C++ compiler (no
pybind11 in the image; plain C ABI + ctypes keeps the binding dependency-
free). See src/treesearch_host.cpp for what lives natively and why.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

_DIR = pathlib.Path(__file__).parent
_SRC = _DIR / "src" / "treesearch_host.cpp"
_LIB = _DIR / "libtreesearch_host.so"

_lib = None


def build(force: bool = False) -> pathlib.Path:
    if force or not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
             "-pthread", str(_SRC), "-o", str(_LIB)],
            check=True, capture_output=True,
        )
    return _LIB


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        handle = ctypes.CDLL(str(build()))
        handle.tts_search.restype = ctypes.c_longlong
        handle.tts_search_from.restype = ctypes.c_longlong
        handle.tts_bfs_frontier.restype = ctypes.c_longlong
        handle.tts_nqueens.restype = ctypes.c_longlong
        handle.tts_async_start.restype = ctypes.c_void_p
        handle.tts_async_best.restype = ctypes.c_int
        handle.tts_async_best.argtypes = [ctypes.c_void_p]
        handle.tts_async_offer.restype = None
        handle.tts_async_offer.argtypes = [ctypes.c_void_p, ctypes.c_int]
        handle.tts_async_done.restype = ctypes.c_int
        handle.tts_async_done.argtypes = [ctypes.c_void_p]
        handle.tts_async_join.restype = ctypes.c_longlong
        handle.tts_async_join.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_ulonglong), ctypes.POINTER(ctypes.c_int)]
        _lib = handle
    return _lib


def processing_times(inst: int) -> np.ndarray:
    h = lib()
    m, n = h.tts_nb_machines(inst), h.tts_nb_jobs(inst)
    out = np.zeros((m, n), dtype=np.int32)
    h.tts_processing_times(inst, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    return out


def optimal_makespan(inst: int) -> int:
    return lib().tts_optimal_makespan(inst)


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           max_nodes: int = 0):
    """Fast sequential DFS oracle. Returns (tree, sol, best, expanded)."""
    p = np.ascontiguousarray(p_times, dtype=np.int32)
    m, n = p.shape
    tree = ctypes.c_ulonglong()
    sol = ctypes.c_ulonglong()
    best = ctypes.c_int()
    expanded = lib().tts_search(
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n, m, lb_kind,
        0 if init_ub is None else int(init_ub), ctypes.c_longlong(max_nodes),
        ctypes.byref(tree), ctypes.byref(sol), ctypes.byref(best))
    return int(tree.value), int(sol.value), int(best.value), int(expanded)


def search_from(p_times: np.ndarray, prmu: np.ndarray, depth: np.ndarray,
                lb_kind: int = 1, init_ub: int | None = None,
                n_threads: int = 0):
    """Multi-threaded DFS from a seed set — the heterogeneous hand-off
    path (device residual pool -> host threads). Returns
    (tree, sol, best, expanded)."""
    import os
    p = np.ascontiguousarray(p_times, dtype=np.int32)
    m, n = p.shape
    prmu = np.ascontiguousarray(prmu, dtype=np.int16).reshape(-1, n)
    depth = np.ascontiguousarray(depth, dtype=np.int16).reshape(-1)
    if n_threads <= 0:
        n_threads = max(1, (os.cpu_count() or 2) - 1)
    tree = ctypes.c_ulonglong()
    sol = ctypes.c_ulonglong()
    best = ctypes.c_int()
    expanded = lib().tts_search_from(
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n, m, lb_kind,
        0 if init_ub is None else int(init_ub),
        prmu.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        depth.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        ctypes.c_longlong(prmu.shape[0]), int(n_threads),
        ctypes.byref(tree), ctypes.byref(sol), ctypes.byref(best))
    return int(tree.value), int(sol.value), int(best.value), int(expanded)


def bfs_frontier(p_times: np.ndarray, lb_kind: int, init_ub: int | None,
                 target: int, cap: int = 1 << 22):
    """Native BFS warm-up. Returns (prmu, depth, tree, sol, best)."""
    p = np.ascontiguousarray(p_times, dtype=np.int32)
    m, n = p.shape
    prmu = np.zeros((cap, n), dtype=np.int16)
    depth = np.zeros(cap, dtype=np.int16)
    tree = ctypes.c_ulonglong()
    sol = ctypes.c_ulonglong()
    best = ctypes.c_int()
    got = lib().tts_bfs_frontier(
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n, m, lb_kind,
        0 if init_ub is None else int(init_ub),
        ctypes.c_longlong(target), ctypes.c_longlong(cap),
        prmu.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        depth.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        ctypes.byref(tree), ctypes.byref(sol), ctypes.byref(best))
    if got < 0:
        raise RuntimeError("frontier exceeded cap")
    n_nodes = int(got)
    return (prmu[:n_nodes].copy(), depth[:n_nodes].copy(),
            int(tree.value), int(sol.value), int(best.value))


def async_start(p_times: np.ndarray, prmu: np.ndarray, depth: np.ndarray,
                lb_kind: int = 1, init_ub: int | None = None,
                n_threads: int = 0):
    """Start a background multi-threaded DFS over a seed set and return an
    opaque session handle — the CONCURRENT heterogeneous tier: the caller
    keeps driving the device loop while these threads run, merging
    incumbents through async_best/async_offer (checkBest semantics,
    reference: pfsp_multigpu_cuda.c:30-50, 159-263). The native side
    copies all inputs before returning."""
    import os
    p = np.ascontiguousarray(p_times, dtype=np.int32)
    m, n = p.shape
    prmu = np.ascontiguousarray(prmu, dtype=np.int16).reshape(-1, n)
    depth = np.ascontiguousarray(depth, dtype=np.int16).reshape(-1)
    if n_threads <= 0:
        n_threads = max(1, (os.cpu_count() or 2) - 1)
    h = lib().tts_async_start(
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n, m, lb_kind,
        0 if init_ub is None else int(init_ub),
        prmu.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        depth.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        ctypes.c_longlong(prmu.shape[0]), int(n_threads))
    return h


def async_best(handle) -> int:
    """Current shared incumbent of a running session."""
    return int(lib().tts_async_best(handle))


def async_offer(handle, best: int) -> None:
    """Merge an externally-found incumbent into the session (CAS min)."""
    lib().tts_async_offer(handle, int(best))


def async_done(handle) -> bool:
    """True when every session thread has drained its pool."""
    return bool(lib().tts_async_done(handle))


def async_join(handle):
    """Join the session and free it. Returns (tree, sol, best, expanded)."""
    tree = ctypes.c_ulonglong()
    sol = ctypes.c_ulonglong()
    best = ctypes.c_int()
    expanded = lib().tts_async_join(handle, ctypes.byref(tree),
                                    ctypes.byref(sol), ctypes.byref(best))
    return int(tree.value), int(sol.value), int(best.value), int(expanded)


def nqueens(n: int, g: int = 1):
    """Native N-Queens backtracking. Returns (tree, sol, expanded)."""
    tree = ctypes.c_ulonglong()
    sol = ctypes.c_ulonglong()
    expanded = lib().tts_nqueens(n, g, ctypes.byref(tree), ctypes.byref(sol))
    return int(tree.value), int(sol.value), int(expanded)
