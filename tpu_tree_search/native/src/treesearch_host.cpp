// Native host runtime for tpu-tree-search.
//
// The reference engine's host side is C (pool management, sequential
// search, instance generation — pfsp/pfsp_c.c, pfsp/lib/*). The TPU
// framework keeps its hot path on-device (JAX/XLA), but still needs a fast
// host engine for: BFS warm-up seeding of device pools (step 1 of the
// reference's 3-phase schedule), golden-count oracles for tests, and a
// host-side drain analogous to the reference's step 3. This file is that
// runtime, written as idiomatic C++17 and exposed through a C ABI consumed
// via ctypes (tpu_tree_search/native/__init__.py).
//
// Algorithmic contracts mirrored exactly (validated against the Python
// oracle and the reference counts in tests):
//   - Taillard generator: Lehmer LCG with float32 division
//     (reference: pfsp/lib/c_taillard.c:76-105)
//   - LB1 / LB1_d / LB2 bounds (c_bound_simple.c, c_bound_johnson.c)
//   - decompose counting semantics (PFSP_lib.c:7-129)
//   - N-Queens safety + branching (nqueens/nqueens_c.c:80-117)

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

namespace {

constexpr int kIntMax = std::numeric_limits<int>::max();

// ---------------------------------------------------------------------- //
// Taillard instances

const long kTimeSeeds[120] = {
    873654221,  379008056,  1866992158, 216771124,  495070989,
    402959317,  1369363414, 2021925980, 573109518,  88325120,
    587595453,  1401007982, 873136276,  268827376,  1634173168,
    691823909,  73807235,   1273398721, 2065119309, 1672900551,
    479340445,  268827376,  1958948863, 918272953,  555010963,
    2010851491, 1519833303, 1748670931, 1923497586, 1829909967,
    1328042058, 200382020,  496319842,  1203030903, 1730708564,
    450926852,  1303135678, 1273398721, 587288402,  248421594,
    1958948863, 575633267,  655816003,  1977864101, 93805469,
    1803345551, 49612559,   1899802599, 2013025619, 578962478,
    1539989115, 691823909,  655816003,  1315102446, 1949668355,
    1923497586, 1805594913, 1861070898, 715643788,  464843328,
    896678084,  1179439976, 1122278347, 416756875,  267829958,
    1835213917, 1328833962, 1418570761, 161033112,  304212574,
    1539989115, 655816003,  960914243,  1915696806, 2013025619,
    1168140026, 1923497586, 167698528,  1528387973, 993794175,
    450926852,  1462772409, 1021685265, 83696007,   508154254,
    1861070898, 26482542,   444956424,  2115448041, 118254244,
    471503978,  1215892992, 135346136,  1602504050, 160037322,
    551454346,  519485142,  383947510,  1968171878, 540872513,
    2013025619, 475051709,  914834335,  810642687,  1019331795,
    2056065863, 1342855162, 1325809384, 1988803007, 765656702,
    1368624604, 450181436,  1927888393, 1759567256, 606425239,
    19268348,   1298201670, 2041736264, 379756761,  28837162};

const int kOptimal[120] = {
    1278, 1359, 1081, 1293, 1235, 1195, 1234, 1206, 1230, 1108,
    1582, 1659, 1496, 1377, 1419, 1397, 1484, 1538, 1593, 1591,
    2297, 2099, 2326, 2223, 2291, 2226, 2273, 2200, 2237, 2178,
    2724, 2834, 2621, 2751, 2863, 2829, 2725, 2683, 2552, 2782,
    2991, 2867, 2839, 3063, 2976, 3006, 3093, 3037, 2897, 3065,
    3846, 3699, 3640, 3719, 3610, 3679, 3704, 3691, 3741, 3755,
    5493, 5268, 5175, 5014, 5250, 5135, 5246, 5094, 5448, 5322,
    5770, 5349, 5676, 5781, 5467, 5303, 5595, 5617, 5871, 5845,
    6173, 6183, 6252, 6254, 6285, 6331, 6223, 6372, 6247, 6404,
    10862, 10480, 10922, 10889, 10524, 10329, 10854, 10730, 10438, 10675,
    11158, 11160, 11281, 11275, 11259, 11176, 11337, 11301, 11146, 11284,
    26040, 26500, 26371, 26456, 26334, 26469, 26389, 26560, 26005, 26457};

int jobsOf(int inst) {
  if (inst > 110) return 500;
  if (inst > 90) return 200;
  if (inst > 60) return 100;
  if (inst > 30) return 50;
  return 20;
}

int machinesOf(int inst) {
  if (inst > 100) return 20;
  if (inst > 90) return 10;
  if (inst > 80) return 20;
  if (inst > 70) return 10;
  if (inst > 60) return 5;
  if (inst > 50) return 20;
  if (inst > 40) return 10;
  if (inst > 30) return 5;
  if (inst > 20) return 20;
  if (inst > 10) return 10;
  return 5;
}

// One Lehmer LCG draw in [lo, hi]; float-division rounding per the
// published generator (c_taillard.c:76-88).
long lehmerDraw(long& seed, long lo, long hi) {
  constexpr long m = 2147483647, a = 16807, b = 127773, c = 2836;
  long k = seed / b;
  seed = a * (seed % b) - k * c;
  if (seed < 0) seed += m;
  double u = static_cast<float>(seed) / static_cast<float>(m);
  return lo + static_cast<long>(u * (hi - lo + 1));
}

void generateMatrix(int inst, int* out) {
  int n = jobsOf(inst), mm = machinesOf(inst);
  long seed = kTimeSeeds[inst - 1];
  for (int i = 0; i < mm * n; ++i) out[i] = static_cast<int>(lehmerDraw(seed, 1, 99));
}

// ---------------------------------------------------------------------- //
// Bounds

struct Bounds {
  int jobs, machines, pairs;
  std::vector<int> p;          // machines x jobs
  std::vector<int> minHeads, minTails;
  // LB2 all-pairs Johnson tables
  std::vector<int> pairM1, pairM2;    // (pairs)
  std::vector<int> lag;               // (pairs x jobs)
  std::vector<int> johnson;           // (pairs x jobs) job ids

  Bounds(const int* pt, int j, int m) : jobs(j), machines(m), p(pt, pt + m * j) {
    buildHeadsTails();
    buildJohnson();
  }

  int pt(int mach, int job) const { return p[mach * jobs + job]; }

  void buildHeadsTails() {
    minHeads.assign(machines, kIntMax);
    minTails.assign(machines, kIntMax);
    minHeads[0] = 0;
    minTails[machines - 1] = 0;
    for (int job = 0; job < jobs; ++job) {
      int acc = 0;
      for (int k = 0; k + 1 < machines; ++k) {
        acc += pt(k, job);
        minHeads[k + 1] = std::min(minHeads[k + 1], acc);
      }
      acc = 0;
      for (int k = machines - 1; k > 0; --k) {
        acc += pt(k, job);
        minTails[k - 1] = std::min(minTails[k - 1], acc);
      }
    }
  }

  void buildJohnson() {
    pairs = machines * (machines - 1) / 2;
    pairM1.reserve(pairs);
    pairM2.reserve(pairs);
    for (int a = 0; a + 1 < machines; ++a)
      for (int b = a + 1; b < machines; ++b) {
        pairM1.push_back(a);
        pairM2.push_back(b);
      }
    lag.assign(static_cast<size_t>(pairs) * jobs, 0);
    johnson.resize(static_cast<size_t>(pairs) * jobs);
    std::vector<int> order(jobs);
    for (int s = 0; s < pairs; ++s) {
      int m1 = pairM1[s], m2 = pairM2[s];
      for (int job = 0; job < jobs; ++job)
        for (int k = m1 + 1; k < m2; ++k) lag[s * jobs + job] += pt(k, job);
      // Johnson's rule for the 2-machine relaxation (ties by job id; any
      // tie-consistent order is optimal so bound values are unaffected)
      for (int job = 0; job < jobs; ++job) order[job] = job;
      const int* lg = &lag[s * jobs];
      std::stable_sort(order.begin(), order.end(), [&](int x, int y) {
        int ax = pt(m1, x) + lg[x], bx = pt(m2, x) + lg[x];
        int ay = pt(m1, y) + lg[y], by = pt(m2, y) + lg[y];
        int px = ax >= bx, py = ay >= by;     // partition: 0 first
        if (px != py) return px < py;
        int kx = px ? -bx : ax;               // asc ptm1 / desc ptm2
        int ky = py ? -by : ay;
        return kx < ky;
      });
      std::copy(order.begin(), order.end(), johnson.begin() + s * jobs);
    }
  }

  // Append one job to a prefix completion vector (add_forward semantics).
  void appendJob(int job, int* front) const {
    front[0] += pt(0, job);
    for (int k = 1; k < machines; ++k)
      front[k] = std::max(front[k - 1], front[k]) + pt(k, job);
  }

  // LB1 of a child = parent front + job, chained with remain and tails
  // (machine_bound_from_parts semantics, c_bound_simple.c:126-158).
  int lb1Child(const int* parentFront, const int* parentRemain, int job) const {
    int f = parentFront[0] + pt(0, job);
    int r = parentRemain[0] - pt(0, job);
    int chain = f + r;
    int lb = chain + minTails[0];
    for (int k = 1; k < machines; ++k) {
      f = std::max(f, parentFront[k]) + pt(k, job);
      r = parentRemain[k] - pt(k, job);
      chain = std::max(chain, f + r);
      lb = std::max(lb, chain + minTails[k]);
    }
    return lb;
  }

  // LB1_d of a child (add_front_and_bound semantics, c_bound_simple.c:218-244).
  int lb1dChild(const int* front, const int* remain, int job) const {
    int lb = front[0] + remain[0] + minTails[0];
    int t = front[0] + pt(0, job);
    for (int k = 1; k < machines; ++k) {
      int u = std::max(t, front[k]);
      lb = std::max(lb, u + remain[k] + minTails[k]);
      t = u + pt(k, job);
    }
    return lb;
  }

  // LB2 of a child whose prefix completion vector is `front` and whose
  // unscheduled set is `unsched` (list of job ids). Early exit once the
  // bound exceeds `cutoff` (c_bound_johnson.c:211-237 semantics).
  int lb2Child(const int* front, const std::vector<char>& isUnsched,
               int cutoff) const {
    int lb = 0;
    for (int s = 0; s < pairs; ++s) {
      int m1 = pairM1[s], m2 = pairM2[s];
      int t0 = front[m1], t1 = front[m2];
      const int* js = &johnson[s * jobs];
      const int* lg = &lag[s * jobs];
      for (int idx = 0; idx < jobs; ++idx) {
        int job = js[idx];
        if (!isUnsched[job]) continue;
        t0 += pt(m1, job);
        t1 = std::max(t1, t0 + lg[job]) + pt(m2, job);
      }
      int val = std::max(t1 + minTails[m2], t0 + minTails[m1]);
      lb = std::max(lb, val);
      if (lb > cutoff) break;
    }
    return lb;
  }
};

// ---------------------------------------------------------------------- //
// Sequential engine (DFS stack or BFS queue over an SoA node store)

struct NodeStore {
  int jobs;
  std::vector<int16_t> prmu;   // n x jobs
  std::vector<int16_t> depth;  // n
  size_t count = 0;
  size_t head = 0;             // BFS read cursor

  explicit NodeStore(int j) : jobs(j) {}

  void push(const int16_t* perm, int16_t d) {
    prmu.insert(prmu.end(), perm, perm + jobs);
    depth.push_back(d);
    ++count;
  }
  bool empty() const { return head >= count; }
  size_t live() const { return count - head; }
  // DFS pop (from the back)
  void popBack(int16_t* perm, int16_t* d) {
    --count;
    std::memcpy(perm, &prmu[count * jobs], jobs * sizeof(int16_t));
    *d = depth[count];
    prmu.resize(count * jobs);
    depth.resize(count);
  }
  // BFS pop (from the front; storage reclaimed lazily)
  void popFront(int16_t* perm, int16_t* d) {
    std::memcpy(perm, &prmu[head * jobs], jobs * sizeof(int16_t));
    *d = depth[head];
    ++head;
  }
};

struct SearchCounters {
  unsigned long long tree = 0, sol = 0;
  int best = kIntMax;
};

// Evaluate + branch one node, with exact decompose counting semantics
// (PFSP_lib.c:7-129). Pushes surviving children into `out`.
void expandNode(const Bounds& b, int lbKind, const int16_t* perm, int d,
                SearchCounters& c, NodeStore& out) {
  const int jobs = b.jobs, machines = b.machines;
  // prefix completion + unscheduled work per machine
  std::vector<int> front(machines, 0), remain(machines, 0);
  for (int i = 0; i < d; ++i) b.appendJob(perm[i], front.data());
  for (int k = 0; k < machines; ++k) {
    int tot = 0;
    for (int i = d; i < jobs; ++i) tot += b.pt(k, perm[i]);
    remain[k] = tot;
  }

  std::vector<char> isUnsched;
  std::vector<int> childFront;
  if (lbKind == 2) {
    isUnsched.assign(jobs, 0);
    for (int i = d; i < jobs; ++i) isUnsched[perm[i]] = 1;
    childFront.resize(machines);
  }

  std::vector<int16_t> child(perm, perm + jobs);
  for (int i = d; i < jobs; ++i) {
    int job = perm[i];
    int bound;
    switch (lbKind) {
      case 0: bound = b.lb1dChild(front.data(), remain.data(), job); break;
      case 2: {
        std::copy(front.begin(), front.end(), childFront.begin());
        b.appendJob(job, childFront.data());
        isUnsched[job] = 0;
        bound = b.lb2Child(childFront.data(), isUnsched, c.best);
        isUnsched[job] = 1;
        break;
      }
      default: bound = b.lb1Child(front.data(), remain.data(), job); break;
    }
    if (d + 1 == jobs) {
      ++c.sol;
      if (bound < c.best) c.best = bound;
    } else if (bound < c.best) {
      std::copy(perm, perm + jobs, child.begin());
      std::swap(child[d], child[i]);
      out.push(child.data(), static_cast<int16_t>(d + 1));
      ++c.tree;
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------- //
// C ABI

extern "C" {

int tts_nb_jobs(int inst) { return jobsOf(inst); }
int tts_nb_machines(int inst) { return machinesOf(inst); }
int tts_optimal_makespan(int inst) { return kOptimal[inst - 1]; }
void tts_processing_times(int inst, int* out) { generateMatrix(inst, out); }

// Depth-first B&B to exhaustion (or maxNodes expansions). initUb <= 0
// means an infinite initial incumbent. Returns expanded-node count.
long long tts_search(const int* p, int jobs, int machines, int lbKind,
                     int initUb, long long maxNodes,
                     unsigned long long* tree, unsigned long long* sol,
                     int* best) {
  Bounds b(p, jobs, machines);
  SearchCounters c;
  if (initUb > 0) c.best = initUb;
  NodeStore pool(jobs);
  std::vector<int16_t> root(jobs);
  for (int i = 0; i < jobs; ++i) root[i] = static_cast<int16_t>(i);
  pool.push(root.data(), 0);

  std::vector<int16_t> perm(jobs);
  int16_t d;
  long long expanded = 0;
  while (pool.count > 0 && (maxNodes <= 0 || expanded < maxNodes)) {
    pool.popBack(perm.data(), &d);
    ++expanded;
    expandNode(b, lbKind, perm.data(), d, c, pool);
  }
  *tree = c.tree;
  *sol = c.sol;
  *best = c.best;
  return expanded;
}

// Breadth-first warm-up: expand until the frontier reaches `target` nodes
// (or the tree is exhausted), then copy the frontier out. Returns the
// frontier size (-1 if it exceeds `cap`).
long long tts_bfs_frontier(const int* p, int jobs, int machines, int lbKind,
                           int initUb, long long target, long long cap,
                           int16_t* outPrmu, int16_t* outDepth,
                           unsigned long long* tree, unsigned long long* sol,
                           int* best) {
  Bounds b(p, jobs, machines);
  SearchCounters c;
  if (initUb > 0) c.best = initUb;
  NodeStore pool(jobs);
  std::vector<int16_t> root(jobs);
  for (int i = 0; i < jobs; ++i) root[i] = static_cast<int16_t>(i);
  pool.push(root.data(), 0);

  std::vector<int16_t> perm(jobs);
  int16_t d;
  while (!pool.empty() && static_cast<long long>(pool.live()) < target) {
    pool.popFront(perm.data(), &d);
    expandNode(b, lbKind, perm.data(), d, c, pool);
  }
  long long n = static_cast<long long>(pool.live());
  if (n > cap) return -1;
  for (long long i = 0; i < n; ++i) {
    std::memcpy(outPrmu + i * jobs, &pool.prmu[(pool.head + i) * jobs],
                jobs * sizeof(int16_t));
    outDepth[i] = pool.depth[pool.head + i];
  }
  *tree = c.tree;
  *sol = c.sol;
  *best = c.best;
  return n;
}

// Depth-first B&B from a given seed set — the heterogeneous hand-off
// path: the device engine pops its residual pool to the host and native
// threads finish it (the analogue of the reference's CPU workers and
// final CPU drain, pfsp_multigpu_cuda.c:236-263 / 487-495). Threads own
// round-robin stripes of the seeds (roundRobin_distribution semantics)
// and share the incumbent through an atomic (checkBest,
// pfsp_multigpu_cuda.c:30-50). Returns expanded-node count.
long long tts_search_from(const int* p, int jobs, int machines, int lbKind,
                          int initUb, const int16_t* seedPrmu,
                          const int16_t* seedDepth, long long nSeeds,
                          int nThreads, unsigned long long* tree,
                          unsigned long long* sol, int* best) {
  Bounds b(p, jobs, machines);
  if (nThreads < 1) nThreads = 1;
  std::atomic<int> sharedBest(initUb > 0 ? initUb : kIntMax);
  std::vector<unsigned long long> trees(nThreads, 0), sols(nThreads, 0);
  std::vector<long long> expandedPer(nThreads, 0);

  auto worker = [&](int t) {
    SearchCounters c;
    c.best = sharedBest.load(std::memory_order_relaxed);
    NodeStore pool(jobs);
    for (long long i = t; i < nSeeds; i += nThreads)
      pool.push(seedPrmu + i * jobs, seedDepth[i]);
    std::vector<int16_t> perm(jobs);
    int16_t d;
    while (pool.count > 0) {
      // refresh + publish the incumbent (checkBest both ways)
      int g = sharedBest.load(std::memory_order_relaxed);
      if (g < c.best) c.best = g;
      pool.popBack(perm.data(), &d);
      ++expandedPer[t];
      expandNode(b, lbKind, perm.data(), d, c, pool);
      if (c.best < g) {
        int cur = g;
        while (c.best < cur &&
               !sharedBest.compare_exchange_weak(cur, c.best)) {
        }
      }
    }
    trees[t] = c.tree;
    sols[t] = c.sol;
  };

  std::vector<std::thread> threads;
  for (int t = 1; t < nThreads; ++t) threads.emplace_back(worker, t);
  worker(0);
  for (auto& th : threads) th.join();

  unsigned long long tt = 0, ss = 0;
  long long expanded = 0;
  for (int t = 0; t < nThreads; ++t) {
    tt += trees[t];
    ss += sols[t];
    expanded += expandedPer[t];
  }
  *tree = tt;
  *sol = ss;
  *best = sharedBest.load();
  return expanded;
}

// Asynchronous host search session — the CONCURRENT heterogeneous tier.
// The reference's -C 1 runs CPU worker threads concurrently with the GPU
// managers, all sharing the incumbent through checkBest CAS
// (pfsp_multigpu_cuda.c:61-69, 159-263). Here the Python side drives the
// compiled device loop in segments while these native threads consume
// their own seed share; every segment boundary merges incumbents both
// ways with tts_async_best / tts_async_offer — so a bound found by
// either side prunes the other while both are still running.

namespace {

struct AsyncSearch {
  Bounds bounds;
  int lbKind;
  int nThreads;
  std::atomic<int> sharedBest;
  std::atomic<int> doneThreads{0};
  std::vector<unsigned long long> trees, sols;
  std::vector<long long> expandedPer;
  std::vector<int16_t> seedPrmu, seedDepth;  // owned copies
  long long nSeeds;
  std::vector<std::thread> threads;

  AsyncSearch(const int* p, int jobs, int machines, int lb, int initUb,
              const int16_t* sp, const int16_t* sd, long long n, int nt)
      : bounds(p, jobs, machines),
        lbKind(lb),
        nThreads(nt < 1 ? 1 : nt),
        sharedBest(initUb > 0 ? initUb : kIntMax),
        trees(nThreads, 0),
        sols(nThreads, 0),
        expandedPer(nThreads, 0),
        seedPrmu(sp, sp + n * jobs),
        seedDepth(sd, sd + n),
        nSeeds(n) {}

  void worker(int t) {
    const int jobs = bounds.jobs;
    SearchCounters c;
    c.best = sharedBest.load(std::memory_order_relaxed);
    NodeStore pool(jobs);
    for (long long i = t; i < nSeeds; i += nThreads)
      pool.push(&seedPrmu[i * jobs], seedDepth[i]);
    std::vector<int16_t> perm(jobs);
    int16_t d;
    while (pool.count > 0) {
      int g = sharedBest.load(std::memory_order_relaxed);
      if (g < c.best) c.best = g;
      pool.popBack(perm.data(), &d);
      ++expandedPer[t];
      expandNode(bounds, lbKind, perm.data(), d, c, pool);
      if (c.best < g) {
        int cur = g;
        while (c.best < cur &&
               !sharedBest.compare_exchange_weak(cur, c.best)) {
        }
      }
    }
    trees[t] = c.tree;
    sols[t] = c.sol;
    doneThreads.fetch_add(1);
  }

  void start() {
    for (int t = 0; t < nThreads; ++t)
      threads.emplace_back(&AsyncSearch::worker, this, t);
  }
};

}  // namespace

void* tts_async_start(const int* p, int jobs, int machines, int lbKind,
                      int initUb, const int16_t* seedPrmu,
                      const int16_t* seedDepth, long long nSeeds,
                      int nThreads) {
  auto* s = new AsyncSearch(p, jobs, machines, lbKind, initUb, seedPrmu,
                            seedDepth, nSeeds, nThreads);
  s->start();
  return s;
}

int tts_async_best(void* h) {
  return static_cast<AsyncSearch*>(h)->sharedBest.load();
}

// Merge an externally-found incumbent (CAS min — checkBest semantics).
void tts_async_offer(void* h, int b) {
  auto& shared = static_cast<AsyncSearch*>(h)->sharedBest;
  int cur = shared.load();
  while (b < cur && !shared.compare_exchange_weak(cur, b)) {
  }
}

int tts_async_done(void* h) {
  auto* s = static_cast<AsyncSearch*>(h);
  return s->doneThreads.load() >= s->nThreads ? 1 : 0;
}

// Join all threads, write out the summed counters, free the session.
long long tts_async_join(void* h, unsigned long long* tree,
                         unsigned long long* sol, int* best) {
  auto* s = static_cast<AsyncSearch*>(h);
  for (auto& th : s->threads) th.join();
  unsigned long long tt = 0, ss = 0;
  long long expanded = 0;
  for (int t = 0; t < s->nThreads; ++t) {
    tt += s->trees[t];
    ss += s->sols[t];
    expanded += s->expandedPer[t];
  }
  *tree = tt;
  *sol = ss;
  *best = s->sharedBest.load();
  delete s;
  return expanded;
}

// N-Queens backtracking (reference semantics: nqueens_c.c:99-148).
long long tts_nqueens(int n, int g, unsigned long long* tree,
                      unsigned long long* sol) {
  std::vector<int16_t> pool;   // SoA boards
  std::vector<int16_t> depths;
  pool.reserve(1024 * n);
  for (int i = 0; i < n; ++i) pool.push_back(static_cast<int16_t>(i));
  depths.push_back(0);
  *tree = 0;
  *sol = 0;
  std::vector<int16_t> board(n);
  long long expanded = 0;
  while (!depths.empty()) {
    int d = depths.back();
    depths.pop_back();
    std::memcpy(board.data(), &pool[(depths.size()) * n], n * sizeof(int16_t));
    pool.resize(depths.size() * n);
    ++expanded;
    if (d == n) ++(*sol);
    for (int j = d; j < n; ++j) {
      bool safe = true;
      for (int rep = 0; rep < g; ++rep)
        for (int i = 0; i < d; ++i) {
          int delta = board[i] - board[j];
          if (delta == d - i || -delta == d - i) safe = false;
        }
      if (safe) {
        size_t base = pool.size();
        pool.resize(base + n);
        std::memcpy(&pool[base], board.data(), n * sizeof(int16_t));
        std::swap(pool[base + d], pool[base + j]);
        depths.push_back(static_cast<int16_t>(d + 1));
        ++(*tree);
      }
    }
  }
  return expanded;
}

}  // extern "C"
