"""Shared experiment-analysis helpers for the `data/` scripts.

The reference ships six pandas analysis scripts over its three CSV
schemas (reference: pfsp/data/multigpu-speedup.py:29-66,
multigpu-boxplot.py, multigpu-stats-analysis.py:43-70,
dist-multigpu-speedup-boxplot.py, dist-multigpu-comparison.py:17-23,
dist-multigpu-DWS.py:30-60). This module centralizes the parsing those
scripts share — the quoted "[a,b,c]" per-PU array cells, speedup tables,
work-stealing summaries — against the schema-compatible CSVs written by
`utils/csv_stats.py`.
"""

from __future__ import annotations

import csv
from collections import defaultdict

import numpy as np

from .stats import BoxplotStats, compute_boxplot_stats


def parse_array_cell(cell: str) -> np.ndarray:
    """Decode the reference's '[a,b,c]' quoted array cell
    (written by PFSP_statistic.c:7-30 / csv_stats._fmt_*_array)."""
    body = cell.strip().strip('"').strip()
    if body.startswith("["):
        body = body[1:-1]
    if not body:
        return np.zeros(0)
    return np.asarray([float(x) for x in body.split(",")])


def read_rows(path: str) -> list[dict]:
    """Read one of the experiment CSVs into dicts; array cells decoded."""
    out = []
    with open(path) as f:
        for row in csv.DictReader(f):
            rec = {}
            for k, v in row.items():
                if v is None:
                    continue
                v = v.strip()
                if v.startswith('"[') or v.startswith("["):
                    rec[k] = parse_array_cell(v)
                else:
                    try:
                        rec[k] = float(v) if "." in v else int(v)
                    except ValueError:
                        rec[k] = v
            out.append(rec)
    return out


def times_by_key(rows: list[dict], key_fields: tuple[str, ...],
                 time_field: str = "total_time") -> dict[tuple, list[float]]:
    """Group run times by a key (instance, PU count, ...) across
    repetitions — the groupby all the reference scripts start with."""
    groups: dict[tuple, list[float]] = defaultdict(list)
    for r in rows:
        key = tuple(r.get(f) for f in key_fields)
        groups[key].append(float(r[time_field]))
    return dict(groups)


def speedup_table(rows: list[dict], scale_field: str,
                  baseline_value) -> dict[tuple, dict]:
    """Median-time speedup of every (instance, scale) point vs the
    baseline scale (reference: multigpu-speedup.py:36-66 computes this
    vs the 1-GPU run with the PU->GPU map {4:1, 8:2, 16:4, 32:8};
    a TPU 'processing unit' is a mesh device, so the scale field is
    used directly)."""
    groups = times_by_key(rows, ("instance_id", scale_field))
    med = {k: float(np.median(v)) for k, v in groups.items()}
    out: dict[tuple, dict] = {}
    for (inst, scale), t in sorted(med.items()):
        base = med.get((inst, baseline_value))
        out[(inst, scale)] = {
            "median_time": t,
            "speedup": (base / t) if base else None,
            "efficiency": (base / t / (scale / baseline_value))
            if base and scale else None,
        }
    return out


def boxplot_by(rows: list[dict], key_fields: tuple[str, ...],
               time_field: str = "total_time") -> dict[tuple, BoxplotStats]:
    """Boxplot stats of run times per key (reference:
    multigpu-boxplot.py / dist-multigpu-speedup-boxplot.py; the math is
    the reference's own util.c toolkit, see utils/stats.py)."""
    return {k: compute_boxplot_stats(v)
            for k, v in times_by_key(rows, key_fields, time_field).items()}


def steal_summary(rows: list[dict]) -> list[dict]:
    """Work-stealing / load-balance success accounting per run
    (reference: dist-multigpu-DWS.py:30-60 sums WS0/WS1 successes per
    rank; here `steals` = balance rounds that delivered nodes and the
    dist column `all_dist_load_bal` = nodes received)."""
    out = []
    for r in rows:
        steals = r.get("all_steals_gpu", r.get("steals_gpu"))
        recv = r.get("all_dist_load_bal")
        rec = {
            "instance_id": r.get("instance_id"),
            "devices": r.get("comm_size", r.get("D")),
            "total_time": r.get("total_time"),
            "steal_rounds": (float(np.sum(steals))
                             if steals is not None else None),
            "nodes_received": (float(np.sum(recv))
                               if recv is not None else None),
        }
        out.append(rec)
    return out


def per_pu_breakdown(rows: list[dict], array_fields: tuple[str, ...]) \
        -> list[dict]:
    """Per-PU min/median/max of the requested array columns
    (reference: multigpu-stats-analysis.py:43-70 does this for the
    per-thread time-breakdown columns)."""
    out = []
    for r in rows:
        rec = {"instance_id": r.get("instance_id"),
               "devices": r.get("comm_size", r.get("D"))}
        for f in array_fields:
            arr = r.get(f)
            if arr is None or np.size(arr) == 0:
                continue
            rec[f] = {"min": float(np.min(arr)),
                      "median": float(np.median(arr)),
                      "max": float(np.max(arr)),
                      "sum": float(np.sum(arr))}
        out.append(rec)
    return out
