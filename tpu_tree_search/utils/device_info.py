"""Device introspection (the reference's gpu_info, common/gpu_util.cu:5-17,
re-expressed for the JAX device model) plus profiler hooks.

The reference instruments phases with omp_get_wtime() brackets and a
manual FLOP model (SURVEY.md §5). Here the compiled loop is opaque to
host timers, so the profiling story is `jax.profiler` traces (`trace`
below — inspect with TensorBoard or xprof) plus the engine's device-side
counters (tree/sol/evals/sent/recv/steals per worker).
"""

from __future__ import annotations

import contextlib

import jax


def apply_platform_override() -> None:
    """Honor a JAX_PLATFORMS request that names a non-TPU backend. The
    environment preloads jax via sitecustomize and pins the TPU plugin,
    so the env var alone cannot flip the platform — the jax.config path
    can. THE single copy of this recipe (fresh subprocesses — campaign
    workers, test children, __graft_entry__ — call it before their
    first backend touch; without it "CPU" subprocesses silently run on
    the live TPU)."""
    import os

    want = os.environ.get("JAX_PLATFORMS", "")
    if want and "axon" not in want and "tpu" not in want:
        jax.config.update("jax_platforms", want)


def describe_devices() -> list[dict]:
    """One record per addressable device (platform, kind, process, memory
    stats when the backend exposes them)."""
    out = []
    for d in jax.devices():
        rec = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "?"),
            "process": getattr(d, "process_index", 0),
        }
        try:
            stats = d.memory_stats()
            if stats:
                rec["bytes_in_use"] = stats.get("bytes_in_use")
                rec["bytes_limit"] = stats.get("bytes_limit")
        except Exception:
            pass
        out.append(rec)
    return out


def print_device_info() -> None:
    for rec in describe_devices():
        line = (f"Device {rec['id']}: {rec['platform']} ({rec['kind']}) "
                f"process {rec['process']}")
        if rec.get("bytes_limit"):
            line += (f", HBM {(rec.get('bytes_in_use') or 0) / 2**30:.2f}/"
                     f"{rec['bytes_limit'] / 2**30:.2f} GiB")
        print(line)


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler trace around a code block."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
