"""Device/host introspection (the reference's gpu_info,
common/gpu_util.cu:5-17, re-expressed for the JAX device model).

Three jobs, all read-only:

- platform plumbing: :func:`apply_platform_override` (the ONE copy of
  the sitecustomize-safe platform flip) and :func:`resolve_backend`
  (the bench driver's degrade-don't-die backend bootstrap);
- memory introspection: :func:`memory_snapshot` (per-device
  bytes-in-use/peak/limit, with a live-array fallback for backends
  like CPU whose ``memory_stats()`` returns nothing) and
  :func:`host_rss_bytes` — the read path under
  ``obs/resource.ResourceSampler``'s gauges and memory lanes;
- human-readable :func:`describe_devices` / :func:`print_device_info`
  (the CLI ``devices`` subcommand).

Profiling does NOT live here any more: the trace-around-a-block helper
moved to ``obs/profiler.trace`` (one-at-a-time session semantics; no
direct ``jax.profiler`` calls outside ``obs/``).
"""

from __future__ import annotations

import os

import jax


def apply_platform_override() -> None:
    """Honor a JAX_PLATFORMS request that names a non-TPU backend. The
    environment preloads jax via sitecustomize and pins the TPU plugin,
    so the env var alone cannot flip the platform — the jax.config path
    can. THE single copy of this recipe (fresh subprocesses — campaign
    workers, test children, __graft_entry__ — call it before their
    first backend touch; without it "CPU" subprocesses silently run on
    the live TPU)."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and "axon" not in want and "tpu" not in want:
        jax.config.update("jax_platforms", want)


def resolve_backend(probe=None, _update=None) -> tuple[str, bool]:
    """Initialize SOME usable backend; returns ``(platform, degraded)``.

    The bench driver's bootstrap: when the default backend fails to
    come up (the ``RuntimeError: Unable to initialize backend`` every
    ``BENCH_r0*.json`` tail showed on TPU-less hosts), fall back to
    automatic selection (``JAX_PLATFORMS=''`` — the failed backend's
    error is cached, so this lands on whatever works) and then to
    ``cpu`` explicitly. ``degraded=True`` means the run is NOT on the
    platform the environment asked for — callers must say so in their
    output instead of reporting a CPU rate as a TPU rate.

    `probe`/`_update` exist for tests (inject a failing probe without
    flipping the live process's real platform config)."""
    if probe is None:
        probe = jax.default_backend
    if _update is None:
        def _update(plats: str) -> None:
            os.environ["JAX_PLATFORMS"] = plats
            jax.config.update("jax_platforms", plats)
    try:
        return probe(), False
    except RuntimeError:
        pass
    last: RuntimeError | None = None
    for plats in ("", "cpu"):
        try:
            _update(plats)
            return probe(), True
        except RuntimeError as e:
            last = e
            continue
    raise RuntimeError(
        f"no usable JAX backend (tried default, '', 'cpu'): {last}")


def describe_devices() -> list[dict]:
    """One record per addressable device (platform, kind, process, memory
    stats when the backend exposes them)."""
    out = []
    for d in jax.devices():
        rec = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "?"),
            "process": getattr(d, "process_index", 0),
        }
        try:
            stats = d.memory_stats()
            if stats:
                rec["bytes_in_use"] = stats.get("bytes_in_use")
                rec["bytes_limit"] = stats.get("bytes_limit")
        except Exception:
            pass
        out.append(rec)
    return out


def _live_array_bytes() -> dict:
    """Live jax-array bytes per device id — the memory fallback for
    backends whose memory_stats() reports nothing (the CPU mesh the
    test suite runs on). Sharded arrays charge each shard to its own
    device."""
    out: dict = {}
    try:
        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 — introspection must never raise
        return out
    for a in arrays:
        try:
            for s in a.addressable_shards:
                out[s.device.id] = out.get(s.device.id, 0) \
                    + int(getattr(s.data, "nbytes", 0))
        except Exception:  # noqa: BLE001 — deleted/donated arrays race
            continue
    return out


def memory_snapshot() -> list[dict]:
    """Per-device memory record for the resource sampler: ``id``,
    ``platform``, ``bytes_in_use`` (backend-reported, else live-array
    bytes), ``peak_bytes_in_use``/``bytes_limit`` when the backend
    reports them (None keys are omitted)."""
    fallback = None
    out = []
    for d in jax.devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without the API
            stats = None
        rec = {"id": int(d.id), "platform": d.platform}
        if stats:
            rec["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            for src, dst in (("peak_bytes_in_use", "peak_bytes_in_use"),
                             ("bytes_limit", "bytes_limit")):
                if stats.get(src) is not None:
                    rec[dst] = int(stats[src])
        else:
            if fallback is None:
                fallback = _live_array_bytes()
            rec["bytes_in_use"] = int(fallback.get(d.id, 0))
        out.append(rec)
    return out


def host_rss_bytes() -> int | None:
    """This process's resident set size in bytes (Linux /proc, with a
    getrusage fallback); None when neither source exists."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        rss_kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss_kib) * 1024      # peak, not current — best effort
    except Exception:  # noqa: BLE001
        return None


def print_device_info() -> None:
    for rec in describe_devices():
        line = (f"Device {rec['id']}: {rec['platform']} ({rec['kind']}) "
                f"process {rec['process']}")
        if rec.get("bytes_limit"):
            line += (f", HBM {(rec.get('bytes_in_use') or 0) / 2**30:.2f}/"
                     f"{rec['bytes_limit'] / 2**30:.2f} GiB")
        print(line)
