from . import stats, csv_stats, config, compile_cache

__all__ = ["stats", "csv_stats", "config", "compile_cache"]
