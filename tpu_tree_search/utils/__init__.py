from . import stats, csv_stats, config

__all__ = ["stats", "csv_stats", "config"]
