"""Run configuration.

One dataclass replaces the reference's three config tiers (SURVEY.md §5):
getopt CLI flags (PFSP_lib.c:173-320), compile-time size macros
(macro.h:9-11 — here just static shapes baked into jit), and site
makefiles (N/A: one toolchain). Reference flags keep their names and
defaults (PFSP_lib.c:175-185); TPU-specific knobs are documented inline.
"""

from __future__ import annotations

import dataclasses
import os

from ..tune import defaults as tune_defaults

_TRUTHY = ("1", "true", "on", "yes")


def _knob_default(name: str, site_default):
    """Resolve an accessor's default: the call site's explicit value
    wins, else the registry row's. TTS_* names MUST be registered
    (tools/tts_lint.py enforces the same at commit time; this raises at
    runtime so a typo'd knob name fails the first read, not silently
    never-applies). Non-TTS names pass through unchecked — the accessors
    stay usable for one-off vars without polluting the registry."""
    if name.startswith("TTS_"):
        knob = KNOBS.get(name)
        if knob is None:
            raise KeyError(
                f"unregistered knob {name!r}: every TTS_* env var must "
                "have a row in utils/config.KNOBS (the single-source "
                "registry tools/tts_lint.py checks)")
        if site_default is None:
            return knob.default
    return site_default


def env_flag(name: str, default: bool | None = None) -> bool:
    """Parse a boolean TTS_* env knob ('1'/'true'/'on'/'yes' = on;
    '0'/'false'/'off'/'no'/'' = off). One parser for every static
    feature flag so the accepted spellings cannot drift per call site."""
    default = bool(_knob_default(name, default) or False)
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw in _TRUTHY


def env_str(name: str, default: str | None = None) -> str | None:
    """String knob; '' and unset both resolve to the default (an empty
    path/spec knob in a fleet unit file means "off", not "here")."""
    default = _knob_default(name, default)
    return os.environ.get(name) or default


def env_int(name: str, default: int | None = None) -> int | None:
    """Integer knob. A malformed value falls back to the default — the
    repo-wide stance that a typo'd env knob must never take down the
    process (it degrades, and the lint-checked registry documents the
    real spelling)."""
    default = _knob_default(name, default)
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def env_float(name: str, default: float | None = None) -> float | None:
    """Float knob; malformed values fall back like :func:`env_int`."""
    default = _knob_default(name, default)
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_ints(name: str, default: tuple = ()) -> tuple:
    """Comma-separated integer-list knob (the tuner's candidate
    ladders: TTS_TUNE_CHUNKS="64,256,1024"). Malformed lists fall back
    whole — a half-parsed candidate ladder is worse than the default."""
    if name.startswith("TTS_") and name not in KNOBS:
        raise KeyError(
            f"unregistered knob {name!r}: add a row to "
            "utils/config.KNOBS")
    raw = os.environ.get(name, "").strip()
    if not raw:
        return tuple(default)
    try:
        vals = tuple(int(t) for t in raw.split(",") if t.strip())
        return vals or tuple(default)
    except ValueError:
        return tuple(default)


def set_env(name: str, value) -> None:
    """The one sanctioned TTS_* env WRITE path (CLI flags propagating
    static knobs to respawned campaign workers / engine state init).
    Registration-checked like the readers, so a flag can't be spelled
    one way at the write site and another in the registry."""
    if name.startswith("TTS_") and name not in KNOBS:
        raise KeyError(
            f"unregistered knob {name!r}: add a row to "
            "utils/config.KNOBS")
    os.environ[name] = str(value)

# Resilience defaults — THE single source for engine/checkpoint.
# run_segmented's env fallbacks (TTS_RETRY_ATTEMPTS / TTS_RETRY_BASE_S /
# TTS_SEG_TIMEOUT_S) and PFSPConfig below both read these, so the
# documented knob and the actual behavior cannot drift apart. Module
# constants (not the dataclass) because engine code importing the
# dataclass for three scalars would be the wrong direction of coupling.
RETRY_ATTEMPTS_DEFAULT = 3
RETRY_BASE_S_DEFAULT = 0.5
SEGMENT_TIMEOUT_S_DEFAULT = 0.0   # 0 = watchdog off

# Search-service defaults (service/server.SearchServer). Module constants
# for the same reason as the retry knobs above: the service and the CLI
# `serve` entry both read them, and env overrides (TTS_SUBMESHES,
# TTS_QUEUE_DEPTH) must survive a campaign-driver respawn.
SERVICE_QUEUE_DEPTH_DEFAULT = 64      # admission control: reject beyond
SERVICE_SEGMENT_ITERS_DEFAULT = 512   # preemption/deadline granularity —
                                      # stop flags are honored at segment
                                      # boundaries, so this bounds the
                                      # service's reaction latency
SERVICE_CHECKPOINT_EVERY_DEFAULT = 4  # segments between periodic saves
                                      # (a stop/preempt always saves)
SERVICE_POLL_S_DEFAULT = 0.02         # scheduler poll period
SERVICE_RETRY_ATTEMPTS_DEFAULT = 2    # re-dispatches after a submesh
                                      # failure before a request FAILs
SERVICE_RETRY_BASE_S_DEFAULT = 0.2    # re-dispatch backoff base

# Observability defaults (tpu_tree_search/obs). Env-driven like the
# resilience knobs (they must survive campaign-worker respawns):
# TTS_TRACE_FILE appends the flight recorder's JSONL event log to a
# file, TTS_TRACE_RING bounds the in-memory ring buffer,
# TTS_SEARCH_TELEMETRY=1 (or --search-telemetry) compiles the
# on-device search-telemetry block into the loop
# (engine/telemetry.py — static flag, read at state init). The HTTP
# front-end is wired per entry point (`serve --http-port`), never
# ambiently — an open port must be an explicit operator choice.
OBS_TRACE_RING_DEFAULT = 16384        # ring-buffer records kept in RAM
OBS_RESOURCE_SAMPLE_S_DEFAULT = 1.0   # serve-session resource-sampler
                                      # cadence (obs/resource): device
                                      # bytes-in-use/peak + host RSS
                                      # gauges and memory trace lanes;
                                      # TTS_RESOURCE_SAMPLE_S overrides,
                                      # <= 0 disables the daemon thread
PROFILE_MAX_DURATION_S = 300.0        # POST /profile duration ceiling —
                                      # a typo'd duration must not pin
                                      # the profiler (and its artifact
                                      # growth) for hours
OBS_TRACE_MAX_MB_DEFAULT = 64         # tracelog JSONL sink rotation cap
                                      # (TTS_TRACE_MAX_MB; 0 disables):
                                      # at the cap the sink rolls to a
                                      # single `.1` sibling so a month-
                                      # long serve session cannot fill
                                      # the disk with its own recorder
OBS_METRIC_MAX_SERIES_DEFAULT = 2048  # per-metric label-set cap
                                      # (TTS_METRIC_MAX_SERIES): above
                                      # it new series are DROPPED and
                                      # counted in
                                      # tts_metrics_dropped_total — a
                                      # leaked per-request label must
                                      # degrade the metric, not the
                                      # process

# Fleet flight recorder (obs/store.py + obs/journey.py). TTS_OBS_STORE
# names the durable observability-store directory (usually inside the
# fleet/ledger root so it survives the host): metric snapshots and
# whitelisted trace events are appended as fsync'd CRC-stamped JSONL
# segments under PER-WRITER file names (obs-<writer>-NNNNNNNN.jsonl —
# the PR-16 quarantine rule, so N peers sharing the store never collide)
# and replayed at boot, so dashboards, health history and tts_* counters
# RESUME across restarts and takeovers instead of zeroing. Unset = off,
# bit-identical to the store-less stack (the sink, the sampler and the
# replay are all vacuous).
OBS_STORE_ENV = "TTS_OBS_STORE"
OBS_STORE_SEGMENT_RECORDS_DEFAULT = 4096  # TTS_OBS_STORE_SEGMENT_RECORDS
#                                           — records per segment before
#                                           rotation (the ledger's bound)
OBS_STORE_RETAIN_S_DEFAULT = 86400.0  # TTS_OBS_STORE_RETAIN_S — whole
#                                       segments whose newest record is
#                                       older than this are pruned at
#                                       rotation (time-series retention;
#                                       the ledger compacts state, the
#                                       store expires history)
OBS_STORE_QUEUE_DEFAULT = 4096        # TTS_OBS_STORE_QUEUE — bounded
#                                       sink-queue depth; a full queue
#                                       DROPS the sample (observability
#                                       must never block the scheduler)

# SLO burn-rate rules (obs/health.py slo_error_burn / slo_latency_burn).
# Classic multi-window burn: the error budget is TTS_SLO_ERROR_BUDGET
# (allowed bad fraction of terminals) and the burn rate is
# bad_fraction/budget over a window; the alert fires only when BOTH the
# fast and the slow window burn above TTS_SLO_BURN_THRESHOLD — fast
# alone is a blip, slow alone is stale history. Windows are computed
# over the durable store's terminal history (wall-clock stamped), so a
# budget spent across three restarts and a takeover still fires.
SLO_ERROR_BUDGET_DEFAULT = 0.01       # TTS_SLO_ERROR_BUDGET
SLO_LATENCY_TARGET_S_DEFAULT = 0.0    # TTS_SLO_LATENCY_TARGET_S — per-
#                                       request spent_s above this is a
#                                       latency violation (0 = latency
#                                       SLO off)
SLO_LATENCY_BUDGET_DEFAULT = 0.05     # TTS_SLO_LATENCY_BUDGET
SLO_BURN_FAST_S_DEFAULT = 300.0       # TTS_SLO_BURN_FAST_S (5m window)
SLO_BURN_SLOW_S_DEFAULT = 3600.0      # TTS_SLO_BURN_SLOW_S (1h window)
SLO_BURN_THRESHOLD_DEFAULT = 2.0      # TTS_SLO_BURN_THRESHOLD — burn
#                                       multiple both windows must
#                                       exceed to fire

# Operational-health defaults (obs/health.py — the SLO/anomaly rules
# engine every serve session runs). Env-driven (TTS_HEALTH_*) for the
# same respawn-survival reason as the knobs above; <= 0 interval
# disables the daemon. Threshold semantics are documented per rule in
# README.md's Operations section.
OBS_HEALTH_INTERVAL_S_DEFAULT = 2.0       # TTS_HEALTH_INTERVAL_S
HEALTH_QUEUE_WAIT_P99_S_DEFAULT = 60.0    # TTS_HEALTH_QUEUE_WAIT_P99_S
HEALTH_STALL_S_DEFAULT = 30.0             # TTS_HEALTH_STALL_S — max
                                          # heartbeat age of a RUNNING
                                          # request before `stall` fires
HEALTH_STALL_WARMUP_S_DEFAULT = 300.0     # TTS_HEALTH_STALL_WARMUP_S —
                                          # the stall limit BEFORE the
                                          # first heartbeat, when the
                                          # gap legitimately includes
                                          # an XLA trace+compile
HEALTH_MEM_FRAC_DEFAULT = 0.92            # TTS_HEALTH_MEM_FRAC —
                                          # in_use/limit above this
                                          # fires `mem_headroom`
HEALTH_COMPILE_STORM_DEFAULT = 6          # TTS_HEALTH_COMPILE_STORM —
                                          # executor-cache misses per
                                          # evaluation interval
HEALTH_PRUNING_MIN_RATE_DEFAULT = 0.0005  # TTS_HEALTH_PRUNING_MIN_RATE
HEALTH_PRUNING_MIN_NODES_DEFAULT = 100_000  # ...only judged past this
                                            # many evaluated children
HEALTH_AUDIT_WINDOW_S_DEFAULT = 300.0     # TTS_HEALTH_AUDIT_WINDOW_S —
                                          # how long an audit failure
                                          # keeps the `audit` rule firing

# Progress / ETA estimation (obs/estimate.py): online tree-size
# estimates published per request behind a warmup gate — both minimums
# must be met before the first gauge sample, so early wild estimates
# (one segment's branching factors extrapolated over the whole tree)
# never reach a dashboard. TTS_PROGRESS=0 removes the estimator layer
# entirely: no gauges, no snapshot keys, no checkpoint-meta key, no
# predictive rules — bit-identical to the pre-estimator server.
PROGRESS_WARMUP_SEGMENTS_DEFAULT = 3      # TTS_PROGRESS_WARMUP_SEGMENTS
PROGRESS_WARMUP_NODES_DEFAULT = 2000      # TTS_PROGRESS_WARMUP_NODES
PROGRESS_EWMA_DEFAULT = 0.3               # TTS_PROGRESS_EWMA — weight
                                          # of the newest segment's raw
                                          # estimate in the smoothed one

# Fleet capacity & utilization observability (obs/capacity.py): the
# lane-state ledger + shape-class demand/capacity model behind
# TTS_CAPACITY. TTS_CAPACITY=0 removes the layer entirely — no lane
# events/counters, no capacity gauges, no snapshot key, no saturation
# rule: bit-identical to the pre-capacity server.
CAPACITY_WINDOW_S_DEFAULT = 300.0         # TTS_CAPACITY_WINDOW_S —
                                          # arrival-rate sliding window
CAPACITY_EWMA_DEFAULT = 0.3               # TTS_CAPACITY_EWMA — weight
                                          # of the newest observation in
                                          # service-rate / demand EWMAs
HEALTH_SATURATION_DEFAULT = 0.85          # TTS_HEALTH_SATURATION —
                                          # sustained ρ above this fires
                                          # `saturation` (before the
                                          # queue_wait p99 rule can)
HEALTH_SATURATION_FOR_S_DEFAULT = 6.0     # TTS_HEALTH_SATURATION_FOR_S
                                          # — dwell before pending
                                          # becomes firing

# Raw-speed flags (both STATIC: read once per search/server, bit-
# identical node accounting on or off — see README's Performance
# section and tests/test_overlap.py's parity suite):
# TTS_OVERLAP=1 pipelines segmented execution — the next segment is
# dispatched (with donated pool carries) before the previous segment's
# counters are fetched, and checkpoint serialization+fsync moves to a
# bounded-queue writer thread — so the device never idles on the host
# between segments (tts_segment_gap_seconds -> ~0).
# TTS_SHARE_INCUMBENT=1 makes the search SERVICE share best-makespan
# incumbents across concurrent same-instance requests through a
# process-wide board (engine/incumbent.py): each segment boundary
# publishes the submesh's best and folds the global best in as the next
# segment's pruning ceiling (monotone-only, audited).
OVERLAP_FLAG = "TTS_OVERLAP"                  # default off
SHARE_INCUMBENT_FLAG = "TTS_SHARE_INCUMBENT"  # default off

# Zero-compile cold start (service/aot_cache.py + serve --aot-cache /
# --prewarm). TTS_AOT_CACHE names the disk directory persisted AOT
# executables live in (empty/unset = in-memory executor cache only);
# a restarted server deserializes previously-compiled loops from it
# instead of re-tracing+compiling (ledger `source=disk`). TTS_PREWARM
# is the boot pre-warm spec ("taillard,spool", explicit "JxM" tokens,
# or "0"/"off"/"no" as a kill-switch that disables pre-warm even when
# the --prewarm CLI flag is set) — executables for
# the standard shape families and the spool backlog are readied before
# the first request arrives.
AOT_CACHE_ENV = "TTS_AOT_CACHE"
PREWARM_ENV = "TTS_PREWARM"
AOT_WRITER_QUEUE_DEPTH = 2    # AOT-cache writer-thread back-pressure
                              # bound (the AsyncCheckpointWriter
                              # discipline: block, never drop/unbound)
PREWARM_CONCURRENCY_DEFAULT = 2   # TTS_PREWARM_CONCURRENCY — parallel
                                  # warm workers at boot; compiles are
                                  # CPU-heavy, so a small bound keeps
                                  # the boot window predictable
# the standard Taillard shape families (jobs, machines) — ta001-ta120;
# `serve --prewarm taillard` readies one executable per family per
# submesh (the instance VALUES are runtime args, so one warm per shape
# covers all ten instances of the class)
PREWARM_TAILLARD_FAMILIES = (
    (20, 5), (20, 10), (20, 20),
    (50, 5), (50, 10), (50, 20),
    (100, 5), (100, 10), (100, 20),
    (200, 10), (200, 20), (500, 20),
)
ASYNC_CKPT_QUEUE_DEPTH = 2    # writer-thread back-pressure bound: a
                              # dispatch thread outrunning the disk
                              # BLOCKS here instead of buffering
                              # unbounded snapshots (never drops one)
INCUMBENT_MAX_KEYS_DEFAULT = 4096  # TTS_INCUMBENT_MAX_KEYS — bound on
                                   # the board's distinct instance
                                   # keys; least-recently-updated
                                   # entries evict first (dropping an
                                   # entry only loses warm-start
                                   # tightening, never correctness) —
                                   # same bounded-observability stance
                                   # as TTS_METRIC_MAX_SERIES

# Adaptive dispatch (tpu_tree_search/tune + engine/ladder):
# TTS_LADDER=1 (STATIC, default off — off is bit-identical to the
# pre-ladder driver) enables chunk-ladder execution in the segmented
# distributed driver: 2-3 pre-built chunk rungs switched only at
# segment boundaries from the pool-occupancy signal, so ramp/drain run
# small-chunk steps instead of underfilled tuned-chunk ones.
# TTS_TUNE_CACHE names the persistent tuning-cache directory
# (tune/cache.TuningCache — fingerprint-checked, CRC-stamped, corrupt
# entries quarantined); TTS_TUNE=1 lets `serve --prewarm` PROBE cold
# shapes at boot (a warm cache replays with zero probes either way).
# Probe knobs for CI/small hosts: TTS_TUNE_CHUNKS / TTS_TUNE_PERIODS
# (comma lists), TTS_TUNE_WINDOW / TTS_TUNE_WARM (iterations).
LADDER_FLAG = "TTS_LADDER"
TUNE_CACHE_ENV = "TTS_TUNE_CACHE"
TUNE_ENV = "TTS_TUNE"
TUNE_WINDOW_ITERS_DEFAULT = 24    # TTS_TUNE_WINDOW — measured iters
                                  # per probe candidate
TUNE_WARM_ITERS_DEFAULT = 200     # TTS_TUNE_WARM — warm-up iters
                                  # before a probe's measured window

# Crash-safe serving (service/ledger.py + serve --ledger). TTS_LEDGER
# names the durable request-ledger directory: every request state
# transition (admit, dispatch, budget, preempt, release, exclusion,
# failure, quarantine/readmit, pause/resume, terminal) is journaled as an
# fsync'd CRC-stamped JSONL record BEFORE it is acknowledged, and a
# restarted server replays the ledger at boot — queued/active requests
# are re-admitted with budgets/exclusions/failure logs intact and
# resume from their checkpoints, terminal results re-serve
# idempotently, standing quarantines and admission pauses are
# restored. Unset = off (bit-identical to the pre-ledger server).
# TTS_DRAIN_TIMEOUT_S bounds the SIGTERM/SIGINT graceful drain (stop
# admission -> preempt at segment boundaries -> drain the checkpoint/
# AOT/ledger writers -> exit 0); past it the serve entry escalates to
# checkpoint-and-abort (the ledger makes even that abort recoverable).
LEDGER_ENV = "TTS_LEDGER"
DRAIN_TIMEOUT_S_DEFAULT = 30.0
LEDGER_BUDGET_EVERY_S_DEFAULT = 5.0   # seconds between journaled
#                                       budget heartbeats per RUNNING
#                                       request (bounds the spent_s a
#                                       hard kill can lose without
#                                       fsyncing at heartbeat rate)

# Fleet failover (service/lease.py + service/failover.py + serve
# --fleet-dir/--failover). Every server that opens a ledger also takes
# a LEASE on it: an fsync'd CRC-stamped lease file (owner id,
# monotonically increasing fencing epoch, TTL TTS_LEASE_TTL_S) renewed
# by a daemon thread. TTS_FLEET_DIR names the shared root peers scan
# for ledgers whose lease expired; TTS_FAILOVER=1 lets the
# FailoverWatcher EXECUTE the takeover protocol (epoch CAS bump,
# truncate-to-last-good, replay + re-admit on the survivor). The
# default (off) is OBSERVE-ONLY: expired peers are journaled
# (failover.peer_down) and surface on /alerts, zero takeovers run —
# the TTS_REMEDIATE rollout discipline. Fencing makes split-brain safe
# by construction: a stale owner discovers the bumped epoch at its
# next append/save/renewal and self-fences (typed LeaseLost, zero
# further commits).
FAILOVER_FLAG = "TTS_FAILOVER"     # default off (observe)
FLEET_DIR_ENV = "TTS_FLEET_DIR"
LEASE_TTL_S_DEFAULT = 10.0         # TTS_LEASE_TTL_S — lease expiry age;
#                                    renewals run at ~TTL/3, takeover
#                                    scans at ~TTL/2 (adoption inside
#                                    2x TTL, the drill's bound)

# Request megabatching (engine/megabatch.py + service batch-former +
# serve --megabatch). TTS_MEGABATCH=1 (STATIC per server; default off =
# bit-identical to the solo scheduler) makes the admission queue a
# BATCH-FORMER: queued requests group by (problem, table shape,
# lb_kind, engine knobs) and a group dispatches to one submesh as ONE
# vmapped compiled loop when it reaches TTS_BATCH_MAX members or its
# oldest member has waited TTS_BATCH_AGE_S seconds (a lone request
# age-closes as a batch of one and runs the ordinary solo path). Every
# batched request's node counts, optimum and telemetry block are
# bit-identical to its solo run (test-pinned).
MEGABATCH_FLAG = "TTS_MEGABATCH"
BATCH_MAX_DEFAULT = 8          # TTS_BATCH_MAX — close a batch at size
BATCH_AGE_S_DEFAULT = 0.25     # TTS_BATCH_AGE_S — or at this age

# Bound-portfolio racing (service/portfolio.py + request `portfolio: K`
# + client --portfolio). A request submitted with portfolio K fans out
# as K sibling sub-requests over DISTINCT configurations (bound tiers
# from the problem's lb_kinds ladder, per-tier tuned chunk plans from
# the Autotuner, chunk variants when tiers run out) that share ONE
# incumbent board via share_group — each sibling's incumbents tighten
# the others' pruning. The first sibling to finish with a PROOF wins:
# the parent finalizes DONE with the winner's result and every losing
# sibling is cancelled through the ordinary member-level stop path at
# its next segment boundary. TTS_PORTFOLIO sets a default K for
# requests that don't carry an explicit `portfolio` (0 = off, the
# default — a portfolio-less request takes the exact pre-portfolio
# path); TTS_PORTFOLIO_MAX caps K at admission.
PORTFOLIO_ENV = "TTS_PORTFOLIO"
PORTFOLIO_MAX_DEFAULT = 8      # TTS_PORTFOLIO_MAX — admission cap on K

# Self-healing (service/remediate.py + serve --remediate).
# TTS_REMEDIATE=1 lets the RemediationController EXECUTE its policy
# table (stall -> preempt+exclude, repeated localized failures ->
# submesh quarantine + canary readmit, cross-submesh failures ->
# dead-letter, compile_storm -> pause admission, mem_headroom ->
# shed + ladder demotion hint, audit -> checkpoint quarantine). The
# default (off) is OBSERVE-ONLY: detection and journaling run, zero
# actions are taken — the same bit-identical-off discipline as
# overlap/ladder. Every executed action is capped per rule per sliding
# window; the quarantine/dead-letter thresholds below are the
# containment geometry (failures localized to ONE submesh = hardware,
# quarantine it; failures FOLLOWING the request across >= K distinct
# submeshes = the request, dead-letter it).
REMEDIATE_FLAG = "TTS_REMEDIATE"              # default off (observe)
REMEDIATE_WINDOW_S_DEFAULT = 300.0            # TTS_REMEDIATE_WINDOW_S
REMEDIATE_MAX_PER_RULE_DEFAULT = 4            # TTS_REMEDIATE_MAX_PER_RULE
REMEDIATE_QUARANTINE_FAILS_DEFAULT = 3        # TTS_REMEDIATE_QUARANTINE_FAILS
REMEDIATE_DEADLETTER_SUBMESHES_DEFAULT = 3    # TTS_REMEDIATE_DEADLETTER_SUBMESHES
REMEDIATE_PROBE_S_DEFAULT = 30.0              # TTS_REMEDIATE_PROBE_S —
                                              # canary cooldown after a
                                              # quarantine/failed probe


# --------------------------------------------------------- knob registry
#
# THE single source of truth for every TTS_* environment knob. The
# static analyzer (tpu_tree_search/analysis/knobs.py, run by
# tools/tts_lint.py and the CI lint leg) enforces that (a) no module
# outside this file reads TTS_* from os.environ directly — everything
# goes through the env_* accessors above, which refuse unregistered
# names — and (b) every registered knob appears in README.md (the
# "Knob registry" table there is GENERATED from this dict by
# `tools/tts_lint.py --write-docs`; edit here, never there).
#
# `scope` partitions the table: "runtime" knobs configure the engine/
# service/obs stack proper; "bench", "tool" and "test" knobs configure
# bench.py, the tools/ drivers and the test suite.

@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str          # "flag" | "int" | "float" | "str" | "ints"
    default: object    # value when unset (None = no default / off)
    doc: str           # one line; lands in the generated README table
    scope: str = "runtime"


def _knob_table(*rows: Knob) -> dict:
    table = {}
    for k in rows:
        if k.name in table:
            raise ValueError(f"duplicate knob {k.name}")
        table[k.name] = k
    return table


KNOBS: dict[str, Knob] = _knob_table(
    # --- static engine flags (read once per search/server; off-modes
    #     are bit-identical by the tier-1 matrix contract)
    Knob("TTS_SEARCH_TELEMETRY", "flag", False,
         "compile the on-device search-telemetry block into the loop "
         "(static, read at state init; counts bit-identical on/off)"),
    Knob("TTS_OVERLAP", "flag", False,
         "pipelined segmented driver: async dispatch, donated carries, "
         "writer-thread checkpoints (segment gap -> ~0)"),
    Knob("TTS_SHARE_INCUMBENT", "flag", False,
         "cross-request incumbent board: concurrent same-instance "
         "requests tighten each other's pruning"),
    Knob("TTS_LADDER", "flag", False,
         "chunk-ladder execution: pre-built rungs switched at segment "
         "boundaries from pool occupancy"),
    Knob("TTS_DEBUG_STEP", "flag", False,
         "compile jax.debug taps into the device step (trace-time "
         "flag; debug builds only)"),
    Knob("TTS_FUSED", "flag", False,
         "fused Pallas bound+prune+compact route (ops/pallas_fused): "
         "pruned children never touch HBM; static per executable, "
         "bit-identical counts on/off. On a TPU backend resolves OFF "
         "(one warning) until the Mosaic lowering's first on-chip "
         "validation round"),
    Knob("TTS_FUSED_INTERPRET", "flag", False,
         "run the fused kernels under the Pallas interpreter on "
         "non-TPU backends (the CI kernel-logic leg; no effect on "
         "TPU)"),
    # --- resilience
    Knob("TTS_RETRY_ATTEMPTS", "int", RETRY_ATTEMPTS_DEFAULT,
         "in-place retries of transient I/O / dispatch errors"),
    Knob("TTS_RETRY_BASE_S", "float", RETRY_BASE_S_DEFAULT,
         "exponential-backoff base for those retries (seconds)"),
    Knob("TTS_SEG_TIMEOUT_S", "float", SEGMENT_TIMEOUT_S_DEFAULT,
         "per-segment wall-clock watchdog (0 = off)"),
    Knob("TTS_FAULTS", "str", None,
         "deterministic fault-injection plan (utils/faults syntax; "
         "test/drill harness)"),
    # --- service
    Knob("TTS_SUBMESHES", "int", 1,
         "serve: submesh partition count (campaign respawn channel)"),
    Knob("TTS_QUEUE_DEPTH", "int", SERVICE_QUEUE_DEPTH_DEFAULT,
         "serve: admission-queue depth (reject beyond)"),
    Knob("TTS_AOT_CACHE", "str", None,
         "disk AOT executable cache directory (unset = in-memory "
         "executor cache only)"),
    Knob("TTS_PREWARM", "str", None,
         "boot pre-warm spec ('taillard,spool', explicit 'JxM' tokens; "
         "'0'/'off'/'no' kill-switch beats the CLI flag)"),
    Knob("TTS_PREWARM_CONCURRENCY", "int", PREWARM_CONCURRENCY_DEFAULT,
         "parallel pre-warm workers at boot"),
    Knob("TTS_INCUMBENT_MAX_KEYS", "int", INCUMBENT_MAX_KEYS_DEFAULT,
         "incumbent-board distinct-instance bound (LRU-evicted)"),
    # --- adaptive dispatch
    Knob("TTS_TUNE_CACHE", "str", None,
         "persistent tuning-cache directory (fingerprint-checked, "
         "CRC-stamped)"),
    Knob("TTS_TUNE", "flag", False,
         "allow boot-time probing of cold shapes during pre-warm"),
    Knob("TTS_TUNE_CHUNKS", "ints", None,
         "probe candidate chunk ladder (comma list; unset = the "
         "tuner's built-in pow2 ladder)"),
    Knob("TTS_TUNE_PERIODS", "ints", None,
         "probe candidate balance periods (comma list)"),
    Knob("TTS_TUNE_WINDOW", "int", TUNE_WINDOW_ITERS_DEFAULT,
         "measured iterations per probe candidate"),
    Knob("TTS_TUNE_WARM", "int", TUNE_WARM_ITERS_DEFAULT,
         "warm-up iterations before a probe's measured window"),
    Knob("TTS_TUNE_RUNGS", "flag", False,
         "tune(): probe the winner's ladder rungs for the per-rung "
         "profitability mask even when the fused route is off "
         "(matmul-only rung admission data; extra compiles per probe)"),
    # --- observability
    Knob("TTS_TRACE_FILE", "str", None,
         "flight-recorder JSONL sink path (unset = ring buffer only)"),
    Knob("TTS_TRACE_RING", "int", OBS_TRACE_RING_DEFAULT,
         "flight-recorder in-RAM ring capacity (records)"),
    Knob("TTS_TRACE_MAX_MB", "float", OBS_TRACE_MAX_MB_DEFAULT,
         "sink rotation cap in MB (one .1 rollover kept; 0 disables)"),
    Knob("TTS_METRIC_MAX_SERIES", "int", OBS_METRIC_MAX_SERIES_DEFAULT,
         "per-metric label-set cap (new series beyond it drop, "
         "counted in tts_metrics_dropped_total)"),
    Knob("TTS_RESOURCE_SAMPLE_S", "float", OBS_RESOURCE_SAMPLE_S_DEFAULT,
         "resource-sampler cadence (device bytes + host RSS; <= 0 "
         "disables the daemon)"),
    # --- fleet flight recorder (obs/store.py + obs/journey.py;
    #     semantics per README "Flight recorder")
    Knob("TTS_OBS_STORE", "str", None,
         "durable observability-store directory (per-writer CRC JSONL "
         "segments, replayed at boot; unset = off, bit-identical)"),
    Knob("TTS_OBS_STORE_SEGMENT_RECORDS", "int",
         OBS_STORE_SEGMENT_RECORDS_DEFAULT,
         "obs store: records per segment before rotation"),
    Knob("TTS_OBS_STORE_RETAIN_S", "float", OBS_STORE_RETAIN_S_DEFAULT,
         "obs store: retention window — whole segments older than this "
         "are pruned at rotation"),
    Knob("TTS_OBS_STORE_QUEUE", "int", OBS_STORE_QUEUE_DEFAULT,
         "obs store: bounded sink-queue depth (full queue drops the "
         "sample, never blocks the scheduler)"),
    # --- SLO burn-rate rules (obs/health.py; multi-window burn over
    #     the durable store's terminal history)
    Knob("TTS_SLO_ERROR_BUDGET", "float", SLO_ERROR_BUDGET_DEFAULT,
         "error SLO: allowed failed fraction of terminal requests"),
    Knob("TTS_SLO_LATENCY_TARGET_S", "float",
         SLO_LATENCY_TARGET_S_DEFAULT,
         "latency SLO: per-request spent_s above this is a violation "
         "(0 = latency SLO off)"),
    Knob("TTS_SLO_LATENCY_BUDGET", "float", SLO_LATENCY_BUDGET_DEFAULT,
         "latency SLO: allowed violating fraction of terminals"),
    Knob("TTS_SLO_BURN_FAST_S", "float", SLO_BURN_FAST_S_DEFAULT,
         "burn-rate fast window (seconds)"),
    Knob("TTS_SLO_BURN_SLOW_S", "float", SLO_BURN_SLOW_S_DEFAULT,
         "burn-rate slow window (seconds)"),
    Knob("TTS_SLO_BURN_THRESHOLD", "float", SLO_BURN_THRESHOLD_DEFAULT,
         "burn multiple BOTH windows must exceed for the slo_* rules "
         "to fire"),
    # --- audit
    Knob("TTS_AUDIT", "str", "1",
         "node-conservation auditor: '1' on (default), '0' off, "
         "'full' adds checkpoint re-read verification"),
    Knob("TTS_AUDIT_CKPT", "flag", False,
         "checkpoint roundtrip verification alone (TTS_AUDIT=full "
         "implies it)"),
    Knob("TTS_AUDIT_HARD", "flag", False,
         "raise AuditError on any failed invariant (the CI mode)"),
    # --- health rules (thresholds; semantics per README Operations)
    Knob("TTS_HEALTH_INTERVAL_S", "float", OBS_HEALTH_INTERVAL_S_DEFAULT,
         "health-monitor evaluation interval (<= 0 disables daemon)"),
    Knob("TTS_HEALTH_QUEUE_WAIT_P99_S", "float",
         HEALTH_QUEUE_WAIT_P99_S_DEFAULT,
         "queue_wait rule: windowed p99 SLO threshold (seconds)"),
    Knob("TTS_HEALTH_STALL_S", "float", HEALTH_STALL_S_DEFAULT,
         "stall rule: max heartbeat age of a RUNNING request"),
    Knob("TTS_HEALTH_STALL_WARMUP_S", "float",
         HEALTH_STALL_WARMUP_S_DEFAULT,
         "stall rule: the limit BEFORE the first heartbeat (covers "
         "XLA trace+compile)"),
    Knob("TTS_HEALTH_MEM_FRAC", "float", HEALTH_MEM_FRAC_DEFAULT,
         "mem_headroom rule: in_use/limit firing fraction"),
    Knob("TTS_HEALTH_COMPILE_STORM", "float", HEALTH_COMPILE_STORM_DEFAULT,
         "compile_storm rule: unplanned fresh compiles per interval"),
    Knob("TTS_HEALTH_PRUNING_MIN_RATE", "float",
         HEALTH_PRUNING_MIN_RATE_DEFAULT,
         "pruning_collapse rule: minimum pruning rate"),
    Knob("TTS_HEALTH_PRUNING_MIN_NODES", "float",
         HEALTH_PRUNING_MIN_NODES_DEFAULT,
         "pruning_collapse rule: judged only past this many children"),
    Knob("TTS_HEALTH_AUDIT_WINDOW_S", "float",
         HEALTH_AUDIT_WINDOW_S_DEFAULT,
         "audit rule: how long a failure keeps the alert firing"),
    Knob("TTS_HEALTH_PERF_JSON", "str", None,
         "perf rule: path to a perf_sentry --json verdict file"),
    Knob("TTS_HEALTH_TENANT_OVERRIDES", "str", None,
         "per-tenant threshold overrides as JSON "
         '({"tenant": {"slo_latency_target_s": 30}}); overridden '
         "tenants get their own burn series and risk-rule judgment"),
    # --- progress / ETA estimation (obs/estimate.py; semantics per
    #     README "Progress & ETA")
    Knob("TTS_PROGRESS", "flag", True,
         "per-request online tree-size/progress/ETA estimation "
         "(observation-only; 0 = estimator layer absent, "
         "bit-identical)"),
    Knob("TTS_PROGRESS_WARMUP_SEGMENTS", "int",
         PROGRESS_WARMUP_SEGMENTS_DEFAULT,
         "progress: segments observed before estimates publish"),
    Knob("TTS_PROGRESS_WARMUP_NODES", "int",
         PROGRESS_WARMUP_NODES_DEFAULT,
         "progress: explored nodes required before estimates publish"),
    Knob("TTS_PROGRESS_EWMA", "float", PROGRESS_EWMA_DEFAULT,
         "progress: EWMA weight of the newest segment's raw estimate"),
    # --- fleet capacity & utilization (obs/capacity.py; semantics per
    #     README "Capacity & utilization")
    Knob("TTS_CAPACITY", "flag", True,
         "lane-state ledger + shape-class capacity model + saturation "
         "rule (observation-only; 0 = capacity layer absent, "
         "bit-identical)"),
    Knob("TTS_CAPACITY_WINDOW_S", "float", CAPACITY_WINDOW_S_DEFAULT,
         "capacity: sliding window for per-class arrival rates"),
    Knob("TTS_CAPACITY_EWMA", "float", CAPACITY_EWMA_DEFAULT,
         "capacity: EWMA weight of the newest service-rate/demand "
         "observation"),
    Knob("TTS_HEALTH_SATURATION", "float", HEALTH_SATURATION_DEFAULT,
         "saturation rule: sustained overall ρ firing threshold"),
    Knob("TTS_HEALTH_SATURATION_FOR_S", "float",
         HEALTH_SATURATION_FOR_S_DEFAULT,
         "saturation rule: dwell seconds before pending -> firing"),
    # --- crash-safe serving (service/ledger.py; semantics per README
    #     "Crash recovery & deployment")
    Knob("TTS_LEDGER", "str", None,
         "serve: durable request-ledger directory (write-ahead JSONL, "
         "replayed at boot; unset = off, bit-identical to today)"),
    Knob("TTS_DRAIN_TIMEOUT_S", "float", DRAIN_TIMEOUT_S_DEFAULT,
         "serve: SIGTERM/SIGINT graceful-drain budget before the "
         "checkpoint-and-abort escalation"),
    # --- request megabatching (engine/megabatch.py; semantics per
    #     README "Request megabatching")
    Knob("TTS_MEGABATCH", "flag", False,
         "serve: batch same-shape-class requests into one vmapped "
         "compiled loop (default off = the solo scheduler exactly)"),
    Knob("TTS_BATCH_MAX", "int", BATCH_MAX_DEFAULT,
         "megabatch: close a forming batch at this many members"),
    Knob("TTS_BATCH_AGE_S", "float", BATCH_AGE_S_DEFAULT,
         "megabatch: close a forming batch once its oldest member has "
         "waited this long (a lone request closes as a batch of one)"),
    # --- bound-portfolio racing (service/portfolio.py; semantics per
    #     README "Portfolio racing")
    Knob("TTS_PORTFOLIO", "int", 0,
         "serve: default portfolio width K for requests without an "
         "explicit `portfolio` (0 = off — a portfolio-less request "
         "takes the exact pre-portfolio path, bit-identical)"),
    Knob("TTS_PORTFOLIO_MAX", "int", PORTFOLIO_MAX_DEFAULT,
         "serve: admission cap on a request's portfolio width K "
         "(reject beyond)"),
    # --- fleet failover (service/lease.py + service/failover.py;
    #     semantics per README "High availability & failover")
    Knob("TTS_FLEET_DIR", "str", None,
         "serve: shared fleet root the FailoverWatcher scans for peer "
         "ledgers whose lease expired (unset = no watcher)"),
    Knob("TTS_FAILOVER", "flag", False,
         "execute ledger takeovers of expired peers (default: "
         "observe-only — peer_down detection and journaling run, zero "
         "takeovers)"),
    Knob("TTS_LEASE_TTL_S", "float", LEASE_TTL_S_DEFAULT,
         "ledger-lease expiry age in seconds (renewed at ~TTL/3; an "
         "unreachable owner is takeover-eligible past it)"),
    # --- self-healing (service/remediate.py; semantics per README
    #     "Self-healing")
    Knob("TTS_REMEDIATE", "flag", False,
         "execute the remediation policy table (default: observe-only "
         "— detection and journaling run, zero actions taken)"),
    Knob("TTS_REMEDIATE_WINDOW_S", "float", REMEDIATE_WINDOW_S_DEFAULT,
         "sliding window for the action rate valve and the "
         "localized-failure quarantine count"),
    Knob("TTS_REMEDIATE_MAX_PER_RULE", "int",
         REMEDIATE_MAX_PER_RULE_DEFAULT,
         "executed actions allowed per rule per window (reversals "
         "exempt); beyond it a flapping rule degrades to observe-only"),
    Knob("TTS_REMEDIATE_QUARANTINE_FAILS", "int",
         REMEDIATE_QUARANTINE_FAILS_DEFAULT,
         "dispatch failures localized to one submesh inside the window "
         "before it is quarantined (drained, held out, canary-probed)"),
    Knob("TTS_REMEDIATE_DEADLETTER_SUBMESHES", "int",
         REMEDIATE_DEADLETTER_SUBMESHES_DEFAULT,
         "distinct submeshes a request may fail on before it "
         "dead-letters as FAILED with its full failure_log"),
    Knob("TTS_REMEDIATE_PROBE_S", "float", REMEDIATE_PROBE_S_DEFAULT,
         "canary-probe cooldown: seconds after a quarantine (or a "
         "failed probe) before the synthetic micro-request retries"),
    # --- XLA persistent compile cache
    Knob("TTS_NO_COMPILE_CACHE", "flag", False,
         "opt out of XLA's persistent compilation cache"),
    Knob("TTS_COMPILE_CACHE_DIR", "str", None,
         "redirect the XLA persistent compilation cache directory"),
    # --- bench.py
    Knob("TTS_BENCH_PLATFORM", "str", None,
         "bench: force a jax platform before backend init", "bench"),
    Knob("TTS_BENCH_INSTANCE", "int", 21,
         "bench: Taillard instance id", "bench"),
    Knob("TTS_BENCH_CHUNK", "int", None,
         "bench: chunk override (unset = measured-defaults table)",
         "bench"),
    Knob("TTS_BENCH_ITERS", "int", 2000,
         "bench: measured loop iterations", "bench"),
    Knob("TTS_BENCH_WARM", "int", None,
         "bench: warm-up iterations override", "bench"),
    Knob("TTS_BENCH_LB", "str", "1,2",
         "bench: comma list of bounds to measure", "bench"),
    Knob("TTS_BENCH_TUNED", "flag", False,
         "bench: resolve chunk/period through the Autotuner", "bench"),
    Knob("TTS_BENCH_SEGGAP", "flag", True,
         "bench: emit the segment-gap row", "bench"),
    Knob("TTS_BENCH_COLDSTART", "flag", True,
         "bench: emit the cold-start rows", "bench"),
    Knob("TTS_BENCH_RAMPDRAIN", "flag", True,
         "bench: emit the ramp/drain ladder rows", "bench"),
    Knob("TTS_BENCH_RAMP_JOBS", "int", 10,
         "bench: ramp/drain synthetic instance jobs", "bench"),
    Knob("TTS_BENCH_RAMP_CHUNK", "int", 1024,
         "bench: ramp/drain tuned-chunk rung", "bench"),
    Knob("TTS_BENCH_SERVE_RPS", "flag", True,
         "bench: emit the serve requests/s row (small-instance mix "
         "through one serve session)", "bench"),
    Knob("TTS_BENCH_SERVE_N", "int", 8,
         "bench: serve-rps request count", "bench"),
    Knob("TTS_BENCH_PORTFOLIO", "flag", True,
         "bench: emit the portfolio-racing speedup row (K-way race "
         "with a shared incumbent board vs the best member solo)",
         "bench"),
    Knob("TTS_BENCH_PORTFOLIO_K", "int", 3,
         "bench: portfolio-speedup race width", "bench"),
    Knob("TTS_BENCH_PORTFOLIO_JOBS", "int", 11,
         "bench: portfolio-speedup synthetic instance jobs (large "
         "enough that runs span many segments — the race only saves "
         "bound evals when losers cancel mid-tree)", "bench"),
    Knob("TTS_BENCH_HBM", "flag", True,
         "bench: emit the step-HBM-bytes row (fused-mode channel; "
         "compiled-loop memory_analysis temp bytes on every backend "
         "— a live peak-bytes delta reads ~0 once the warm run "
         "establishes the lifetime high-water)",
         "bench"),
    # --- tools/ drivers
    Knob("TTS_CAMPAIGN_OUT", "str", "/tmp/campaign.jsonl",
         "run_campaign: result JSONL path", "tool"),
    Knob("TTS_WORKDIR", "str", "/tmp",
         "run_campaign: checkpoint/workdir root", "tool"),
    Knob("TTS_LB", "int", 2, "run_campaign: bound kind", "tool"),
    Knob("TTS_CHUNK", "int", 32768, "run_campaign: pop chunk", "tool"),
    Knob("TTS_POOL_ROWS", "int", 0,
         "run_campaign: pool rows (0 = sized from the instance; "
         "formerly TTS_CAPACITY, renamed when the capacity "
         "observability layer claimed that name)",
         "tool"),
    Knob("TTS_BUDGET_S", "float", 7200.0,
         "run_campaign: per-instance execution budget", "tool"),
    Knob("TTS_SEG", "int", 2000,
         "run_campaign: segment iterations", "tool"),
    Knob("TTS_CKPT_EVERY", "int", 8,
         "run_campaign: segments between checkpoints", "tool"),
    Knob("TTS_UB", "str", "opt",
         "run_campaign: incumbent seed ('opt' | 'inf')", "tool"),
    Knob("TTS_STALL_GRACE", "float", 900.0,
         "run_campaign: supervisor stall grace (seconds)", "tool"),
    Knob("TTS_STALL_FACTOR", "float", 4.0,
         "run_campaign: stall limit as a multiple of segment time",
         "tool"),
    Knob("TTS_STALL_MIN", "float", 720.0,
         "run_campaign: stall limit floor (seconds)", "tool"),
    Knob("TTS_MAX_RESTARTS", "int", 50,
         "run_campaign: worker respawn budget", "tool"),
    Knob("TTS_DEAD_LIMIT", "int", 5,
         "run_campaign: consecutive no-progress restarts before an "
         "instance is declared dead", "tool"),
    Knob("TTS_TABLE_OUT", "str", "/tmp/single_device_table.jsonl",
         "run_single_device_table: output path", "tool"),
    Knob("TTS_BAL_CHUNK", "int", 32768,
         "bench_balance: chunk", "tool"),
    Knob("TTS_BAL_CAP", "int", 1 << 21,
         "bench_balance: pool capacity", "tool"),
    Knob("TTS_BAL_ROUNDS", "int", 20,
         "bench_balance: measured rounds", "tool"),
    Knob("TTS_BRACKET_REPS", "int", 256,
         "validate_attribution: bracket repetitions", "tool"),
    # --- test suite
    Knob("TTS_TEST_TPU", "flag", False,
         "tests: keep the attached TPU backend instead of the 8-device "
         "virtual CPU mesh", "test"),
    Knob("TTS_TEST_STALL_AT_SEG", "int", 0,
         "campaign kill-drill: worker self-stalls at this segment",
         "test"),
    Knob("TTS_OBS_ARTIFACT_DIR", "str", None,
         "tests: export serve-session trace artifacts here (the CI "
         "upload dir)", "test"),
)


@dataclasses.dataclass
class PFSPConfig:
    # --- reference flags (semantics per README.md:49-101)
    inst: int = 14        # -i Taillard instance id
    lb: int = 1           # -l bound: 0=lb1_d, 1=lb1, 2=lb2
    ub: int = 1           # -u 1: seed incumbent with known optimum; 0: inf
    m: int = 25           # -m min pool before offload -> min seed/worker;
                          #    with -C 1 also the host hand-off threshold
    M: int = 50000        # -M max offload chunk -> pop-chunk ceiling
    T: int = 5000         # -T CPU-thread chunk (accepted for CLI parity;
                          #    the native drain sizes itself from cpu_count)
    D: int = 0            # -D devices (0 = all addressable)
    C: int = 0            # -C heterogeneous co-processing: native host
                          #    warm-up + device loop + multi-threaded
                          #    native host drain (engine/hybrid.py)
    ws: int = 1           # -w intra-mesh balancing on/off
    L: int = 1            # -L inter-node balancing on/off (same collective
                          #    tier on TPU; ws==0 and L==0 disable balance)
    perc: float = 0.5     # -p steal fraction (steal-half = 0.5)
    # --- TPU engine knobs (defaults single-sourced in
    # tune/defaults.py — the measured table bench and serve also read;
    # the Autotuner's fallback tier)
    chunk: int = tune_defaults.CLI_CHUNK_DEFAULT
    #                         # parents popped per compiled step
    capacity: int = 1 << 20   # per-device pool rows
    balance_period: int = tune_defaults.BALANCE_PERIOD_DEFAULT
    #                         # steps between collective balance rounds
    csv: str | None = None    # append a reference-schema CSV row here
    # Resilience knobs deliberately do NOT live on this dataclass: the
    # override channel is env vars (TTS_RETRY_ATTEMPTS / TTS_RETRY_BASE_S
    # / TTS_SEG_TIMEOUT_S / TTS_FAULTS) or CLI flags, because the
    # campaign supervisor's worker subprocesses must inherit them across
    # respawns — a Python object cannot ride a respawn. The defaults are
    # the module constants above.

    @property
    def balancing_enabled(self) -> bool:
        return bool(self.ws or self.L)


@dataclasses.dataclass
class NQueensConfig:
    N: int = 14           # -N board size
    g: int = 1            # -g safety-check repetitions (work scaling)
    D: int = 0            # devices (0 = all)
    chunk: int = tune_defaults.CLI_CHUNK_DEFAULT
    capacity: int = 1 << 20
    balance_period: int = tune_defaults.BALANCE_PERIOD_DEFAULT
