"""Run configuration.

One dataclass replaces the reference's three config tiers (SURVEY.md §5):
getopt CLI flags (PFSP_lib.c:173-320), compile-time size macros
(macro.h:9-11 — here just static shapes baked into jit), and site
makefiles (N/A: one toolchain). Reference flags keep their names and
defaults (PFSP_lib.c:175-185); TPU-specific knobs are documented inline.
"""

from __future__ import annotations

import dataclasses
import os

from ..tune import defaults as tune_defaults

_TRUTHY = ("1", "true", "on", "yes")


def env_flag(name: str, default: bool = False) -> bool:
    """Parse a boolean TTS_* env knob ('1'/'true'/'on'/'yes' = on;
    '0'/'false'/'off'/'no'/'' = off). One parser for every static
    feature flag so the accepted spellings cannot drift per call site."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    return raw in _TRUTHY

# Resilience defaults — THE single source for engine/checkpoint.
# run_segmented's env fallbacks (TTS_RETRY_ATTEMPTS / TTS_RETRY_BASE_S /
# TTS_SEG_TIMEOUT_S) and PFSPConfig below both read these, so the
# documented knob and the actual behavior cannot drift apart. Module
# constants (not the dataclass) because engine code importing the
# dataclass for three scalars would be the wrong direction of coupling.
RETRY_ATTEMPTS_DEFAULT = 3
RETRY_BASE_S_DEFAULT = 0.5
SEGMENT_TIMEOUT_S_DEFAULT = 0.0   # 0 = watchdog off

# Search-service defaults (service/server.SearchServer). Module constants
# for the same reason as the retry knobs above: the service and the CLI
# `serve` entry both read them, and env overrides (TTS_SUBMESHES,
# TTS_QUEUE_DEPTH) must survive a campaign-driver respawn.
SERVICE_QUEUE_DEPTH_DEFAULT = 64      # admission control: reject beyond
SERVICE_SEGMENT_ITERS_DEFAULT = 512   # preemption/deadline granularity —
                                      # stop flags are honored at segment
                                      # boundaries, so this bounds the
                                      # service's reaction latency
SERVICE_CHECKPOINT_EVERY_DEFAULT = 4  # segments between periodic saves
                                      # (a stop/preempt always saves)
SERVICE_POLL_S_DEFAULT = 0.02         # scheduler poll period
SERVICE_RETRY_ATTEMPTS_DEFAULT = 2    # re-dispatches after a submesh
                                      # failure before a request FAILs
SERVICE_RETRY_BASE_S_DEFAULT = 0.2    # re-dispatch backoff base

# Observability defaults (tpu_tree_search/obs). Env-driven like the
# resilience knobs (they must survive campaign-worker respawns):
# TTS_TRACE_FILE appends the flight recorder's JSONL event log to a
# file, TTS_TRACE_RING bounds the in-memory ring buffer,
# TTS_SEARCH_TELEMETRY=1 (or --search-telemetry) compiles the
# on-device search-telemetry block into the loop
# (engine/telemetry.py — static flag, read at state init). The HTTP
# front-end is wired per entry point (`serve --http-port`), never
# ambiently — an open port must be an explicit operator choice.
OBS_TRACE_RING_DEFAULT = 16384        # ring-buffer records kept in RAM
OBS_RESOURCE_SAMPLE_S_DEFAULT = 1.0   # serve-session resource-sampler
                                      # cadence (obs/resource): device
                                      # bytes-in-use/peak + host RSS
                                      # gauges and memory trace lanes;
                                      # TTS_RESOURCE_SAMPLE_S overrides,
                                      # <= 0 disables the daemon thread
PROFILE_MAX_DURATION_S = 300.0        # POST /profile duration ceiling —
                                      # a typo'd duration must not pin
                                      # the profiler (and its artifact
                                      # growth) for hours
OBS_TRACE_MAX_MB_DEFAULT = 64         # tracelog JSONL sink rotation cap
                                      # (TTS_TRACE_MAX_MB; 0 disables):
                                      # at the cap the sink rolls to a
                                      # single `.1` sibling so a month-
                                      # long serve session cannot fill
                                      # the disk with its own recorder
OBS_METRIC_MAX_SERIES_DEFAULT = 2048  # per-metric label-set cap
                                      # (TTS_METRIC_MAX_SERIES): above
                                      # it new series are DROPPED and
                                      # counted in
                                      # tts_metrics_dropped_total — a
                                      # leaked per-request label must
                                      # degrade the metric, not the
                                      # process

# Operational-health defaults (obs/health.py — the SLO/anomaly rules
# engine every serve session runs). Env-driven (TTS_HEALTH_*) for the
# same respawn-survival reason as the knobs above; <= 0 interval
# disables the daemon. Threshold semantics are documented per rule in
# README.md's Operations section.
OBS_HEALTH_INTERVAL_S_DEFAULT = 2.0       # TTS_HEALTH_INTERVAL_S
HEALTH_QUEUE_WAIT_P99_S_DEFAULT = 60.0    # TTS_HEALTH_QUEUE_WAIT_P99_S
HEALTH_STALL_S_DEFAULT = 30.0             # TTS_HEALTH_STALL_S — max
                                          # heartbeat age of a RUNNING
                                          # request before `stall` fires
HEALTH_STALL_WARMUP_S_DEFAULT = 300.0     # TTS_HEALTH_STALL_WARMUP_S —
                                          # the stall limit BEFORE the
                                          # first heartbeat, when the
                                          # gap legitimately includes
                                          # an XLA trace+compile
HEALTH_MEM_FRAC_DEFAULT = 0.92            # TTS_HEALTH_MEM_FRAC —
                                          # in_use/limit above this
                                          # fires `mem_headroom`
HEALTH_COMPILE_STORM_DEFAULT = 6          # TTS_HEALTH_COMPILE_STORM —
                                          # executor-cache misses per
                                          # evaluation interval
HEALTH_PRUNING_MIN_RATE_DEFAULT = 0.0005  # TTS_HEALTH_PRUNING_MIN_RATE
HEALTH_PRUNING_MIN_NODES_DEFAULT = 100_000  # ...only judged past this
                                            # many evaluated children
HEALTH_AUDIT_WINDOW_S_DEFAULT = 300.0     # TTS_HEALTH_AUDIT_WINDOW_S —
                                          # how long an audit failure
                                          # keeps the `audit` rule firing

# Raw-speed flags (both STATIC: read once per search/server, bit-
# identical node accounting on or off — see README's Performance
# section and tests/test_overlap.py's parity suite):
# TTS_OVERLAP=1 pipelines segmented execution — the next segment is
# dispatched (with donated pool carries) before the previous segment's
# counters are fetched, and checkpoint serialization+fsync moves to a
# bounded-queue writer thread — so the device never idles on the host
# between segments (tts_segment_gap_seconds -> ~0).
# TTS_SHARE_INCUMBENT=1 makes the search SERVICE share best-makespan
# incumbents across concurrent same-instance requests through a
# process-wide board (engine/incumbent.py): each segment boundary
# publishes the submesh's best and folds the global best in as the next
# segment's pruning ceiling (monotone-only, audited).
OVERLAP_FLAG = "TTS_OVERLAP"                  # default off
SHARE_INCUMBENT_FLAG = "TTS_SHARE_INCUMBENT"  # default off

# Zero-compile cold start (service/aot_cache.py + serve --aot-cache /
# --prewarm). TTS_AOT_CACHE names the disk directory persisted AOT
# executables live in (empty/unset = in-memory executor cache only);
# a restarted server deserializes previously-compiled loops from it
# instead of re-tracing+compiling (ledger `source=disk`). TTS_PREWARM
# is the boot pre-warm spec ("taillard,spool", explicit "JxM" tokens,
# or "0"/"off"/"no" as a kill-switch that disables pre-warm even when
# the --prewarm CLI flag is set) — executables for
# the standard shape families and the spool backlog are readied before
# the first request arrives.
AOT_CACHE_ENV = "TTS_AOT_CACHE"
PREWARM_ENV = "TTS_PREWARM"
AOT_WRITER_QUEUE_DEPTH = 2    # AOT-cache writer-thread back-pressure
                              # bound (the AsyncCheckpointWriter
                              # discipline: block, never drop/unbound)
PREWARM_CONCURRENCY_DEFAULT = 2   # TTS_PREWARM_CONCURRENCY — parallel
                                  # warm workers at boot; compiles are
                                  # CPU-heavy, so a small bound keeps
                                  # the boot window predictable
# the standard Taillard shape families (jobs, machines) — ta001-ta120;
# `serve --prewarm taillard` readies one executable per family per
# submesh (the instance VALUES are runtime args, so one warm per shape
# covers all ten instances of the class)
PREWARM_TAILLARD_FAMILIES = (
    (20, 5), (20, 10), (20, 20),
    (50, 5), (50, 10), (50, 20),
    (100, 5), (100, 10), (100, 20),
    (200, 10), (200, 20), (500, 20),
)
ASYNC_CKPT_QUEUE_DEPTH = 2    # writer-thread back-pressure bound: a
                              # dispatch thread outrunning the disk
                              # BLOCKS here instead of buffering
                              # unbounded snapshots (never drops one)
INCUMBENT_MAX_KEYS_DEFAULT = 4096  # TTS_INCUMBENT_MAX_KEYS — bound on
                                   # the board's distinct instance
                                   # keys; least-recently-updated
                                   # entries evict first (dropping an
                                   # entry only loses warm-start
                                   # tightening, never correctness) —
                                   # same bounded-observability stance
                                   # as TTS_METRIC_MAX_SERIES

# Adaptive dispatch (tpu_tree_search/tune + engine/ladder):
# TTS_LADDER=1 (STATIC, default off — off is bit-identical to the
# pre-ladder driver) enables chunk-ladder execution in the segmented
# distributed driver: 2-3 pre-built chunk rungs switched only at
# segment boundaries from the pool-occupancy signal, so ramp/drain run
# small-chunk steps instead of underfilled tuned-chunk ones.
# TTS_TUNE_CACHE names the persistent tuning-cache directory
# (tune/cache.TuningCache — fingerprint-checked, CRC-stamped, corrupt
# entries quarantined); TTS_TUNE=1 lets `serve --prewarm` PROBE cold
# shapes at boot (a warm cache replays with zero probes either way).
# Probe knobs for CI/small hosts: TTS_TUNE_CHUNKS / TTS_TUNE_PERIODS
# (comma lists), TTS_TUNE_WINDOW / TTS_TUNE_WARM (iterations).
LADDER_FLAG = "TTS_LADDER"
TUNE_CACHE_ENV = "TTS_TUNE_CACHE"
TUNE_ENV = "TTS_TUNE"


@dataclasses.dataclass
class PFSPConfig:
    # --- reference flags (semantics per README.md:49-101)
    inst: int = 14        # -i Taillard instance id
    lb: int = 1           # -l bound: 0=lb1_d, 1=lb1, 2=lb2
    ub: int = 1           # -u 1: seed incumbent with known optimum; 0: inf
    m: int = 25           # -m min pool before offload -> min seed/worker;
                          #    with -C 1 also the host hand-off threshold
    M: int = 50000        # -M max offload chunk -> pop-chunk ceiling
    T: int = 5000         # -T CPU-thread chunk (accepted for CLI parity;
                          #    the native drain sizes itself from cpu_count)
    D: int = 0            # -D devices (0 = all addressable)
    C: int = 0            # -C heterogeneous co-processing: native host
                          #    warm-up + device loop + multi-threaded
                          #    native host drain (engine/hybrid.py)
    ws: int = 1           # -w intra-mesh balancing on/off
    L: int = 1            # -L inter-node balancing on/off (same collective
                          #    tier on TPU; ws==0 and L==0 disable balance)
    perc: float = 0.5     # -p steal fraction (steal-half = 0.5)
    # --- TPU engine knobs (defaults single-sourced in
    # tune/defaults.py — the measured table bench and serve also read;
    # the Autotuner's fallback tier)
    chunk: int = tune_defaults.CLI_CHUNK_DEFAULT
    #                         # parents popped per compiled step
    capacity: int = 1 << 20   # per-device pool rows
    balance_period: int = tune_defaults.BALANCE_PERIOD_DEFAULT
    #                         # steps between collective balance rounds
    csv: str | None = None    # append a reference-schema CSV row here
    # Resilience knobs deliberately do NOT live on this dataclass: the
    # override channel is env vars (TTS_RETRY_ATTEMPTS / TTS_RETRY_BASE_S
    # / TTS_SEG_TIMEOUT_S / TTS_FAULTS) or CLI flags, because the
    # campaign supervisor's worker subprocesses must inherit them across
    # respawns — a Python object cannot ride a respawn. The defaults are
    # the module constants above.

    @property
    def balancing_enabled(self) -> bool:
        return bool(self.ws or self.L)


@dataclasses.dataclass
class NQueensConfig:
    N: int = 14           # -N board size
    g: int = 1            # -g safety-check repetitions (work scaling)
    D: int = 0            # devices (0 = all)
    chunk: int = tune_defaults.CLI_CHUNK_DEFAULT
    capacity: int = 1 << 20
    balance_period: int = tune_defaults.BALANCE_PERIOD_DEFAULT
