"""Descriptive statistics for experiment analysis.

Mirrors the reference's sorted-vector statistics toolkit
(reference: common/util.c:94-201): min/max/median, Tukey quartiles,
arbitrary percentile, standard deviation, and the boxplot-stats bundle the
`data/*.py` analysis scripts consume.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class BoxplotStats:
    """Same fields as the reference's compute_boxplot_stats
    (common/util.c:168-201)."""

    minimum: float
    q1: float
    median: float
    q3: float
    maximum: float
    mean: float
    stddev: float
    iqr: float
    lower_fence: float
    upper_fence: float


def median_sorted(v: np.ndarray) -> float:
    n = len(v)
    mid = n // 2
    return float(v[mid]) if n % 2 else float((v[mid - 1] + v[mid]) / 2.0)


def quartiles_sorted(v: np.ndarray) -> tuple[float, float]:
    """Tukey hinges: median of lower/upper half, halves excluding the
    middle element for odd n (the reference's convention, util.c:128-145).
    A single sample is its own hinge (the reference never hits n == 1;
    the analysis scripts do, for unreplicated runs)."""
    n = len(v)
    if n == 1:
        return float(v[0]), float(v[0])
    half = n // 2
    lower = v[:half]
    upper = v[half + (n % 2):]
    return median_sorted(lower), median_sorted(upper)


def percentile_sorted(v: np.ndarray, p: float) -> float:
    """Linear-interpolated percentile on a sorted vector (util.c:147-157)."""
    n = len(v)
    if n == 1:
        return float(v[0])
    rank = p * (n - 1)
    lo = int(np.floor(rank))
    frac = rank - lo
    hi = min(lo + 1, n - 1)
    return float(v[lo] + frac * (v[hi] - v[lo]))


def compute_boxplot_stats(values) -> BoxplotStats:
    v = np.sort(np.asarray(values, dtype=np.float64))
    q1, q3 = quartiles_sorted(v)
    iqr = q3 - q1
    return BoxplotStats(
        minimum=float(v[0]), q1=q1, median=median_sorted(v), q3=q3,
        maximum=float(v[-1]), mean=float(v.mean()),
        stddev=float(v.std(ddof=0)), iqr=iqr,
        lower_fence=q1 - 1.5 * iqr, upper_fence=q3 + 1.5 * iqr,
    )
