"""Persistent XLA compilation cache.

The engine's compiled loop costs ~45 s to build on a TPU backend (the
one-off `jit` compile BENCHMARKS.md's ta029 row carries); the reference
pays this cost once at BUILD time — its binaries ship AOT-compiled
kernels (pfsp/makefile nvcc/hipcc invocations), so a 4-second instance
really takes 4 seconds. JAX's persistent compilation cache is the
equivalent: the first process compiles and writes the executable to
disk, every later process (same program shape + jaxlib + flags) loads it
in ~1 s. Enabled by every entry point (CLI, bench, tools) via
enable(); opt out with TTS_NO_COMPILE_CACHE=1 or point the directory
elsewhere with TTS_COMPILE_CACHE_DIR.
"""

from __future__ import annotations

import pathlib

_DEFAULT_DIR = "~/.cache/tpu_tree_search/xla"


def enable(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's persistent compilation cache (best-effort: unknown
    backends or read-only filesystems degrade to in-memory caching, never
    to an error). Returns the directory in use, or None if disabled."""
    from . import config as _cfg
    if _cfg.env_flag("TTS_NO_COMPILE_CACHE"):
        return None
    path = (cache_dir or _cfg.env_str("TTS_COMPILE_CACHE_DIR")
            or _DEFAULT_DIR)
    path = str(pathlib.Path(path).expanduser())
    try:
        pathlib.Path(path).mkdir(parents=True, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # (jax's default min-compile-time threshold already skips
        # sub-second compiles — the right call here: the engine's small
        # helper jits are cheap to rebuild and would churn the cache)
        return path
    except Exception:
        return None
