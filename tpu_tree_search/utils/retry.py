"""Exponential-backoff retry for transient failures.

One helper for every retry site in the tree-search runtime: segment
execution and checkpoint I/O (engine/checkpoint.run_segmented, where the
PR-1 version lived inline), host fetches, and the search service's
request re-dispatch after a submesh failure (service/server.py). The
policy is deliberately minimal and uniform:

- only TRANSIENT error types are retried; everything else (wrong
  answers, schema errors, watchdog timeouts) propagates immediately —
  retrying a deterministic failure only delays the loud abort;
- delays grow exponentially (``base_s * 2**attempt``) with no jitter:
  the engine's retries guard a single-process resource (device runtime,
  local filesystem), not a contended fleet endpoint, and deterministic
  delays keep the fault-injection tests exact.

Every scheduled retry is recorded in the flight recorder (a ``retry``
event with the operation name, attempt number and error) and counted in
the metrics registry (``tts_retries_total{what=...}``) — one increment
per transient failure that was retried, so the fault-injection tests
can assert the counter exactly (`fail_host_fetch=1` => exactly 1).
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, Sequence

__all__ = ["backoff_delay", "backoff_delays", "retry_call"]


def backoff_delay(attempt: int, base_s: float) -> float:
    """Delay before retry number `attempt` (0-based): base_s * 2**attempt."""
    return base_s * (2 ** attempt)


def backoff_delays(attempts: int, base_s: float) -> list[float]:
    """The full backoff schedule: one delay per retry (attempts - 1 of
    them — the last attempt's failure is raised, not slept on)."""
    return [backoff_delay(k, base_s) for k in range(max(attempts, 1) - 1)]


def retry_call(fn: Callable, *, what: str = "operation",
               attempts: int = 3, base_s: float = 0.5,
               transient: Sequence[type] | tuple = (OSError,),
               on_retry: Callable | None = None,
               sleep: Callable[[float], None] = time.sleep):
    """Run `fn()` with exponential-backoff retry on transient errors.

    `transient` is the tuple of exception types worth retrying; any
    other exception propagates immediately. After the final attempt the
    transient error itself is re-raised. `on_retry(attempt, delay, exc)`
    (0-based attempt) is called before each sleep; the default emits a
    RuntimeWarning so silent retries cannot mask a degrading system.
    `sleep` is injectable for deterministic tests.
    """
    transient = tuple(transient)
    attempts = max(attempts, 1)
    for attempt in range(attempts):
        try:
            return fn()
        except transient as e:
            if attempt >= attempts - 1:
                raise
            delay = backoff_delay(attempt, base_s)
            from ..obs import metrics, tracelog
            tracelog.event("retry", what=what, attempt=attempt,
                           delay_s=delay, error=repr(e))
            metrics.default().counter(
                "tts_retries_total",
                "transient-failure retries by operation").inc(what=what)
            if on_retry is not None:
                on_retry(attempt, delay, e)
            else:
                warnings.warn(
                    f"transient {what} failure "
                    f"(attempt {attempt + 1}/{attempts}): {e!r}; "
                    f"retrying in {delay:.2f}s", RuntimeWarning,
                    stacklevel=2)
            sleep(delay)
