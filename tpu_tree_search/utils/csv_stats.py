"""Experiment CSV writers, schema-compatible with the reference.

Column headers and array serialization ("[a,b,c]" in a quoted cell) match
the reference's appenders exactly (reference: pfsp/lib/PFSP_statistic.c:
36-58 singlegpu, 69-112 multigpu, 123-167 dist_multigpu), so pandas-based
analysis written for the reference's `pfsp/data/*.py` keeps working.

Semantic mapping of per-PU columns to the TPU engine:
- a "processing unit" is a mesh device (the reference's is an OpenMP
  thread that may manage a GPU);
- `steals` / `success_steals` are balance exchanges with nodes received
  (there are no failed lock acquisitions to count);
- timing columns carry MEASURED phase attributions (utils/phase_timing:
  bound-kernel vs compaction unit costs timed on the real shapes, scaled
  by each worker's counters; the per-worker remainder is idle) —
  `gpu_kernel_time` = bound evaluation, `gen_child_time` = prune+branch
  compaction (the regather step IS the reference's generate_children),
  `time_load_bal` = measured balance exchanges, `gpu_idle_time` = the
  remainder, so the columns sum to ~total;
- `gpu_kernel_time` SEMANTICS: the column brackets pop + mask + dense
  bound evaluation — mirroring the reference's kernel timer, which
  wraps the whole evaluate_gpu region including copies and launch
  (PFSP_statistic.c vs PFSP_gpu_lib.cu:129-152) — NOT the bound op
  alone. For LB2 the dense sweeps dominate the bracket so the column
  ~equals op-level kernel time (validated to ~3% against profiler
  traces); for LB1 the bound op is a small part of its bracket, so
  the column reads ~2.4x the op-level trace share BY DEFINITION
  (tools/validate_attribution.py reports both semantics with error
  bars — the bracket-vs-bracket error is the attribution's accuracy);
- memcpy/malloc columns are structurally zero — those phases genuinely
  do not exist here (HBM-resident pool, static allocation), which is
  the honest datum; headers are retained so existing analysis parses
  rows unchanged.
"""

from __future__ import annotations

import os
from typing import Sequence


def _fmt_int_array(arr: Sequence[int]) -> str:
    return '"[' + ",".join(str(int(x)) for x in arr) + ']"'


def _fmt_float_array(arr: Sequence[float]) -> str:
    return '"[' + ",".join(f"{float(x):.4f}" for x in arr) + ']"'


def _append(path: str, header: str, row: str) -> None:
    new = not os.path.exists(path) or os.path.getsize(path) == 0
    with open(path, "a") as f:
        if new:
            f.write(header + "\n")
        f.write(row + "\n")


SINGLE_HEADER = ("instance_id,lower_bound,optimum,m,M,total_time,"
                 "gpu_memcpy_time,gpu_malloc_time,gpu_kernel_time,"
                 "gen_child_time,explored_tree,explored_sol")


def write_single(path: str, inst: int, lb: int, optimum: int, m: int, M: int,
                 total_time: float, kernel_time: float,
                 explored_tree: int, explored_sol: int,
                 gen_child_time: float = 0.0) -> None:
    """Single-device row (reference: print_results_file_single_gpu)."""
    row = (f"{inst},{lb},{optimum},{m},{M},{total_time:.4f},0.0000,0.0000,"
           f"{kernel_time:.4f},{gen_child_time:.4f},"
           f"{explored_tree},{explored_sol}")
    _append(path, SINGLE_HEADER, row)


MULTI_HEADER = (
    "instance_id,D,C,lower_bound,work_stealing,optimum,m,M,T,total_time,"
    "total_tree,total_sol,"
    "exp_tree_gpu,exp_sol_gpu,gen_child_gpu,steals_gpu,success_steals_gpu,"
    "termination_gpu,gpu_memcpy_time,gpu_malloc_time,gpu_kernel_time,"
    "gpu_gen_child_time,pool_ops_time,gpu_idle_time,termination_time")


def write_multi(path: str, inst: int, lb: int, D: int, C: int, ws: int,
                optimum: int, m: int, M: int, T: int, total_time: float,
                total_tree: int, total_sol: int, per_device: dict) -> None:
    """Multi-device row (reference: print_results_file_multi_gpu).

    `per_device` holds (D,)-arrays: tree, sol, evals, steals, recv,
    kernel_time (seconds).
    """
    n = len(per_device["tree"])
    zeros_i = [0] * n
    zeros_f = [0.0] * n
    cells = [
        f"{inst},{D},{C},{lb},{ws},{optimum},{m},{M},{T},"
        f"{total_time:.4f},{total_tree},{total_sol}",
        _fmt_int_array(per_device["tree"]),
        _fmt_int_array(per_device["sol"]),
        _fmt_int_array(per_device.get("evals", zeros_i)),
        _fmt_int_array(per_device.get("steals", zeros_i)),
        _fmt_int_array(per_device.get("steals", zeros_i)),
        _fmt_int_array(zeros_i),                       # termination retries: N/A
        _fmt_float_array(zeros_f),                     # memcpy: fused
        _fmt_float_array(zeros_f),                     # malloc: static pool
        _fmt_float_array(per_device.get("kernel_time", zeros_f)),
        _fmt_float_array(per_device.get("gen_child_time", zeros_f)),
        # pool_ops column: the balance exchange is this engine's only
        # out-of-step pool manipulation (the reference counts steal-lock
        # pool ops here)
        _fmt_float_array(per_device.get("balance_time", zeros_f)),
        _fmt_float_array(per_device.get("idle_time", zeros_f)),
        _fmt_float_array(zeros_f),                     # termination: in-loop
    ]
    _append(path, MULTI_HEADER, ",".join(cells).rstrip(","))


DIST_HEADER = (
    "instance_id,D,C,comm_size,lower_bound,load_balancing,optimum,m,M,T,"
    "total_time,total_tree,total_sol,"
    "all_exp_tree_gpu,all_exp_sol_gpu,all_gen_child_gpu,all_steals_gpu,"
    "all_success_steals_gpu,all_termination_gpu,all_dist_load_bal,"
    "all_gpu_memcpy_time,all_gpu_malloc_time,all_gpu_kernel_time,"
    "all_gpu_gen_child_time,all_pool_ops_time,all_gpu_idle_time,"
    "all_termination_time,all_time_load_bal")


def write_dist(path: str, inst: int, lb: int, D: int, C: int, LB: int,
               comm_size: int, optimum: int, m: int, M: int, T: int,
               total_time: float, total_tree: int, total_sol: int,
               per_device: dict) -> None:
    """Distributed row (reference: print_results_file_dist_multi_gpu)."""
    n = len(per_device["tree"])
    zeros_i = [0] * n
    zeros_f = [0.0] * n
    cells = [
        f"{inst},{D},{C},{comm_size},{lb},{LB},{optimum},{m},{M},{T},"
        f"{total_time:.4f},{total_tree},{total_sol}",
        _fmt_int_array(per_device["tree"]),
        _fmt_int_array(per_device["sol"]),
        _fmt_int_array(per_device.get("evals", zeros_i)),
        _fmt_int_array(per_device.get("steals", zeros_i)),
        _fmt_int_array(per_device.get("steals", zeros_i)),
        _fmt_int_array(zeros_i),
        _fmt_int_array(per_device.get("recv", zeros_i)),   # dist load-bal nodes
        _fmt_float_array(zeros_f),
        _fmt_float_array(zeros_f),
        _fmt_float_array(per_device.get("kernel_time", zeros_f)),
        _fmt_float_array(per_device.get("gen_child_time", zeros_f)),
        _fmt_float_array(zeros_f),                     # pool ops: fused
        _fmt_float_array(per_device.get("idle_time", zeros_f)),
        _fmt_float_array(zeros_f),
        _fmt_float_array(per_device.get("balance_time", zeros_f)),
    ]
    _append(path, DIST_HEADER, ",".join(cells).rstrip(","))
