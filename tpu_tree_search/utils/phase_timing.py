"""Measured per-phase cost attribution for the CSV timing columns.

The reference brackets every phase of its host loop with wall-clock
timers (memcpy/malloc/kernel/genchild/poolops/idle/termination,
PFSP_statistic.c:69-112) and its `data/` scripts analyze the breakdown
(data/multigpu-stats-analysis.py:43-70). The TPU engine fuses the whole
pop->bound->prune->branch cycle into ONE compiled loop — the fusion is
the design's performance story, but it means phases cannot be timed
in-flight.

Instead the phase costs are MEASURED (not modeled) on the real instance
and the real shapes: the bound evaluation alone vs. the full step, each
compiled and timed on a warmed pool state; on a mesh additionally one
balance exchange. Wall-clock attribution then scales the measured unit
costs by each worker's actual counters:

    kernel_time[w]   = evals[w]  * (bound step time / evals per step)
    gen_child_time[w] = iters[w] * (full step - bound step)   # compaction
    time_load_bal[w] = rounds    * balance round time
    idle_time[w]     = elapsed - (the above)                  # remainder

so the columns are nonzero, per-worker-differentiated (a starved
worker's masked no-op steps land in idle), and sum to the measured loop
time by construction. memcpy/malloc stay structurally zero — those
phases truly do not exist here (HBM-resident pool, static allocation),
which is itself the honest datum.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import pallas_expand
from ..ops.batched import BoundTables


def _time_fn(fn, args, reps: int) -> float:
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def _time_in_loop(make_body, reps: int):
    """Returns a runner timing `reps` chained applications of
    `make_body(i, *args) -> scalar` in ONE compiled fori_loop dispatch.
    Isolated jit calls carry a ~7 ms dispatch floor through the
    remote-TPU runtime (measured), which inflates sub-millisecond unit
    costs 4-20x — exactly the error tools/validate_attribution.py
    caught in the round-2 attribution."""
    @jax.jit
    def loop(*a):
        def body(i, acc):
            return acc + make_body(i, *a)
        return jax.lax.fori_loop(0, reps, body, jnp.float32(0.0))

    def run(*a):
        loop(*a).block_until_ready()
        t0 = time.perf_counter()
        loop(*a).block_until_ready()
        return (time.perf_counter() - t0) / reps
    return run


@functools.partial(jax.jit, static_argnames=("lb_kind", "chunk", "tile"))
def _pop_and_bound(tables: BoundTables, state, lb_kind: int, chunk: int,
                   tile: int):
    """The step's pop + dense bound evaluation, nothing else — the
    'kernel' phase in reference terms (evaluate_gpu,
    PFSP_gpu_lib.cu:129-152). For LB2 this times the dense path through
    the same sweep implementation the engine uses (pallas pair kernel
    when lb2_kernel_fits, the XLA scan otherwise — timing the WRONG
    implementation overestimated the unit cost ~7x, caught by
    tools/validate_attribution.py). The dense sweep still overestimates
    the production two-phase route's sweep width (full N vs the
    survivor tiers); profile_phases scales it by the tier fraction —
    margins documented in BENCHMARKS.md."""
    from ..engine import device

    J = state.prmu.shape[0]
    M = tables.p.shape[0]
    P = int(tables.ma0.shape[0])
    if lb_kind == 2:
        # device.lb2_route owns BOTH the tile and the
        # which-implementation decision — the dense proxy must be timed
        # through the same sweep implementation the engine's route uses
        _, TB, pair_kernel = device.lb2_route(J, M, P, chunk, tile)
    else:
        TB = pallas_expand.effective_tile(J, chunk, tile, lb_kind,
                                          machines=M)
        pair_kernel = False
    p_prmu, p_depth, p_aux, *_ = device.pop_chunk(state, chunk, M)
    if lb_kind == 2 and pair_kernel:
        _, _, bounds = pallas_expand.expand(tables, p_prmu, p_depth,
                                            p_aux, lb_kind=2, tile=TB)
        return bounds
    if lb_kind == 2:
        # J > 64: production sweeps ride the streaming big-J pallas
        # kernel when its tile exists (lb2_bounds' own dispatch via
        # lb2_sweep_tile) — price THROUGH lb2_bounds so the proxy uses
        # the same implementation, not the dense-XLA scan (pricing the
        # wrong implementation is the round-2 bug class
        # tools/validate_attribution.py exists to catch)
        lb1b = pallas_expand.expand_bounds(tables, p_prmu, p_depth,
                                           p_aux, lb_kind=1, tile=TB)
        cf = pallas_expand._xla_parts(tables, p_prmu, p_depth,
                                      p_aux.astype(jnp.int32))[4]
        G = p_prmu.shape[1] // TB
        cf_cols = pallas_expand._to_cols(cf.astype(jnp.int32), G, TB, J)
        sched = pallas_expand.sched_mask_cols(p_prmu, p_depth, TB)
        return lb1b + pallas_expand.lb2_bounds(tables, cf_cols, sched)
    return pallas_expand.expand_bounds(tables, p_prmu, p_depth, p_aux,
                                       lb_kind=lb_kind, tile=TB)


def profile_phases(tables: BoundTables, state, lb_kind: int, chunk: int,
                   tile: int = 1024, reps: int = 3,
                   warm_iters: int = 8) -> dict:
    """Measured per-step phase costs on this instance/shapes.

    Returns {"bound": s/step, "step": s/step, "compact": s/step,
    "per_eval": s/eval}. `state` is any seeded pool state; it is run
    forward a few steps first (functionally — the caller's state is
    untouched) so the timed pops see realistic depths."""
    from ..engine import device

    warm = device.run(tables, state, lb_kind, chunk, max_iters=warm_iters,
                      tile=tile)
    if int(np.asarray(warm.size)) < 1:
        warm = state                      # tiny instance: profile the seed
    K = max(reps, 64)

    def timed_bound(kind):
        # K pops at K different window offsets (the -i*128 keeps the
        # loop body loop-variant so XLA cannot hoist it, while
        # preserving the pop window's lane-alignment residue — a -i
        # shift was measured ~4x slower through relayout copies)
        return _time_in_loop(
            lambda i, s: _pop_and_bound(
                tables, s._replace(size=jnp.maximum(s.size - i * 128, 1)),
                kind, chunk, tile).sum(dtype=jnp.float32), K)(warm)

    J = state.prmu.shape[0]
    M = tables.p.shape[0]
    P = int(tables.ma0.shape[0])
    from ..ops import batched as _b

    # device.lb2_route IS the engine's routing decision — sharing it is
    # what keeps the attribution from pricing a path the engine does
    # not take (the round-2 bug class tools/validate_attribution.py
    # exists to catch)
    route, _, _ = device.lb2_route(J, M, P, chunk, tile)
    if lb_kind == 2 and route == "prefilter":
        # prefilter engine: the timeable dense proxy sweeps ALL pairs
        # over the FULL grid; production sweeps run min(KH, P) head
        # pairs over the ~N/4 candidate tier and any remaining tail
        # pairs over the survivor tier — since the round-4 fine sweep
        # ladder (device.step sweep_tiers, rungs of N/64) the tail rung
        # sits snugly at ~5N/64 on the measured ta021 steady state
        # (nkeep ~43k of N=655k) rather than the old coarse 3N/32 rung.
        # Scale the sweep part by that tier fraction so the attribution
        # prices the path the engine actually takes (applies to the
        # J>64 classes too, whose sweeps run as the XLA scan over the
        # same tiers; for P <= KH the tail term is zero — one full
        # sweep at the candidate tier)
        t1 = timed_bound(1)
        t2 = max(timed_bound(2), t1)
        KH = _b.PAIR_PREFILTER
        frac = (0.25 * min(KH, P) / P
                + (5 / 64) * max(P - KH, 0) / P)
        t_bound = t1 + (t2 - t1) * frac
    else:
        t_bound = timed_bound(lb_kind)
    # full step: K live steps of the real compiled loop, one dispatch
    start = int(np.asarray(warm.iters))
    out0 = device.run(tables, warm, lb_kind, chunk, max_iters=start + 1,
                      tile=tile)
    out0.size.block_until_ready()       # compile outside the window
    t0 = time.perf_counter()
    out = device.run(tables, out0, lb_kind, chunk,
                     max_iters=start + 1 + K, tile=tile)
    out.size.block_until_ready()
    did = max(int(np.asarray(out.iters)) - start - 1, 1)
    t_step = max((time.perf_counter() - t0) / did, t_bound)
    return {
        "bound": t_bound,
        "step": t_step,
        "compact": t_step - t_bound,
        "per_eval": t_bound / float(chunk * J),
    }


def profile_balance(mesh, state_stacked, transfer_cap: int,
                    min_transfer: int, limit: int, reps: int = 3) -> float:
    """Measured wall time of one collective balance exchange on the mesh
    (the reference's `time_load_bal`, PFSP_statistic.c:123-167)."""
    from jax.sharding import PartitionSpec as P

    from ..engine import distributed
    from ..engine.device import SearchState
    from ..parallel.mesh import shard_map

    def one_round(*leaves):
        s = distributed._local_state(*leaves)
        s = distributed._balance_round(s, transfer_cap, min_transfer, limit)
        return distributed._expand(s)

    spec = tuple(P(distributed.AX) for _ in SearchState._fields)
    fn = jax.jit(shard_map(one_round, mesh, in_specs=spec, out_specs=spec))
    t_raw = _time_fn(lambda *s: fn(*s), tuple(state_stacked), reps)
    # balance rounds cannot chain inside one dispatch without measuring
    # the cheap cond-gated no-flow path instead of a real exchange, so
    # subtract the measured per-dispatch floor (a trivial jit call)
    trivial = jax.jit(lambda x: x + 1)
    t_disp = _time_fn(trivial, (jnp.float32(0.0),), reps)
    return max(t_raw - t_disp, 0.0)


def attribute(prof: dict, elapsed: float, evals, iters,
              balance_rounds: int = 0, t_balance: float = 0.0) -> dict:
    """Per-worker wall-clock attribution (see module docstring).

    `evals`/`iters` are (D,) arrays (or scalars for one device); returns
    {"kernel_time", "gen_child_time", "balance_time", "idle_time"} as
    (D,) float arrays summing (with the others) to ~elapsed."""
    evals = np.atleast_1d(np.asarray(evals, dtype=float))
    iters = np.broadcast_to(
        np.atleast_1d(np.asarray(iters, dtype=float)), evals.shape)
    kernel = evals * prof["per_eval"]
    compact = iters * prof["compact"]
    balance = np.full_like(kernel, balance_rounds * t_balance)
    idle = np.clip(elapsed - kernel - compact - balance, 0.0, None)
    return {"kernel_time": kernel, "gen_child_time": compact,
            "balance_time": balance, "idle_time": idle}


def publish_attribution(att: dict, registry=None, **labels) -> None:
    """Publish an :func:`attribute` result into a metrics registry
    (obs/metrics) as ``tts_phase_seconds{phase=, worker=, ...labels}``
    gauges — the live exposition of the per-worker breakdown that used
    to exist only in end-of-run CSV rows (the reference's
    PFSP_statistic.c columns). The search service calls this per
    heartbeat with ``request=<id>`` labels (server.py `phase_profile`);
    the CLI's CSV writer publishes its end-of-run attribution the same
    way, so `/metrics` and the CSV can never disagree."""
    from ..obs import metrics as obs_metrics

    reg = registry if registry is not None else obs_metrics.default()
    g = reg.gauge("tts_phase_seconds",
                  "measured per-worker wall-clock phase attribution")
    for phase, arr in att.items():
        name = phase[:-5] if phase.endswith("_time") else phase
        for w, v in enumerate(np.atleast_1d(np.asarray(arr, float))):
            g.set(float(v), phase=name, worker=w, **labels)
