"""Measured per-phase cost attribution for the CSV timing columns.

The reference brackets every phase of its host loop with wall-clock
timers (memcpy/malloc/kernel/genchild/poolops/idle/termination,
PFSP_statistic.c:69-112) and its `data/` scripts analyze the breakdown
(data/multigpu-stats-analysis.py:43-70). The TPU engine fuses the whole
pop->bound->prune->branch cycle into ONE compiled loop — the fusion is
the design's performance story, but it means phases cannot be timed
in-flight.

Instead the phase costs are MEASURED (not modeled) on the real instance
and the real shapes: the bound evaluation alone vs. the full step, each
compiled and timed on a warmed pool state; on a mesh additionally one
balance exchange. Wall-clock attribution then scales the measured unit
costs by each worker's actual counters:

    kernel_time[w]   = evals[w]  * (bound step time / evals per step)
    gen_child_time[w] = iters[w] * (full step - bound step)   # compaction
    time_load_bal[w] = rounds    * balance round time
    idle_time[w]     = elapsed - (the above)                  # remainder

so the columns are nonzero, per-worker-differentiated (a starved
worker's masked no-op steps land in idle), and sum to the measured loop
time by construction. memcpy/malloc stay structurally zero — those
phases truly do not exist here (HBM-resident pool, static allocation),
which is itself the honest datum.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import pallas_expand
from ..ops.batched import BoundTables


def _time_fn(fn, args, reps: int) -> float:
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


@functools.partial(jax.jit, static_argnames=("lb_kind", "chunk", "tile"))
def _pop_and_bound(tables: BoundTables, state, lb_kind: int, chunk: int,
                   tile: int):
    """The step's pop + dense bound evaluation, nothing else — the
    'kernel' phase in reference terms (evaluate_gpu,
    PFSP_gpu_lib.cu:129-152)."""
    from ..engine import device

    J = state.prmu.shape[0]
    M = tables.p.shape[0]
    TB = pallas_expand.effective_tile(J, chunk, tile, lb_kind)
    p_prmu, p_depth, p_aux, *_ = device.pop_chunk(state, chunk, M)
    return pallas_expand.expand_bounds(tables, p_prmu, p_depth, p_aux,
                                       lb_kind=lb_kind, tile=TB)


def profile_phases(tables: BoundTables, state, lb_kind: int, chunk: int,
                   tile: int = 1024, reps: int = 3,
                   warm_iters: int = 8) -> dict:
    """Measured per-step phase costs on this instance/shapes.

    Returns {"bound": s/step, "step": s/step, "compact": s/step,
    "per_eval": s/eval}. `state` is any seeded pool state; it is run
    forward a few steps first (functionally — the caller's state is
    untouched) so the timed pops see realistic depths."""
    from ..engine import device

    warm = device.run(tables, state, lb_kind, chunk, max_iters=warm_iters)
    if int(np.asarray(warm.size)) < 1:
        warm = state                      # tiny instance: profile the seed
    t_bound = _time_fn(
        lambda s: _pop_and_bound(tables, s, lb_kind, chunk, tile),
        (warm,), reps)
    step_fn = jax.jit(functools.partial(device.step, tables, lb_kind,
                                        chunk, tile=tile))
    t_step = _time_fn(step_fn, (warm,), reps)
    t_step = max(t_step, t_bound)
    J = state.prmu.shape[0]
    return {
        "bound": t_bound,
        "step": t_step,
        "compact": t_step - t_bound,
        "per_eval": t_bound / float(chunk * J),
    }


def profile_balance(mesh, state_stacked, transfer_cap: int,
                    min_transfer: int, limit: int, reps: int = 3) -> float:
    """Measured wall time of one collective balance exchange on the mesh
    (the reference's `time_load_bal`, PFSP_statistic.c:123-167)."""
    from jax.sharding import PartitionSpec as P

    from ..engine import distributed
    from ..engine.device import SearchState
    from ..parallel.mesh import shard_map

    def one_round(*leaves):
        s = distributed._local_state(*leaves)
        s = distributed._balance_round(s, transfer_cap, min_transfer, limit)
        return distributed._expand(s)

    spec = tuple(P(distributed.AX) for _ in SearchState._fields)
    fn = jax.jit(shard_map(one_round, mesh, in_specs=spec, out_specs=spec))
    return _time_fn(lambda *s: fn(*s), tuple(state_stacked), reps)


def attribute(prof: dict, elapsed: float, evals, iters,
              balance_rounds: int = 0, t_balance: float = 0.0) -> dict:
    """Per-worker wall-clock attribution (see module docstring).

    `evals`/`iters` are (D,) arrays (or scalars for one device); returns
    {"kernel_time", "gen_child_time", "balance_time", "idle_time"} as
    (D,) float arrays summing (with the others) to ~elapsed."""
    evals = np.atleast_1d(np.asarray(evals, dtype=float))
    iters = np.broadcast_to(
        np.atleast_1d(np.asarray(iters, dtype=float)), evals.shape)
    kernel = evals * prof["per_eval"]
    compact = iters * prof["compact"]
    balance = np.full_like(kernel, balance_rounds * t_balance)
    idle = np.clip(elapsed - kernel - compact - balance, 0.0, None)
    return {"kernel_time": kernel, "gen_child_time": compact,
            "balance_time": balance, "idle_time": idle}
