"""Analytic roofline model for the bound kernels.

TPU re-expression of the reference's per-invocation FLOP/byte model
(reference: pfsp/lib/PFSP_gpu_lib.cu:213-267 — `flop_lb1`, `flop_lb2`,
`bytes_per_inv_lb1`, `bytes_per_inv_lb2`, `P_of`). The reference flagged
its model TODO/unused; here it is wired to the live engine so a bench run
can report arithmetic intensity and the roofline-implied ceiling next to
the measured rate.

Op counts follow the reference's accounting style (one add and one max of
the DP chain both count as one "flop"-equivalent integer op):

- LB1 per child (the engine's incremental form): the `add_forward` chain
  into the child front is 2M ops (max+add per machine), the remain update
  is M subtracts, and `machine_bound_from_parts` is ~3M ops
  (add, max, max per machine) — c_bound_simple.c:31-38, 126-141.
- LB1_d per child: `add_front_and_bound` is ~5 ops per machine
  (c_bound_simple.c:218-244).
- LB2 per child: the Johnson sweep over all P = M(M-1)/2 machine pairs
  costs ~5 ops per (pair, job) plus the 2M-op child-front chain
  (c_bound_johnson.c:190-237).

Bytes per invocation count the pool-row traffic the engine actually
moves per child slot (pop + push of [prmu | depth | front | remain]).
"""

from __future__ import annotations

import dataclasses

# v5e ballpark peaks (per chip). The model only needs orders of
# magnitude: it classifies kernels as compute- vs bandwidth-bound and
# bounds the achievable node-eval rate.
DEFAULT_PEAK_VECTOR_OPS = 4.0e13   # int/f32 elementwise ops/s (VPU+MXU)
DEFAULT_PEAK_HBM_BYTES = 8.0e11    # HBM bytes/s


def pairs_of(machines: int) -> int:
    """Number of two-machine pairs (reference: P_of, PFSP_gpu_lib.cu:262)."""
    return machines * (machines - 1) // 2


def flops_per_child(lb_kind: int, jobs: int, machines: int) -> float:
    """Integer-op count to bound one child (reference: flop_lb1/flop_lb2,
    PFSP_gpu_lib.cu:213-233, restated for the incremental TPU kernels)."""
    m = machines
    if lb_kind == 0:      # LB1_d: add_front_and_bound
        return 5.0 * m
    if lb_kind == 1:      # LB1: child-front chain + remain + combine
        return 2.0 * m + m + 3.0 * m
    if lb_kind == 2:      # LB2: child-front chain + all-pairs Johnson sweep
        return 2.0 * m + 5.0 * jobs * pairs_of(m) + 2.0 * pairs_of(m)
    raise ValueError(f"unknown lb_kind {lb_kind}")


def bytes_per_child(lb_kind: int, jobs: int, machines: int) -> float:
    """Pool-row HBM traffic per child slot (reference: bytes_per_inv_*,
    PFSP_gpu_lib.cu:236-259). A pushed child writes its permutation
    (int16), depth (int16) and front vector (M int32; remain is
    reconstructed in-kernel); a pop re-reads them. Amortized per dense
    child slot."""
    row = 2 * jobs + 2 + 4 * machines
    # pop read + push write (+ the compaction pass reads and rewrites the
    # row once more)
    return 3.0 * row


@dataclasses.dataclass
class RooflinePoint:
    lb_kind: int
    jobs: int
    machines: int
    flops_per_child: float
    bytes_per_child: float
    intensity: float                 # ops per HBM byte
    bound_compute: float             # children/s ceiling, compute roof
    bound_memory: float              # children/s ceiling, bandwidth roof
    bound: float                     # min of the two

    @property
    def regime(self) -> str:
        return ("compute-bound" if self.bound_compute < self.bound_memory
                else "bandwidth-bound")


def analyze(lb_kind: int, jobs: int, machines: int,
            peak_ops: float = DEFAULT_PEAK_VECTOR_OPS,
            peak_bytes: float = DEFAULT_PEAK_HBM_BYTES) -> RooflinePoint:
    """Roofline ceiling for one (bound, instance-class) point."""
    f = flops_per_child(lb_kind, jobs, machines)
    b = bytes_per_child(lb_kind, jobs, machines)
    bc = peak_ops / f
    bm = peak_bytes / b
    return RooflinePoint(
        lb_kind=lb_kind, jobs=jobs, machines=machines,
        flops_per_child=f, bytes_per_child=b, intensity=f / b,
        bound_compute=bc, bound_memory=bm, bound=min(bc, bm),
    )


def report(lb_kind: int, jobs: int, machines: int,
           measured_rate: float | None = None) -> str:
    """Human-readable roofline summary (optionally vs a measured rate)."""
    pt = analyze(lb_kind, jobs, machines)
    lines = [
        f"roofline lb{lb_kind} ({jobs} jobs x {machines} machines): "
        f"{pt.flops_per_child:.0f} ops/child, {pt.bytes_per_child:.0f} "
        f"B/child, intensity {pt.intensity:.2f} ops/B -> {pt.regime}",
        f"  ceiling: {pt.bound:.3e} children/s "
        f"(compute roof {pt.bound_compute:.3e}, "
        f"memory roof {pt.bound_memory:.3e})",
    ]
    if measured_rate is not None:
        lines.append(f"  measured: {measured_rate:.3e} children/s "
                     f"({measured_rate / pt.bound:.1%} of ceiling)")
    return "\n".join(lines)
