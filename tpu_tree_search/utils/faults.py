"""Deterministic fault injection for the resilience layer.

None of the recovery paths (checkpoint rollback, segment retry, campaign
respawn, elastic resume) can be trusted without a way to make the
failures happen on demand. This module is that way: a handful of named
injection points threaded through the segmented driver
(engine/checkpoint.run_segmented), the host-fetch path
(checkpoint._fetch_many) and the campaign supervisor
(tools/run_campaign.py), each firing deterministically from an
env-/config-driven plan — so every fault a production run can hit has a
repeatable test (tests/test_resilience.py).

The plan is declared as a comma-separated spec, either via the
``TTS_FAULTS`` environment variable (it survives the campaign
supervisor's worker respawns — the worker subprocess inherits it) or
programmatically via :func:`configure`:

    TTS_FAULTS="kill_after_segment=3"        # os._exit(137) after seg 3's
                                             # checkpoint (preemption)
    TTS_FAULTS="corrupt_checkpoint=2"        # flip bytes in the file
                                             # written at segment 2
                                             # (torn/corrupt write)
    TTS_FAULTS="delay_segment=2:1.5"         # sleep 1.5 s before seg 2
                                             # (slow dispatch)
    TTS_FAULTS="fail_host_fetch=1"           # first 1 host fetches raise
                                             # InjectedFault (transient
                                             # device/tunnel error)
    TTS_FAULTS="delay_every=0.05"            # sleep 0.05 s before EVERY
                                             # segment (uniform slowdown —
                                             # makes short searches span
                                             # many wall-clock segments so
                                             # preemption/deadline tests
                                             # have a window to act in)

Specs compose: ``"delay_segment=2:0.1,kill_after_segment=4"``. Unknown
names raise at parse time — a typo'd fault spec that silently injects
nothing would green-light an untested recovery path.

Counters ("once" semantics, e.g. fail_host_fetch) live ON the plan
object: a respawned worker re-parses TTS_FAULTS into a fresh plan and
re-arms them — exactly the transient-error model (the retried operation
succeeds) — and concurrently scoped plans each have their own budget.

Plans can also be THREAD-SCOPED via :func:`scoped`: the search service
runs one executor thread per submesh, and a per-request fault plan must
hit only that request's segments — a process-global plan would delay or
kill every concurrently served request. ``scoped(None)`` masks the
global plan for the thread (a clean request beside a faulty one).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time


class InjectedFault(RuntimeError):
    """A deliberately injected transient fault (retryable by design)."""


# exit code used by the kill injection; distinct from Python tracebacks
# (1) and the campaign's wrong-answer abort (3), and conventionally
# SIGKILL's 128+9 — what a real preemption looks like to the supervisor
KILL_EXIT_CODE = 137


@dataclasses.dataclass
class FaultPlan:
    """Parsed injection plan; all fields optional (None/0 = disarmed)."""

    kill_after_segment: int | None = None    # os._exit after this segment
    corrupt_checkpoint: int | None = None    # flip bytes in the file
                                             # written at this segment
    delay_segment: tuple[int, float] | None = None   # (segment, seconds)
    delay_every: float = 0.0                 # sleep before EVERY segment
    fail_host_fetch: int = 0                 # fail the first N fetches
    # fire count lives ON the plan (not module state): a thread-scoped
    # plan must have its own injection budget — concurrent requests with
    # scoped plans would otherwise spend each other's failures
    fetch_failures_fired: int = dataclasses.field(default=0, repr=False)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            name, _, val = item.partition("=")
            name = name.strip()
            if name == "kill_after_segment":
                plan.kill_after_segment = int(val)
            elif name == "corrupt_checkpoint":
                plan.corrupt_checkpoint = int(val)
            elif name == "delay_segment":
                seg, _, secs = val.partition(":")
                plan.delay_segment = (int(seg), float(secs or 0.1))
            elif name == "delay_every":
                plan.delay_every = float(val)
            elif name == "fail_host_fetch":
                plan.fail_host_fetch = int(val)
            else:
                raise ValueError(
                    f"unknown fault {name!r} in TTS_FAULTS spec {spec!r}")
        return plan


# module state: the active global plan (fire counters live on the plan)
_plan: FaultPlan | None = None
_configured = False        # False: (re)read TTS_FAULTS lazily
_tls = threading.local()   # per-thread plan overlay stack (scoped())


def configure(plan: FaultPlan | str | None) -> None:
    """Install a plan programmatically (tests); None disarms entirely."""
    global _plan, _configured
    _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    _configured = True


def reset() -> None:
    """Back to env-driven lazy configuration (test teardown)."""
    global _plan, _configured
    _plan = None
    _configured = False


@contextlib.contextmanager
def scoped(plan: FaultPlan | str | None):
    """Overlay a plan for the CURRENT THREAD only (nestable). Inside the
    context, :func:`active` returns this plan instead of the global one;
    other threads keep seeing the global/env plan. ``scoped(None)``
    masks any global plan (a deliberately clean thread). The search
    service uses this so a per-request fault spec fires only in that
    request's executor thread."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(FaultPlan.parse(plan) if isinstance(plan, str) else plan)
    try:
        yield
    finally:
        stack.pop()


def active() -> FaultPlan | None:
    """The current plan — the innermost thread-scoped overlay if one is
    installed (see :func:`scoped`), else the global/env plan (lazily
    parsed from TTS_FAULTS), or None."""
    global _plan, _configured
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    if not _configured:
        from . import config as _cfg
        spec = _cfg.env_str("TTS_FAULTS") or ""
        _plan = FaultPlan.parse(spec) if spec else None
        _configured = True
    return _plan


def corrupt_file(path, offset_frac: float = 0.5, n_bytes: int = 64) -> None:
    """Flip `n_bytes` bytes in the middle of `path` in place — the
    deterministic stand-in for a torn write / bit rot. Flipping (XOR
    0xFF) the compressed payload breaks both the zip member CRC and the
    checkpoint's own embedded CRC32, so every integrity tier sees it."""
    size = os.path.getsize(path)
    off = max(0, min(int(size * offset_frac), size - n_bytes))
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n_bytes)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))


def fire(point: str, segment: int | None = None, path=None) -> None:
    """Trigger the injection point `point` if the active plan arms it.

    Points (all no-ops without a matching plan entry):
    - "segment_start"   (segment=k): sleep delay_every (every segment)
      and/or the delay_segment sleep if it targets k.
    - "post_checkpoint" (segment=k, path=...): corrupt the just-written
      checkpoint file if corrupt_checkpoint targets k.
    - "post_segment"    (segment=k): os._exit(KILL_EXIT_CODE) if
      kill_after_segment targets k — fires at the END of segment k,
      after any checkpoint that segment wrote. Like a real preemption
      it is NOT checkpoint-aligned: with checkpoint_every > 1 the
      snapshot on disk may be older and resume redoes that interval.
    - "host_fetch": raise InjectedFault while the fail_host_fetch
      budget lasts (then succeed — the transient-error model).
    """
    plan = active()
    if plan is None:
        return
    if point == "segment_start":
        if plan.delay_every > 0:
            _record(point, "delay_every", segment=segment,
                    seconds=plan.delay_every)
            time.sleep(plan.delay_every)
        if plan.delay_segment and segment == plan.delay_segment[0]:
            _record(point, "delay_segment", segment=segment,
                    seconds=plan.delay_segment[1])
            time.sleep(plan.delay_segment[1])
    elif point == "post_checkpoint":
        if (plan.corrupt_checkpoint is not None
                and segment == plan.corrupt_checkpoint
                and path is not None and os.path.exists(path)):
            _record(point, "corrupt_checkpoint", segment=segment,
                    path=str(path))
            corrupt_file(path)
    elif point == "post_segment":
        if (plan.kill_after_segment is not None
                and segment == plan.kill_after_segment):
            # the flight-recorder sink is line-buffered, so the record
            # reaches the OS before the exit below skips every flush
            _record(point, "kill_after_segment", segment=segment)
            # a preemption does not run exit handlers or flush buffers;
            # os._exit is the honest simulation
            os._exit(KILL_EXIT_CODE)
    elif point == "host_fetch":
        if plan.fetch_failures_fired < plan.fail_host_fetch:
            plan.fetch_failures_fired += 1
            _record(point, "fail_host_fetch",
                    fired=plan.fetch_failures_fired,
                    budget=plan.fail_host_fetch)
            raise InjectedFault(
                f"injected host-fetch failure "
                f"{plan.fetch_failures_fired}/{plan.fail_host_fetch}")


def _record(point: str, fault: str, **attrs) -> None:
    """Flight-record an injection that actually FIRED (armed-but-idle
    points stay silent): a `fault.injected` event plus the
    `tts_faults_injected_total{point,fault}` counter, so a resilience
    drill's timeline shows the cause next to the recovery it tests."""
    from ..obs import metrics, tracelog
    tracelog.event("fault.injected", point=point, fault=fault, **attrs)
    metrics.default().counter(
        "tts_faults_injected_total",
        "deterministic fault injections that fired").inc(point=point,
                                                         fault=fault)
