"""Deterministic fault injection for the resilience layer.

None of the recovery paths (checkpoint rollback, segment retry, campaign
respawn, elastic resume) can be trusted without a way to make the
failures happen on demand. This module is that way: a handful of named
injection points threaded through the segmented driver
(engine/checkpoint.run_segmented), the host-fetch path
(checkpoint._fetch_many) and the campaign supervisor
(tools/run_campaign.py), each firing deterministically from an
env-/config-driven plan — so every fault a production run can hit has a
repeatable test (tests/test_resilience.py).

The plan is declared as a comma-separated spec, either via the
``TTS_FAULTS`` environment variable (it survives the campaign
supervisor's worker respawns — the worker subprocess inherits it) or
programmatically via :func:`configure`:

    TTS_FAULTS="kill_after_segment=3"        # os._exit(137) after seg 3's
                                             # checkpoint (preemption)
    TTS_FAULTS="corrupt_checkpoint=2"        # flip bytes in the file
                                             # written at segment 2
                                             # (torn/corrupt write)
    TTS_FAULTS="delay_segment=2:1.5"         # sleep 1.5 s before seg 2
                                             # (slow dispatch)
    TTS_FAULTS="fail_host_fetch=1"           # first 1 host fetches raise
                                             # InjectedFault (transient
                                             # device/tunnel error)
    TTS_FAULTS="delay_every=0.05"            # sleep 0.05 s before EVERY
                                             # segment (uniform slowdown —
                                             # makes short searches span
                                             # many wall-clock segments so
                                             # preemption/deadline tests
                                             # have a window to act in)
    TTS_FAULTS="kill_submesh=2:1@0"          # raise InjectedKill at the
                                             # start of segment 2, at most
                                             # 1 time, only on submesh 0 —
                                             # a submesh dying mid-request
                                             # (the thread-level analogue
                                             # of kill_after_segment; the
                                             # service retry/remediation
                                             # tier is the recovery)
    TTS_FAULTS="oom_segment=2"               # raise InjectedOOM (a
                                             # RESOURCE_EXHAUSTED-shaped
                                             # transient) at segment 2
    TTS_FAULTS="wedge_executor=2:5.0"        # sleep 5 s at the start of
                                             # segment 2, once — a wedged
                                             # device dispatch: heartbeats
                                             # stop, the health layer's
                                             # stall rule fires, the
                                             # remediation drill acts
    TTS_FAULTS="kill_server=3"               # os._exit(137) at the START
                                             # of segment 3, before it
                                             # dispatches — the WHOLE
                                             # serving process dies hard
                                             # (no flush, no handlers: a
                                             # real kill -9/OOM). The
                                             # request ledger + restart
                                             # replay is the recovery
                                             # (CI crash-restart leg)
    TTS_FAULTS="sigterm_server=3"            # deliver SIGTERM to our own
                                             # process at the start of
                                             # segment 3, once — the
                                             # graceful-drain drill: the
                                             # serve entry stops
                                             # admission, preempts at
                                             # segment boundaries, drains
                                             # every writer and exits 0
                                             # inside TTS_DRAIN_TIMEOUT_S
    TTS_FAULTS="pause_server=2:12"           # at the start of segment 2,
                                             # once: suspend this
                                             # process's lease renewals
                                             # (service/lease.py) AND
                                             # sleep 12 s — a stalled-
                                             # but-alive owner (GC pause,
                                             # NFS hang). With the pause
                                             # longer than TTS_LEASE_TTL_S
                                             # a peer adopts the ledger
                                             # mid-pause, and on waking
                                             # the stale owner must
                                             # SELF-FENCE at its next
                                             # append/save — the split-
                                             # brain drill the fencing
                                             # epoch exists for

The chaos-drill kinds (kill_submesh / oom_segment / wedge_executor /
kill_server / sigterm_server / pause_server) accept an optional
``@SUBMESH`` suffix: the injection fires only in a
thread whose ambient flight-recorder context (obs/tracelog) carries
that submesh index — so a GLOBAL plan can target one submesh of a
serving mesh while requests on the other submeshes run clean, which is
exactly the failure geometry the quarantine path exists for.
kill_submesh and oom_segment also take a fire budget
(``kill_submesh=SEG:BUDGET``, default 1) counted on the plan like
fail_host_fetch; wedge_executor and pause_server fire at most once per
plan.

Specs compose: ``"delay_segment=2:0.1,kill_after_segment=4"``. Unknown
names raise at parse time — a typo'd fault spec that silently injects
nothing would green-light an untested recovery path.

Counters ("once" semantics, e.g. fail_host_fetch) live ON the plan
object: a respawned worker re-parses TTS_FAULTS into a fresh plan and
re-arms them — exactly the transient-error model (the retried operation
succeeds) — and concurrently scoped plans each have their own budget.

Plans can also be THREAD-SCOPED via :func:`scoped`: the search service
runs one executor thread per submesh, and a per-request fault plan must
hit only that request's segments — a process-global plan would delay or
kill every concurrently served request. ``scoped(None)`` masks the
global plan for the thread (a clean request beside a faulty one).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time


class InjectedFault(RuntimeError):
    """A deliberately injected transient fault (retryable by design)."""


class InjectedKill(InjectedFault):
    """A submesh 'died' under this request (kill_submesh): the dispatch
    is gone, the thread survives. Transient-class on purpose — the
    service retry/remediation tier redispatches elsewhere."""


class InjectedOOM(InjectedFault):
    """An injected device OOM (oom_segment) — the message mimics the
    runtime's RESOURCE_EXHAUSTED wording so log-greppers treat drills
    and real incidents alike."""


# exit code used by the kill injection; distinct from Python tracebacks
# (1) and the campaign's wrong-answer abort (3), and conventionally
# SIGKILL's 128+9 — what a real preemption looks like to the supervisor
KILL_EXIT_CODE = 137


@dataclasses.dataclass
class FaultPlan:
    """Parsed injection plan; all fields optional (None/0 = disarmed)."""

    kill_after_segment: int | None = None    # os._exit after this segment
    corrupt_checkpoint: int | None = None    # flip bytes in the file
                                             # written at this segment
    delay_segment: tuple[int, float] | None = None   # (segment, seconds)
    delay_every: float = 0.0                 # sleep before EVERY segment
    fail_host_fetch: int = 0                 # fail the first N fetches
    # chaos-drill kinds (the self-healing service's reproducible fault
    # geometry): (segment, budget, submesh|None) for the raisers,
    # (segment, seconds, submesh|None) for the wedge
    kill_submesh: tuple[int, int, int | None] | None = None
    oom_segment: tuple[int, int, int | None] | None = None
    wedge_executor: tuple[int, float, int | None] | None = None
    # crash-safe-serving drills: kill_server hard-kills the WHOLE
    # process (os._exit, no flush — a real SIGKILL/OOM) at the start
    # of the segment, BEFORE it dispatches, so the death is
    # checkpoint-exact like kill_submesh; sigterm_server delivers
    # SIGTERM to our own pid (the graceful-drain drill)
    kill_server: tuple[int, int, int | None] | None = None
    sigterm_server: tuple[int, int, int | None] | None = None
    # split-brain drill: (segment, seconds, submesh|None) — suspend
    # lease renewals AND wedge the thread for `seconds`, once: a
    # stalled-but-alive owner whose lease expires under it
    pause_server: tuple[int, float, int | None] | None = None
    # fire count lives ON the plan (not module state): a thread-scoped
    # plan must have its own injection budget — concurrent requests with
    # scoped plans would otherwise spend each other's failures
    fetch_failures_fired: int = dataclasses.field(default=0, repr=False)
    kills_fired: int = dataclasses.field(default=0, repr=False)
    ooms_fired: int = dataclasses.field(default=0, repr=False)
    wedges_fired: int = dataclasses.field(default=0, repr=False)
    sigterms_fired: int = dataclasses.field(default=0, repr=False)
    pauses_fired: int = dataclasses.field(default=0, repr=False)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            name, _, val = item.partition("=")
            name = name.strip()
            if name == "kill_after_segment":
                plan.kill_after_segment = int(val)
            elif name == "corrupt_checkpoint":
                plan.corrupt_checkpoint = int(val)
            elif name == "delay_segment":
                seg, _, secs = val.partition(":")
                plan.delay_segment = (int(seg), float(secs or 0.1))
            elif name == "delay_every":
                plan.delay_every = float(val)
            elif name == "fail_host_fetch":
                plan.fail_host_fetch = int(val)
            elif name == "kill_submesh":
                plan.kill_submesh = _parse_drill(val, int, 1)
            elif name == "oom_segment":
                plan.oom_segment = _parse_drill(val, int, 1)
            elif name == "wedge_executor":
                plan.wedge_executor = _parse_drill(val, float, 5.0)
            elif name == "kill_server":
                plan.kill_server = _parse_drill(val, int, 1)
            elif name == "sigterm_server":
                plan.sigterm_server = _parse_drill(val, int, 1)
            elif name == "pause_server":
                plan.pause_server = _parse_drill(val, float, 5.0)
            else:
                raise ValueError(
                    f"unknown fault {name!r} in TTS_FAULTS spec {spec!r}")
        return plan


def _parse_drill(val: str, second_type, second_default):
    """Parse a chaos-drill value ``SEG[:X][@SUBMESH]`` into
    (segment, x, submesh|None) — x is the fire budget (kill/oom) or the
    wedge seconds, submesh the optional ambient-context filter."""
    body, _, submesh = val.partition("@")
    seg, _, x = body.partition(":")
    return (int(seg),
            second_type(x) if x.strip() else second_type(second_default),
            int(submesh) if submesh.strip() else None)


def _ambient_submesh() -> int | None:
    """The submesh index of the calling thread's flight-recorder
    context (obs/tracelog) — how an @SUBMESH-filtered drill decides
    whether THIS thread is on the targeted submesh. None outside any
    service executor/canary context (the filter then never matches)."""
    from ..obs import tracelog
    sm = tracelog.current_context().get("submesh")
    return int(sm) if sm is not None else None


def _submesh_matches(target: int | None) -> bool:
    return target is None or _ambient_submesh() == target


# module state: the active global plan (fire counters live on the plan)
_plan: FaultPlan | None = None
_configured = False        # False: (re)read TTS_FAULTS lazily
_tls = threading.local()   # per-thread plan overlay stack (scoped())


def configure(plan: FaultPlan | str | None) -> None:
    """Install a plan programmatically (tests); None disarms entirely."""
    global _plan, _configured
    _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    _configured = True


def reset() -> None:
    """Back to env-driven lazy configuration (test teardown)."""
    global _plan, _configured
    _plan = None
    _configured = False


@contextlib.contextmanager
def scoped(plan: FaultPlan | str | None):
    """Overlay a plan for the CURRENT THREAD only (nestable). Inside the
    context, :func:`active` returns this plan instead of the global one;
    other threads keep seeing the global/env plan. ``scoped(None)``
    masks any global plan (a deliberately clean thread). The search
    service uses this so a per-request fault spec fires only in that
    request's executor thread."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(FaultPlan.parse(plan) if isinstance(plan, str) else plan)
    try:
        yield
    finally:
        stack.pop()


def active() -> FaultPlan | None:
    """The current plan — the innermost thread-scoped overlay if one is
    installed (see :func:`scoped`), else the global/env plan (lazily
    parsed from TTS_FAULTS), or None."""
    global _plan, _configured
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    if not _configured:
        from . import config as _cfg
        spec = _cfg.env_str("TTS_FAULTS") or ""
        _plan = FaultPlan.parse(spec) if spec else None
        _configured = True
    return _plan


def corrupt_file(path, offset_frac: float = 0.5, n_bytes: int = 64) -> None:
    """Flip `n_bytes` bytes in the middle of `path` in place — the
    deterministic stand-in for a torn write / bit rot. Flipping (XOR
    0xFF) the compressed payload breaks both the zip member CRC and the
    checkpoint's own embedded CRC32, so every integrity tier sees it."""
    size = os.path.getsize(path)
    off = max(0, min(int(size * offset_frac), size - n_bytes))
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n_bytes)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))


def fire(point: str, segment: int | None = None, path=None) -> None:
    """Trigger the injection point `point` if the active plan arms it.

    Points (all no-ops without a matching plan entry):
    - "segment_start"   (segment=k): sleep delay_every (every segment)
      and/or the delay_segment sleep if it targets k. The chaos-drill
      kinds fire here too, before the segment dispatches: wedge_executor
      sleeps its seconds (once per plan — a wedged dispatch), then
      kill_submesh raises InjectedKill / oom_segment raises InjectedOOM
      while their budgets last, each gated on the optional @SUBMESH
      ambient-context filter. Raising BEFORE the dispatch keeps the
      failure checkpoint-exact: segment k never ran, so a redispatch
      resuming from segment k-1's snapshot repeats nothing.
    - "post_checkpoint" (segment=k, path=...): corrupt the just-written
      checkpoint file if corrupt_checkpoint targets k.
    - "post_segment"    (segment=k): os._exit(KILL_EXIT_CODE) if
      kill_after_segment targets k — fires at the END of segment k,
      after any checkpoint that segment wrote. Like a real preemption
      it is NOT checkpoint-aligned: with checkpoint_every > 1 the
      snapshot on disk may be older and resume redoes that interval.
    - "host_fetch": raise InjectedFault while the fail_host_fetch
      budget lasts (then succeed — the transient-error model).
    """
    plan = active()
    if plan is None:
        return
    if point == "segment_start":
        if plan.delay_every > 0:
            _record(point, "delay_every", segment=segment,
                    seconds=plan.delay_every)
            time.sleep(plan.delay_every)
        if plan.delay_segment and segment == plan.delay_segment[0]:
            _record(point, "delay_segment", segment=segment,
                    seconds=plan.delay_segment[1])
            time.sleep(plan.delay_segment[1])
        if (plan.wedge_executor is not None
                and segment == plan.wedge_executor[0]
                and plan.wedges_fired < 1
                and _submesh_matches(plan.wedge_executor[2])):
            plan.wedges_fired += 1
            seconds = plan.wedge_executor[1]
            _record(point, "wedge_executor", segment=segment,
                    seconds=seconds, submesh=_ambient_submesh())
            # an uninterruptible sleep is the POINT: a wedged device
            # dispatch does not honor stop flags either — recovery is
            # the remediation tier acting from outside, never the
            # wedge cooperating. Keep drill durations bounded.
            time.sleep(seconds)
        if (plan.pause_server is not None
                and segment == plan.pause_server[0]
                and plan.pauses_fired < 1
                and _submesh_matches(plan.pause_server[2])):
            plan.pauses_fired += 1
            seconds = plan.pause_server[1]
            _record(point, "pause_server", segment=segment,
                    seconds=seconds, submesh=_ambient_submesh())
            # the split-brain drill: stop renewing OUR lease(s), then
            # wedge like wedge_executor — a GC pause / NFS hang where
            # the process is alive but the lease expires under it. A
            # peer adopts mid-pause; on waking, the next ledger append
            # or checkpoint save must SELF-FENCE (LeaseLost), which is
            # exactly what the drill's test asserts.
            try:
                from ..service import lease as _lease
                _lease.suspend_renewals(seconds)
            except ImportError:
                pass   # engine-only install: plain wedge, still a drill
            time.sleep(seconds)
        if (plan.kill_submesh is not None
                and segment == plan.kill_submesh[0]
                and plan.kills_fired < plan.kill_submesh[1]
                and _submesh_matches(plan.kill_submesh[2])):
            plan.kills_fired += 1
            _record(point, "kill_submesh", segment=segment,
                    fired=plan.kills_fired, budget=plan.kill_submesh[1],
                    submesh=_ambient_submesh())
            raise InjectedKill(
                f"injected submesh kill at segment {segment} "
                f"({plan.kills_fired}/{plan.kill_submesh[1]})")
        if (plan.oom_segment is not None
                and segment == plan.oom_segment[0]
                and plan.ooms_fired < plan.oom_segment[1]
                and _submesh_matches(plan.oom_segment[2])):
            plan.ooms_fired += 1
            _record(point, "oom_segment", segment=segment,
                    fired=plan.ooms_fired, budget=plan.oom_segment[1],
                    submesh=_ambient_submesh())
            raise InjectedOOM(
                f"RESOURCE_EXHAUSTED: injected device OOM at segment "
                f"{segment} ({plan.ooms_fired}/{plan.oom_segment[1]})")
        if (plan.sigterm_server is not None
                and segment == plan.sigterm_server[0]
                and plan.sigterms_fired < plan.sigterm_server[1]
                and _submesh_matches(plan.sigterm_server[2])):
            plan.sigterms_fired += 1
            _record(point, "sigterm_server", segment=segment,
                    submesh=_ambient_submesh())
            # our own pid: the graceful-drain drill — the serve entry's
            # handler stops admission, preempts at segment boundaries,
            # drains the writers and exits 0 (a process without that
            # handler just terminates, the default SIGTERM disposition)
            import signal
            os.kill(os.getpid(), signal.SIGTERM)
        if (plan.kill_server is not None
                and segment == plan.kill_server[0]
                and plan.kill_server[1] > 0
                and _submesh_matches(plan.kill_server[2])):
            # budget > 0 honored like the sibling drills (a fired kill
            # needs no counter: the process does not survive it)
            # the line-buffered recorder gets the record out before the
            # exit below skips every flush
            _record(point, "kill_server", segment=segment,
                    submesh=_ambient_submesh())
            # a hard host death runs no exit handlers and flushes no
            # buffers; firing BEFORE the segment dispatches keeps the
            # death checkpoint-exact (segment k never ran), and the
            # request ledger + restart replay is the recovery the
            # drill exists to prove
            os._exit(KILL_EXIT_CODE)
    elif point == "post_checkpoint":
        if (plan.corrupt_checkpoint is not None
                and segment == plan.corrupt_checkpoint
                and path is not None and os.path.exists(path)):
            _record(point, "corrupt_checkpoint", segment=segment,
                    path=str(path))
            corrupt_file(path)
    elif point == "post_segment":
        if (plan.kill_after_segment is not None
                and segment == plan.kill_after_segment):
            # the flight-recorder sink is line-buffered, so the record
            # reaches the OS before the exit below skips every flush
            _record(point, "kill_after_segment", segment=segment)
            # a preemption does not run exit handlers or flush buffers;
            # os._exit is the honest simulation
            os._exit(KILL_EXIT_CODE)
    elif point == "host_fetch":
        if plan.fetch_failures_fired < plan.fail_host_fetch:
            plan.fetch_failures_fired += 1
            _record(point, "fail_host_fetch",
                    fired=plan.fetch_failures_fired,
                    budget=plan.fail_host_fetch)
            raise InjectedFault(
                f"injected host-fetch failure "
                f"{plan.fetch_failures_fired}/{plan.fail_host_fetch}")


def _record(point: str, fault: str, **attrs) -> None:
    """Flight-record an injection that actually FIRED (armed-but-idle
    points stay silent): a `fault.injected` event plus the
    `tts_faults_injected_total{point,fault}` counter, so a resilience
    drill's timeline shows the cause next to the recovery it tests."""
    from ..obs import metrics, tracelog
    tracelog.event("fault.injected", point=point, fault=fault, **attrs)
    metrics.default().counter(
        "tts_faults_injected_total",
        "deterministic fault injections that fired").inc(point=point,
                                                         fault=fault)
