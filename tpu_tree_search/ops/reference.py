"""Scalar numpy reference implementations of the PFSP lower bounds.

These are the ground-truth semantics for LB1 / LB1_d / LB2, written for
clarity and used (a) by the sequential oracle engine and (b) as the golden
values the batched JAX/Pallas kernels are tested against. The math follows
the reference exactly:

- LB1  one-machine bound         (reference: pfsp/lib/c_bound_simple.c:143-158)
- LB1_d incremental all-children (reference: c_bound_simple.c:160-244)
- LB2  two-machine Johnson bound (reference: pfsp/lib/c_bound_johnson.c:211-254)

Conventions: `p_times` is (machines, jobs); a partial permutation `perm`
has its scheduled prefix at positions `0..limit1` and suffix at
`limit2..jobs-1` (all engines here branch forward only, so `limit2 == jobs`
and the suffix is empty — kept general to match the reference signatures).
"""

from __future__ import annotations

import dataclasses

import numpy as np


# ---------------------------------------------------------------------------
# LB1: one-machine bound


@dataclasses.dataclass
class LB1Data:
    """Precomputed tables for LB1 (reference: c_bound_simple.h:51-53)."""

    p_times: np.ndarray    # (machines, jobs) int
    min_heads: np.ndarray  # (machines,) earliest possible arrival at machine k
    min_tails: np.ndarray  # (machines,) minimal run-out after machine k


def make_lb1_data(p_times: np.ndarray) -> LB1Data:
    """Precompute min_heads/min_tails (reference: c_bound_simple.c:277-322).

    min_heads[k] = min over jobs of the completion time of the job on
    machine k-1 when it runs first (the earliest any job can reach machine
    k); min_tails[k] = min over jobs of the tail below machine k when the
    job runs last.
    """
    p = np.asarray(p_times, dtype=np.int64)
    m, n = p.shape

    heads = np.cumsum(p, axis=0)              # (m, n): head of job j through mach k
    min_heads = np.empty(m, dtype=np.int64)
    min_heads[0] = 0
    if m > 1:
        min_heads[1:] = heads[:-1].min(axis=1)

    tails = np.cumsum(p[::-1], axis=0)[::-1]  # (m, n): tail of job j from mach k down
    min_tails = np.empty(m, dtype=np.int64)
    min_tails[m - 1] = 0
    if m > 1:
        min_tails[:-1] = tails[1:].min(axis=1)

    return LB1Data(p_times=p, min_heads=min_heads, min_tails=min_tails)


def add_forward(job: int, p: np.ndarray, front: np.ndarray) -> None:
    """Append `job` to the prefix schedule (reference: c_bound_simple.c:31-38)."""
    front[0] += p[0, job]
    for k in range(1, p.shape[0]):
        front[k] = max(front[k - 1], front[k]) + p[k, job]


def add_backward(job: int, p: np.ndarray, back: np.ndarray) -> None:
    """Prepend `job` to the suffix schedule (reference: c_bound_simple.c:40-49)."""
    m = p.shape[0]
    back[m - 1] += p[m - 1, job]
    for k in range(m - 2, -1, -1):
        back[k] = max(back[k], back[k + 1]) + p[k, job]


def schedule_front(data: LB1Data, perm, limit1: int) -> np.ndarray:
    """Machine completion times of the prefix (reference: c_bound_simple.c:51-69)."""
    m = data.p_times.shape[0]
    if limit1 == -1:
        return data.min_heads.copy()
    front = np.zeros(m, dtype=np.int64)
    for i in range(limit1 + 1):
        add_forward(int(perm[i]), data.p_times, front)
    return front


def schedule_back(data: LB1Data, perm, limit2: int) -> np.ndarray:
    """Machine tail times of the suffix (reference: c_bound_simple.c:71-90)."""
    m, n = data.p_times.shape
    if limit2 == n:
        return data.min_tails.copy()
    back = np.zeros(m, dtype=np.int64)
    for i in range(n - 1, limit2 - 1, -1):
        add_backward(int(perm[i]), data.p_times, back)
    return back


def sum_unscheduled(data: LB1Data, perm, limit1: int, limit2: int) -> np.ndarray:
    """Total unscheduled work per machine (reference: c_bound_simple.c:108-124)."""
    jobs = [int(perm[k]) for k in range(limit1 + 1, limit2)]
    if not jobs:
        return np.zeros(data.p_times.shape[0], dtype=np.int64)
    return data.p_times[:, jobs].sum(axis=1).astype(np.int64)


def machine_bound_from_parts(front, back, remain) -> int:
    """Chained per-machine bound (reference: c_bound_simple.c:126-141).

    On machine i the earliest completion of all remaining work is
    max_{j<=i}(chain) + remain contributions carried through a running max —
    note this is *not* simply max_i(front+remain+back); the running value
    `tmp0` threads machine-to-machine precedence.
    """
    m = len(front)
    tmp0 = int(front[0]) + int(remain[0])
    lb = tmp0 + int(back[0])
    for i in range(1, m):
        tmp1 = max(tmp0, int(front[i]) + int(remain[i]))
        lb = max(lb, tmp1 + int(back[i]))
        tmp0 = tmp1
    return lb


def lb1_bound(data: LB1Data, perm, limit1: int, limit2: int) -> int:
    """Full LB1 of one partial permutation (reference: c_bound_simple.c:143-158)."""
    front = schedule_front(data, perm, limit1)
    back = schedule_back(data, perm, limit2)
    remain = sum_unscheduled(data, perm, limit1, limit2)
    return machine_bound_from_parts(front, back, remain)


def add_front_and_bound(data: LB1Data, job: int, front, back, remain) -> int:
    """Bound of the child obtained by appending `job` to the prefix, computed
    incrementally from the parent's front/back/remain in O(machines)
    (reference: c_bound_simple.c:218-244). This is the LB1_d bound; its value
    differs from LB1's chained `machine_bound_from_parts` in general.
    """
    p = data.p_times
    m = p.shape[0]
    lb = int(front[0]) + int(remain[0]) + int(back[0])
    tmp0 = int(front[0]) + int(p[0, job])
    for i in range(1, m):
        tmp1 = max(tmp0, int(front[i]))
        lb = max(lb, tmp1 + int(remain[i]) + int(back[i]))
        tmp0 = tmp1 + int(p[i, job])
    return lb


def lb1_children_bounds(data: LB1Data, perm, limit1: int, limit2: int) -> np.ndarray:
    """LB1_d bounds of all children at once, indexed by job id
    (reference: c_bound_simple.c:160-211)."""
    n = data.p_times.shape[1]
    front = schedule_front(data, perm, limit1)
    back = schedule_back(data, perm, limit2)
    remain = sum_unscheduled(data, perm, limit1, limit2)
    lb_begin = np.zeros(n, dtype=np.int64)
    for i in range(limit1 + 1, limit2):
        job = int(perm[i])
        lb_begin[job] = add_front_and_bound(data, job, front, back, remain)
    return lb_begin


def prefix_front_remain(p_times: np.ndarray, prmu: np.ndarray,
                        depth: np.ndarray) -> np.ndarray:
    """Per-node pool auxiliary data `[front | remain]` (n, 2*machines) int32.

    `front` is the actual machine-completion vector of the scheduled prefix
    (zeros for an empty prefix — children chain from the parent's true
    front, not from min_heads) and `remain` the per-machine unscheduled
    work. This is what the device engines carry in the pool so bounds never
    rescan the prefix (the reference recomputes it per bound,
    c_bound_simple.c:51-69).
    """
    p = np.asarray(p_times, dtype=np.int64)
    m = p.shape[0]
    prmu = np.asarray(prmu).reshape(-1, p.shape[1])
    depth = np.asarray(depth).reshape(-1)
    total = p.sum(axis=1)
    out = np.zeros((prmu.shape[0], 2 * m), dtype=np.int32)
    for b in range(prmu.shape[0]):
        front = np.zeros(m, dtype=np.int64)
        sched = np.zeros(m, dtype=np.int64)
        for i in range(int(depth[b])):
            job = int(prmu[b, i])
            add_forward(job, p, front)
            sched += p[:, job]
        out[b, :m] = front
        out[b, m:] = total - sched
    return out


def eval_solution(data: LB1Data, perm) -> int:
    """Makespan of a complete permutation (reference: c_bound_simple.c:92-106)."""
    front = np.zeros(data.p_times.shape[0], dtype=np.int64)
    for job in perm:
        add_forward(int(job), data.p_times, front)
    return int(front[-1])


# ---------------------------------------------------------------------------
# LB2: two-machine Johnson bound (LB2_FULL variant: all machine pairs)


@dataclasses.dataclass
class LB2Data:
    """Precomputed tables for LB2 (reference: c_bound_johnson.h:32-49).

    For each ordered machine pair (m1 < m2): `lags[p, j]` is the total
    processing of job j on the machines strictly between m1 and m2
    (term q_iuv of [Lageweg'78]); `johnson_schedules[p]` is the optimal
    2-machine order of all jobs for the pair under Johnson's rule.
    """

    pairs_m1: np.ndarray            # (P,) first machine of each pair
    pairs_m2: np.ndarray            # (P,) second machine
    lags: np.ndarray                # (P, jobs)
    johnson_schedules: np.ndarray   # (P, jobs) job ids in Johnson order


def make_lb2_data(lb1: LB1Data) -> LB2Data:
    """Build all-pairs Johnson tables (reference: c_bound_johnson.c:48-178).

    Ties under Johnson's comparator are broken stably by job id (the
    reference uses qsort, whose tie order is unspecified); any
    tie-consistent order is Johnson-optimal so the bound values — and hence
    search trees — are unaffected.
    """
    p = lb1.p_times
    m, n = p.shape
    m1s, m2s = [], []
    for i in range(m - 1):
        for j in range(i + 1, m):
            m1s.append(i)
            m2s.append(j)
    pairs_m1 = np.array(m1s, dtype=np.int64)
    pairs_m2 = np.array(m2s, dtype=np.int64)
    npairs = len(m1s)

    # cumulative sums make lag(m1, m2) = sum of rows m1+1..m2-1 an O(1) lookup
    csum = np.concatenate([np.zeros((1, n), dtype=np.int64),
                           np.cumsum(p, axis=0)])
    lags = csum[pairs_m2] - csum[pairs_m1 + 1]          # (P, n)

    ptm1 = p[pairs_m1] + lags                           # (P, n)
    ptm2 = p[pairs_m2] + lags
    partition = (ptm1 >= ptm2).astype(np.int64)         # 0: ptm1 < ptm2
    # partition 0 first by ascending ptm1; partition 1 by descending ptm2
    within = np.where(partition == 0, ptm1, -ptm2)
    order = np.lexsort((within, partition), axis=-1)    # stable; last key primary
    johnson = order.astype(np.int64)                    # (P, n) job ids

    return LB2Data(pairs_m1=pairs_m1, pairs_m2=pairs_m2, lags=lags,
                   johnson_schedules=johnson)


def set_flags(perm, limit1: int, limit2: int, n: int) -> np.ndarray:
    """1 for scheduled job ids, 0 for unscheduled (reference: c_bound_johnson.c:180-188)."""
    flags = np.zeros(n, dtype=np.int64)
    for j in range(limit1 + 1):
        flags[int(perm[j])] = 1
    for j in range(limit2, n):
        flags[int(perm[j])] = 1
    return flags


def compute_cmax_johnson(lb1: LB1Data, lb2: LB2Data, flags, tmp0: int, tmp1: int,
                         ma0: int, ma1: int, pair: int) -> tuple[int, int]:
    """Simulate the 2-machine schedule of the unscheduled jobs in Johnson
    order with lags as transfer delays (reference: c_bound_johnson.c:190-209)."""
    p = lb1.p_times
    n = p.shape[1]
    for j in range(n):
        job = int(lb2.johnson_schedules[pair, j])
        if flags[job] == 0:
            lag = int(lb2.lags[pair, job])
            tmp0 += int(p[ma0, job])
            tmp1 = max(tmp1, tmp0 + lag)
            tmp1 += int(p[ma1, job])
    return tmp0, tmp1


def lb_makespan(lb1: LB1Data, lb2: LB2Data, flags, front, back,
                min_cmax: int) -> int:
    """Max of the two-machine bounds over all machine pairs, with the
    reference's early exit once the bound exceeds `min_cmax`
    (reference: c_bound_johnson.c:211-237). The early exit never changes
    pruning decisions (any early-exited value already exceeds the best)."""
    lb = 0
    for pair in range(len(lb2.pairs_m1)):
        ma0 = int(lb2.pairs_m1[pair])
        ma1 = int(lb2.pairs_m2[pair])
        tmp0, tmp1 = int(front[ma0]), int(front[ma1])
        tmp0, tmp1 = compute_cmax_johnson(lb1, lb2, flags, tmp0, tmp1, ma0, ma1, pair)
        tmp1 = max(tmp1 + int(back[ma1]), tmp0 + int(back[ma0]))
        lb = max(lb, tmp1)
        if lb > min_cmax:
            break
    return lb


def lb2_bound(lb1: LB1Data, lb2: LB2Data, perm, limit1: int, limit2: int,
              best_cmax: int) -> int:
    """Full LB2 of one partial permutation (reference: c_bound_johnson.c:239-254)."""
    front = schedule_front(lb1, perm, limit1)
    back = schedule_back(lb1, perm, limit2)
    flags = set_flags(perm, limit1, limit2, lb1.p_times.shape[1])
    return lb_makespan(lb1, lb2, flags, front, back, best_cmax)
