"""Fused Pallas bound+prune+compact: pruned children never touch HBM.

The two-phase step (engine/device.step) still round-trips three dense
(child-grid-wide) intermediates through HBM between separate XLA ops
every iteration: the (1, N) bound row the bounds kernel writes, the
(N,) prune mask, and the (N,) packed sort keys + permutation of the
stable partition — all sized for EVERY child, although the majority of
children on a healthy search are pruned and only their bound's
comparison against the incumbent ever mattered. The reference's answer
is its hand-written CUDA bound kernels with the per-child early exit
(bounds_gpu.cu / evaluate_gpu); the TPU answer here is one fused
kernel per chunk that performs

    expand (children + fronts) -> bound (the LB1 chain) ->
    prune-compare against the traced ``bound_cap`` ->
    within-tile compaction -> cursor write of the SURVIVORS ONLY

entirely in VMEM, double-buffered over the chunk with a grid over
column tiles (the same tiling scheme as the streaming big-J pair
sweep, ops/pallas_expand._lb2_bigj_kernel). What reaches HBM is the
compacted survivor block (children, [front | depth+1] aux, bounds and
— for the two-phase LB2 route — the scheduled-set bitmask words), one
survivor count, and (telemetry builds only) a BOUND_BINS x tiles
histogram of the pruned children's bounds so the audit's
``bound_hist_exact`` identity holds bit-identically without the pruned
bounds themselves ever being materialized.

Survivor storage is capped at ``cap_width`` columns (the engine passes
its steady N/4 frame): a step whose survivors outgrow the cap keeps a
correct COUNT (the cursor keeps accumulating; stores stop), and the
engine's fused route falls back to the unfused pipeline for that rare
step via one lax.cond — bit-identical bounds, so the explored set
cannot depend on which branch ran.

Compaction inside the kernel uses the engine's packed-key partition
trick (device._partition): flag in bit 31, column index in the low
bits, one unstable u32 sort — deterministic because every key is
unique, and stable-in-column-order because tiles are visited in grid
order and the cursor advances monotonically. The in-kernel sort and
the cross-grid-step dynamic stores are validated under the Pallas
INTERPRETER on the CPU mesh (the CI `fused-interpret` leg and the
tests/test_fused.py parity suite); the Mosaic hardware lowering of
both (sort -> cumsum+gather, cursor stores -> ANY-space async copies)
is the next hardware round's work, which is why `fused_ok` admits the
hardware route only behind the exact expand-kernel shape rule
(pallas_expand.kernel_shape_ok) AND the TTS_FUSED flag — a shape the
expand kernel rejects must never reach the fused kernels either.

Mode resolution (all env reads HOST-side — the traced step receives
the resolved mode as a static argument, never reads the environment):

- ``off``       fused disabled (the default; bit-identical legacy path)
- ``hw``        the TPU kernels behind the expand shape rule —
                reachable ONLY through an explicit fused="hw" argument
                until the Mosaic lowering's first on-chip validation
                round: TTS_FUSED=1 on a TPU backend resolves "off"
                with a one-time warning (resolve_mode), because a
                serve boot must not be the place a never-compiled
                lowering error surfaces
- ``interpret`` TTS_FUSED=1 + TTS_FUSED_INTERPRET=1 on a non-TPU
                backend: the kernels run under pl.pallas_call's
                interpreter inside the compiled step — the CI leg that
                fails kernel-logic regressions without TPU hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_expand
from .batched import BoundTables

I32_MAX = jnp.int32(2**31 - 1)


def store_sub(n_cols: int) -> int:
    """Cursor-store sub-block width for a tile of `n_cols` children —
    ALSO the output frame's store slack (fused_expand's WPAD), so the
    kernel and its caller must derive it from this one function. The
    whole-tile store needed a whole tile of slack past the survivor
    cap; storing in ~N/8 sub-blocks gated on the live survivor count
    cuts the slack (and the engine-side narrowing copy) to one
    sub-block while keeping the store count per tile small. 128-lane
    aligned for the hardware route."""
    if n_cols <= 128:
        return n_cols
    eighth = (n_cols + 7) // 8
    return max(128, (eighth + 127) // 128 * 128)

FUSED_FLAG = "TTS_FUSED"
FUSED_INTERPRET_FLAG = "TTS_FUSED_INTERPRET"

_HW_WARNED = False      # one boot-time warning, not one per executor


def resolve_mode(flag: bool | str | None = None) -> str:
    """HOST-side resolution of the fused dispatch mode: "off" | "hw" |
    "interpret". `flag` None reads the TTS_FUSED env knob; an explicit
    string mode passes through (the tests' control channel); True
    resolves against the backend like the env flag. The result is a
    STATIC argument of the compiled step — flipping the env mid-process
    retraces rather than silently reusing a stale executable."""
    if isinstance(flag, str):
        assert flag in ("off", "hw", "interpret"), flag
        return flag
    from ..utils import config as _cfg
    if flag is None:
        flag = _cfg.env_flag(FUSED_FLAG)
    if not flag:
        return "off"
    if jax.default_backend() == "tpu":
        # the Mosaic lowering of the in-kernel sort and the cursor
        # stores is the NEXT hardware round's work (module docstring):
        # the env flag must not route a production boot onto a
        # never-compiled path — a serve boot is not the place to
        # discover a lowering error. The hardware round drives the
        # kernels through the explicit fused="hw" control channel
        # (device.run(fused="hw") / the string passthrough above)
        # until the lowering is validated on chip, then flips this
        # gate open.
        global _HW_WARNED
        if not _HW_WARNED:
            _HW_WARNED = True
            import warnings
            warnings.warn(
                "TTS_FUSED=1: the fused kernels' TPU (Mosaic) "
                "lowering is pending its first on-chip validation "
                "round — running the unfused pipeline. Drive "
                "fused=\"hw\" explicitly to validate the lowering.",
                RuntimeWarning, stacklevel=2)
        return "off"
    if _cfg.env_flag(FUSED_INTERPRET_FLAG):
        return "interpret"
    return "off"


def fused_ok(mode: str, jobs: int, eff_tile: int, lb_kind: int,
             machines: int | None = None) -> bool:
    """THE fused-route admission rule (device.step's gate and the
    tuner's probe gate share it). The hardware route sits behind the
    exact expand-kernel shape rule — kernel_shape_ok's lane floors,
    the hardware-validated eff_tile==64 family admission and the
    scoped-VMEM unit cap — so a shape the expand kernel rejects can
    never reach the fused kernels. The interpreter route has no Mosaic
    layout constraints (it exists to validate kernel LOGIC on the CPU
    mesh) and admits any shape."""
    if mode == "off" or lb_kind not in (1, 2):
        return False
    if mode == "hw":
        return (jax.default_backend() == "tpu"
                and pallas_expand.kernel_shape_ok(jobs, eff_tile, lb_kind,
                                                  machines=machines))
    return mode == "interpret"


def _tile_lanes(x: jax.Array, reps: int) -> jax.Array:
    return jnp.concatenate([x] * reps, axis=1)


def _fused_kernel(J: int, M: int, TB: int, W: int, SW: int, BINS: int,
                  BNDS: bool, AUXI16: bool,
                  p_ref, tails_ref, prmu_ref, depth_ref, front_ref,
                  n_ref, cap_ref, *refs):
    """One grid step = one tile of TB parents -> the tile's SURVIVING
    children appended at the running cursor. Bound math is kept
    formula-identical to ops/pallas_expand._expand_math's LB1 branch
    (the parity suite pins the two bit-exact); pruning compares against
    the traced ``bound_cap`` scalar (the incumbent with this chunk's
    leaf improvements already folded in — the caller's parent-level
    leaf scan owns leaves, so leaf columns are never pushed here).

    ``SW`` > 0 additionally emits the scheduled-set bitmask words of
    every survivor (the two-phase LB2 route's pair-sweep input);
    ``BINS`` > 0 emits the per-tile pruned-bound histogram (engine
    telemetry's bound_hist binning, int64 math — exact, the interpret
    path runs under the package's ambient x64)."""
    out = list(refs)
    children_ref, caux_ref = out[:2]
    out = out[2:]
    bounds_ref = out.pop(0) if BNDS else None
    sched_ref = out.pop(0) if SW else None
    cnt_ref = out.pop(0)
    hist_ref = out.pop(0) if BINS else None
    cur_ref = out.pop(0)

    g = pl.program_id(0)

    @pl.when(g == 0)
    def _init():
        cur_ref[0] = jnp.int32(0)

    N = J * TB
    prmu = prmu_ref[:].astype(jnp.int32)          # (J, TB)
    depth = depth_ref[:]                          # (1, TB)

    prmu_flat = prmu.reshape(1, N)
    depth_flat = _tile_lanes(depth, J)

    # --- child processing times + parent remain: the one-hot matmuls
    # of _expand_math, verbatim (COUPLED COPY — see the marker on
    # pallas_expand._expand_math: any math change there must be
    # mirrored through this block and the LB1 chain below; the parity
    # suite fails CI on divergence)
    onehot = (prmu_flat == jax.lax.broadcasted_iota(
        jnp.int32, (J, 1), 0)).astype(jnp.float32)             # (J, N)
    child_p = jax.lax.dot_general(
        p_ref[:], onehot, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)                                        # (M, N)

    iota_v = jax.lax.broadcasted_iota(jnp.int32, (J, 1), 0)
    mh = jnp.zeros((J, TB), jnp.float32)
    zero_f = jnp.zeros((), jnp.float32)
    for i in range(J):
        sched_i = (depth <= i).astype(jnp.float32)
        mh = mh + jnp.where(prmu[i:i + 1, :] == iota_v, sched_i, zero_f)
    remain = jax.lax.dot_general(
        p_ref[:], mh, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)                                        # (M, TB)

    front_rep = _tile_lanes(front_ref[:], J)
    remain_rep = _tile_lanes(remain, J)

    cf = front_rep[0:1] + child_p[0:1]
    cf_rows = [cf]
    for k in range(1, M):
        cf = jnp.maximum(cf, front_rep[k:k + 1]) + child_p[k:k + 1]
        cf_rows.append(cf)

    # --- children permutations (prefix swap), _expand_math's emit block
    at_depth = prmu[0:1, :]
    for pos in range(1, J):
        at_depth = jnp.where(depth == pos, prmu[pos:pos + 1, :], at_depth)
    slot_flat = jnp.concatenate(
        [jnp.full((1, TB), i, jnp.int32) for i in range(J)], axis=1)
    at_depth_flat = _tile_lanes(at_depth, J)
    child_rows = []
    for pos in range(J):
        base = _tile_lanes(prmu[pos:pos + 1, :], J)
        child_rows.append(
            jnp.where(depth_flat == pos, prmu_flat,
                      jnp.where(slot_flat == pos, at_depth_flat, base)))
    children = jnp.concatenate(child_rows, axis=0)             # (J, N)
    caux = jnp.concatenate(cf_rows + [depth_flat + 1], axis=0)  # (M+1, N)

    # --- LB1 chain (machine_bound_from_parts on the child)
    cr = remain_rep[0:1] - child_p[0:1]
    tmp0 = cf_rows[0] + cr
    lb = tmp0 + tails_ref[0, 0]
    for k in range(1, M):
        crk = remain_rep[k:k + 1] - child_p[k:k + 1]
        tmp1 = jnp.maximum(tmp0, cf_rows[k] + crk)
        lb = jnp.maximum(lb, tmp1 + tails_ref[0, k])
        tmp0 = tmp1

    # --- prune against the traced cap; leaves are the caller's
    # parent-level scan, never pushed
    lane_b = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1) % TB
    valid_flat = (g * TB + lane_b) < n_ref[0, 0]
    maskv = (slot_flat >= depth_flat) & valid_flat
    is_leaf = (depth_flat + 1) == J
    push = maskv & ~is_leaf & (lb < cap_ref[0, 0])
    n_tile = push.sum().astype(jnp.int32)

    if BINS:
        # pruned-bound histogram, telemetry.bound_hist's exact binning:
        # the only trace the pruned children leave
        pruned = (maskv & ~is_leaf & ~push).reshape(-1)
        b64 = lb.reshape(-1).astype(jnp.int64)
        ref64 = jnp.maximum(cap_ref[0, 0].astype(jnp.int64), 1)
        gap = jnp.abs(b64 - ref64)
        bins = jnp.minimum(gap * BINS // ref64, BINS - 1)
        hist_ref[:, :] = jnp.stack(
            [jnp.sum(pruned & (bins == k), dtype=jnp.int32)
             for k in range(BINS)]).reshape(BINS, 1)

    # --- within-tile compaction: the engine's packed-key partition
    key = (jnp.where(push, jnp.uint32(0), jnp.uint32(1) << 31)
           | jax.lax.broadcasted_iota(jnp.uint32, (1, N), 1))
    perm = (jax.lax.sort(key.reshape(-1), is_stable=False)
            & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)
    children_c = jnp.take(children, perm, axis=1).astype(jnp.int16)
    caux_c = jnp.take(caux, perm, axis=1)
    if AUXI16:
        # the engine's pool aux rides the narrow per-instance dtype
        # (device.aux_dtype); when the class fits int16 the LB1
        # route's caux block is emitted in it directly — the i32
        # version only ever got cast at the pool write, and the wide
        # frame is pure HBM
        caux_c = caux_c.astype(jnp.int16)
    if BNDS:
        bounds_c = jnp.take(lb, perm, axis=1)

    if SW:
        one = jnp.int32(1)
        rows_i = jax.lax.broadcasted_iota(jnp.int32, (J, TB), 0)
        words = []
        for w in range(SW):
            inw = (prmu >= 32 * w) & (prmu < 32 * (w + 1))
            bit = one << jnp.where(inw, prmu - 32 * w, 0)
            pmask = jnp.sum(jnp.where((rows_i < depth) & inw, bit, 0),
                            axis=0, dtype=jnp.int32)[None, :]   # (1, TB)
            pmask_c = _tile_lanes(pmask, J)
            ainw = (prmu_flat >= 32 * w) & (prmu_flat < 32 * (w + 1))
            abit = jnp.where(
                ainw, one << jnp.where(ainw, prmu_flat - 32 * w, 0), 0)
            words.append(pmask_c | abit)
        sched_c = jnp.take(jnp.concatenate(words, axis=0), perm, axis=1)

    # --- cursor write of the survivors, in SUB-column sub-blocks each
    # gated on the live survivor count: a sub-block with no survivor
    # column never stores, so the frame only needs ONE sub-block of
    # slack past the cap (store_sub — vs a whole tile for the
    # monolithic store; the frame bytes ARE the route's HBM
    # footprint). The second gate keeps a spilling step's stores
    # inside the frame (cur <= W: stores stop past the cap, the count
    # keeps accumulating — the engine's spill test). In the fit case
    # no survivor is dropped: k < n_tile <= W - cur there, so the
    # count gate is the tighter one. A read-merge-write exact-frame
    # variant was measured WORSE on the interpret leg (the grid scan
    # carries each output buffer functionally — every in-kernel read
    # of an output adds a whole-buffer copy).
    SUB = store_sub(N)
    cur = cur_ref[0]
    zero = jnp.int32(0)

    for k in range(0, N, SUB):
        wk = min(SUB, N - k)

        @pl.when((jnp.int32(k) < n_tile) & (cur + k <= jnp.int32(W)))
        def _store(k=k, wk=wk):
            at = cur + k
            pl.store(children_ref, (pl.ds(zero, J), pl.ds(at, wk)),
                     children_c[:, k:k + wk])
            pl.store(caux_ref, (pl.ds(zero, M + 1), pl.ds(at, wk)),
                     caux_c[:, k:k + wk])
            if BNDS:
                pl.store(bounds_ref, (pl.ds(zero, 1), pl.ds(at, wk)),
                         bounds_c[:, k:k + wk])
            if SW:
                pl.store(sched_ref, (pl.ds(zero, SW), pl.ds(at, wk)),
                         sched_c[:, k:k + wk])

    cur_ref[0] = cur + n_tile
    cnt_ref[0, 0] = cur + n_tile


@functools.partial(jax.jit, static_argnames=(
    "lb_kind", "tile", "cap_width", "with_sched", "tele_bins",
    "with_bounds", "aux_i16", "interpret"))
def fused_expand(tables: BoundTables, prmu_T, depth2, front_T,
                 n_valid, bound_cap, lb_kind: int = 1, tile: int = 1024,
                 cap_width: int = 0, with_sched: bool = False,
                 tele_bins: int = 0, with_bounds: bool = True,
                 aux_i16: bool = False, interpret: bool = False):
    """Fused expand+bound+prune+compact over one chunk. Shapes: prmu_T
    (J, B) i16, depth2 (1, B) i32, front_T (M, B) i32 (the pool aux
    widened by the caller), `n_valid` the traced popped count,
    `bound_cap` the traced pruning incumbent. Returns

        (children (J, WPAD) i16,
         caux (M+1, WPAD) i32 — or i16 under `aux_i16`,
         bounds (1, WPAD) i32 | None, sched (SW, WPAD) i32 | None,
         n_surv () i32, hist_pruned (BINS,) i64 | None)

    with WPAD = cap_width + store_sub(J*tile) (one count-gated
    sub-block of store slack; the engine narrows to cap_width where it
    must) — only columns
    [0, min(n_surv, cap_width)) are survivors, in the same global
    column order the unfused partition produces; everything past them
    is unread garbage (the engine's scratch-margin contract). Every
    output byte here is the route's whole HBM footprint, so the
    survivors-only frames come as small as their consumers allow:
    `with_bounds=False` drops the survivor-bound row (only the LB1
    telemetry histogram ever reads it — the LB2 route re-bounds
    survivors with the pair sweeps anyway), and `aux_i16` emits caux
    in the pool's narrow aux dtype when the class fits it (the i32
    version only ever got cast at the pool write). When
    n_surv > cap_width the block is INCOMPLETE and the caller must
    take its unfused fallback; hist_pruned stays valid either way
    (pruning never spills). `lb_kind` must be 1: the LB2 route uses
    this kernel as its fused LB1 prefilter (with_sched=True) and
    sweeps the surviving columns with the existing pair-sweep
    kernels."""
    assert lb_kind == 1, lb_kind
    J, B = prmu_T.shape
    M = front_T.shape[0]
    TB = tile
    assert B % TB == 0, (B, TB)
    G = B // TB
    W = cap_width        # static (static_argnames), already concrete
    assert W >= 1
    WPAD = W + store_sub(J * TB)
    SW = pallas_expand.sched_words(J) if with_sched else 0
    BINS = tele_bins
    adt = jnp.int16 if aux_i16 else jnp.int32

    p_f32 = tables.p.astype(jnp.float32)
    tails = tables.min_tails.reshape(1, M)
    n2 = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
    cap2 = jnp.asarray(bound_cap, jnp.int32).reshape(1, 1)

    kernel = functools.partial(_fused_kernel, J, M, TB, W, SW, BINS,
                               with_bounds, aux_i16)
    out_specs = [
        pl.BlockSpec((J, WPAD), lambda g: (0, 0)),          # children
        pl.BlockSpec((M + 1, WPAD), lambda g: (0, 0)),      # caux
    ]
    out_shape = [
        jax.ShapeDtypeStruct((J, WPAD), jnp.int16),
        jax.ShapeDtypeStruct((M + 1, WPAD), adt),
    ]
    if with_bounds:
        out_specs.append(pl.BlockSpec((1, WPAD), lambda g: (0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((1, WPAD), jnp.int32))
    if SW:
        out_specs.append(pl.BlockSpec((SW, WPAD), lambda g: (0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((SW, WPAD), jnp.int32))
    out_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))  # count
    out_shape.append(jax.ShapeDtypeStruct((1, 1), jnp.int32))
    if BINS:
        out_specs.append(pl.BlockSpec((BINS, 1), lambda g: (0, g)))
        out_shape.append(jax.ShapeDtypeStruct((BINS, G), jnp.int32))

    call = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),          # p
            pl.BlockSpec(memory_space=pltpu.VMEM),          # tails
            pl.BlockSpec((J, TB), lambda g: (0, g)),        # prmu
            pl.BlockSpec((1, TB), lambda g: (0, g)),        # depth
            pl.BlockSpec((M, TB), lambda g: (0, g)),        # front
            pl.BlockSpec(memory_space=pltpu.SMEM),          # n_valid
            pl.BlockSpec(memory_space=pltpu.SMEM),          # bound_cap
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],       # cursor
        interpret=interpret,
    )
    outs = list(call(p_f32, tails, prmu_T, depth2, front_T, n2, cap2))
    children, caux = outs[:2]
    outs = outs[2:]
    bounds = outs.pop(0) if with_bounds else None
    sched = outs.pop(0) if SW else None
    n_surv = outs.pop(0)[0, 0]
    hist = (outs.pop(0).astype(jnp.int64).sum(axis=1) if BINS else None)
    return children, caux, bounds, sched, n_surv, hist
