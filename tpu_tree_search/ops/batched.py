"""Batched JAX bound kernels: (B,) parent nodes -> (B, J) child bounds.

This is the TPU replacement for the reference's CUDA bound kernels
(reference: pfsp/lib/bounds_gpu.cu, pfsp/lib/PFSP_gpu_lib.cu:43-127).
Where the GPU code launches one thread per (parent, child) with ragged
`nodeIndex`/`sumOffSets` maps, the TPU version evaluates a *dense*
`(batch, jobs)` grid of candidate children — slot `i` of parent `b` is the
child created by swapping `prmu[b, depth] <-> prmu[b, i]` — and masks the
slots `i < depth` that do not correspond to real children. Wasted lanes are
the price of static shapes; they vanish as depth grows.

Key algebraic fact used throughout: a child's scheduled prefix is its
parent's prefix plus one appended job, so the child's machine-completion
vector (`front`) is one O(machines) `add_forward` chain away from the
parent's — no per-child O(jobs * machines) DP is needed. The machine-axis
max-plus chains are unrolled Python loops over `machines <= 20`, which XLA
fuses into a handful of vector ops over the (B, J) lanes.

All engines branch forward-only, so the suffix is empty, `limit2 == jobs`,
and `back == min_tails` (reference: c_bound_simple.c:78-81).

Dtypes: permutations int16, bound arithmetic int32.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import reference as ref

I32_MAX = jnp.int32(2**31 - 1)


class BoundTables(NamedTuple):
    """Device-resident precomputed tables for all three bounds.

    The LB1 part mirrors `lb1_bound_data` (reference: c_bound_simple.h:21-27);
    the LB2 part mirrors `lb2_bound_data` (c_bound_johnson.h:32-40) but with
    the Johnson schedules pre-gathered into contiguous per-pair arrays so the
    device never chases job-id indirection for processing times.
    """

    p: jax.Array          # (M, J) int32 processing times
    p_t: jax.Array        # (J, M) int32 transpose (gather-friendly)
    min_tails: jax.Array  # (M,) int32
    total_work: jax.Array  # (M,) int32 = p.sum(axis=1)
    # LB2 tables, one row per machine pair (P = M*(M-1)/2):
    ma0: jax.Array        # (P,) int32 first machine of pair
    ma1: jax.Array        # (P,) int32 second machine
    js: jax.Array         # (P, J) int32 job ids in Johnson order
    ptm0_js: jax.Array    # (P, J) int32 p[ma0, js] in Johnson order
    ptm1_js: jax.Array    # (P, J) int32 p[ma1, js]
    lag_js: jax.Array     # (P, J) int32 lags[pair, js]


# pair count of the strong-pair prefilter tier (engine/device.step):
# calibration shows the top frequency-ordered pairs reproduce the full
# 190-pair prune decision for >99.5% of pruned children on the 20x20
# class. 24 measured fastest end-to-end on chip (r3 sweep over
# {16,20,24,28,32,48,64}: 41.4M evals/s vs 39.4M at 32 on ta021, with
# bit-identical explored trees — the prefilter is a pure perf knob)
PAIR_PREFILTER = 24


def _calibrate_pair_order(p, ma0, ma1, js, pt0, pt1, lag, min_tails,
                          n_samples: int = 2048, seed: int = 0):
    """Order machine pairs by how often each one attains the LB2 max on a
    deterministic synthetic sample of partial schedules of THIS instance.

    This realizes the reference's declared-but-never-implemented
    `LB2_LEARN` variant (c_bound_johnson.h:29, hardcoded to FULL at :15):
    the reference's scalar loop gets its savings from an early exit once
    the running max crosses `best` (c_bound_johnson.c:231-233); a vector
    unit cannot exit early, but it CAN sweep a strong prefix of pairs
    first and only pay for the rest on the children that prefix fails to
    prune — provided strong pairs sort first, which is what this order
    delivers. Reordering pairs never changes the bound itself (integer
    max over all pairs is order-invariant)."""
    M, J = p.shape
    P = len(ma0)
    rng = np.random.default_rng(seed)
    prmu = np.argsort(rng.random((n_samples, J)), axis=1)
    lo = max(1, J // 4)
    depth = rng.integers(lo, max(lo + 1, J - 1), n_samples)

    front = np.zeros((n_samples, M), np.int64)
    for q in range(J - 1):
        act = q < depth
        pj = p[:, prmu[:, q]].T                       # (n, M)
        c = np.empty_like(front)
        c[:, 0] = front[:, 0] + pj[:, 0]
        for k in range(1, M):
            c[:, k] = np.maximum(c[:, k - 1], front[:, k]) + pj[:, k]
        front = np.where(act[:, None], c, front)
    # job v is scheduled iff its position in the permutation < depth
    # (a bool matrix, not a bitmask — no word-size cliff at any J)
    sched = np.argsort(prmu, axis=1) < depth[:, None]   # (n, J)

    t0 = front[:, ma0].T.astype(np.int64).copy()      # (P, n)
    t1 = front[:, ma1].T.astype(np.int64).copy()
    for j in range(J):
        active = ~sched[:, js[:, j]].T                # (P, n)
        n0 = t0 + pt0[:, j][:, None]
        n1 = np.maximum(t1, n0 + lag[:, j][:, None]) + pt1[:, j][:, None]
        t0 = np.where(active, n0, t0)
        t1 = np.where(active, n1, t1)
    per_pair = np.maximum(t1 + min_tails[ma1][:, None],
                          t0 + min_tails[ma0][:, None])
    freq = np.bincount(per_pair.argmax(axis=0), minlength=P)
    return np.argsort(-freq, kind="stable")


def pair_split(t: BoundTables, k: int):
    """(head, tail) BoundTables whose pair arrays are the first k /
    remaining P-k rows. max(head sweep, tail sweep) == the full LB2 —
    used by the two-phase engine's prefilter tier."""
    def cut(sl):
        return t._replace(ma0=t.ma0[sl], ma1=t.ma1[sl], js=t.js[sl],
                          ptm0_js=t.ptm0_js[sl], ptm1_js=t.ptm1_js[sl],
                          lag_js=t.lag_js[sl])
    return cut(slice(None, k)), cut(slice(k, None))


def make_tables(p_times: np.ndarray) -> BoundTables:
    """Host-side precompute; the analogue of `lb1_alloc_gpu`/`lb2_alloc_gpu`
    (reference: PFSP_gpu_lib.cu:154-200). Machine pairs are stored
    strongest-first (see _calibrate_pair_order)."""
    lb1 = ref.make_lb1_data(p_times)
    lb2 = ref.make_lb2_data(lb1)
    p = np.asarray(p_times, dtype=np.int32)
    # The TPU pair-sweep kernel (pallas_expand._lb2_kernel) runs its
    # Johnson chain in f32, which is exact only while every partial
    # completion value stays below 2^24. A sound ceiling on any chain
    # value is front+lag accumulation bounded by twice the total work
    # plus the largest tail; enforce it HERE (host side, concrete
    # values) because inside jit the magnitudes are untraceable.
    ceiling = 2 * int(p.sum()) + int(np.asarray(lb1.min_tails).max())
    if ceiling >= 1 << 24:
        raise ValueError(
            f"instance magnitudes too large for the f32-exact LB2 kernel "
            f"(bound ceiling {ceiling} >= 2^24); rescale processing times")
    ma0 = np.asarray(lb2.pairs_m1)
    ma1 = np.asarray(lb2.pairs_m2)
    js = np.asarray(lb2.johnson_schedules)
    pt0 = p[ma0[:, None], js]
    pt1 = p[ma1[:, None], js]
    lag = np.take_along_axis(lb2.lags, lb2.johnson_schedules, axis=1)
    # calibrate only when the prefilter can consume the order (enough
    # pairs to split into a strong head and a tail)
    if len(ma0) > 2 * PAIR_PREFILTER and p.shape[1] >= 3:
        order = _calibrate_pair_order(p, ma0, ma1, js, pt0, pt1, lag,
                                      np.asarray(lb1.min_tails))
    else:
        order = np.arange(len(ma0))
    return BoundTables(
        p=jnp.asarray(p),
        p_t=jnp.asarray(p.T.copy()),
        min_tails=jnp.asarray(lb1.min_tails, dtype=jnp.int32),
        total_work=jnp.asarray(p.sum(axis=1), dtype=jnp.int32),
        ma0=jnp.asarray(ma0[order], dtype=jnp.int32),
        ma1=jnp.asarray(ma1[order], dtype=jnp.int32),
        js=jnp.asarray(js[order], dtype=jnp.int32),
        ptm0_js=jnp.asarray(pt0[order], dtype=jnp.int32),
        ptm1_js=jnp.asarray(pt1[order], dtype=jnp.int32),
        lag_js=jnp.asarray(lag[order], dtype=jnp.int32),
    )


def parent_tables(t: BoundTables, prmu: jax.Array, depth: jax.Array):
    """front/remain of each parent's prefix, one `lax.scan` over positions.

    Equivalent of `schedule_front` + `sum_unscheduled`
    (reference: c_bound_simple.c:51-69, 108-124) for a whole batch: scan
    positions j = 0..J-1; a position participates only while j < depth(b).

    Returns front (B, M) and remain (B, M), both int32.
    """
    prmu = jnp.asarray(prmu)
    depth = jnp.asarray(depth)
    B, J = prmu.shape
    M = t.p.shape[0]

    def body(carry, j):
        front, sched_sum = carry
        job = prmu[:, j].astype(jnp.int32)          # (B,)
        pj = t.p_t[job]                              # (B, M)
        active = (j < depth)[:, None]                # (B, 1)

        # add_forward chain over machines (unrolled, M small)
        chain = front[:, 0] + pj[:, 0]
        cols = [chain]
        for k in range(1, M):
            chain = jnp.maximum(chain, front[:, k]) + pj[:, k]
            cols.append(chain)
        new_front = jnp.stack(cols, axis=1)

        front = jnp.where(active, new_front, front)
        sched_sum = sched_sum + jnp.where(active, pj, 0)
        return (front, sched_sum), None

    init = (jnp.zeros((B, M), jnp.int32), jnp.zeros((B, M), jnp.int32))
    (front, sched_sum), _ = jax.lax.scan(body, init, jnp.arange(J))
    remain = t.total_work[None, :] - sched_sum
    return front, remain


def _child_fronts(t: BoundTables, prmu, front):
    """front of every dense child: append job prmu[b, i] to parent b's prefix
    (one add_forward chain, c_bound_simple.c:31-38, on (B, J) lanes).

    The job-id -> processing-times lookup is a one-hot matmul on the MXU
    rather than a gather: per-element dynamic gathers serialize on TPU
    (~ms at 100k+ lanes) while a (B*J, J) x (J, M) matmul is microseconds.
    f32 accumulates integers exactly (p_times < 2^24).

    Returns (child_front [(B, J, M)], child_p [(B, J, M)] the per-machine
    processing times of the appended job)."""
    B, J = prmu.shape
    M = t.p.shape[0]
    onehot = (prmu[..., None].astype(jnp.int32)
              == jnp.arange(J, dtype=jnp.int32)).astype(jnp.float32)
    # HIGHEST precision: the default TPU matmul pass rounds f32 inputs
    # through bfloat16, which would corrupt processing times > 256
    child_p = jnp.dot(onehot.reshape(B * J, J),
                      t.p_t.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)
    child_p = child_p.astype(jnp.int32).reshape(B, J, M)   # (B, J, M)
    chain = front[:, None, 0] + child_p[..., 0]
    cols = [chain]
    M = t.p.shape[0]
    for k in range(1, M):
        chain = jnp.maximum(chain, front[:, None, k]) + child_p[..., k]
        cols.append(chain)
    return jnp.stack(cols, axis=-1), child_p


def child_mask(prmu: jax.Array, depth: jax.Array, valid: jax.Array):
    """(B, J) mask of real children: slot i exists iff depth <= i < J."""
    B, J = prmu.shape
    depth = jnp.asarray(depth)
    valid = jnp.asarray(valid)
    return (jnp.arange(J)[None, :] >= depth[:, None]) & valid[:, None]


def lb1_from_parts(t: BoundTables, child_front, child_remain, mask):
    """LB1 combine chain given each child's front/remain
    (machine_bound_from_parts, c_bound_simple.c:126-141, on (B, J) lanes).

    Returns (B, J) int32; masked slots hold I32_MAX (always pruned).
    """
    M = t.p.shape[0]
    back = t.min_tails
    tmp0 = child_front[..., 0] + child_remain[..., 0]
    lb = tmp0 + back[0]
    for k in range(1, M):
        tmp1 = jnp.maximum(tmp0, child_front[..., k] + child_remain[..., k])
        lb = jnp.maximum(lb, tmp1 + back[k])
        tmp0 = tmp1
    return jnp.where(mask, lb, I32_MAX)


def lb1_children(t: BoundTables, prmu, depth, valid):
    """LB1 bound of every child (reference semantics: lb1_bound of the child
    permutation, c_bound_simple.c:143-158, as launched per-child by
    evaluate_gpu_lb1, PFSP_gpu_lib.cu:43-65).

    Recomputes the parents' prefix tables; the engines instead carry
    front/remain in the pool and call `lb1_from_parts` directly.
    """
    front, remain = parent_tables(t, prmu, depth)
    child_front, child_p = _child_fronts(t, prmu, front)
    child_remain = remain[:, None, :] - child_p       # job leaves 'remain'
    return lb1_from_parts(t, child_front, child_remain,
                          child_mask(prmu, depth, valid))


def lb1d_from_parts(t: BoundTables, front, remain, child_p, mask):
    """LB1_d chain given the parents' front/remain and each child's
    per-machine processing times (`add_front_and_bound`,
    c_bound_simple.c:218-244, on (B, J) lanes).

    Returns (B, J) int32; masked slots hold I32_MAX.
    """
    back = t.min_tails
    M = t.p.shape[0]
    lb = (front[:, None, 0] + remain[:, None, 0] + back[0]) \
        * jnp.ones_like(child_p[..., 0])
    tmp0 = front[:, None, 0] + child_p[..., 0]
    for k in range(1, M):
        tmp1 = jnp.maximum(tmp0, front[:, None, k])
        lb = jnp.maximum(lb, tmp1 + remain[:, None, k] + back[k])
        tmp0 = tmp1 + child_p[..., k]
    return jnp.where(mask, lb, I32_MAX)


def lb1d_children(t: BoundTables, prmu, depth, valid):
    """LB1_d incremental bound of every child (as launched per-parent by
    evaluate_gpu_lb1_d, PFSP_gpu_lib.cu:73-102). Recomputes parent tables;
    engines use `lb1d_from_parts`."""
    front, remain = parent_tables(t, prmu, depth)
    _, child_p = _child_fronts(t, prmu, front)        # only needs p of the job
    return lb1d_from_parts(t, front, remain, child_p,
                           child_mask(prmu, depth, valid))


def lb2_from_parts(t: BoundTables, prmu, depth, child_front, mask):
    """LB2 Johnson bound of every child given each child's front
    (reference: lb2_bound, c_bound_johnson.c:239-254, per-child as
    evaluate_gpu_lb2, PFSP_gpu_lib.cu:105-127).

    The reference's data-dependent early exit over machine pairs
    (c_bound_johnson.c:231-233) is replaced by a full masked max over all
    pairs — the exit can only fire when the bound already exceeds the
    incumbent, in which case the child is pruned either way, so search
    behavior is identical (and the vector unit stays busy).

    Returns (B, J) int32; masked slots hold I32_MAX.
    """
    prmu = jnp.asarray(prmu)
    depth = jnp.asarray(depth)
    B, J = prmu.shape

    # inverse permutation: slot_of_job[b, job] = position of job in prmu[b]
    slot_of_job = jnp.zeros((B, J), jnp.int32).at[
        jnp.arange(B)[:, None], prmu.astype(jnp.int32)
    ].set(jnp.arange(J, dtype=jnp.int32)[None, :])

    # tmp0/tmp1 start at the child's front on each pair's two machines
    tmp0 = jnp.take(child_front, t.ma0, axis=-1)      # (B, J, P)
    tmp1 = jnp.take(child_front, t.ma1, axis=-1)

    depth_b = depth[:, None, None]                    # (B, 1, 1)

    def body(carry, j):
        tmp0, tmp1 = carry
        jsj = t.js[:, j]                              # (P,) job id per pair
        # child-unscheduled test: job's slot >= depth and it is not the
        # appended job (which sits at slot i of the dense child grid)
        slot = jnp.take(slot_of_job, jsj, axis=1)     # (B, P)
        is_appended = slot[:, None, :] == jnp.arange(J)[None, :, None]
        active = (slot[:, None, :] >= depth_b) & ~is_appended    # (B, J, P)

        pt0 = t.ptm0_js[:, j]                         # (P,)
        pt1 = t.ptm1_js[:, j]
        lag = t.lag_js[:, j]
        new0 = tmp0 + pt0
        new1 = jnp.maximum(tmp1, new0 + lag) + pt1
        tmp0 = jnp.where(active, new0, tmp0)
        tmp1 = jnp.where(active, new1, tmp1)
        return (tmp0, tmp1), None

    (tmp0, tmp1), _ = jax.lax.scan(body, (tmp0, tmp1), jnp.arange(J))

    back0 = jnp.take(t.min_tails, t.ma0)              # (P,)
    back1 = jnp.take(t.min_tails, t.ma1)
    per_pair = jnp.maximum(tmp1 + back1, tmp0 + back0)
    lb = per_pair.max(axis=-1)                        # (B, J)
    return jnp.where(mask, lb, I32_MAX)


def lb2_children(t: BoundTables, prmu, depth, valid):
    """LB2 bound of every child, recomputing parent tables; engines use
    `lb2_from_parts`."""
    front, _ = parent_tables(t, prmu, depth)
    child_front, _ = _child_fronts(t, prmu, front)    # (B, J, M)
    return lb2_from_parts(t, prmu, depth, child_front,
                          child_mask(prmu, depth, valid))


def children_bounds(lb_kind: int):
    """Dispatch like the reference's `decompose`/`evaluate_gpu`
    (PFSP_lib.h:30-48, PFSP_gpu_lib.cu:129-152): 0=LB1_d, 1=LB1, 2=LB2."""
    return {0: lb1d_children, 1: lb1_children, 2: lb2_children}[lb_kind]


def bounds_from_parts(lb_kind: int, t: BoundTables, prmu, depth, valid,
                      front, remain, child_front, child_p, mask):
    """Bound dispatch for engines that carry front/remain in the pool —
    no O(jobs) prefix rescan (the reference pays that rescan per bound,
    c_bound_simple.c:51-69; here each node's tables ride along with it)."""
    if lb_kind == 0:
        return lb1d_from_parts(t, front, remain, child_p, mask)
    if lb_kind == 1:
        child_remain = remain[:, None, :] - child_p
        return lb1_from_parts(t, child_front, child_remain, mask)
    if lb_kind == 2:
        return lb2_from_parts(t, prmu, depth, child_front, mask)
    raise ValueError(f"unknown lb_kind {lb_kind}")
