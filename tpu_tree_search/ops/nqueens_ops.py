"""Batched N-Queens safety evaluation.

TPU replacement for the reference's CUDA safety kernel, which launches one
thread per (parent, candidate-column) pair (reference:
nqueens_gpu_cuda.cu:143-171). Here the dense (B, N) child grid is
evaluated with one broadcasted comparison over the placed prefix — the
(B, N, N) intermediate is tiny for N <= 20 and fuses into a handful of
VPU ops.

`g` replicates the check to scale arithmetic intensity for benchmarking,
matching the reference's `-g` knob (nqueens_c.c:80-96); results are
independent of it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def safe_children(board: jax.Array, depth: jax.Array, valid: jax.Array,
                  g: int = 1) -> jax.Array:
    """(B, N) mask: slot j is a real, diagonal-safe child.

    Child j places row `board[b, j]` in column `depth`; it conflicts with
    the queen in column i < depth iff their rows differ by exactly
    depth - i (same diagonal). Row conflicts cannot occur: boards are
    permutations.
    """
    board = jnp.asarray(board)
    depth = jnp.asarray(depth).astype(jnp.int32)
    valid = jnp.asarray(valid)
    B, N = board.shape
    b32 = board.astype(jnp.int32)

    cols = jnp.arange(N, dtype=jnp.int32)
    placed = cols[None, :] < depth[:, None]                 # (B, i): i placed
    dist = depth[:, None] - cols[None, :]                   # (B, i) = depth - i

    def check(_, acc):
        diff = b32[:, :, None] - b32[:, None, :]            # (B, i, j) row deltas
        conflict = (jnp.abs(diff) == dist[:, :, None]) & placed[:, :, None]
        return acc & ~conflict.any(axis=1)                  # (B, j)

    safe = jax.lax.fori_loop(0, g, check, jnp.ones((B, N), bool)) \
        if g > 1 else check(0, jnp.ones((B, N), bool))

    real = (cols[None, :] >= depth[:, None]) & valid[:, None]
    return safe & real
