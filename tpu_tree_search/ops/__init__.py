from . import reference

__all__ = ["reference"]
