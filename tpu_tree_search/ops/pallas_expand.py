"""Pallas TPU kernel for the B&B expand step: parents -> bounded children.

This is the hand-scheduled replacement for the XLA elementwise pipeline in
`ops/batched.py` (itself the TPU re-expression of the reference's CUDA
bound kernels, pfsp/lib/bounds_gpu.cu:174-248 and PFSP_gpu_lib.cu:43-102).
Two observations motivate hand-scheduling:

1. **Lane utilization.** The natural `(batch, jobs)` arrays put jobs=20
   on the 128-wide lane axis — 84% of every vector register wasted. The
   kernel works feature-major: the batch rides the lanes, features ride
   the sublanes, every register full.
2. **Fusion boundaries.** Compiled as one XLA graph, the expand step's
   producers/consumers force layout conversions (reshapes/copies) that
   cost more than the math. A pallas_call is an opaque fusion barrier
   with exactly the layouts we choose.

Contract (all feature-major, `c = i*TB + b` columns within a grid tile —
slot-major within a tile of TB parents):

    expand(tables, lb_kind, prmu_T (J,B) i16, depth (1,B) i32,
           front_T (M,B) i32)
      -> children_T (J, B*J) i16     child permutations
         aux_T (M+1, B*J) i32       [child front | depth+1]
         bounds (1, B*J) i32        LB of every child slot (garbage on
                                     masked slots — caller masks)

The per-machine unscheduled work (`remain`) is reconstructed inside the
kernel from the permutation with a masked one-hot matmul, so the pool
only carries each node's front vector.

The caller derives masks/pruning/compaction from `bounds` plus the parent
depths; the kernel is pure expand+bound math (the reference splits this
the same way: evaluate_gpu writes bounds[], generate_children prunes,
PFSP_gpu_lib.cu:129-152 / PFSP_lib.h:51-95).

On non-TPU backends the same math runs as the `expand_xla` fallback
(also used for LB2 until its pair-sweep kernel lands).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .batched import BoundTables


def _x64_off():
    """Scope a trace to x32 (see the load-bearing comment at the LB2
    pallas call). `jax.enable_x64(False)` only exists on newer jax; the
    pinned 0.4.x line spells it `jax.experimental.disable_x64()` — the
    seed suite's three big-J interpret tests failed on exactly this
    AttributeError."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import disable_x64
    return disable_x64()

I32_MAX = jnp.int32(2**31 - 1)


def _tile_lanes(x: jax.Array, reps: int) -> jax.Array:
    """(R, T) -> (R, reps*T) by concatenation along lanes (jnp.tile)."""
    return jnp.concatenate([x] * reps, axis=1)




def _expand_kernel(lb_kind: int, J: int, M: int, TB: int,
                   p_ref, tails_ref, prmu_ref, depth_ref, front_ref,
                   children_ref, aux_ref, bounds_ref):
    """One tile: TB parents -> J*TB dense child slots (slot-major)."""
    _expand_math(lb_kind, J, M, TB, p_ref, tails_ref, prmu_ref, depth_ref,
                 front_ref, children_ref, aux_ref, bounds_ref)


def _bounds_kernel(lb_kind: int, J: int, M: int, TB: int,
                   p_ref, tails_ref, prmu_ref, depth_ref, front_ref,
                   bounds_ref):
    """Bounds-only variant: same math, no children/aux materialization.

    The regather step architecture (engine/device.step) only consumes the
    bound of every child slot here; surviving children are rebuilt from
    their parents after pruning, so writing the full (J+M+2, N) child
    block from the kernel would be pure wasted HBM traffic."""
    _expand_math(lb_kind, J, M, TB, p_ref, tails_ref, prmu_ref, depth_ref,
                 front_ref, None, None, bounds_ref)


def _expand_math(lb_kind: int, J: int, M: int, TB: int,
                 p_ref, tails_ref, prmu_ref, depth_ref, front_ref,
                 children_ref, aux_ref, bounds_ref):
    # COUPLED COPY: ops/pallas_fused._fused_kernel re-implements this
    # math (one-hot child_p, remain matmul, cf chain, prefix-swap emit,
    # LB1 chain) inline so it can fuse prune+compact behind it — the
    # ref-write shapes differ too much to share the body today. ANY
    # change to the math here must be mirrored there; the fused-vs-
    # unfused bit-parity suite (tests/test_fused.py, the CI `fused`
    # leg) fails on divergence. Extracting a value-level shared core
    # is named in ROADMAP item 4's hardware-round follow-ons.
    emit = children_ref is not None
    N = J * TB
    prmu = prmu_ref[:].astype(jnp.int32)          # (J, TB)
    depth = depth_ref[:]                          # (1, TB)

    # --- flat views over the child axis: column c = i*TB + b
    prmu_flat = prmu.reshape(1, N)                # value prmu[i, b] at c
    depth_flat = _tile_lanes(depth, J)            # depth[b] at c

    # --- child processing times via one-hot matmul on the MXU:
    # child_p[k, c] = p[k, prmu_flat[c]]
    onehot = (prmu_flat == jax.lax.broadcasted_iota(
        jnp.int32, (J, 1), 0)).astype(jnp.float32)             # (J, N)
    child_p = jax.lax.dot_general(
        p_ref[:], onehot, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,   # default rounds via bf16,
        preferred_element_type=jnp.float32,    # corrupting p_times > 256
    ).astype(jnp.int32)                                        # (M, N)

    # --- parent remain (unscheduled work per machine) reconstructed from
    # the permutation: remain[k, b] = sum_{i >= depth_b} p[k, prmu[i, b]]
    # as one masked one-hot matmul — the pool does not store remain (it
    # would double the aux traffic through compaction; the reference
    # recomputes it per bound too, c_bound_simple.c:108-124)
    iota_v = jax.lax.broadcasted_iota(jnp.int32, (J, 1), 0)    # values
    mh = jnp.zeros((J, TB), jnp.float32)
    zero_f = jnp.zeros((), jnp.float32)   # explicit f32: a python-float
    for i in range(J):                    # literal is weak f64 under x64
        sched = (depth <= i).astype(jnp.float32)               # (1, TB)
        mh = mh + jnp.where(prmu[i:i + 1, :] == iota_v,
                            sched, zero_f)                     # (J, TB)
    remain = jax.lax.dot_general(
        p_ref[:], mh, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)                                        # (M, TB)

    # --- child front chain (add_forward, c_bound_simple.c:31-38)
    front_rep = _tile_lanes(front_ref[:], J)      # (M, N)
    remain_rep = _tile_lanes(remain, J)

    cf = front_rep[0:1] + child_p[0:1]
    cf_rows = [cf]
    for k in range(1, M):
        cf = jnp.maximum(cf, front_rep[k:k + 1]) + child_p[k:k + 1]
        cf_rows.append(cf)

    if emit:
        # --- children permutations: position row by position row
        # child(i, b)[pos] = prmu[i,b] if pos==depth[b]; prmu[depth[b],b]
        # if pos==i; else prmu[pos,b] (prefix-swap, PFSP_lib.c:13-16)
        # at_depth[b] = prmu[depth[b], b] (the job being displaced)
        at_depth = prmu[0:1, :]
        for pos in range(1, J):
            at_depth = jnp.where(depth == pos, prmu[pos:pos + 1, :],
                                 at_depth)
        # slot index i at column c = i*TB + b, as a concat of constants
        # (NOT `lane // TB` — a python-int divisor becomes a weak i64
        # under x64 and mosaic's i32<->i64 convert recurses; NOT a
        # reshaped sublane iota — mosaic fails to legalize the
        # sublane->lane iota relayout)
        slot_flat = jnp.concatenate(
            [jnp.full((1, TB), i, jnp.int32) for i in range(J)], axis=1)
        at_depth_flat = _tile_lanes(at_depth, J)
        for pos in range(J):
            base = _tile_lanes(prmu[pos:pos + 1, :], J)
            row = jnp.where(depth_flat == pos, prmu_flat,
                            jnp.where(slot_flat == pos, at_depth_flat,
                                      base))
            children_ref[pos:pos + 1, :] = row.astype(jnp.int16)

        # --- child pool tables [front | depth+1]
        for k in range(M):
            aux_ref[k:k + 1, :] = cf_rows[k]
        aux_ref[M:M + 1, :] = depth_flat + 1

    # --- bound chains last (write order matters to mosaic's scheduler:
    # bounds-first failed to legalize, see module docstring)
    if lb_kind == 1:
        # machine_bound_from_parts on the child (c_bound_simple.c:126-141)
        cr = remain_rep[0:1] - child_p[0:1]
        tmp0 = cf_rows[0] + cr
        lb = tmp0 + tails_ref[0, 0]
        for k in range(1, M):
            crk = remain_rep[k:k + 1] - child_p[k:k + 1]
            tmp1 = jnp.maximum(tmp0, cf_rows[k] + crk)
            lb = jnp.maximum(lb, tmp1 + tails_ref[0, k])
            tmp0 = tmp1
    else:
        # add_front_and_bound from the parent (c_bound_simple.c:218-244)
        lb = front_rep[0:1] + remain_rep[0:1] + tails_ref[0, 0]
        tmp0 = front_rep[0:1] + child_p[0:1]
        for k in range(1, M):
            tmp1 = jnp.maximum(tmp0, front_rep[k:k + 1])
            lb = jnp.maximum(
                lb, tmp1 + remain_rep[k:k + 1] + tails_ref[0, k])
            tmp0 = tmp1 + child_p[k:k + 1]
    bounds_ref[:] = lb


@functools.partial(jax.jit, static_argnames=("lb_kind", "tile"))
def expand_tpu(tables: BoundTables, prmu_T, depth2, front_T,
               lb_kind: int = 1, tile: int = 1024):
    """Pallas path (TPU). Shapes: prmu_T (J,B) i16, depth2 (1,B) i32,
    front_T (M,B) i32; B must be a multiple of `tile`.

    One grid-free pallas_call per tile, inputs statically sliced and
    outputs concatenated in XLA. A gridded kernel would be the natural
    shape, but under 64-bit mode (which the package enables for its tree
    counters) mosaic fails to legalize ANY grid index_map on this JAX
    version — grid-free full-block kernels compile fine, and at ~20
    fused vector ops per tile the per-call overhead is noise.
    """
    J, B = prmu_T.shape
    M = front_T.shape[0]
    TB = tile
    assert B % TB == 0, (B, TB)
    G = B // TB

    p_f32 = tables.p.astype(jnp.float32)           # (M, J)
    tails = tables.min_tails.reshape(1, M)

    kernel = functools.partial(_expand_kernel, lb_kind, J, M, TB)
    call = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_shape=[
            jax.ShapeDtypeStruct((J, J * TB), jnp.int16),
            jax.ShapeDtypeStruct((M + 1, J * TB), jnp.int32),
            jax.ShapeDtypeStruct((1, J * TB), jnp.int32),
        ],
    )
    pieces = []
    for g in range(G):
        sl = slice(g * TB, (g + 1) * TB)
        pieces.append(call(p_f32, tails, prmu_T[:, sl], depth2[:, sl],
                           front_T[:, sl]))
    if G == 1:
        return pieces[0]
    return tuple(jnp.concatenate([p[k] for p in pieces], axis=1)
                 for k in range(3))


@functools.partial(jax.jit, static_argnames=("lb_kind", "tile"))
def expand_bounds_tpu(tables: BoundTables, prmu_T, depth2, front_T,
                      lb_kind: int = 1, tile: int = 1024):
    """Pallas bounds-only expand: (1, B*J) int32 child bounds in the same
    slot-major column order as expand_tpu, without materializing the
    children (see _bounds_kernel)."""
    J, B = prmu_T.shape
    M = front_T.shape[0]
    TB = tile
    assert B % TB == 0, (B, TB)
    G = B // TB

    p_f32 = tables.p.astype(jnp.float32)
    tails = tables.min_tails.reshape(1, M)
    kernel = functools.partial(_bounds_kernel, lb_kind, J, M, TB)
    call = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, J * TB), jnp.int32),
    )
    pieces = []
    for g in range(G):
        sl = slice(g * TB, (g + 1) * TB)
        pieces.append(call(p_f32, tails, prmu_T[:, sl], depth2[:, sl],
                           front_T[:, sl]))
    return pieces[0] if G == 1 else jnp.concatenate(pieces, axis=1)


def kernel_ok(jobs: int, eff_tile: int, lb_kind: int,
              machines: int | None = None) -> bool:
    """THE eligibility rule for the Pallas expand kernels — shared by
    expand(), expand_bounds() and device.step's two-phase gate so the
    dispatch can never diverge between them. The scheduled-set bitmask is
    multi-word (ceil(jobs/32) int32 rows) so LB2 has no job-count cliff;
    whether the pair sweep itself runs as the Pallas kernel or the XLA
    bitmask path is lb2_bounds' own VMEM decision (lb2_kernel_fits).
    When `machines` is given, the expand kernel's scoped-VMEM unit cap
    (EXPAND_TILE_UNITS) is enforced too — a trusted caller-supplied tile
    over the cap must fall back to XLA rather than compile-OOM."""
    if jax.default_backend() != "tpu":
        return False
    return kernel_shape_ok(jobs, eff_tile, lb_kind, machines=machines)


def kernel_shape_ok(jobs: int, eff_tile: int, lb_kind: int,
                    machines: int | None = None) -> bool:
    """The backend-independent SHAPE half of :func:`kernel_ok` — the
    hardware-validated tile-family rule (including the jobs >= 128
    eff_tile == 64 admission) plus the lane and scoped-VMEM caps. Split
    out so the FUSED bound+prune+compact entry points
    (ops/pallas_fused.fused_ok) enforce the exact same rule on their
    hardware route: a shape the expand kernel rejects must never reach
    the fused kernels either (the fused math is the expand math)."""
    lane_cap = MAX_TILE_LANES // 2 if lb_kind == 2 else MAX_TILE_LANES
    return (eff_tile >= min_tile(jobs)
            # lane-aligned reshapes: the kernel's (J, TB) -> (1, J*TB)
            # flattening needs the flat lane count 128-aligned; TB
            # itself only has to be 128-aligned down to the hardware-
            # validated TB=64 family (min_tile's jobs >= 128 floor,
            # J*64 still 128-aligned at even J — validated bit-exact at
            # 200x20, tests/test_pallas_tpu.py). A trusted
            # caller-supplied tile below 64 (TB=32, TB=16...) can also
            # satisfy the raw (jobs*eff_tile) % 128 == 0 arithmetic,
            # but no such mosaic layout has ever run on hardware —
            # admit ONLY the validated family and let everything else
            # take the XLA fallback (ADVICE.md round 5).
            and (eff_tile % 128 == 0
                 or (jobs >= 128 and eff_tile == 64
                     and (jobs * eff_tile) % 128 == 0))
            and jobs * eff_tile <= lane_cap
            and (machines is None
                 or jobs * machines * eff_tile <= EXPAND_TILE_UNITS))


def sched_words(jobs: int) -> int:
    """Rows of the scheduled-set bitmask: one int32 word per 32 jobs."""
    return (jobs + 31) // 32


LB2_ONEHOT_VMEM = 4 << 20

# pair-sweep kernel tuning knobs (see lb2_bounds_tpu): sublane block of
# pair rows, and the column-tile cap
LB2_PB = 64
LB2_TILE = 4096


# Wider tiles were tried for the few-pair classes (50x5: P=10 uses 10
# of 64 sublanes, so the J=50 step chain is per-step-latency-bound and
# wider NT would amortize it) and OOM the scoped-VMEM stack: mosaic
# materializes the per-unrolled-step activation temporaries without
# stack reuse, so scoped usage scales with (pair-block rows x NT x J)
# (measured: 17.76 MB at J=50/P=10/NT=8192; 18.18 MB at
# J=20/P=190/NT=8192; 18.09 MB at J=50/P=166/NT=4096 — the last one a
# round-3 REGRESSION: KH 32->24 grew the 50x20 tail block enough to
# blow the 16 MB limit at the fixed 4096 tile, caught by re-measuring
# ta056). lb2_tile() sizes NT against that model instead of trusting
# one constant.

# Scoped-VMEM model for lb2_tile: bytes ~= (rows*J + 2048) * NT — an
# affine fit with a row-independent term, deliberately CONSERVATIVE over
# all three measured points (predicts 21.5/20.9/27.0 MB for the
# 18.09/17.76/18.18 MB measurements, so every configuration that
# measured over the limit is rejected, including J=50/P=10/NT=8192,
# which a pure rows*NT*J model would wrongly approve), while keeping
# the proven production tiles: 20x20 -> 4096 (13.6 MB model), 50x20
# tail -> 2048 (10.7 MB), 50x5 dense -> 4096 (10.4 MB).
_LB2_SCOPED_BASE = 2048
_LB2_SCOPED_BUDGET = 15e6


def lb2_tile(jobs: int, pairs: int, width: int) -> int:
    """Largest legal pallas column tile for a pair sweep over `width`
    columns: divides width (power-of-two factor), caps at LB2_TILE, and
    respects the scoped-VMEM model above. Returns 0 when no tile
    >= MIN_PALLAS_TILE exists (callers then take the XLA path)."""
    rows = min(LB2_PB, pairs)
    nt = min(LB2_TILE, width & -width)
    while nt >= MIN_PALLAS_TILE and (
            (rows * jobs + _LB2_SCOPED_BASE) * nt > _LB2_SCOPED_BUDGET):
        nt //= 2
    return nt if nt >= MIN_PALLAS_TILE else 0


def lb2_sweep_tile(jobs: int, pairs: int, machines: int,
                   width: int) -> int:
    """THE single which-pallas-pair-kernel predicate: the column tile
    the LB2 sweep at `width` will actually run with — the register
    kernel's tile (lb2_tile) when lb2_kernel_fits, else the streaming
    big-J kernel's (lb2_bigj_tile). 0 means the sweep takes the XLA
    scan. Shared by lb2_bounds' dispatch and device.step's sweep-rung
    admission so tier admission can never diverge from the dispatch."""
    if lb2_kernel_fits(jobs, pairs):
        return lb2_tile(jobs, pairs, width)
    return lb2_bigj_tile(jobs, machines, width)


def lb2_kernel_fits(jobs: int, pairs: int) -> bool:
    """The pair-sweep kernel keeps its (J, P, J) bf16 per-step job
    one-hot resident in VMEM; past ~4 MB it cannot share VMEM with the
    column tiles. Jobs are additionally capped at 64: mosaic's
    scoped-VMEM stack behavior changes qualitatively past the validated
    classes (measured: J=100/P=24/NT=512 allocates 24.8 MB where the
    J<=50 model predicts 2.3 MB — the J-step unrolled temporaries stop
    being reused). Classes outside either cap take the XLA bitmask path
    (lb2_cols, a lax.scan), which the two-phase route still runs only
    over survivor tiers."""
    return jobs <= 64 and jobs * pairs * jobs * 2 <= LB2_ONEHOT_VMEM


def expand_bounds(tables: BoundTables, prmu_T, depth2, front_T,
                  lb_kind: int = 1, tile: int = 1024):
    """Bounds of every child slot, (1, B*J) int32, slot-major columns:
    the Pallas bounds kernel on TPU for LB1/LB1_d when the tile is legal,
    the XLA fallback otherwise — including ALL of LB2, whose TPU fast
    path needs the child fronts this function never materializes
    (device.step's two-phase route owns that case: LB1 kernel for the
    pre-prune, then lb2_bounds over the regathered survivors). The column
    order is identical to expand()'s for the same tile.

    front_T may arrive in the pool's narrow aux dtype (device.aux_dtype);
    the kernels' chain arithmetic needs i32."""
    front_T = front_T.astype(jnp.int32)
    J, B = prmu_T.shape
    eff_tile = (tile if B % tile == 0
                else effective_tile(J, B, tile, lb_kind,
                                    machines=front_T.shape[0]))
    if kernel_ok(J, eff_tile, lb_kind,
                 machines=front_T.shape[0]) and lb_kind in (0, 1):
        return expand_bounds_tpu(tables, prmu_T, depth2, front_T,
                                 lb_kind=lb_kind, tile=eff_tile)
    return expand_bounds_xla(tables, prmu_T, depth2, front_T,
                             lb_kind=lb_kind, tile=eff_tile)


def lb2_cols(tables: BoundTables, sched_mask, child_front_cols):
    """Feature-major LB2: the Johnson all-pairs sweep on (P, N) lanes.

    The reference's per-child pair loop with early exit
    (c_bound_johnson.c:211-237) becomes an unrolled J-step chain over all
    P = M(M-1)/2 pairs at once — children on the lanes, pairs on the
    sublanes, so every register is full (the row-major scan fallback in
    batched.lb2_from_parts leaves most lanes idle and materializes its
    scan carries every step).

    The child-unscheduled test is one shift of a multi-word scheduled-set
    bitmask (ceil(J/32) int32 words per child), so no job-position
    gathers are needed — one word row covers every 20-job class, two the
    50-job north-star classes.

    sched_mask: (W, N) int32, bit (v % 32) of word (v // 32) set iff job
    v is scheduled in the child (parent prefix + appended job);
    child_front_cols: (M, N) int32. Returns (1, N) int32 bounds.
    """
    t = tables
    J = t.js.shape[1]
    W = sched_mask.shape[0]
    one = jnp.int32(1)

    # pair-machine selection as one-hot matmuls (dynamic row gathers of
    # (P, N) from (M, N) serialize on TPU; the MXU does this in microseconds)
    M = t.p.shape[0]
    sel0 = (t.ma0[:, None] == jnp.arange(M)).astype(jnp.float32)  # (P, M)
    sel1 = (t.ma1[:, None] == jnp.arange(M)).astype(jnp.float32)
    cf_f = child_front_cols.astype(jnp.float32)
    tmp0 = jnp.dot(sel0, cf_f, precision=jax.lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32).astype(jnp.int32)
    tmp1 = jnp.dot(sel1, cf_f, precision=jax.lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32).astype(jnp.int32)

    # The J-step chain runs as a lax.scan, NOT an unrolled python loop:
    # unrolled, XLA keeps O(J) of the (P, N) step temporaries live at
    # once — at 100 jobs x 190 pairs x 409600 children that is ~28 GB
    # of HBM (measured compile OOM on ta081-class); the scan carries
    # exactly two (P, N) buffers. Bit-identical math either way.
    def chain(carry, xs):
        t0, t1 = carry
        jsj, pt0j, pt1j, lagj = xs                      # (P,) each
        jsc = jsj[:, None]                              # (P, 1)
        if W == 1:
            active = ((sched_mask >> jsc) & one) == 0   # (P, N)
        else:
            word = jnp.take(sched_mask, jsj // 32, axis=0)        # (P, N)
            active = ((word >> (jsc % 32)) & one) == 0
        new0 = t0 + pt0j[:, None]
        new1 = jnp.maximum(t1, new0 + lagj[:, None]) + pt1j[:, None]
        return (jnp.where(active, new0, t0),
                jnp.where(active, new1, t1)), None

    (tmp0, tmp1), _ = jax.lax.scan(
        chain, (tmp0, tmp1),
        (t.js.T, t.ptm0_js.T, t.ptm1_js.T, t.lag_js.T))
    back0 = jnp.take(t.min_tails, t.ma0)[:, None]       # (P, 1)
    back1 = jnp.take(t.min_tails, t.ma1)[:, None]
    per_pair = jnp.maximum(tmp1 + back1, tmp0 + back0)
    return per_pair.max(axis=0, keepdims=True)          # (1, N)


def _lb2_kernel(J: int, M: int, P: int, PB: int,
                sel0_ref, sel1_ref, js1h_ref, pt0_ref, pt1_ref, lag_ref,
                tails0_ref, tails1_ref, cf_ref, unsched_ref, bounds_ref):
    """All-pairs Johnson sweep for one column tile: pairs ride the
    sublanes in blocks of PB, children ride the lanes. Machine selection
    and the per-step active test are one-hot matmuls on the MXU (dynamic
    row indexing inside mosaic is either unsupported or serializes).

    cf_ref (M, NT) child fronts; unsched_ref (J, NT) bf16 0/1 per job;
    tables: sel0/sel1 (P, M) f32 pair-machine one-hots, js1h (J, P, J)
    bf16 per-step job one-hots, pt0/pt1/lag (P, J) f32, tails (P, 1)
    f32. Output bounds (1, NT) i32.
    """
    cf_f = cf_ref[:].astype(jnp.float32)            # (M, NT)
    unsched = unsched_ref[:]                        # (J, NT) bf16
    hi = jax.lax.Precision.HIGHEST
    lb = None
    # All values are small non-negative integers (completion times
    # < 2^24), so f32 arithmetic is EXACT and the active-select chain
    # becomes mul/max forms the VPU executes with fewer ops than
    # compare+select: t0 update is one fma (act is exactly 0/1 from the
    # one-hot matmul), and the t1 select is max(t1, act*cand) — valid
    # because cand >= t1 whenever act == 1 and everything is >= 0.
    #
    # The ACT matmul runs in bf16: both operands are exactly-
    # representable 0/1 one-hots and the J-wide dot accumulates to at
    # most J <= 64 in f32 — bit-exact, and the MXU takes one pass where
    # an f32 HIGHEST dot decomposes into several. The VALUE matmuls
    # (sel @ cf: completion times in the thousands, > bf16's 256-exact
    # integer range) stay f32/HIGHEST.
    for lo in range(0, P, PB):
        nrows = min(PB, P - lo)
        sl = slice(lo, lo + nrows)
        t0 = jnp.dot(sel0_ref[sl, :], cf_f, precision=hi,
                     preferred_element_type=jnp.float32)
        t1 = jnp.dot(sel1_ref[sl, :], cf_f, precision=hi,
                     preferred_element_type=jnp.float32)
        for j in range(J):
            act = jnp.dot(js1h_ref[j, sl, :], unsched,
                          preferred_element_type=jnp.float32)
            t0 = t0 + act * pt0_ref[sl, j:j + 1]
            cand = jnp.maximum(t1, t0 + lag_ref[sl, j:j + 1]) \
                + pt1_ref[sl, j:j + 1]
            t1 = jnp.maximum(t1, act * cand)
        per_pair = jnp.maximum(t1 + tails1_ref[sl, :], t0 + tails0_ref[sl, :])
        blk = jnp.max(per_pair, axis=0, keepdims=True)
        lb = blk if lb is None else jnp.maximum(lb, blk)
    bounds_ref[:] = lb.astype(jnp.int32)


def lb2_bounds(tables: BoundTables, child_front_cols, sched_mask):
    """LB2 over child columns from the scheduled-set bitmask: Pallas
    pair-sweep kernel when a legal column tile exists and the pair tables
    fit VMEM, the XLA bitmask path (lb2_cols) otherwise.
    child_front_cols (M, N) i32, sched_mask (W, N) i32 -> (1, N) i32.

    THE single entry point for column-major LB2 — both device.step's
    two-phase tiers and expand()'s one-shot path go through here, so the
    tile rule and the fallback cannot diverge.

    Accepts the pool's narrow aux dtype (engine/device.aux_dtype) for
    child_front_cols; widened to i32 here at entry (full width — a no-op
    for the i32 blocks the engine's compaction path passes)."""
    child_front_cols = child_front_cols.astype(jnp.int32)
    M, N = child_front_cols.shape
    J = tables.js.shape[1]
    P = int(tables.ma0.shape[0])
    nt = lb2_sweep_tile(J, P, M, N)
    if jax.default_backend() != "tpu" or nt == 0:
        return lb2_cols(tables, sched_mask, child_front_cols)
    vj = jnp.arange(J, dtype=jnp.int32)
    word = (sched_mask if sched_mask.shape[0] == 1
            else jnp.take(sched_mask, vj // 32, axis=0))       # (J|1, N)
    unsched = (((word >> (vj % 32)[:, None]) & jnp.int32(1)) == 0) \
        .astype(jnp.bfloat16)                   # (J, N) 0/1: bf16-exact
    if lb2_kernel_fits(J, P):
        return lb2_bounds_tpu(tables, child_front_cols, unsched, tile=nt)
    return lb2_bounds_bigj_tpu(tables, child_front_cols, unsched,
                               tile=nt)


@functools.partial(jax.jit, static_argnames=("tile",))
def lb2_bounds_tpu(tables: BoundTables, child_front_cols, unsched_cols,
                   tile: int = LB2_TILE):
    """Pallas LB2 over child columns: child_front_cols (M, N) i32,
    unsched_cols (J, N) bf16 0/1 — returns (1, N) i32 bounds."""
    M, N = child_front_cols.shape
    J = unsched_cols.shape[0]
    P = tables.ma0.shape[0]
    PB = LB2_PB
    NT = tile
    assert N % NT == 0, (N, NT)

    sel0 = (tables.ma0[:, None] == jnp.arange(M)).astype(jnp.float32)
    sel1 = (tables.ma1[:, None] == jnp.arange(M)).astype(jnp.float32)
    js1h = (tables.js.T[:, :, None]
            == jnp.arange(J)).astype(jnp.bfloat16)      # (J, P, J) one-hot
    # f32 tables: the kernel's whole chain runs in (exact) f32
    pt0 = tables.ptm0_js.astype(jnp.float32)
    pt1 = tables.ptm1_js.astype(jnp.float32)
    lag = tables.lag_js.astype(jnp.float32)
    tails0 = jnp.take(tables.min_tails, tables.ma0)[:, None] \
        .astype(jnp.float32)
    tails1 = jnp.take(tables.min_tails, tables.ma1)[:, None] \
        .astype(jnp.float32)

    kernel = functools.partial(_lb2_kernel, J, M, P, PB)
    # ONE pallas_call with a grid over column tiles (round 2 issued one
    # call per tile: at production shapes that is ~55 dispatches/step,
    # each re-fetching every pair table into VMEM — measured 27% of the
    # two-phase step). Constant index_maps keep the tables resident
    # across grid steps while the column blocks double-buffer.
    # The x64-off scope is load-bearing: the package enables x64 globally
    # (engine counters are int64), and under x64 the grid index maps
    # trace their constants as i64 — mosaic then fails to legalize the
    # index-map function ("failed to legalize operation 'func.return'").
    # Nothing in this call touches 64-bit data, so scoping the trace to
    # x32 is semantics-preserving.
    with _x64_off():
        call = pl.pallas_call(
            kernel,
            grid=(N // NT,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 8 + [
                pl.BlockSpec((M, NT), lambda g: (0, g)),
                pl.BlockSpec((J, NT), lambda g: (0, g)),
            ],
            out_specs=pl.BlockSpec((1, NT), lambda g: (0, g)),
            out_shape=jax.ShapeDtypeStruct((1, N), jnp.int32),
        )
        return call(sel0, sel1, js1h, pt0, pt1, lag, tails0, tails1,
                    child_front_cols, unsched_cols)


LB2_BIGJ_MIN_TILE = 512


def lb2_bigj_tile(jobs: int, machines: int, width: int) -> int:
    """Column tile for the STREAMING big-J pair sweep
    (lb2_bounds_bigj_tpu): a power-of-two divisor of `width`, sized so
    the per-tile VMEM residents — unsched (J, NT) bf16, cf (M, NT) f32,
    two (PB, NT) f32 chain scratches, the (1, NT) output and the
    double-buffered per-step blocks — fit the scoped budget. Returns 0
    when no tile >= LB2_BIGJ_MIN_TILE exists (callers then take the XLA
    scan)."""
    nt = min(LB2_TILE, width & -width)
    per_col = 2 * jobs + 4 * machines + 8 * LB2_PB + 16
    while nt >= LB2_BIGJ_MIN_TILE and nt * per_col > 12e6:
        nt //= 2
    return nt if nt >= LB2_BIGJ_MIN_TILE else 0


def _lb2_bigj_kernel(J, P, PB,
                     sel0_ref, sel1_ref, tails0_ref, tails1_ref,
                     js_ref, pt0_ref, pt1_ref, lag_ref,
                     cf_ref, unsched_ref, bounds_ref, t0_ref, t1_ref):
    """Streaming all-pairs Johnson sweep for J > 64: one grid step per
    (column tile, pair block, JOB step). The J-step chain that the
    small-J kernel unrolls in registers (and whose (J, P, J) one-hot
    must sit whole in VMEM — both walls cap it at J <= 64,
    lb2_kernel_fits) here carries (PB, NT) f32 chain state in VMEM
    scratch across sequential j grid steps, while the per-step one-hot
    block (1, PB, J) bf16 and the (1, PB, 1) pt/lag columns STREAM from
    HBM. Init (pair-machine selection matmul) and the final
    per-pair/tails reduction run under pl.when at the chain's
    endpoints; the output block is revisited across pair blocks with a
    running max. Same mul/max active-select math as _lb2_kernel —
    bit-exact f32, bf16 act matmul (0/1 one-hots)."""
    pb = pl.program_id(1)
    j = pl.program_id(2)
    hi = jax.lax.Precision.HIGHEST

    @pl.when(j == 0)
    def _init():
        cf = cf_ref[:]
        t0_ref[:] = jnp.dot(sel0_ref[:], cf, precision=hi,
                            preferred_element_type=jnp.float32)
        t1_ref[:] = jnp.dot(sel1_ref[:], cf, precision=hi,
                            preferred_element_type=jnp.float32)

    act = jnp.dot(js_ref[0], unsched_ref[:],
                  preferred_element_type=jnp.float32)       # (PB, NT)
    pt0j = pt0_ref[0]                                       # (PB, 1)
    pt1j = pt1_ref[0]
    lagj = lag_ref[0]
    t0 = t0_ref[:] + act * pt0j
    cand = jnp.maximum(t1_ref[:], t0 + lagj) + pt1j
    t1 = jnp.maximum(t1_ref[:], act * cand)
    t0_ref[:] = t0
    t1_ref[:] = t1

    @pl.when(j == J - 1)
    def _fin():
        per_pair = jnp.maximum(t1 + tails1_ref[:], t0 + tails0_ref[:])
        blk = jnp.max(per_pair, axis=0, keepdims=True).astype(jnp.int32)

        @pl.when(pb == 0)
        def _first():
            bounds_ref[:] = blk

        @pl.when(pb > 0)
        def _acc():
            bounds_ref[:] = jnp.maximum(bounds_ref[:], blk)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def lb2_bounds_bigj_tpu(tables: BoundTables, child_front_cols,
                        unsched_cols, tile: int,
                        interpret: bool = False):
    """Streaming pallas LB2 for J > 64 (see _lb2_bigj_kernel):
    child_front_cols (M, N) i32, unsched_cols (J, N) bf16 0/1 ->
    (1, N) i32 bounds. `interpret=True` runs the pallas interpreter
    (CPU) — used by the CPU parity tests; hardware parity is pinned by
    tests/test_pallas_tpu.py."""
    M, N = child_front_cols.shape
    J = unsched_cols.shape[0]
    P = int(tables.ma0.shape[0])
    PB = LB2_PB
    NB = -(-P // PB)
    PP = NB * PB
    NT = tile
    assert N % NT == 0, (N, NT)

    def pad_rows(x, rows, fill=0.0):
        pad = rows - x.shape[0]
        if pad == 0:
            return x
        return jnp.concatenate(
            [x, jnp.full((pad,) + x.shape[1:], fill, x.dtype)], axis=0)

    with _x64_off():
        sel0 = pad_rows((tables.ma0[:, None]
                         == jnp.arange(M)).astype(jnp.float32), PP)
        sel1 = pad_rows((tables.ma1[:, None]
                         == jnp.arange(M)).astype(jnp.float32), PP)
        # pad pairs with -3e8 tails: their all-zero chains then lose
        # every max against any real pair's non-negative bound
        tails0 = pad_rows(jnp.take(tables.min_tails, tables.ma0)[:, None]
                          .astype(jnp.float32), PP, -3e8)
        tails1 = pad_rows(jnp.take(tables.min_tails, tables.ma1)[:, None]
                          .astype(jnp.float32), PP, -3e8)
        # per-step tables, job-step-major so grid blocks stream one
        # (1, PB, ·) slab per (j, pb): one-hots bf16 (exact), pt/lag as
        # (J, PP, 1) f32 columns (pairs ride the sublanes, matching the
        # (PB, NT) chain blocks)
        js = pad_rows((tables.js.T[:, :, None]
                       == jnp.arange(J)).astype(jnp.bfloat16)
                      .transpose(1, 0, 2), PP).transpose(1, 0, 2)
        pt0 = pad_rows(tables.ptm0_js.astype(jnp.float32), PP) \
            .T[:, :, None]
        pt1 = pad_rows(tables.ptm1_js.astype(jnp.float32), PP) \
            .T[:, :, None]
        lag = pad_rows(tables.lag_js.astype(jnp.float32), PP) \
            .T[:, :, None]
        cf = child_front_cols.astype(jnp.float32)
        unsched = unsched_cols.astype(jnp.bfloat16)

        kernel = functools.partial(_lb2_bigj_kernel, J, P, PB)
        call = pl.pallas_call(
            kernel,
            grid=(N // NT, NB, J),
            in_specs=[
                pl.BlockSpec((PB, M), lambda t, pb, j: (pb, 0)),    # sel0
                pl.BlockSpec((PB, M), lambda t, pb, j: (pb, 0)),    # sel1
                pl.BlockSpec((PB, 1), lambda t, pb, j: (pb, 0)),    # tails0
                pl.BlockSpec((PB, 1), lambda t, pb, j: (pb, 0)),    # tails1
                pl.BlockSpec((1, PB, J), lambda t, pb, j: (j, pb, 0)),
                pl.BlockSpec((1, PB, 1), lambda t, pb, j: (j, pb, 0)),
                pl.BlockSpec((1, PB, 1), lambda t, pb, j: (j, pb, 0)),
                pl.BlockSpec((1, PB, 1), lambda t, pb, j: (j, pb, 0)),
                pl.BlockSpec((M, NT), lambda t, pb, j: (0, t)),     # cf
                pl.BlockSpec((J, NT), lambda t, pb, j: (0, t)),     # unsched
            ],
            out_specs=pl.BlockSpec((1, NT), lambda t, pb, j: (0, t)),
            out_shape=jax.ShapeDtypeStruct((1, N), jnp.int32),
            scratch_shapes=[pltpu.VMEM((PB, NT), jnp.float32),
                            pltpu.VMEM((PB, NT), jnp.float32)],
            interpret=interpret,
        )
        return call(sel0, sel1, tails0, tails1, js, pt0, pt1, lag,
                    cf, unsched)


def _to_cols(x, G: int, TB: int, J: int):
    """Reorder (B, J, X) -> (X, tile-slot-major columns): within each
    tile of TB parents, column c = i*TB + b."""
    x = x.reshape(G, TB, J, x.shape[-1])
    x = x.transpose(3, 0, 2, 1)                     # (X, G, J, TB)
    return x.reshape(x.shape[0], G * J * TB)


def _xla_parts(tables: BoundTables, prmu_T, depth2, front_T):
    """Shared row-major intermediates of the XLA expand paths: parent
    views, per-machine remain (reconstructed from the permutation,
    kernel-parity), and the child front chains."""
    from . import batched

    J, B = prmu_T.shape
    prmu = prmu_T.T                                 # (B, J)
    depth = depth2.reshape(B)
    front = front_T.T
    sched_mask = jnp.arange(J)[None, :] >= depth[:, None]      # (B, J)
    onehot = (prmu[..., None].astype(jnp.int32)
              == jnp.arange(J, dtype=jnp.int32)) & sched_mask[..., None]
    remain = jnp.einsum("bjv,mv->bm", onehot.astype(jnp.int32),
                        tables.p,
                        preferred_element_type=jnp.int32)      # (B, M)
    child_front, child_p = batched._child_fronts(tables, prmu, front)
    return prmu, depth, front, remain, child_front, child_p


def _bounds_rows(tables: BoundTables, lb_kind: int, prmu, depth, front,
                 remain, child_front, child_p):
    """(B, J) bounds from the row-major parts, or None for LB2, which the
    callers evaluate column-major via lb2_cols on the child fronts (the
    multi-word bitmask covers any job count)."""
    from . import batched

    B, J = prmu.shape
    mask = jnp.ones((B, J), bool)
    if lb_kind == 2:
        return None
    if lb_kind == 1:
        return batched.lb1_from_parts(
            tables, child_front, remain[:, None, :] - child_p, mask)
    return batched.lb1d_from_parts(tables, front, remain, child_p, mask)


def expand_xla(tables: BoundTables, prmu_T, depth2, front_T,
               lb_kind: int = 1, tile: int | None = None):
    """Pure-XLA fallback with the identical contract (feature-major,
    slot-major columns with the given tile size — tile defaults to B so
    the column order matches a single-tile kernel).

    Used on CPU (tests / host debugging) and for LB2.
    """
    J, B = prmu_T.shape
    M = front_T.shape[0]
    TB = B if tile is None else tile
    assert B % TB == 0
    G = B // TB

    prmu, depth, front, remain, child_front, child_p = _xla_parts(
        tables, prmu_T, depth2, front_T)
    bounds = _bounds_rows(tables, lb_kind, prmu, depth, front, remain,
                          child_front, child_p)

    from ..engine.device import make_children
    children = make_children(prmu, depth)           # (B, J, J)
    child_aux = jnp.concatenate(
        [child_front.astype(jnp.int32),
         jnp.broadcast_to((depth + 1)[:, None, None], (B, J, 1))],
        axis=-1)                                    # (B, J, M+1)

    children_T = _to_cols(children.astype(jnp.int32), G, TB, J) \
        .astype(jnp.int16)
    aux_T = _to_cols(child_aux, G, TB, J)
    if bounds is not None:
        bounds_row = _to_cols(bounds[:, :, None], G, TB, J) \
            .astype(jnp.int32)
    else:
        # LB2 bitmask fast path on (pairs, children) lanes; aux rows
        # [0:M] are exactly the child fronts in column order
        sched = sched_mask_cols(prmu_T, depth2, TB)
        bounds_row = lb2_cols(tables, sched, aux_T[:M])
    return children_T, aux_T, bounds_row


def expand_bounds_xla(tables: BoundTables, prmu_T, depth2, front_T,
                      lb_kind: int = 1, tile: int | None = None):
    """Bounds-only XLA fallback: same column order and bound math as
    expand_xla, but never materializes the children/aux block — the
    regather step architecture rebuilds survivors from their parents, so
    building the dense child block here would be pure wasted work."""
    J, B = prmu_T.shape
    TB = B if tile is None else tile
    assert B % TB == 0
    G = B // TB

    prmu, depth, front, remain, child_front, child_p = _xla_parts(
        tables, prmu_T, depth2, front_T)
    bounds = _bounds_rows(tables, lb_kind, prmu, depth, front, remain,
                          child_front, child_p)
    if bounds is not None:
        return _to_cols(bounds[:, :, None], G, TB, J).astype(jnp.int32)
    cf_cols = _to_cols(child_front.astype(jnp.int32), G, TB, J)
    sched = sched_mask_cols(prmu_T, depth2, TB)
    return lb2_cols(tables, sched, cf_cols)


MIN_PALLAS_TILE = 256   # below this mosaic rejects the lane reshapes
MAX_TILE_LANES = 1 << 15  # J*tile cap keeping the tile's VMEM ~10 MB

# Expand-kernel scoped-VMEM cap in J*M*TB units: the kernel's unrolled
# J-loops materialize ~37 B of per-step temporaries per unit. The 512k
# unit point hard-OOMs the 16 MB stack at BOTH measured J's (18.73 MB
# at 100x20x256 AND 18.53 MB at 50x20x512 — so the unit model is
# J-independent, and the pre-cap code had a LATENT compile crash on any
# 50x20 LB1 run, never hit only because that class's LB2 route happens
# to use tile 256); 20x20x1024 = 409.6k units is the proven production
# ceiling, and 100x20x128 compiles and matches the XLA oracle
# bit-exactly. Applied only when the caller supplies `machines`.
EXPAND_TILE_UNITS = 20 * 20 * 1024


def min_tile(jobs: int) -> int:
    """Mosaic's lane-reshape floor for the expand kernels: 256 in
    general; 128 is validated for the wide classes (jobs >= 64 keeps
    the J*tile lane count >= 8192 — measured bit-exact at J=100/TB=128,
    which the 100x20 class needs to fit the scoped-VMEM stack); 64 for
    jobs >= 128 (lane count still >= 8192; the 200x20 class needs
    TB=64 to fit the J*M*TB scoped-VMEM unit cap — validated bit-exact
    at J=200/TB=64 on hardware, tests/test_pallas_tpu.py)."""
    if jobs >= 128:
        return 64
    return 128 if jobs >= 64 else 256


def effective_tile(jobs: int, batch: int, tile: int = 1024,
                   lb_kind: int = 1, machines: int | None = None) -> int:
    """The tile expand() will actually use — THE single source of truth
    for the output column order. Shrinks the requested tile while the
    (jobs x tile) working set exceeds the VMEM budget or, when
    `machines` is given, while the expand kernel's scoped-VMEM units
    (J*M*TB, see EXPAND_TILE_UNITS) exceed the measured ceiling — so
    20x20 runs at 1024, 50x20 and 100x10 at 256, 100x20 and 200x10 at
    128; then falls back to one batch-wide tile if the batch is not a
    multiple. LB2 halves the
    lane budget — its pair-sweep kernel shares the program's VMEM
    headroom. step() derives its mask column order from this same
    function; they must never diverge.
    """
    cap = MAX_TILE_LANES // 2 if lb_kind == 2 else MAX_TILE_LANES
    floor = min_tile(jobs)

    def too_big(t):
        if jobs * t > cap:
            return True
        return machines is not None and jobs * machines * t > EXPAND_TILE_UNITS

    while tile >= floor and too_big(tile):
        tile //= 2
    return tile if batch % tile == 0 else batch


def sched_mask_cols(prmu_T, depth2, tile: int):
    """(W, N) int32 per-child scheduled-set bitmask in the expand column
    order (c = (g*J + i)*TB + b), W = ceil(J/32) words: the parent's
    prefix bits plus the appended job's bit; bit (v % 32) of word
    (v // 32) stands for job v."""
    J, B = prmu_T.shape
    W = sched_words(J)
    G = B // tile
    N = B * J
    one = jnp.int32(1)
    ppi = prmu_T.astype(jnp.int32)
    appended = ppi.reshape(J, G, tile).transpose(1, 0, 2).reshape(1, N)
    in_prefix = jax.lax.broadcasted_iota(jnp.int32, (J, B), 0) < depth2
    words = []
    for w in range(W):
        inw = (ppi >= 32 * w) & (ppi < 32 * (w + 1))
        bit = one << jnp.where(inw, ppi - 32 * w, 0)
        pmask = jnp.sum(jnp.where(in_prefix & inw, bit, 0),
                        axis=0, dtype=jnp.int32)[None, :]      # (1, B)
        pmask_c = jnp.broadcast_to(
            pmask.reshape(G, 1, tile), (G, J, tile)).reshape(1, N)
        ainw = (appended >= 32 * w) & (appended < 32 * (w + 1))
        abit = jnp.where(
            ainw, one << jnp.where(ainw, appended - 32 * w, 0), 0)
        words.append(pmask_c | abit)
    return jnp.concatenate(words, axis=0)


def expand(tables: BoundTables, prmu_T, depth2, front_T,
           lb_kind: int = 1, tile: int = 1024):
    """Dispatch: Pallas on TPU (LB1/LB1_d directly; LB2 as the expand
    kernel for children/aux + the pair-sweep kernel for bounds, when the
    job count fits the scheduled-set bitmask), XLA otherwise.

    front_T may arrive in the pool's narrow aux dtype (device.aux_dtype).
    """
    front_T = front_T.astype(jnp.int32)
    J, B = prmu_T.shape
    # A tile that divides the batch is trusted as-is: step() derives it
    # through effective_tile and builds its masks in that column order,
    # so re-deriving here could silently diverge from the caller
    # (kernel_ok below still gates hardware limits — an oversized trusted
    # tile falls back to XLA, never to a different column order).
    eff_tile = (tile if B % tile == 0
                else effective_tile(J, B, tile, lb_kind,
                                    machines=front_T.shape[0]))
    ok = kernel_ok(J, eff_tile, lb_kind, machines=front_T.shape[0])
    if ok and lb_kind in (0, 1):
        return expand_tpu(tables, prmu_T, depth2, front_T,
                          lb_kind=lb_kind, tile=eff_tile)
    if ok and lb_kind == 2:
        N = B * J
        if lb2_tile(J, int(tables.ma0.shape[0]), N) > 0:
            children, aux, _ = expand_tpu(tables, prmu_T, depth2, front_T,
                                          lb_kind=1, tile=eff_tile)
            sched = sched_mask_cols(prmu_T, depth2, eff_tile)  # (W, N)
            M = tables.p.shape[0]
            bounds = lb2_bounds(tables, aux[:M], sched)
            return children, aux, bounds
    return expand_xla(tables, prmu_T, depth2, front_T,
                      lb_kind=lb_kind, tile=eff_tile)
