"""Fleet failover: watch peer ledger leases, adopt the expired ones.

PR 12's write-ahead ledger makes a 200 a durability promise *within
one server's lifetimes*: a hard-killed server's requests wait for that
exact process to reboot. At fleet scale the host itself is what dies —
so every peer runs a :class:`FailoverWatcher` that scans a shared
fleet root (``TTS_FLEET_DIR``, one subdirectory per server's ledger)
for leases (service/lease.py) that have EXPIRED without being
released, and runs the takeover protocol:

1. **CAS the epoch** — ``LeaseKeeper.takeover`` claims exactly
   ``current_epoch + 1`` through an O_EXCL claim file; two peers racing
   one expired lease get exactly one adopter, the loser backs off.
2. **Adopt** — ``SearchServer.adopt_ledger`` replays the orphan
   through the PR-12 boot path (truncate-to-last-good included),
   re-admits its QUEUED/ACTIVE requests on the survivor with budgets /
   exclusions / spool ids intact, re-serves DONE tags idempotently,
   and journals ``forget`` tombstones into the orphan so a rebooted
   original owner replays an empty live set.
3. **Hold the lease** — the adopter keeps renewing the orphan's lease,
   so a stale original owner that restarts finds a LIVE foreign lease
   and boots fenced (zero commits), and no second peer re-adopts.

Rollout discipline is the TTS_REMEDIATE one: the watcher always runs
when a fleet dir is configured, but the DEFAULT (``TTS_FAILOVER``
unset) is **observe-only** — peer-down detection, journaling and
metrics happen, zero takeovers execute, and the server's behavior is
bit-identical to the PR-12 server (test-pinned). ``TTS_FAILOVER=1``
arms the takeover path.

Observability: ``failover.peer_down`` / ``failover.adopted`` trace
events, ``tts_takeovers_total{outcome}``, a bounded remediation-style
``actions`` journal, and :meth:`snapshot` riding ``status_snapshot()``
(the doctor/dashboard failover columns read it; the health layer's
``peer_down`` rule reads the per-peer lease ages).
"""

from __future__ import annotations

import pathlib
import threading
import time

from ..obs import tracelog
from ..utils import config as cfg
from . import lease as lease_mod
from .ledger import SEGMENT_PREFIX, SEGMENT_SUFFIX

__all__ = ["FailoverWatcher"]

ACTIONS_CAP = 64    # bounded action journal (the remediation cap)


def _has_segments(d: pathlib.Path) -> bool:
    try:
        return any(p.name.startswith(SEGMENT_PREFIX)
                   and p.name.endswith(SEGMENT_SUFFIX)
                   for p in d.iterdir())
    except OSError:
        return False


class FailoverWatcher:
    """One peer's scanner over the shared fleet root (see module
    docstring). ``act=None`` resolves ``TTS_FAILOVER`` (default:
    observe-only). The scan period defaults to TTL/2 so an expired
    lease is noticed — and, armed, adopted — inside 2x the TTL."""

    def __init__(self, server, fleet_dir, own_root=None,
                 act: bool | None = None,
                 scan_period_s: float | None = None, registry=None):
        self.server = server
        self.fleet_dir = pathlib.Path(fleet_dir)
        self.own_root = (pathlib.Path(own_root).resolve()
                         if own_root else None)
        self.act = bool(cfg.env_flag(cfg.FAILOVER_FLAG)
                        if act is None else act)
        ttl = cfg.env_float("TTS_LEASE_TTL_S")
        self.scan_period_s = float(
            scan_period_s if scan_period_s is not None
            else max(ttl / 2.0, 0.05))
        self.scans = 0              # guarded-by: self._lock
        self.takeovers = 0          # guarded-by: self._lock
        self.observed = 0           # guarded-by: self._lock
        self.errors = 0             # guarded-by: self._lock
        self.actions: list[dict] = []     # guarded-by: self._lock
        self.peers: list[dict] = []   # last scan — guarded-by: self._lock
        # (dir, epoch) pairs already acted on / observed: one action
        # per expired incarnation, not one per scan tick
        self._noted: set[tuple[str, int]] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._takeovers_c = None
        if registry is not None:
            self._takeovers_c = registry.counter(
                "tts_takeovers_total",
                "expired peer leases handled by the FailoverWatcher, "
                "by outcome (adopted|observed|lost_race|error)")

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="tts-failover-watcher", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.scan_period_s):
            try:
                self.scan_once()
            except Exception as e:  # noqa: BLE001 — the watcher is a
                # resilience daemon; one bad scan must not kill it
                tracelog.event("failover.scan_error", error=repr(e))

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    # --------------------------------------------------------------- scan

    def scan_once(self) -> list[dict]:
        """One sweep of the fleet root. Returns (and retains, for
        snapshot/health) the per-peer lease view; expired unreleased
        leases trigger the peer-down path."""
        peers: list[dict] = []
        try:
            subdirs = sorted(p for p in self.fleet_dir.iterdir()
                             if p.is_dir())
        except OSError as e:
            tracelog.event("failover.fleet_dir_error",
                           dir=str(self.fleet_dir), error=repr(e))
            subdirs = []
        for d in subdirs:
            try:
                if self.own_root is not None \
                        and d.resolve() == self.own_root:
                    continue
            except OSError:
                continue
            info = lease_mod.read_lease(d)
            if info is None:
                # a ledger directory with segments but no lease is a
                # pre-fleet (PR-12) ledger: surfaced, never adopted —
                # without an epoch to CAS there is no safe takeover
                if _has_segments(d):
                    peers.append({"dir": str(d), "owner": None,
                                  "epoch": None, "age_s": None,
                                  "released": False, "expired": False,
                                  "leaseless": True})
                continue
            expired = info.expired()
            peers.append({"dir": str(d), "owner": info.owner,
                          "epoch": info.epoch,
                          "age_s": round(info.age_s(), 3),
                          "ttl_s": info.ttl_s,
                          "released": info.released,
                          "expired": expired})
            if expired and not info.released:
                self._peer_down(d, info)
        with self._lock:
            self.peers = peers
            self.scans += 1
        return peers

    def _peer_down(self, d: pathlib.Path, info) -> None:
        key = (str(d), info.epoch)
        with self._lock:
            if key in self._noted:
                return
            self._noted.add(key)
        tracelog.event("failover.peer_down", dir=str(d),
                       owner=info.owner, epoch=info.epoch,
                       age_s=round(info.age_s(), 3),
                       mode="act" if self.act else "observe")
        if not self.act:
            # observe-only (the default): the detection is journaled,
            # the action is not taken — the TTS_REMEDIATE discipline
            self._record(d, info, "observed", None)
            return
        try:
            result = self.server.adopt_ledger(
                str(d), current_epoch=info.epoch)
            outcome = result.get("outcome", "error")
            detail = {k: v for k, v in result.items() if k != "outcome"}
        except Exception as e:  # noqa: BLE001 — a failed takeover must
            # not kill the watcher; retry on the next expiry observation
            outcome, detail = "error", {"error": repr(e)}
            with self._lock:
                # un-note so the next scan retries this incarnation
                self._noted.discard(key)
        self._record(d, info, outcome, detail)

    def _record(self, d: pathlib.Path, info, outcome: str,
                detail: dict | None) -> None:
        action = {"t": time.time(), "dir": str(d), "owner": info.owner,
                  "epoch": info.epoch, "outcome": outcome,
                  **(detail or {})}
        with self._lock:
            self.actions.append(action)
            del self.actions[:-ACTIONS_CAP]
            if outcome == "adopted":
                self.takeovers += 1
            elif outcome == "observed":
                self.observed += 1
            elif outcome == "error":
                self.errors += 1
        if self._takeovers_c is not None:
            self._takeovers_c.inc(outcome=outcome)
        if outcome != "observed":
            tracelog.event("failover.takeover", **action)

    # ----------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """JSON-safe view for status_snapshot()'s `failover` key (the
        doctor/dashboard columns and the health `peer_down` rule)."""
        with self._lock:
            return {"fleet_dir": str(self.fleet_dir),
                    "mode": "act" if self.act else "observe",
                    "scan_period_s": self.scan_period_s,
                    "scans": self.scans,
                    "takeovers": self.takeovers,
                    "observed": self.observed,
                    "errors": self.errors,
                    "peers": [dict(p) for p in self.peers],
                    "actions": [dict(a) for a in self.actions]}
