"""Lease-fenced ledger ownership: the fleet-failover primitive.

Every SearchServer that opens a request ledger also takes a **lease**
on it — a single fsync'd, CRC-stamped JSON file (``lease.json``) in
the ledger directory carrying the owner id, a monotonically increasing
**fencing epoch**, the TTL and the last renewal time — renewed by a
daemon thread at ~TTL/3. Peers (service/failover.FailoverWatcher) scan
a shared fleet root for ledgers whose lease has expired and adopt
them; the epoch is what makes that safe:

- **Exactly-one adopter by construction**: bumping the epoch goes
  through an ``O_CREAT|O_EXCL`` *claim file* (``lease.claim-<epoch>``)
  — the one writer the kernel lets create it wins; the loser backs
  off. Plain temp+rename CAN'T arbitrate two racing writers (both
  renames succeed, last one silently wins); exclusive create can.
- **Self-fencing**: a stale owner that wakes from a pause (GC,
  partition, wedged disk — the ``pause_server`` drill's geometry)
  discovers the bumped epoch at its next renewal or
  :meth:`LeaseKeeper.check` and refuses further commits with a typed
  :class:`LeaseLost`. ``check()`` revalidates against the FILE whenever
  the last successful renewal is older than the TTL, so the fence does
  not depend on the renewal daemon winning a thread race after the
  wake.
- **Epoch stamps outlive the lease**: every ledger append and
  checkpoint save carries the owner's epoch (service/ledger.py,
  engine/checkpoint.py), so even a write that slips out during the
  revalidation window is discarded at replay / refused at save — the
  fence is in the data, not just the timing.

Write discipline is the AOTCache/TuningCache one: unique per-writer
temp name, payload CRC32, flush + fsync + atomic rename; a corrupt
lease file is quarantined (``lease.json.corrupt``) and treated as
absent — the next acquirer re-creates it at a bumped epoch.

Same-host fast path: the lease records the owner's host and pid; a
reader on the same host treats a dead pid's lease as expired
immediately (a dead process cannot hold a lease), which is what lets
the PR-12 crash-restart flow — kill -9 then immediate reboot on the
same ledger — re-acquire without waiting out the TTL.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import pathlib
import socket
import threading
import time
import weakref
import zlib

from ..obs import tracelog
from ..utils import config as cfg

__all__ = ["LeaseLost", "LeaseInfo", "LeaseKeeper", "read_lease",
           "claim_epoch", "suspend_renewals", "owner_id"]

LEASE_NAME = "lease.json"
CLAIM_PREFIX = "lease.claim-"
QUARANTINE_SUFFIX = ".corrupt"


class LeaseLost(RuntimeError):
    """This process no longer owns the lease (epoch bumped by an
    adopter, owner changed, or held by a live peer at boot). Commits
    must stop: the request ledger refuses appends, checkpoint saves
    refuse to land, and the server exits its scheduler tick cleanly."""


def owner_id() -> str:
    """A per-process owner identity. Includes the pid so a same-host
    reader can liveness-check it, and a random suffix so a recycled
    pid cannot impersonate a previous incarnation."""
    return (f"{socket.gethostname()}:{os.getpid()}:"
            f"{os.urandom(4).hex()}")


@dataclasses.dataclass(frozen=True)
class LeaseInfo:
    """One parsed lease file."""

    owner: str
    epoch: int
    ttl_s: float
    renewed_unix: float
    host: str
    pid: int
    released: bool = False

    def age_s(self, now: float | None = None) -> float:
        return max(0.0, (time.time() if now is None else now)
                   - self.renewed_unix)

    def expired(self, now: float | None = None) -> bool:
        """Past the TTL — or provably dead: released cleanly, or owned
        by a no-longer-running pid on THIS host (the same-host restart
        fast path; cross-host readers wait out the TTL)."""
        if self.released:
            return True
        if self.age_s(now) > self.ttl_s:
            return True
        if self.host == socket.gethostname() and not _pid_alive(self.pid):
            return True
        return False


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except OSError as e:
        # EPERM = alive but not ours; ESRCH = gone
        return e.errno == errno.EPERM
    return True


def _lease_path(root) -> pathlib.Path:
    return pathlib.Path(root) / LEASE_NAME


def read_lease(root) -> LeaseInfo | None:
    """Parse the lease file under `root`. Never raises: absent returns
    None; a corrupt/truncated file is QUARANTINED (renamed
    ``*.corrupt``) and treated as absent — the ledger/checkpoint
    integrity discipline."""
    path = _lease_path(root)
    try:
        raw = path.read_bytes()
    except FileNotFoundError:
        return None
    except OSError as e:
        tracelog.event("lease.read_error", path=str(path), error=repr(e))
        return None
    try:
        obj = json.loads(raw.decode())
        rec = obj["r"]
        body = json.dumps(rec, sort_keys=True,
                          separators=(",", ":")).encode()
        if zlib.crc32(body) != int(obj["c"]):
            raise ValueError("lease CRC mismatch")
        return LeaseInfo(owner=str(rec["owner"]), epoch=int(rec["epoch"]),
                         ttl_s=float(rec["ttl_s"]),
                         renewed_unix=float(rec["renewed_unix"]),
                         host=str(rec.get("host", "")),
                         pid=int(rec.get("pid", 0)),
                         released=bool(rec.get("released", False)))
    except Exception as e:  # noqa: BLE001 — torn/truncated/garbled
        qpath = str(path) + QUARANTINE_SUFFIX
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = None
        tracelog.event("lease.quarantine", path=str(path),
                       quarantined_to=qpath, error=repr(e))
        return None


def _write_lease(root, info: LeaseInfo) -> None:
    """CRC-stamp + unique temp + fsync + atomic rename (the AOTCache
    write discipline): a concurrent reader sees the old lease or the
    new one, never a torn mix, and two writers never interleave a
    temp file."""
    rec = {"owner": info.owner, "epoch": info.epoch,
           "ttl_s": info.ttl_s, "renewed_unix": info.renewed_unix,
           "host": info.host, "pid": info.pid,
           "released": info.released}
    body = json.dumps(rec, sort_keys=True, separators=(",", ":")).encode()
    blob = json.dumps({"c": zlib.crc32(body), "r": rec},
                      sort_keys=True).encode()
    path = _lease_path(root)
    tmp = path.with_name(
        f".{path.name}.{os.getpid()}-{threading.get_ident()}.tmp")
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def claim_epoch(root, epoch: int) -> bool:
    """Atomically claim the right to publish `epoch`: create
    ``lease.claim-<epoch>`` with O_CREAT|O_EXCL. Exactly one caller
    per epoch gets True — the compare-and-swap two peers racing one
    expired lease are arbitrated by. The loser does NOT retry at a
    higher epoch (that would mint a second adopter); it re-scans later
    and finds a fresh lease."""
    path = pathlib.Path(root) / f"{CLAIM_PREFIX}{epoch:08d}"
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    except OSError as e:
        tracelog.event("lease.claim_error", path=str(path), error=repr(e))
        return False
    try:
        os.write(fd, owner_id().encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    return True


def _max_claim(root) -> int:
    """Highest epoch any claim file records. The lease file can vanish
    (corruption -> quarantine) while claim files survive — a booter
    must bid ABOVE every epoch ever claimed, or its CAS loses forever
    against a tombstone claim and fencing could regress."""
    best = 0
    try:
        for p in pathlib.Path(root).iterdir():
            if p.name.startswith(CLAIM_PREFIX):
                try:
                    best = max(best, int(p.name[len(CLAIM_PREFIX):]))
                except ValueError:
                    pass
    except OSError:
        pass
    return best


def _gc_claims(root, keep_from: int) -> None:
    """Best-effort cleanup of claim files below `keep_from` (takeovers
    are rare; this just keeps the ledger dir tidy)."""
    try:
        for p in pathlib.Path(root).iterdir():
            if p.name.startswith(CLAIM_PREFIX):
                try:
                    if int(p.name[len(CLAIM_PREFIX):]) < keep_from:
                        p.unlink()
                except (ValueError, OSError):
                    pass
    except OSError:
        pass


# Every live keeper registers here so the pause_server drill
# (utils/faults.py) can freeze renewals process-wide: a real GC pause /
# partition stops ALL threads, so a drill that sleeps only the executor
# thread while the renewal daemon keeps the lease fresh would never
# create the split-brain geometry the drill exists to pin.
_keepers: "weakref.WeakSet[LeaseKeeper]" = weakref.WeakSet()


def suspend_renewals(seconds: float) -> None:
    """Freeze every registered keeper's renewal daemon for `seconds`
    (the pause_server drill's hook). After the freeze the next renewal
    re-reads the lease file and self-fences if the epoch moved."""
    until = time.monotonic() + seconds
    for k in list(_keepers):
        k._suspend_until = max(k._suspend_until, until)
    tracelog.event("lease.renewals_suspended", seconds=seconds,
                   keepers=len(list(_keepers)))


class LeaseKeeper:
    """Owns one ledger directory's lease: acquires it (epoch bump via
    the claim-file CAS), renews it on a daemon thread, and fences this
    process the moment the file says someone else owns it.

    ``acquire()`` raises :class:`LeaseLost` when the lease is HELD by a
    live other owner — a booting server must not steal a ledger an
    adopter is serving (the stale-A-restarts geometry); an expired /
    released / dead-pid lease is re-acquired at a bumped epoch.
    ``takeover(target_epoch)`` is the peer-adoption variant: claim
    exactly ``current+1`` once, no retry — False means another peer
    won the race."""

    def __init__(self, root, owner: str | None = None,
                 ttl_s: float | None = None, registry=None,
                 on_lost=None):
        self.root = pathlib.Path(root)
        self.owner = owner or owner_id()
        self.ttl_s = float(ttl_s if ttl_s is not None
                           else cfg.env_float("TTS_LEASE_TTL_S"))
        self.epoch = 0
        self.renewals = 0           # guarded-by: self._lock
        self.lost_reason: str | None = None   # guarded-by: self._lock
        self._on_lost = on_lost
        self._lock = threading.Lock()
        self._fenced = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # monotonic time of the last successful renewal: check() trusts
        # the in-memory state only this long (the TTL), then revalidates
        # against the file — the fence survives a paused renewal daemon
        self._renewed_mono = time.monotonic()
        self._suspend_until = 0.0   # pause_server drill (suspend_renewals)
        self._epoch_g = self._renew_c = self._lost_c = None
        if registry is not None:
            self._epoch_g = registry.gauge(
                "tts_lease_epoch",
                "fencing epoch of the ledger lease this server holds")
            self._renew_c = registry.counter(
                "tts_lease_renewals_total",
                "successful ledger-lease renewals")
            self._lost_c = registry.counter(
                "tts_lease_lost_total",
                "lease losses (epoch bumped by an adopter / owner "
                "changed): the server self-fenced")
        _keepers.add(self)

    # ------------------------------------------------------- acquire

    def acquire(self) -> "LeaseKeeper":
        """Take the lease (boot path). Raises LeaseLost if a live other
        owner holds it; otherwise bumps the epoch through the claim
        CAS and publishes the lease file."""
        for _ in range(64):     # bounded: concurrent booters interleave
            info = read_lease(self.root)
            if info is not None and not info.expired():
                raise LeaseLost(
                    f"ledger {self.root} lease held by {info.owner} "
                    f"(epoch {info.epoch}, age {info.age_s():.2f}s < "
                    f"ttl {info.ttl_s:g}s)")
            target = max(info.epoch if info is not None else 0,
                         _max_claim(self.root)) + 1
            if not claim_epoch(self.root, target):
                # another booter claimed this epoch between our read
                # and our claim; re-read and try the next one
                time.sleep(0.01)
                continue
            self.epoch = target
            self._publish(renew=False)
            _gc_claims(self.root, keep_from=target)
            self._start_renewal()
            tracelog.event("lease.acquired", dir=str(self.root),
                           owner=self.owner, epoch=self.epoch,
                           ttl_s=self.ttl_s)
            return self
        raise LeaseLost(f"could not claim an epoch on {self.root} "
                        "(claim contention)")

    def takeover(self, current_epoch: int) -> bool:
        """Peer-adoption CAS: claim exactly ``current_epoch + 1``.
        False = another peer won (exactly one adopter per epoch by
        construction — no retry at a higher epoch)."""
        target = current_epoch + 1
        if not claim_epoch(self.root, target):
            return False
        self.epoch = target
        self._publish(renew=False)
        _gc_claims(self.root, keep_from=target)
        self._start_renewal()
        tracelog.event("lease.taken_over", dir=str(self.root),
                       owner=self.owner, epoch=self.epoch)
        return True

    def _publish(self, renew: bool) -> None:
        _write_lease(self.root, LeaseInfo(
            owner=self.owner, epoch=self.epoch, ttl_s=self.ttl_s,
            renewed_unix=time.time(), host=socket.gethostname(),
            pid=os.getpid()))
        self._renewed_mono = time.monotonic()
        if self._epoch_g is not None:
            self._epoch_g.set(self.epoch)
        if renew:
            with self._lock:
                self.renewals += 1
            if self._renew_c is not None:
                self._renew_c.inc()

    # --------------------------------------------------------- renew

    def _start_renewal(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._renew_loop, name=f"lease-{self.root.name}",
            daemon=True)
        self._thread.start()

    def _renew_loop(self) -> None:
        period = max(self.ttl_s / 3.0, 0.05)
        while not self._stop.wait(period):
            if time.monotonic() < self._suspend_until:
                continue    # pause_server drill: the 'GC pause'
            try:
                self.renew()
            except LeaseLost:
                return      # fenced: the daemon's job is done
            except OSError as e:
                # transient fleet-storage hiccup: keep trying inside
                # the TTL; check() revalidates before trusting us
                tracelog.event("lease.renew_error", dir=str(self.root),
                               error=repr(e))

    def renew(self) -> None:
        """Re-read the lease file and, if it is still ours, refresh the
        renewal stamp. The re-read IS the fence: an adopter's bumped
        epoch (or changed owner) is discovered here and fences this
        process with a typed LeaseLost."""
        if self._fenced.is_set():
            raise LeaseLost(self.lost_reason or "lease lost")
        info = read_lease(self.root)
        if (info is None or info.owner != self.owner
                or info.epoch != self.epoch):
            self._fence(
                f"lease on {self.root} now "
                + (f"owned by {info.owner} at epoch {info.epoch}"
                   if info is not None else "absent/quarantined")
                + f" (ours was epoch {self.epoch})")
        self._publish(renew=True)

    def check(self) -> None:
        """Cheap fence check for commit paths (ledger appends,
        checkpoint saves). In-memory while the last renewal is younger
        than the TTL; past it — a paused daemon, exactly the
        split-brain window — revalidates against the file before
        letting the commit through."""
        if self._fenced.is_set():
            raise LeaseLost(self.lost_reason or "lease lost")
        if time.monotonic() - self._renewed_mono > self.ttl_s:
            self.renew()

    @property
    def fenced(self) -> bool:
        return self._fenced.is_set()

    def _fence(self, reason: str) -> None:
        with self._lock:
            already = self._fenced.is_set()
            self.lost_reason = reason
        self._fenced.set()
        if not already:
            if self._lost_c is not None:
                self._lost_c.inc()
            tracelog.event("failover.fenced", dir=str(self.root),
                           owner=self.owner, epoch=self.epoch,
                           reason=reason)
            cb = self._on_lost
            if cb is not None:
                try:
                    cb(reason)
                except Exception as e:  # noqa: BLE001 — a fence
                    # callback must never mask the fence itself
                    tracelog.event("failover.fence_callback_error",
                                   error=repr(e))
        raise LeaseLost(reason)

    # ------------------------------------------------------- release

    def release(self) -> None:
        """Clean shutdown: stop renewing and mark the lease released
        so peers do not 'adopt' a cleanly drained ledger. A fenced
        keeper leaves the file alone — it belongs to the adopter."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        if self._fenced.is_set():
            return
        info = read_lease(self.root)
        if info is not None and info.owner == self.owner \
                and info.epoch == self.epoch:
            try:
                _write_lease(self.root, dataclasses.replace(
                    info, renewed_unix=time.time(), released=True))
            except OSError as e:
                tracelog.event("lease.release_error",
                               dir=str(self.root), error=repr(e))
        tracelog.event("lease.released", dir=str(self.root),
                       owner=self.owner, epoch=self.epoch)

    def snapshot(self) -> dict:
        with self._lock:
            return {"dir": str(self.root), "owner": self.owner,
                    "epoch": self.epoch, "ttl_s": self.ttl_s,
                    "renewals": self.renewals,
                    "fenced": self._fenced.is_set(),
                    "lost_reason": self.lost_reason}
