"""Request model and lifecycle records for the search service.

A `SearchRequest` is everything a client must say to get an instance
solved: the problem table, the bound, an optional seed incumbent, and
the serving policy knobs (priority, compute deadline, checkpoint tag).
The server wraps each admitted request in a `RequestRecord` — the
mutable lifecycle object that carries queue/run state, live progress
counters (fed by the engine's per-segment heartbeat), and the terminal
result.

Lifecycle::

    QUEUED -> RUNNING -> DONE
                 |-> PREEMPTED -> (requeued) -> RUNNING -> ...
                 |-> DEADLINE / CANCELLED / FAILED
    QUEUED -> CANCELLED

PREEMPTED is the only non-terminal detour: the request's state was
checkpointed at the stop boundary, so the next dispatch RESUMES it —
possibly on a different-sized submesh (the checkpoint layer's elastic
reshard). DONE / CANCELLED / DEADLINE / FAILED are terminal.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from ..tune import defaults as tune_defaults

# request states
QUEUED = "QUEUED"
RUNNING = "RUNNING"
PREEMPTED = "PREEMPTED"
DONE = "DONE"
CANCELLED = "CANCELLED"
DEADLINE = "DEADLINE"
FAILED = "FAILED"

TERMINAL_STATES = frozenset({DONE, CANCELLED, DEADLINE, FAILED})

FAILURE_LOG_CAP = 32        # failure_log entries kept per request


@dataclasses.dataclass
class SearchRequest:
    """One solve request.

    `deadline_s` bounds the request's ACCUMULATED EXECUTION time (summed
    across dispatches), not its wall-clock time in the queue — the same
    semantics as the campaign driver's per-instance TTS_BUDGET_S: a
    request that waited behind others is not charged for the wait. A
    request over its deadline is stopped at the next segment boundary
    and lands in the DEADLINE terminal state with its partial counters
    (and its checkpoint kept, so a later request with a larger deadline
    can resume the work via the same `tag`).

    `tag` names the request's checkpoint family inside the server's
    workdir; it defaults to the assigned request id. Reusing a tag
    across server lifetimes resumes the on-disk state.

    `faults` is a TEST-ONLY per-request fault-injection spec
    (utils/faults syntax), applied thread-scoped so it fires only in
    this request's executor — the deterministic-service-test hook.

    `problem` names the registered workload plugin (problems/base.py);
    `p_times` is then that problem's 2-D instance table (the name is
    kept for wire/schema compatibility — every transport already
    carries it). The default keeps the server a drop-in for every
    existing PFSP client.
    """

    p_times: np.ndarray
    problem: str = "pfsp"
    lb_kind: int = 1
    init_ub: int | None = None
    priority: int = 0            # higher preempts lower
    deadline_s: float | None = None
    tag: str | None = None
    # engine knobs. Defaults single-sourced in tune/defaults.py (the
    # measured table config and bench read too). chunk=None /
    # balance_period=None opts into ADAPTIVE resolution: the server's
    # tuning cache when one is configured, else the defaults table
    # (tune/tuner.Autotuner.resolve — never a probe on the request
    # path). Spool payloads say {"tuned": true} for the same.
    chunk: int | None = tune_defaults.SERVING_CHUNK_DEFAULT
    capacity: int | None = None
    balance_period: int | None = tune_defaults.BALANCE_PERIOD_DEFAULT
    min_seed: int = 32
    segment_iters: int | None = None
    checkpoint_every: int | None = None
    faults: str | None = None
    # extra meta merged into every checkpoint this request writes (the
    # campaign driver stamps inst/lb/chunk/ub_mode so the legacy
    # supervisor's config screen accepts serve-mode checkpoints)
    checkpoint_meta: dict | None = None
    # incumbent-sharing namespace (server-side TTS_SHARE_INCUMBENT /
    # share_incumbent must be on): by default every request solving the
    # SAME instance shares best-makespan bounds (engine/incumbent's
    # content-hash key); a share_group narrows that to requests naming
    # the same group — the tenant/tag-family isolation knob
    share_group: str | None = None
    # bound-portfolio racing (service/portfolio.py): K >= 2 fans this
    # request out as K sibling sub-requests over distinct
    # configurations (bound tiers, tuned chunk plans) sharing one
    # incumbent board; the first sibling to complete with a proof wins
    # and the losers cancel. None (default) = the exact pre-portfolio
    # path; the server may fill in TTS_PORTFOLIO when set
    portfolio: int | None = None
    # accounting tenant: an OPAQUE label the client may stamp on the
    # request ("-" = unattributed). Rides the admit ledger record, the
    # request/phase/search metric families (behind the per-metric
    # cardinality valve) and the flight-recorder journey, so per-team
    # SLO burn and budget spend can be split without the server knowing
    # anything about the teams. Never interpreted by scheduling.
    tenant: str = "-"

    def __post_init__(self):
        # wire payloads carry portfolio as a plain int; normalize the
        # off spellings (0, 1 = a race of one = no race) to None so
        # `portfolio` is truthy exactly when a race is requested
        if self.portfolio in (0, 1):
            self.portfolio = None
        # wire payloads may carry tenant as null/""; both mean
        # unattributed — normalize so label values are never empty
        if not self.tenant:
            self.tenant = "-"

    def validate(self) -> str | None:
        """Admission-side validation; returns a rejection reason or
        None. Table-shape and lb rules come from the problem plugin —
        the single place each workload's instance format is defined."""
        from .. import problems
        try:
            prob = problems.get(self.problem)
        except KeyError:
            return (f"unknown problem {self.problem!r} "
                    f"(registered: {problems.names()})")
        p = np.asarray(self.p_times)
        if p.ndim != 2:
            return f"p_times must be a 2-D table, got shape {p.shape}"
        reason = prob.validate(p)
        if reason is not None:
            return reason
        if self.lb_kind not in prob.lb_kinds:
            return (f"lb_kind must be one of {prob.lb_kinds} for "
                    f"problem {prob.name!r}, got {self.lb_kind}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            return f"deadline_s must be positive, got {self.deadline_s}"
        if self.chunk is not None and self.chunk < 1:
            return f"chunk must be >= 1 (or None = tuned), got {self.chunk}"
        if self.portfolio is not None:
            from ..utils import config
            cap = config.env_int("TTS_PORTFOLIO_MAX",
                                 config.PORTFOLIO_MAX_DEFAULT)
            if not 2 <= self.portfolio <= cap:
                return (f"portfolio must be 2..{cap} "
                        f"(TTS_PORTFOLIO_MAX), got {self.portfolio}")
            if self.faults:
                return "portfolio cannot combine with per-request faults"
        return None


@dataclasses.dataclass
class RequestRecord:
    """Server-side lifecycle record for one admitted request."""

    id: str
    request: SearchRequest
    state: str = QUEUED
    submitted_t: float = 0.0
    queued_t: float = 0.0               # last admit/requeue time — the
                                        # queue-wait clock's start
    last_heartbeat_t: float | None = None   # last engine heartbeat (or
                                        # dispatch) — the stall rule's
                                        # liveness signal
    dispatch_heartbeats: int = 0        # heartbeats since the CURRENT
                                        # dispatch started; 0 means the
                                        # dispatch is still warming
                                        # (possibly an XLA compile on a
                                        # cold submesh), so the stall
                                        # rule judges it against the
                                        # warmup threshold — per
                                        # DISPATCH, or a remediation
                                        # preempt that resumes on a
                                        # cold submesh would re-fire
                                        # stall during the compile and
                                        # ping-pong the request
    started_t: float | None = None      # current dispatch's start
    finished_t: float | None = None
    spent_prev_s: float = 0.0           # execution time of past dispatches
    submesh: int | None = None
    dispatches: int = 0
    preemptions: int = 0
    failures: int = 0                   # submesh failures (re-dispatched)
    # one entry per dispatch failure: {"t", "submesh", "attempt",
    # "error"} — the post-hoc diagnosis surface a dead-lettered FAILED
    # record used to lack (it carried only the LAST error string).
    # Bounded at FAILURE_LOG_CAP; always recorded, remediation on or off
    failure_log: list = dataclasses.field(default_factory=list)
    # submeshes this request must not be dispatched to again (the
    # remediation tier appends the offender on failures/stall preempts;
    # the scheduler honors it). Always empty while TTS_REMEDIATE is
    # off — the default dispatch order is then bit-identical to the
    # pre-remediation scheduler
    excluded_submeshes: set = dataclasses.field(default_factory=set)
    error: str | None = None
    checkpoint_path: str | None = None
    hold: bool = False                  # preempted-and-held (ops drain)
    # the request's PARSED fault plan (utils/faults), built once at
    # first dispatch and reused on every redispatch so injection
    # budgets (kill_submesh=SEG:N, fail_host_fetch=N) span the
    # request's whole service lifetime — a drill fault follows the
    # request like a real poisoned input, it does not re-arm per
    # dispatch. (The GLOBAL TTS_FAULTS plan keeps the per-process
    # re-arm model for respawned campaign workers.)
    fault_plan: object | None = None
    # megabatching (service/batching + engine/megabatch): the id of the
    # batch this request last dispatched in (None = solo), and the
    # batch-close timestamp — the moment the former released it. The
    # tts_queue_wait_seconds observation happens AT close (so the
    # health engine's queue_wait p99 sees the full held wait, not just
    # the post-close dispatch hop); the snapshot keeps the raw
    # admit->dispatch wait separately (dispatch_wait_s)
    batch_id: str | None = None
    batch_closed_t: float | None = None
    # set when a batch dispatch found this request's RESUME STATE
    # incompatible with batching (legacy checkpoint dtype/telemetry
    # width, cross-problem tag): the batch key never groups it again —
    # it age-closes onto the solo path, which handles (or properly
    # rejects) the legacy snapshot. In-memory only: a restart
    # re-discovers the incompatibility at the first re-batch
    solo_only: bool = False
    progress: dict = dataclasses.field(default_factory=dict)
    # online tree-size/progress/ETA estimator (obs/estimate), attached
    # at admission when TTS_PROGRESS is on — None otherwise, and with
    # it every estimator surface (gauges, snapshot keys, checkpoint
    # meta) is absent. Updated from the heartbeat thread; its state
    # vector rides checkpoint meta so resume continues it warm
    estimator: object | None = None
    # last time this request's cumulative spent_s was journaled to the
    # request ledger (service/ledger) — the heartbeat hook throttles
    # budget records to LEDGER_BUDGET_EVERY_S so a fast-heartbeating
    # request does not fsync the journal at heartbeat rate
    ledger_budget_t: float = 0.0
    result: object | None = None        # DistResult (final or partial)
    seq: int = 0                        # FIFO tiebreak within a priority
    stop_reason: str | None = None      # why the current stop was asked
    # bound-portfolio racing (service/portfolio.py). A PARENT record
    # (portfolio_members set) is never queued or dispatched — it
    # finalizes from its members' terminals: first proof wins, the
    # rest cancel. A MEMBER record (portfolio_parent set) runs through
    # the ordinary scheduler; its terminal feeds the parent's race.
    portfolio_members: list | None = None   # member rids, fan-out order
    portfolio_parent: str | None = None     # parent rid on members
    portfolio_winner: str | None = None     # winning member rid (parent)
    portfolio_config: dict | None = None    # member's raced config, or
    #                                         the winner's on the parent
    portfolio_cancelled: int = 0            # losers cancelled (parent)
    # failover id lineage (service/failover.adopt_ledger): an adopted
    # orphan re-admits under a FRESH rid; these point back at the rid
    # it held in the dead owner's ledger (and that ledger's directory
    # name), so the flight recorder can stitch ONE request journey
    # across the takeover instead of two unrelated lifecycles. None on
    # every locally-admitted request.
    origin_rid: str | None = None
    origin_owner: str | None = None
    done_event: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def spent_s(self, now: float | None = None) -> float:
        """Accumulated execution seconds (the deadline clock)."""
        spent = self.spent_prev_s
        if self.state == RUNNING and self.started_t is not None:
            spent += (now if now is not None else time.monotonic()) \
                - self.started_t
        return spent

    def over_deadline(self, now: float | None = None) -> bool:
        d = self.request.deadline_s
        return d is not None and self.spent_s(now) > d

    def snapshot(self) -> dict:
        """JSON-safe view for the status API."""
        out = {
            "id": self.id,
            "state": self.state,
            "problem": self.request.problem,
            "priority": self.request.priority,
            "deadline_s": self.request.deadline_s,
            "lb_kind": self.request.lb_kind,
            "shape": list(np.asarray(self.request.p_times).shape),
            "submesh": self.submesh,
            "dispatches": self.dispatches,
            "preemptions": self.preemptions,
            "failures": self.failures,
            "failure_log": [dict(f) for f in self.failure_log],
            "excluded_submeshes": sorted(self.excluded_submeshes),
            "spent_s": round(self.spent_s(), 3),
            "error": self.error,
            # flight-recorder cross-reference: filter the JSONL event
            # log / Chrome trace by these to see this request's story
            "tag": self.request.tag or self.id,
            "tenant": self.request.tenant,
            "share_group": self.request.share_group,
            "stop_reason": self.stop_reason,
            "hold": self.hold,
            # liveness for the health layer's stall rule / dashboard:
            # seconds since the engine last heartbeat this request
            # (None unless RUNNING)
            "heartbeat_age_s": (
                round(time.monotonic() - self.last_heartbeat_t, 3)
                if self.state == RUNNING
                and self.last_heartbeat_t is not None else None),
            "dispatch_heartbeats": self.dispatch_heartbeats,
            "batch": self.batch_id,
            # the raw admit/requeue -> dispatch wait of the CURRENT
            # dispatch (None until dispatched). Under megabatching the
            # histogram observes at batch-close instead, so this is
            # the snapshot's per-request witness of the full wait
            "dispatch_wait_s": (
                round(self.started_t - self.queued_t, 3)
                if self.started_t is not None and self.queued_t
                else None),
            "progress": dict(self.progress),
        }
        if self.origin_rid is not None:
            # failover lineage: present only on adopted records, so the
            # snapshot (and the terminal ledger record that embeds it)
            # names the rid/owner this request continued from
            out["origin_rid"] = self.origin_rid
            out["origin_owner"] = self.origin_owner
        if self.portfolio_members is not None:
            out["portfolio"] = {
                "k": len(self.portfolio_members),
                "members": list(self.portfolio_members),
                "winner": self.portfolio_winner,
                "winner_config": (dict(self.portfolio_config)
                                  if self.portfolio_config else None),
                "cancelled": self.portfolio_cancelled,
            }
        elif self.portfolio_parent is not None:
            out["portfolio"] = {
                "parent": self.portfolio_parent,
                "config": (dict(self.portfolio_config)
                           if self.portfolio_config else None),
            }
        res = self.result
        if res is not None:
            out["result"] = {
                "best": int(res.best),
                "explored_tree": int(res.explored_tree),
                "explored_sol": int(res.explored_sol),
                "complete": bool(res.complete),
            }
            tree = np.asarray(res.per_device.get("tree", []))
            if tree.size:
                # per-worker spread of the explored-node counters —
                # the reference's boxplot bundle (utils/stats) riding
                # the status API instead of a CSV post-pass
                from ..utils import stats
                bs = stats.compute_boxplot_stats(tree)
                out["result"]["tree_per_worker"] = dataclasses.asdict(bs)
        return out
