"""Search-as-a-service: an in-process async request scheduler.

Public surface:

- `SearchRequest` / request states — the request model (request.py)
- `SearchServer` — submit/status/cancel/result over partitioned
  submeshes with priority preemption and executable reuse (server.py)
- `AdmissionError` — bounded-queue rejection (queueing.py)
- `ExecutorCache` — serve-many-compile-once executable cache
  (executors.py)
- `AOTCache` — disk-persistent AOT executable tier under it: a
  restarted server replays compiled loops from disk instead of
  recompiling (aot_cache.py)
- `spool` — file-based front-end used by the `serve`/`client` CLI
  (spool.py)
- `RequestLedger` — durable write-ahead journal of request state
  transitions: a hard-killed server replays it at boot and resumes
  every request (ledger.py)
"""

from .aot_cache import AOTCache
from .executors import ExecutorCache
from .ledger import RequestLedger
from .queueing import AdmissionError, RequestQueue
from .request import (CANCELLED, DEADLINE, DONE, FAILED, PREEMPTED, QUEUED,
                      RUNNING, TERMINAL_STATES, RequestRecord, SearchRequest)
from .server import SearchServer

__all__ = [
    "AdmissionError", "AOTCache", "ExecutorCache", "RequestLedger",
    "RequestQueue",
    "RequestRecord",
    "SearchRequest", "SearchServer",
    "QUEUED", "RUNNING", "PREEMPTED", "DONE", "CANCELLED", "DEADLINE",
    "FAILED", "TERMINAL_STATES",
]
