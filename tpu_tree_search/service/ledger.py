"""Durable write-ahead request ledger: crash-safe serving state.

The checkpoint layer (PR 1) makes a *request's search state* durable and
the AOT cache (PR 8) makes its *compiled executables* durable — but the
SearchServer process itself was still a single point of total amnesia:
a SIGKILL/OOM/host-reboot lost every HTTP-submitted request, every
budget clock, every excluded-submesh set and every quarantine decision,
even though the files on disk could rebuild all of it in seconds. This
module is the missing piece: an append-only JSONL journal of every
request **state transition** (admit, dispatch, budget heartbeat,
preempt, release, exclusion, failure, quarantine/readmit, admission
pause/resume, terminal) that a restarted server replays at boot, so "the host died"
becomes "the ledger replayed on a survivor".

Durability discipline (the same one `engine/checkpoint.py` and
`service/aot_cache.py` already enforce):

- every record is one JSON line wrapped with a CRC32 stamp over its
  canonical serialization — a torn/garbled line is *detected*, never
  half-applied;
- `journal()` writes + flushes + fsyncs before returning, so an
  acknowledgement built on top of it (the HTTP 200 from ``POST
  /submit``) is a durability promise, not a hope;
- segments rotate at a record bound and rotation COMPACTS: the new
  segment starts with absolute-state records (one ``restore`` per live
  request, explicit pause/quarantine state), then older segments are
  deleted — replay cost stays proportional to live state, not to
  history. Compaction is itself crash-safe: the new segment is complete
  and fsync'd before any old segment is removed, ``restore`` /
  ``*_state`` records *overwrite* rather than accumulate, and aged-out
  terminals get explicit ``forget`` tombstones, so a crash at any
  point between the two steps replays to the same state;
- on replay, a corrupt record truncates the ledger to the last good
  record: the torn segment file is truncated in place at the last good
  byte offset and any later segment is quarantined ``*.corrupt``
  (counted, never applied) — exactly `checkpoint.load_resilient`'s
  roll-back-to-last-good stance.

What replay yields (:class:`LedgerState`): every request keyed by id
with its spool payload, resolved tag, cumulative ``spent_s`` budget,
dispatch/preemption/failure counters, ``failure_log``, excluded-submesh
set and — for terminal requests — the recorded terminal snapshot (the
idempotent re-serve source for a re-submitted duplicate tag); plus the
standing submesh quarantines and the admission-pause reason, so a crash
can never launder a degraded configuration back to healthy.

Two deliberate non-replays: per-request ``faults`` specs are journaled
but STRIPPED on re-admission (a kill drill must not follow the request
across the very restart it exists to prove), and terminal snapshots age
out of the compacted ledger beyond ``terminal_keep`` entries (the
idempotency window is bounded; live requests are never aged out).

Observability: ``tts_ledger_{records,replayed,truncated}_total``
counters when a registry is supplied, ``ledger.*`` flight-recorder
events, and :meth:`snapshot` riding ``status_snapshot()``'s ``ledger``
key (the ``doctor`` CLI renders restarts / recovered / lag columns
from it).
"""

from __future__ import annotations

import json
import os
import pathlib
import threading
import time
import zlib

from ..obs import tracelog
from .lease import LeaseLost

__all__ = ["RequestLedger", "LedgerState", "FAILURE_LOG_CAP"]

SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".jsonl"
QUARANTINE_SUFFIX = ".corrupt"

SEGMENT_RECORDS_DEFAULT = 4096   # records per segment before rotation
TERMINAL_KEEP_DEFAULT = 4096     # terminal snapshots kept through
#                                  compaction (the idempotent re-serve
#                                  window; live requests never age out)
FAILURE_LOG_CAP = 32             # mirrors request.FAILURE_LOG_CAP
#                                  (kept local: stdlib-only module)


def _canonical(rec: dict) -> bytes:
    return json.dumps(rec, sort_keys=True,
                      separators=(",", ":")).encode()


def _line(rec: dict) -> bytes:
    body = _canonical(rec)
    return json.dumps({"c": zlib.crc32(body),
                       "r": rec}, sort_keys=True,
                      separators=(",", ":")).encode() + b"\n"


def _parse_line(raw: bytes) -> dict | None:
    """One wrapped record, or None on any damage (torn/garbled/CRC)."""
    try:
        outer = json.loads(raw.decode())
        rec = outer["r"]
        if not isinstance(rec, dict):
            return None
        if zlib.crc32(_canonical(rec)) != int(outer["c"]):
            return None
        return rec
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return None


class LedgerState:
    """The replayed (and live-mirrored) serving state.

    ``requests`` maps request id -> a JSON-safe entry dict; the server's
    replay pass turns non-terminal entries back into queued
    RequestRecords and terminal entries into idempotently re-servable
    records. The ledger keeps this mirror updated on every append so
    compaction can emit absolute state without asking the server.
    """

    def __init__(self):
        self.boots = 0
        self.paused: str | None = None
        self.quarantined: dict[int, str] = {}
        self.requests: dict[str, dict] = {}
        # lease-fencing epoch (failover): the highest epoch stamp seen.
        # Records stamped with a LOWER epoch are a fenced-out owner's
        # stale appends and are discarded on apply — the split-brain
        # fence lives in the data, not in timing
        self.epoch = 0
        self.fenced_discards = 0
        self.takeovers = 0
        # True while the last journaled lifetime ended with a graceful
        # `drain` marker; a boot record clears it. At replay this says
        # whether the PRIOR lifetime drained cleanly or died hard —
        # surfaced in snapshot()["last_shutdown"]
        self.clean_shutdown = False

    # ------------------------------------------------------------ apply

    def apply(self, rec: dict) -> None:
        """Fold one record in. Unknown kinds are ignored (forward
        compatibility: an old binary replaying a newer ledger must not
        die on a record it does not understand). Records carrying an
        epoch stamp ``"e"`` below the current fencing epoch are a stale
        owner's post-takeover appends: discarded (counted), on this
        replay and every future one."""
        e = rec.get("e")
        if isinstance(e, int):
            if e < self.epoch:
                self.fenced_discards += 1
                return
            self.epoch = e
        kind = rec.get("k")
        fn = getattr(self, f"_apply_{kind}", None)
        if fn is not None:
            fn(rec)

    def _entry(self, rec: dict) -> dict | None:
        return self.requests.get(rec.get("rid"))

    def _apply_boot(self, rec: dict) -> None:
        self.boots += 1
        self.clean_shutdown = False

    def _apply_boots(self, rec: dict) -> None:
        # compaction's absolute form: SET, don't add — after a crash
        # between compaction and old-segment deletion the old boot
        # records replay first and must not double-count
        self.boots = max(self.boots, int(rec.get("n", 0)))
        self.clean_shutdown = bool(rec.get("clean",
                                           self.clean_shutdown))

    def _apply_drain(self, rec: dict) -> None:
        self.clean_shutdown = True

    def _apply_forget(self, rec: dict) -> None:
        # compaction's aged-out-terminal tombstone: without it, a crash
        # between the new segment's fsync and the old segments' unlink
        # would replay the old admit/terminal records and resurrect
        # entries the compaction dropped
        self.requests.pop(rec.get("rid"), None)

    def _apply_admit(self, rec: dict) -> None:
        self.requests[rec["rid"]] = {
            "rid": rec["rid"], "tag": rec.get("tag"),
            "seq": int(rec.get("seq", 0)),
            "payload": rec.get("payload") or {},
            "spool_id": rec.get("spool_id"),
            "state": "QUEUED", "hold": False,
            "spent_s": float(rec.get("spent_s", 0.0)),
            "dispatches": 0, "preemptions": 0, "failures": 0,
            "submesh": None, "failure_log": [], "excluded": [],
            "terminal": None, "error": None,
            # accounting + failover lineage: the tenant label and (on
            # an adoption re-admit) the rid/ledger-dir this request
            # held under its dead owner — carried through compaction's
            # restore records verbatim so the flight recorder can
            # stitch one journey across the takeover
            "tenant": rec.get("tenant") or "-",
            "origin_rid": rec.get("origin_rid"),
            "origin_owner": rec.get("origin_owner"),
        }

    def _apply_dispatch(self, rec: dict) -> None:
        e = self._entry(rec)
        if e is None:
            return
        e["state"] = "RUNNING"
        e["submesh"] = rec.get("submesh")
        e["dispatches"] = int(rec.get("dispatch", e["dispatches"] + 1))

    def _apply_budget(self, rec: dict) -> None:
        e = self._entry(rec)
        if e is not None:
            e["spent_s"] = max(e["spent_s"],
                               float(rec.get("spent_s", 0.0)))

    def _apply_preempt(self, rec: dict) -> None:
        e = self._entry(rec)
        if e is None:
            return
        e["hold"] = bool(rec.get("hold"))
        e["state"] = "PREEMPTED" if e["hold"] else "QUEUED"
        e["preemptions"] = int(rec.get("preemptions",
                                       e["preemptions"] + 1))
        e["spent_s"] = max(e["spent_s"], float(rec.get("spent_s", 0.0)))

    def _apply_failure(self, rec: dict) -> None:
        e = self._entry(rec)
        if e is None:
            return
        e["failure_log"].append(
            {"t": rec.get("t"), "submesh": rec.get("submesh"),
             "attempt": rec.get("attempt"), "error": rec.get("error")})
        del e["failure_log"][:-FAILURE_LOG_CAP]
        e["failures"] = int(rec.get("failures", e["failures"] + 1))
        e["spent_s"] = max(e["spent_s"], float(rec.get("spent_s", 0.0)))
        e["error"] = rec.get("error")
        e["state"] = "QUEUED"    # a terminal record follows if it died

    def _apply_release(self, rec: dict) -> None:
        # operator release of a held preemption: back in line
        e = self._entry(rec)
        if e is not None and e.get("terminal") is None:
            e["hold"] = False
            e["state"] = "QUEUED"

    def _apply_exclude(self, rec: dict) -> None:
        e = self._entry(rec)
        if e is not None:
            # absolute form (add_exclusion can also RESET the set at
            # the everywhere-excluded cap, so a relative append would
            # replay wrong)
            e["excluded"] = sorted(int(s) for s in
                                   rec.get("excluded", []))

    def _apply_terminal(self, rec: dict) -> None:
        e = self._entry(rec)
        if e is None:
            return
        e["state"] = rec.get("state", "DONE")
        e["terminal"] = rec.get("snapshot") or {}
        e["error"] = e["terminal"].get("error")
        e["spent_s"] = max(e["spent_s"],
                           float(e["terminal"].get("spent_s") or 0.0))

    def _apply_portfolio(self, rec: dict) -> None:
        """Parent -> member linkage of a portfolio race
        (service/portfolio). Stamped onto the ENTRIES (parent gets the
        member list, each member a back-pointer + its raced config), so
        the linkage rides compaction for free — `_apply_restore`
        carries entry dicts verbatim."""
        e = self._entry(rec)
        if e is None:
            return
        members = [dict(m) for m in rec.get("members") or []]
        e["portfolio_members"] = members
        for m in members:
            me = self.requests.get(m.get("rid") or "")
            if me is not None:
                me["portfolio_parent"] = rec["rid"]
                me["portfolio_config"] = m.get("config")

    def _apply_quarantine(self, rec: dict) -> None:
        self.quarantined[int(rec["submesh"])] = str(
            rec.get("reason") or "")

    def _apply_readmit(self, rec: dict) -> None:
        self.quarantined.pop(int(rec["submesh"]), None)

    def _apply_quarantine_state(self, rec: dict) -> None:
        self.quarantined = {int(k): str(v) for k, v in
                            (rec.get("submeshes") or {}).items()}

    def _apply_pause(self, rec: dict) -> None:
        self.paused = str(rec.get("reason") or "paused")

    def _apply_resume(self, rec: dict) -> None:
        self.paused = None

    def _apply_pause_state(self, rec: dict) -> None:
        self.paused = rec.get("reason")

    def _apply_takeover(self, rec: dict) -> None:
        # the durable fence line a peer journals when it adopts this
        # ledger: the epoch ratchet itself happened in apply() — this
        # handler just keeps the count for snapshot()/doctor
        self.takeovers += 1

    def _apply_restore(self, rec: dict) -> None:
        e = dict(rec.get("entry") or {})
        if e.get("rid"):
            self.requests[e["rid"]] = e

    # ------------------------------------------------------- compaction

    def to_records(self, terminal_keep: int = TERMINAL_KEEP_DEFAULT
                   ) -> list[dict]:
        """Absolute-state records reconstructing this state exactly —
        what compaction writes at the head of a fresh segment. Live
        (non-terminal) requests are all kept; terminal snapshots keep
        only the newest `terminal_keep` (the bounded idempotency
        window)."""
        out: list[dict] = []
        if self.epoch:
            # the fencing epoch must survive compaction: without this
            # head record a rotation would forget the fence and a stale
            # owner's discarded appends could replay on the next boot
            out.append({"k": "epoch", "e": self.epoch})
        out.append({"k": "boots", "n": self.boots,
                    "clean": self.clean_shutdown})
        out.extend([{"k": "pause_state", "reason": self.paused},
                    {"k": "quarantine_state",
                     "submeshes": {str(k): v for k, v in
                                   self.quarantined.items()}}])
        entries = sorted(self.requests.values(),
                         key=lambda e: e.get("seq", 0))
        terminal = [e for e in entries if e.get("terminal") is not None]
        if terminal_keep < 0:
            drop: set = set()
        else:
            # [:-0] would slice to [], silently keeping everything —
            # keep=0 must mean "no idempotency window", so spell the
            # kept tail explicitly
            keep = terminal[-terminal_keep:] if terminal_keep else []
            drop = {e["rid"] for e in terminal} - {e["rid"]
                                                   for e in keep}
        out.extend({"k": "restore", "entry": e} for e in entries
                   if e["rid"] not in drop)
        # tombstones for the aged-out terminals: a crash between this
        # segment's fsync and the old segments' unlink replays the old
        # history first, and these are what keep the dropped entries
        # dropped (the documented replays-to-the-same-state invariant)
        out.extend({"k": "forget", "rid": rid} for rid in sorted(drop))
        return out


class RequestLedger:
    """One serving process's durable journal (see module docstring).

    Constructing it REPLAYS any existing ledger in `root` into
    ``self.state`` (read ``state`` / ``replayed`` / ``truncated``
    before appending this lifetime's records). An unusable directory
    raises: the caller asked for durability, and a ledger that silently
    degrades would turn the HTTP 200 durability promise into a lie —
    the opposite of the cache tiers' degrade-don't-die stance, on
    purpose.
    """

    def __init__(self, root: str | os.PathLike, registry=None,
                 segment_records: int = SEGMENT_RECORDS_DEFAULT,
                 terminal_keep: int = TERMINAL_KEEP_DEFAULT,
                 fsync: bool = True, lease=None, on_fenced=None):
        self._lease = lease         # LeaseKeeper fencing this ledger's
        #                             appends (None = single-host mode,
        #                             byte-identical PR-12 behavior)
        self._on_fenced = on_fenced  # fired once, outside the lock
        self.fenced = False
        self.fence_reason: str | None = None
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.segment_records = max(2, int(segment_records))
        self.terminal_keep = int(terminal_keep)
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None                 # guarded-by: self._lock
        self._seg_index = 0             # guarded-by: self._lock
        self._seg_records = 0           # guarded-by: self._lock
        self._rotate_at = self.segment_records  # guarded-by: self._lock
        self._closed = False            # guarded-by: self._lock
        self._last_append_t: float | None = None
        self.state = LedgerState()
        self._prior_clean = False   # the replayed clean_shutdown flag,
        #                             captured before this lifetime's
        #                             boot record clears it
        self._prior_boots = 0       # boots replayed (0 = fresh ledger)
        self.records = 0                # appended this lifetime
        self.replayed = 0               # good records replayed at boot
        self.truncated = 0              # corrupt-tail records discarded
        self.quarantined_segments = 0   # whole segments set aside
        self.compactions = 0
        self.write_errors = 0           # failed appends (durability
        #                                 degraded, loudly — see
        #                                 journal())
        self._m_records = self._m_replayed = self._m_truncated = None
        self._m_errors = None
        if registry is not None:
            self._m_records = registry.counter(
                "tts_ledger_records_total",
                "request-ledger records appended (fsync'd) by kind")
            self._m_replayed = registry.counter(
                "tts_ledger_replayed_total",
                "ledger records replayed at boot")
            self._m_truncated = registry.counter(
                "tts_ledger_truncated_total",
                "corrupt-tail ledger records discarded at replay")
            self._m_errors = registry.counter(
                "tts_ledger_errors_total",
                "failed ledger appends (ENOSPC/IO) — crash-durability "
                "degraded until the disk recovers")
        self._replay()

    # ----------------------------------------------------------- replay

    def _segments(self) -> list[pathlib.Path]:
        return sorted(p for p in self.root.iterdir()
                      if p.name.startswith(SEGMENT_PREFIX)
                      and p.name.endswith(SEGMENT_SUFFIX))

    def _replay(self) -> None:
        segments = self._segments()
        corrupt_at: tuple[pathlib.Path, int] | None = None
        for i, seg in enumerate(segments):
            if corrupt_at is not None:
                # everything after the first corruption is suspect —
                # a later segment was written after bytes this replay
                # refused; set it aside rather than apply history with
                # a hole in it
                self._quarantine_segment(seg)
                continue
            data = seg.read_bytes()
            pos = good_end = 0
            while pos < len(data):
                nl = data.find(b"\n", pos)
                raw, nxt = ((data[pos:], len(data)) if nl < 0
                            else (data[pos:nl], nl + 1))
                if raw:
                    rec = _parse_line(raw)
                    if rec is None:
                        corrupt_at = (seg, good_end)
                        break
                    self.state.apply(rec)
                    self.replayed += 1
                pos = good_end = nxt
            if corrupt_at is None:
                continue
            # count every discarded line in the torn region
            bad = [ln for ln in data[good_end:].split(b"\n") if ln]
            self.truncated += len(bad)
            self._truncate_segment(seg, good_end)
        if self._m_replayed is not None and self.replayed:
            self._m_replayed.inc(self.replayed)
        if self._m_truncated is not None and self.truncated:
            self._m_truncated.inc(self.truncated)
        segments = self._segments()
        if segments:
            last = segments[-1]
            with self._lock:
                self._seg_index = int(
                    last.name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
                self._seg_records = sum(
                    1 for ln in last.read_bytes().split(b"\n") if ln)
        self._prior_clean = self.state.clean_shutdown
        self._prior_boots = self.state.boots
        if self.replayed or self.truncated:
            tracelog.event("ledger.replay", dir=str(self.root),
                           replayed=self.replayed,
                           truncated=self.truncated,
                           quarantined_segments=self.quarantined_segments,
                           boots=self.state.boots,
                           prior_shutdown=("clean" if self._prior_clean
                                           else "crash"),
                           requests=len(self.state.requests))

    def _truncate_segment(self, seg: pathlib.Path, offset: int) -> None:
        """Cut the torn tail off in place (best effort: a read-only
        ledger still replays its good prefix)."""
        try:
            with open(seg, "r+b") as f:
                f.truncate(offset)
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            tracelog.event("ledger.truncate_failed", path=seg.name,
                           error=repr(e))
        else:
            tracelog.event("ledger.truncated", path=seg.name,
                           offset=offset, discarded=self.truncated)

    def _quarantine_segment(self, seg: pathlib.Path) -> None:
        self.quarantined_segments += 1
        try:
            os.replace(seg, str(seg) + QUARANTINE_SUFFIX)
        except OSError:
            pass
        tracelog.event("ledger.segment_quarantined", path=seg.name)

    # ----------------------------------------------------------- append

    def _seg_path(self, index: int) -> pathlib.Path:
        return self.root / f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}"

    def _open_active(self) -> None:   # holds: self._lock
        if self._fh is None:
            if self._seg_index == 0:
                self._seg_index = 1
            self._fh = open(self._seg_path(self._seg_index), "ab")

    def _write(self, data: bytes) -> None:
        self._fh.write(data)
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def journal(self, kind: str, **fields) -> None:
        """Journal one record durably (fsync'd before returning) and
        fold it into the live state mirror. A no-op after close() —
        late executor-thread records on a non-waiting shutdown lose
        only their journaling, like the AOT writer's late stores.

        A write/fsync error (ENOSPC, a failing mount) does NOT raise:
        raising out of the server's lifecycle paths would hang
        `result()` waiters mid-_finalize or strand an already-admitted
        request unacknowledged — worse than the durability gap itself.
        Instead the record is still applied to the live mirror and the
        failure is surfaced three ways (`ledger.write_error` event,
        `tts_ledger_errors_total`, `write_errors` in snapshot — the
        doctor's signal that the durability promise is degraded until
        the disk recovers).

        Under a lease (fleet mode) every record is stamped with the
        owner's fencing epoch, and a lost lease FENCES the ledger: the
        record is neither written nor applied, every later journal is a
        no-op (zero commits by construction), and ``on_fenced`` fires
        once. Fencing does not raise here for the same reason write
        errors don't — the typed ``LeaseLost`` surfaces on the admission
        and checkpoint paths instead."""
        rec = {"k": kind, "t": time.time(), **fields}
        if self._lease is not None:
            if self.fenced:
                return
            try:
                self._lease.check()
            except LeaseLost as e:
                self._fence(str(e) or "lease lost", kind)
                return
            rec["e"] = self._lease.epoch
        compacted = error = None
        with self._lock:
            if self._closed:
                return
            try:
                self._open_active()
                self._write(_line(rec))
                self._seg_records += 1
                self._last_append_t = time.monotonic()
            except OSError as e:
                error = repr(e)
                self.write_errors += 1
            # the live mirror stays correct either way — this lifetime
            # keeps serving accurately; only crash-durability degrades
            self.state.apply(rec)
            self.records += 1
            if error is None and self._seg_records >= self._rotate_at:
                try:
                    compacted = self._compact_locked()
                except OSError as e:
                    error = f"compaction: {e!r}"
                    self.write_errors += 1
        if compacted is not None:
            # emitted OUTSIDE the ledger lock: the recorder has its own
            # lock and the two must never nest in both orders
            tracelog.event("ledger.compacted", **compacted)
        if error is not None:
            if self._m_errors is not None:
                self._m_errors.inc()
            tracelog.event("ledger.write_error", kind=kind, error=error)
        if self._m_records is not None:
            self._m_records.inc(kind=kind)

    def _fence(self, reason: str, kind: str) -> None:
        """Mark the ledger fenced (idempotent) and fire `on_fenced`
        once. After this every journal() is a no-op: a fenced-out
        stale owner commits NOTHING, by construction."""
        with self._lock:
            if self.fenced:
                return
            self.fenced = True
            self.fence_reason = reason
        tracelog.event("ledger.fenced", dir=str(self.root),
                       kind=kind, reason=reason)
        if self._on_fenced is not None:
            try:
                self._on_fenced(reason)
            except Exception as e:  # noqa: BLE001 — journal never raises
                tracelog.event("ledger.fence_callback_error",
                               error=repr(e))

    def _compact_locked(self) -> dict:   # holds: self._lock
        """Rotate to a fresh segment seeded with absolute state, then
        delete the old ones (caller holds the lock; returns the event
        payload the caller emits after releasing it). Crash-safe: the
        new segment is complete and fsync'd before anything is removed,
        and its records overwrite rather than accumulate on replay.

        Deliberately SYNCHRONOUS: the rewrite is bounded by live state
        (live requests + the terminal_keep window + tombstones), not by
        segment size, and the `_rotate_at` doubling keeps it rare. The
        event's `seconds` field is the observed stall; if a fleet's
        live state ever makes it hurt, a double-buffered background
        compactor is the follow-on — not worth the swap-in complexity
        until a measurement says so."""
        t0 = time.monotonic()
        old = self._segments()
        self._seg_index += 1
        new_path = self._seg_path(self._seg_index)
        # unique temp + atomic rename: a peer scanning the directory
        # mid-compaction (FailoverWatcher, an adopting survivor) sees
        # either the old segment set or the complete new segment, never
        # a torn half-written one (`_segments` skips dot-temp names)
        tmp = new_path.with_name(
            f".{new_path.name}.{os.getpid()}-{threading.get_ident()}.tmp")
        stamp = ({} if self._lease is None or self.fenced
                 else {"e": self._lease.epoch})
        try:
            with open(tmp, "wb") as f:
                n = 0
                for rec in self.state.to_records(self.terminal_keep):
                    f.write(_line({"t": time.time(), **stamp, **rec}))
                    n += 1
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, new_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._fsync_dir()
        if self._fh is not None:
            self._fh.close()
        self._fh = open(new_path, "ab")
        self._seg_records = n
        # a big live state compacts into a big segment: require real
        # headroom before the next rotation, or a state whose size
        # rivals the bound would re-compact on nearly every append
        self._rotate_at = max(self.segment_records, 2 * n)
        for seg in old:
            if seg != new_path:
                try:
                    os.unlink(seg)
                except OSError:
                    pass
        self._fsync_dir()
        self.compactions += 1
        # aged-out terminals leave the live mirror too, or the NEXT
        # compaction would resurrect them from state
        dropped = len(self.state.requests)
        self.state = self._reload_state(new_path)
        dropped -= len(self.state.requests)
        return {"segment": new_path.name, "records": n,
                "dropped_terminals": max(dropped, 0),
                "old_segments": len(old),
                "seconds": round(time.monotonic() - t0, 4)}

    @staticmethod
    def _reload_state(path: pathlib.Path) -> LedgerState:
        state = LedgerState()
        for raw in path.read_bytes().split(b"\n"):
            if raw:
                rec = _parse_line(raw)
                if rec is not None:
                    state.apply(rec)
        return state

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass    # platform without dir fsync: the entry fsyncs stand

    # ------------------------------------------------------------ misc

    def lag_s(self) -> float | None:
        """Seconds since the last durable append (None before any) —
        the doctor's staleness column: how far behind the journal
        could be at worst if the process died right now."""
        t = self._last_append_t
        return None if t is None else round(time.monotonic() - t, 3)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
                self._fh.close()
                self._fh = None

    def snapshot(self) -> dict:
        """JSON-safe stats for status_snapshot()'s `ledger` key."""
        with self._lock:
            extra = {}
            if (self._lease is not None or self.state.epoch
                    or self.state.fenced_discards):
                extra = {"epoch": self.state.epoch,
                         "fenced": self.fenced,
                         "fence_reason": self.fence_reason,
                         "fenced_discards": self.state.fenced_discards,
                         "takeovers": self.state.takeovers}
            return {"dir": str(self.root),
                    **extra,
                    "records": self.records,
                    "replayed": self.replayed,
                    "truncated": self.truncated,
                    "write_errors": self.write_errors,
                    "quarantined_segments": self.quarantined_segments,
                    "compactions": self.compactions,
                    "restarts": self.state.boots - 1
                    if self.state.boots else 0,
                    # what the replay said about the PRIOR lifetime
                    # (None on a fresh ledger): "clean" = it drained,
                    # "crash" = it died without the drain marker
                    "last_shutdown": (None if self._prior_boots == 0
                                      else ("clean"
                                            if self._prior_clean
                                            else "crash")),
                    "lag_s": self.lag_s()}
