"""Compiled-executable cache for the search service, with a
compile-cost ledger and an optional disk-persistent AOT tier.

The distributed loop costs seconds to minutes to trace + compile (the
one-off cost utils/compile_cache amortizes ACROSS processes via XLA's
persistent disk cache). This cache is the IN-PROCESS tier above it: the
compiled callable itself, keyed by everything the trace specializes on —
problem kind, (jobs, machines), lb_kind, chunk, aux dtype, the submesh's
device identities, capacity and the balance knobs — and explicitly NOT
on the instance data (the problem tables are runtime arguments to the
compiled loop; see engine/distributed.build_dist_loop).

That key design is the serve-many-compile-once property: all ten
instances of a Taillard class (same jobs x machines) served at the same
bound on the same submesh share ONE trace and ONE executable — request 1
pays the compile, requests 2..10 start exploring immediately. The
hit/miss counters ride the server's JSON status snapshot so the reuse is
observable (and testable) in production, not assumed.

The LEDGER makes the compile cost itself observable: every entry
records its trace and compile wall seconds (measured on the entry's
first invocation via the jit AOT path — ``fn.lower(...).compile()`` —
so the cost is attributed to the entry, not smeared into whichever
request happened to arrive first) and, where the backend supports
``compiled.cost_analysis()``, the executable's FLOPs and
bytes-accessed. The ledger rides ``status_snapshot()`` (the
``compile_ledger`` key), feeds the ``tts_compile_seconds`` histogram
on ``/metrics``, and renders as a table via
``tools/compile_report.py``. When the AOT path is unsupported for a
program, the entry falls back to timing the first call (compile
dominated) and says so in its ``method`` field.

The AOT tier (service/aot_cache.AOTCache, injected by the server when
``probe()`` passes) makes the compile a once-per-KEY cost across
server LIFETIMES: a miss first tries a disk deserialize (~0.2 s on the
CPU test mesh, zero ``lower()``/``compile()`` calls) and only compiles
— then persists, off the hot path — when no loadable entry exists.
Each ledger entry records where its executable came from
(``source=disk|compile``) and the deserialize seconds, so the
restart-replay contract ("a redeploy does zero fresh compiles for
previously-served shapes") is assertable from the ledger alone.
:meth:`_Entry.warm` is the boot pre-warm hook: it readies the
executable from disk or an abstract-shape compile WITHOUT executing it
(engine/distributed._DistDriver.warm drives it with ShapeDtypeStruct
arguments).

Between this cache (same process), the AOT tier (same key across
processes) and compile_cache.enable() (XLA's persistent HLO cache), a
restarted server re-serves a warm traffic mix with sub-second loads
instead of ~45 s compiles.
"""

from __future__ import annotations

import threading
import time

from ..obs import tracelog


class _Entry:
    """One cached loop: the built callable plus its cost record. The
    trace/compile (or disk-load) measurement happens on the FIRST
    invocation — or at :meth:`warm` time for pre-warmed entries (jit is
    lazy; at build() time there is nothing to measure yet)."""

    __slots__ = ("fn", "compiled", "record", "_lock", "_measured",
                 "_on_measured", "_on_fallback", "_aot", "_key")

    def __init__(self, fn, record: dict, on_measured, aot=None,
                 key: tuple = (), on_fallback=None):
        self.fn = fn
        self.compiled = None     # guarded-by: self._lock
        self.record = record
        # reentrant: _first_call runs under it and may book a fallback
        self._lock = threading.RLock()
        self._measured = False   # guarded-by: self._lock
        self._on_measured = on_measured
        self._on_fallback = on_fallback
        self._aot = aot
        self._key = key

    def __call__(self, *args):
        if not self._measured:
            with self._lock:
                if not self._measured:
                    return self._first_call(*args)
        if self.compiled is not None:
            try:
                return self.compiled(*args)
            except (TypeError, ValueError) as e:
                # AOT executables are stricter about argument layout
                # than jit; if a later call stops matching, fall back
                # to the jitted fn permanently (same trace -> the jit
                # cache compiles once more, correctness unaffected).
                # The downgrade is BOOKED: a disk/warm-sourced entry
                # that silently recompiled via jit would leave the
                # ledger claiming source=disk and the compile
                # invisible to the storm signal and the restart-replay
                # assertions.
                self._book_fallback(e)
        return self.fn(*args)

    def _book_fallback(self, error: Exception) -> None:
        with self._lock:
            if self.compiled is None:
                return                       # a racing call booked it
            self.compiled = None
            rec = self.record
            rec.update(fallback_from=rec.get("source"),
                       source="compile", method="jit_fallback")
            tracelog.event("executor.aot_fallback", key=rec["key"],
                           fallback_from=rec.get("fallback_from"),
                           error=repr(error))
            if self._on_fallback is not None:
                self._on_fallback(rec)

    def _load_from_disk(self) -> bool:   # holds: self._lock
        """Try the disk AOT tier (caller holds the lock). A hit readies
        `self.compiled` with ZERO lower()/compile() calls and books the
        entry as source=disk."""
        if self._aot is None:
            return False
        got = self._aot.load(self._key)
        if got is None:
            return False
        compiled, dt = got
        self.record.update(trace_s=0.0, compile_s=0.0, method="aot",
                           source="disk", deserialize_s=round(dt, 6))
        self._cost_analysis(compiled, self.record)
        self.compiled = compiled
        self._measured = True
        self._record_measured()
        return True

    def _compile_fresh(self, *args):
        """The jit AOT path — the ONLY place in the entry that traces
        or compiles (tests monkeypatch it to pin the zero-compile
        restart-replay contract). Returns (compiled, trace_s,
        compile_s); raises when the AOT path cannot handle the
        program/backend."""
        t0 = time.perf_counter()
        lowered = self.fn.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        return compiled, t1 - t0, t2 - t1

    def warm(self, *abstract_args, via: str = "prewarm") -> str:
        """Ready the executable WITHOUT executing it (the boot
        pre-warm hook; `abstract_args` are jax.ShapeDtypeStructs).
        Returns how: "warm" (already measured — idempotent), "disk"
        (deserialized), "compile" (fresh compile, persisted), or
        "skipped" (the AOT path failed; the first real call takes the
        normal path and nothing is booked). `via` labels the ledger
        record ("prewarm" / "ladder" — any warm-initiated compile is
        PLANNED and excluded from the compile_storm signal)."""
        with self._lock:
            if self._measured:
                return "warm"
            if self._load_from_disk():
                return "disk"
            rec = self.record
            try:
                compiled, trace_s, compile_s = self._compile_fresh(
                    *abstract_args)
            except Exception as e:  # noqa: BLE001 — warming is an
                # optimization; a program the AOT path rejects still
                # serves (and measures) through the first-call path
                tracelog.event("executor.warm_skipped", key=rec["key"],
                               error=repr(e))
                return "skipped"
            rec.update(trace_s=round(trace_s, 6),
                       compile_s=round(compile_s, 6),
                       method="aot", source="compile", via=via)
            self._cost_analysis(compiled, rec)
            self.compiled = compiled
            self._measured = True
            self._record_measured()
            if self._aot is not None:
                self._aot.store(self._key, compiled,
                                key_repr=rec["key"])
            return "compile"

    def _first_call(self, *args):        # holds: self._lock
        rec = self.record
        if self._load_from_disk():
            try:
                return self.compiled(*args)
            except (TypeError, ValueError) as e:
                # same AOT-strictness net as __call__: a replayed
                # entry whose layout drifted in a way the fingerprint
                # missed must degrade to jit (booked), not fail the
                # request on its very first post-restart invocation
                self._book_fallback(e)
                return self.fn(*args)
        # ONLY lower/compile inside the try: a runtime failure of the
        # compiled loop itself must propagate to the service retry tier
        # (re-running it here would be a hidden second execution outside
        # the retry accounting) and must not be booked as compile cost
        try:
            compiled, trace_s, compile_s = self._compile_fresh(*args)
            rec.update(trace_s=round(trace_s, 6),
                       compile_s=round(compile_s, 6),
                       method="aot", source="compile")
            self._cost_analysis(compiled, rec)
            self.compiled = compiled
        except Exception:  # noqa: BLE001 — a backend/program that the
            # AOT path cannot handle still serves through plain jit
            self.compiled = compiled = None
        if compiled is not None:
            self._measured = True
            self._record_measured()
            if self._aot is not None:
                self._aot.store(self._key, compiled,
                                key_repr=rec["key"])
            return compiled(*args)
        # fallback: the first jit call IS trace+compile (+ one execute)
        t0 = time.perf_counter()
        out = self.fn(*args)
        rec.update(trace_s=0.0,
                   compile_s=round(time.perf_counter() - t0, 6),
                   method="first_call", source="compile")
        self._measured = True
        self._record_measured()
        return out

    def _record_measured(self) -> None:
        rec = self.record
        tracelog.event("executor.compile", key=rec["key"],
                       trace_s=rec["trace_s"],
                       compile_s=rec["compile_s"],
                       method=rec["method"], source=rec.get("source"),
                       deserialize_s=rec.get("deserialize_s"),
                       flops=rec.get("flops"))
        if self._on_measured is not None:
            self._on_measured(rec)

    @staticmethod
    def _cost_analysis(compiled, rec: dict) -> None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                if ca.get("flops") is not None:
                    rec["flops"] = float(ca["flops"])
                if ca.get("bytes accessed") is not None:
                    rec["bytes_accessed"] = float(ca["bytes accessed"])
        except Exception:  # noqa: BLE001 — optional per backend
            pass
        try:
            mem = compiled.memory_analysis()
            if mem is not None:
                rec["temp_bytes"] = int(
                    getattr(mem, "temp_size_in_bytes", 0))
        except Exception:  # noqa: BLE001
            pass


class ExecutorCache:
    """Thread-safe get-or-build cache of compiled search loops.

    `get_or_build(key, build)` is the whole interface
    (engine/distributed._DistDriver consults it when a `loop_cache` is
    injected). Builds run under the lock: two requests racing to build
    the SAME key must not trace twice — and distinct keys are distinct
    submeshes or shapes, whose builds are cheap closures anyway (jit is
    lazy; XLA compilation happens at first call, outside the lock).

    `aot` (service/aot_cache.AOTCache, optional) is the disk tier:
    entries first try a deserialize and persist fresh compiles, so a
    restarted process replays this cache from disk. `compiles` /
    `planned_compiles` count TRUE fresh XLA compiles (total / initiated
    by pre-warm) — the health layer's compile_storm rule reads their
    difference so a boot-time cache replay or an operator-requested
    pre-warm never reads as a storm (see `storm_signal`).
    """

    def __init__(self, registry=None, aot=None):
        self._lock = threading.Lock()
        self._fns: dict[tuple, _Entry] = {}   # guarded-by: self._lock
        self.hits = 0                # guarded-by: self._lock
        self.misses = 0              # guarded-by: self._lock
        self.aot = aot
        self.compiles = 0            # guarded-by: self._lock
        #                              (fresh XLA compiles, any origin)
        self.planned_compiles = 0    # guarded-by: self._lock
        #                              (...of which pre-warm initiated)
        # optional metrics mirror (obs/metrics.Registry): the server
        # passes its per-server registry so /metrics exposes the same
        # hit/miss counts the JSON snapshot reports, plus the
        # compile-cost histogram the ledger feeds
        self._hits_c = self._misses_c = self._entries_g = None
        self._compile_h = None
        if registry is not None:
            self._hits_c = registry.counter(
                "tts_executor_cache_hits_total",
                "requests served from an already-compiled loop")
            self._misses_c = registry.counter(
                "tts_executor_cache_misses_total",
                "compiled-loop builds (traces/compiles paid)")
            self._entries_g = registry.gauge(
                "tts_executor_cache_entries",
                "distinct compiled loops held")
            self._entries_g.set_fn(lambda: len(self))
            self._compile_h = registry.histogram(
                "tts_compile_seconds",
                "trace+compile wall seconds per new executable")

    def _measured(self, record: dict) -> None:
        # disk-sourced entries paid a deserialize, not a compile: they
        # must feed neither the compile histogram nor the storm signal
        if record.get("source") != "compile":
            return
        with self._lock:
            self.compiles += 1
            # any warm-initiated compile is planned: boot pre-warm
            # ("prewarm") and chunk-ladder rung pre-readies ("ladder")
            if record.get("via"):
                self.planned_compiles += 1
        if self._compile_h is not None:
            self._compile_h.observe(record["trace_s"]
                                    + record["compile_s"])

    def _fallback(self, record: dict) -> None:
        """An AOT executable was downgraded to plain jit mid-lifetime
        (argument mismatch): the jit cache compiles once more, so the
        storm signal must count it — but there is no fresh AOT
        measurement to feed the compile histogram."""
        with self._lock:
            self.compiles += 1

    def storm_signal(self) -> int:
        """Fresh UNPLANNED compiles so far — the compile_storm rule's
        input (obs/health). Disk-cache replays and pre-warm compiles
        are excluded: a mass boot replay must not fire the alert."""
        with self._lock:
            return self.compiles - self.planned_compiles

    def get_or_build(self, key: tuple, build):
        with self._lock:
            entry = self._fns.get(key)
            if entry is not None:
                self.hits += 1
                if self._hits_c is not None:
                    self._hits_c.inc()
                return entry
            self.misses += 1
            if self._misses_c is not None:
                self._misses_c.inc()
            t0 = time.perf_counter()
            fn = build()
            record = {
                "key": _key_repr(key),
                "build_s": round(time.perf_counter() - t0, 6),
                # filled in on the entry's first invocation (or warm):
                # source records disk-deserialize vs fresh compile
                "trace_s": None, "compile_s": None, "method": None,
                "source": None, "deserialize_s": None,
                "created_unix": time.time(),
            }
            entry = self._fns[key] = _Entry(fn, record, self._measured,
                                            aot=self.aot, key=key,
                                            on_fallback=self._fallback)
            return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._fns)

    def snapshot(self) -> dict:
        """JSON-safe stats for the status API. (Schema frozen — the
        ledger rides status_snapshot()'s own `compile_ledger` key, see
        ledger_snapshot(); the disk tier's stats ride its `aot_cache`
        key.)"""
        with self._lock:
            return {"entries": len(self._fns), "hits": self.hits,
                    "misses": self.misses}

    def ledger_snapshot(self) -> list[dict]:
        """Per-entry compile-cost records, oldest first. `trace_s` /
        `compile_s` are None until the entry's first invocation has
        measured them; `source` says disk|compile once it has."""
        with self._lock:
            entries = list(self._fns.values())
        return sorted((dict(e.record) for e in entries),
                      key=lambda r: r["created_unix"])


def _key_repr(key: tuple) -> str:
    """A stable human-readable form of a cache key (tuples of scalars
    by construction; keep it JSON-safe)."""
    return "/".join(str(k) for k in key)
