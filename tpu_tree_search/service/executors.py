"""Compiled-executable cache for the search service.

The distributed loop costs seconds to minutes to trace + compile (the
one-off cost utils/compile_cache amortizes ACROSS processes via XLA's
persistent disk cache). This cache is the IN-PROCESS tier above it: the
compiled callable itself, keyed by everything the trace specializes on —
problem kind, (jobs, machines), lb_kind, chunk, aux dtype, the submesh's
device identities, capacity and the balance knobs — and explicitly NOT
on the instance data (the problem tables are runtime arguments to the
compiled loop; see engine/distributed.build_dist_loop).

That key design is the serve-many-compile-once property: all ten
instances of a Taillard class (same jobs x machines) served at the same
bound on the same submesh share ONE trace and ONE executable — request 1
pays the compile, requests 2..10 start exploring immediately. The
hit/miss counters ride the server's JSON status snapshot so the reuse is
observable (and testable) in production, not assumed.

Between this cache (same process) and compile_cache.enable() (XLA's
persistent disk cache, same program shape across processes), a restarted
server re-serves a warm traffic mix with ~1 s loads instead of ~45 s
compiles.
"""

from __future__ import annotations

import threading


class ExecutorCache:
    """Thread-safe get-or-build cache of compiled search loops.

    `get_or_build(key, build)` is the whole interface
    (engine/distributed._DistDriver consults it when a `loop_cache` is
    injected). Builds run under the lock: two requests racing to build
    the SAME key must not trace twice — and distinct keys are distinct
    submeshes or shapes, whose builds are cheap closures anyway (jit is
    lazy; XLA compilation happens at first call, outside the lock).
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._fns: dict[tuple, object] = {}
        self.hits = 0
        self.misses = 0
        # optional metrics mirror (obs/metrics.Registry): the server
        # passes its per-server registry so /metrics exposes the same
        # hit/miss counts the JSON snapshot reports
        self._hits_c = self._misses_c = self._entries_g = None
        if registry is not None:
            self._hits_c = registry.counter(
                "tts_executor_cache_hits_total",
                "requests served from an already-compiled loop")
            self._misses_c = registry.counter(
                "tts_executor_cache_misses_total",
                "compiled-loop builds (traces/compiles paid)")
            self._entries_g = registry.gauge(
                "tts_executor_cache_entries",
                "distinct compiled loops held")
            self._entries_g.set_fn(lambda: len(self))

    def get_or_build(self, key: tuple, build):
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                self.hits += 1
                if self._hits_c is not None:
                    self._hits_c.inc()
                return fn
            self.misses += 1
            if self._misses_c is not None:
                self._misses_c.inc()
            fn = build()
            self._fns[key] = fn
            return fn

    def __len__(self) -> int:
        with self._lock:
            return len(self._fns)

    def snapshot(self) -> dict:
        """JSON-safe stats for the status API."""
        with self._lock:
            return {"entries": len(self._fns), "hits": self.hits,
                    "misses": self.misses}
