"""Compiled-executable cache for the search service, with a
compile-cost ledger.

The distributed loop costs seconds to minutes to trace + compile (the
one-off cost utils/compile_cache amortizes ACROSS processes via XLA's
persistent disk cache). This cache is the IN-PROCESS tier above it: the
compiled callable itself, keyed by everything the trace specializes on —
problem kind, (jobs, machines), lb_kind, chunk, aux dtype, the submesh's
device identities, capacity and the balance knobs — and explicitly NOT
on the instance data (the problem tables are runtime arguments to the
compiled loop; see engine/distributed.build_dist_loop).

That key design is the serve-many-compile-once property: all ten
instances of a Taillard class (same jobs x machines) served at the same
bound on the same submesh share ONE trace and ONE executable — request 1
pays the compile, requests 2..10 start exploring immediately. The
hit/miss counters ride the server's JSON status snapshot so the reuse is
observable (and testable) in production, not assumed.

The LEDGER makes the compile cost itself observable: every entry
records its trace and compile wall seconds (measured on the entry's
first invocation via the jit AOT path — ``fn.lower(...).compile()`` —
so the cost is attributed to the entry, not smeared into whichever
request happened to arrive first) and, where the backend supports
``compiled.cost_analysis()``, the executable's FLOPs and
bytes-accessed. The ledger rides ``status_snapshot()`` (the
``compile_ledger`` key), feeds the ``tts_compile_seconds`` histogram
on ``/metrics``, and renders as a table via
``tools/compile_report.py``. When the AOT path is unsupported for a
program, the entry falls back to timing the first call (compile
dominated) and says so in its ``method`` field.

Between this cache (same process) and compile_cache.enable() (XLA's
persistent disk cache, same program shape across processes), a restarted
server re-serves a warm traffic mix with ~1 s loads instead of ~45 s
compiles.
"""

from __future__ import annotations

import threading
import time

from ..obs import tracelog


class _Entry:
    """One cached loop: the built callable plus its cost record. The
    trace/compile measurement happens on the FIRST invocation (jit is
    lazy — at build() time there is nothing to measure yet)."""

    __slots__ = ("fn", "compiled", "record", "_lock", "_measured",
                 "_on_measured")

    def __init__(self, fn, record: dict, on_measured):
        self.fn = fn
        self.compiled = None
        self.record = record
        self._lock = threading.Lock()
        self._measured = False
        self._on_measured = on_measured

    def __call__(self, *args):
        if not self._measured:
            with self._lock:
                if not self._measured:
                    return self._first_call(*args)
        if self.compiled is not None:
            try:
                return self.compiled(*args)
            except (TypeError, ValueError):
                # AOT executables are stricter about argument layout
                # than jit; if a later call stops matching, fall back
                # to the jitted fn permanently (same trace -> the jit
                # cache compiles once more, correctness unaffected)
                self.compiled = None
        return self.fn(*args)

    def _first_call(self, *args):
        rec = self.record
        # ONLY lower/compile inside the try: a runtime failure of the
        # compiled loop itself must propagate to the service retry tier
        # (re-running it here would be a hidden second execution outside
        # the retry accounting) and must not be booked as compile cost
        try:
            t0 = time.perf_counter()
            lowered = self.fn.lower(*args)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
            rec.update(trace_s=round(t1 - t0, 6),
                       compile_s=round(t2 - t1, 6),
                       method="aot")
            self._cost_analysis(compiled, rec)
            self.compiled = compiled
        except Exception:  # noqa: BLE001 — a backend/program that the
            # AOT path cannot handle still serves through plain jit
            self.compiled = compiled = None
        if compiled is not None:
            self._measured = True
            self._record_measured()
            return compiled(*args)
        # fallback: the first jit call IS trace+compile (+ one execute)
        t0 = time.perf_counter()
        out = self.fn(*args)
        rec.update(trace_s=0.0,
                   compile_s=round(time.perf_counter() - t0, 6),
                   method="first_call")
        self._measured = True
        self._record_measured()
        return out

    def _record_measured(self) -> None:
        rec = self.record
        tracelog.event("executor.compile", key=rec["key"],
                       trace_s=rec["trace_s"],
                       compile_s=rec["compile_s"],
                       method=rec["method"], flops=rec.get("flops"))
        if self._on_measured is not None:
            self._on_measured(rec)

    @staticmethod
    def _cost_analysis(compiled, rec: dict) -> None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                if ca.get("flops") is not None:
                    rec["flops"] = float(ca["flops"])
                if ca.get("bytes accessed") is not None:
                    rec["bytes_accessed"] = float(ca["bytes accessed"])
        except Exception:  # noqa: BLE001 — optional per backend
            pass
        try:
            mem = compiled.memory_analysis()
            if mem is not None:
                rec["temp_bytes"] = int(
                    getattr(mem, "temp_size_in_bytes", 0))
        except Exception:  # noqa: BLE001
            pass


class ExecutorCache:
    """Thread-safe get-or-build cache of compiled search loops.

    `get_or_build(key, build)` is the whole interface
    (engine/distributed._DistDriver consults it when a `loop_cache` is
    injected). Builds run under the lock: two requests racing to build
    the SAME key must not trace twice — and distinct keys are distinct
    submeshes or shapes, whose builds are cheap closures anyway (jit is
    lazy; XLA compilation happens at first call, outside the lock).
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._fns: dict[tuple, _Entry] = {}
        self.hits = 0
        self.misses = 0
        # optional metrics mirror (obs/metrics.Registry): the server
        # passes its per-server registry so /metrics exposes the same
        # hit/miss counts the JSON snapshot reports, plus the
        # compile-cost histogram the ledger feeds
        self._hits_c = self._misses_c = self._entries_g = None
        self._compile_h = None
        if registry is not None:
            self._hits_c = registry.counter(
                "tts_executor_cache_hits_total",
                "requests served from an already-compiled loop")
            self._misses_c = registry.counter(
                "tts_executor_cache_misses_total",
                "compiled-loop builds (traces/compiles paid)")
            self._entries_g = registry.gauge(
                "tts_executor_cache_entries",
                "distinct compiled loops held")
            self._entries_g.set_fn(lambda: len(self))
            self._compile_h = registry.histogram(
                "tts_compile_seconds",
                "trace+compile wall seconds per new executable")

    def _measured(self, record: dict) -> None:
        if self._compile_h is not None:
            self._compile_h.observe(record["trace_s"]
                                    + record["compile_s"])

    def get_or_build(self, key: tuple, build):
        with self._lock:
            entry = self._fns.get(key)
            if entry is not None:
                self.hits += 1
                if self._hits_c is not None:
                    self._hits_c.inc()
                return entry
            self.misses += 1
            if self._misses_c is not None:
                self._misses_c.inc()
            t0 = time.perf_counter()
            fn = build()
            record = {
                "key": _key_repr(key),
                "build_s": round(time.perf_counter() - t0, 6),
                # filled in on the entry's first invocation
                "trace_s": None, "compile_s": None, "method": None,
                "created_unix": time.time(),
            }
            entry = self._fns[key] = _Entry(fn, record, self._measured)
            return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._fns)

    def snapshot(self) -> dict:
        """JSON-safe stats for the status API. (Schema frozen — the
        ledger rides status_snapshot()'s own `compile_ledger` key, see
        ledger_snapshot().)"""
        with self._lock:
            return {"entries": len(self._fns), "hits": self.hits,
                    "misses": self.misses}

    def ledger_snapshot(self) -> list[dict]:
        """Per-entry compile-cost records, oldest first. `trace_s` /
        `compile_s` are None until the entry's first invocation has
        measured them."""
        with self._lock:
            entries = list(self._fns.values())
        return sorted((dict(e.record) for e in entries),
                      key=lambda r: r["created_unix"])


def _key_repr(key: tuple) -> str:
    """A stable human-readable form of a cache key (tuples of scalars
    by construction; keep it JSON-safe)."""
    return "/".join(str(k) for k in key)
