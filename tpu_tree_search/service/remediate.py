"""Self-healing: alert-driven remediation for the search service.

PR 6 built the layer that *judges* the serving stack (obs/health's
SLO/anomaly alerts, obs/audit's conservation findings); PR 1 built the
machinery that *survives* faults (checkpoint + elastic resume). This
module connects them: a :class:`RemediationController` per
`SearchServer` subscribes to the health monitor's alert transitions and
executes **bounded, journaled, rate-limited** actions from a fixed
rule -> action policy table, so the server detects, contains and
repairs its own failures instead of paging a human:

==================  ====================================================
alert rule          action on ``firing``
==================  ====================================================
``stall``           ``preempt_requeue`` — stop the stalled request at
                    its next segment boundary (the checkpoint machinery
                    makes the stop lossless), append the offending
                    submesh to the request's **excluded-submesh set**
                    (the scheduler honors it at dispatch), and requeue;
                    the request resumes elastically on a healthy submesh
``mem_headroom``    ``shed_memory`` — preempt the lowest-priority
                    RUNNING request (its pools free between dispatches)
                    and raise the chunk-ladder memory-pressure hint
                    (engine/ladder: ramp momentum suppressed, the
                    controller holds the smallest covering rung — node
                    accounting unchanged); cleared on ``resolved``
``compile_storm``   ``pause_admission`` — new submissions are rejected
                    with an explicit "admission paused" reason (HTTP
                    429 through obs/httpd; the file spool HOLDS its
                    backlog instead of rejecting it) until the alert
                    resolves
``audit``           ``quarantine_checkpoint`` — a failed
                    ``checkpoint_roundtrip`` invariant names the bad
                    snapshot; rename it ``*.corrupt`` so the next load
                    rolls back to the rotating ``.prev`` last-good
==================  ====================================================

Beyond the alert feed, the server's retry tier consults the controller
on every dispatch failure (:meth:`on_dispatch_failure`):

- every failure lands in the request's ``failure_log`` (timestamp,
  submesh, attempt, error — the post-hoc diagnosis surface on
  ``/status`` and in tools/trace_summary.py);
- the failing submesh joins the request's excluded set, so the retry
  tier never redispatches a request onto the submesh that just failed
  it while healthy ones are available;
- failures that FOLLOW the request across >= K distinct submeshes
  (``TTS_REMEDIATE_DEADLETTER_SUBMESHES``) **dead-letter** it: terminal
  FAILED with the complete failure_log, never an infinite redispatch
  loop — the fault is the request, not the hardware;
- failures that stay LOCALIZED to one submesh
  (``TTS_REMEDIATE_QUARANTINE_FAILS`` within the window) **quarantine**
  it: the slot is drained and held out of the partition, then
  **canary-probed** with a synthetic micro-request on a cooldown
  (``TTS_REMEDIATE_PROBE_S``) and readmitted when the probe completes —
  the fault was the hardware, requests route around it meanwhile.

Discipline (the flag-gated, bit-identical-off contract of
overlap/ladder): the whole controller sits behind **TTS_REMEDIATE**
(`serve --remediate`). Default OFF = **observe-only**: detection runs
and every action is journaled as the action the controller *would*
take (outcome ``observed``), but nothing is mutated — behavior is
bit-identical to the pre-remediation server. Every executed action is
hysteresis-gated by the alert lifecycle itself (actions fire on
pending->firing transitions, which carry the rules' dwell) and capped
per rule per sliding window (``TTS_REMEDIATE_MAX_PER_RULE`` /
``TTS_REMEDIATE_WINDOW_S``) — a flapping rule degrades to observe-only
instead of thrashing the scheduler. Everything is journaled three
ways: ``remediation.*`` flight-recorder events,
``tts_remediations_total{rule,action,outcome}`` (plus the
``tts_quarantined_submeshes`` / ``tts_admission_paused`` gauges), and
the ``remediation`` key of ``status_snapshot()`` that the dashboard
panel and the ``doctor`` columns render.

Lock order: the server calls into the controller while holding the
server lock (failure verdicts, snapshots), so the controller NEVER
calls into the server while holding its own lock — decisions are taken
under ``self._lock``, actions execute after it is released.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from ..obs import tracelog
from ..utils import config as cfg

__all__ = ["RemediationController", "POLICY"]

# rule -> action executed on the pending->firing transition. Rules
# absent here (queue_wait, pruning_collapse, perf) are diagnosis-only:
# no safe mechanical remediation exists, a human reads the alert.
POLICY = {
    "stall": "preempt_requeue",
    "mem_headroom": "shed_memory",
    "compile_storm": "pause_admission",
    "audit": "quarantine_checkpoint",
}

# actions with a reversal executed on the firing->resolved transition
# (reversals are never rate-limited: a cap that could strand admission
# paused after the storm cleared would turn the valve into an outage)
_REVERSALS = {
    "pause_admission": "resume_admission",
    "shed_memory": "clear_memory_pressure",
}

_JOURNAL_CAP = 256        # bounded journal (snapshot shows the tail)
_FAILURE_WINDOW_CAP = 64  # per-submesh failure timestamps kept


class RemediationController:
    """One per SearchServer; see the module docstring for the policy.

    `enabled=None` resolves TTS_REMEDIATE (default False =
    observe-only). The controller subscribes itself to
    ``server.health`` at construction; `close()` stops the worker.
    """

    def __init__(self, server, enabled: bool | None = None,
                 registry=None,
                 window_s: float | None = None,
                 max_per_rule: int | None = None,
                 quarantine_fails: int | None = None,
                 deadletter_submeshes: int | None = None,
                 probe_s: float | None = None):
        self.server = server
        self.enabled = (cfg.env_flag(cfg.REMEDIATE_FLAG)
                        if enabled is None else bool(enabled))
        self.window_s = float(
            cfg.env_float("TTS_REMEDIATE_WINDOW_S")
            if window_s is None else window_s)
        self.max_per_rule = int(
            cfg.env_int("TTS_REMEDIATE_MAX_PER_RULE")
            if max_per_rule is None else max_per_rule)
        self.quarantine_fails = int(
            cfg.env_int("TTS_REMEDIATE_QUARANTINE_FAILS")
            if quarantine_fails is None else quarantine_fails)
        self.deadletter_submeshes = int(
            cfg.env_int("TTS_REMEDIATE_DEADLETTER_SUBMESHES")
            if deadletter_submeshes is None else deadletter_submeshes)
        self.probe_s = float(
            cfg.env_float("TTS_REMEDIATE_PROBE_S")
            if probe_s is None else probe_s)
        if registry is None:
            from ..obs import metrics as obs_metrics
            registry = obs_metrics.default()
        self._m_actions = registry.counter(
            "tts_remediations_total",
            "remediation decisions by rule/action/outcome")
        self._g_quar = registry.gauge(
            "tts_quarantined_submeshes",
            "submesh slots currently held out of the partition")
        self._g_paused = registry.gauge(
            "tts_admission_paused",
            "1 while the controller holds admission paused")
        self._g_quar.set(0.0)
        self._g_paused.set(0.0)
        self.journal: collections.deque = collections.deque(
            maxlen=_JOURNAL_CAP)                 # guarded-by: self._lock
        self._rule_actions: dict[str, list] = {}  # guarded-by: self._lock
        self._submesh_fails: dict[int, list] = {}  # guarded-by: self._lock
        self._probes_due: dict[int, float] = {}   # guarded-by: self._lock
        # a ledger-restored admission pause awaiting revalidation (the
        # alert that caused it did not survive the crash, so no
        # firing->resolved transition will ever clear it; the worker
        # re-judges the rule itself on this cooldown instead)
        self._pause_check_due: float | None = None  # guarded-by: self._lock
        self._probe_threads: dict = {}            # guarded-by: self._lock
        self._canaries = 0                        # guarded-by: self._lock
        self._lock = threading.Lock()
        self._closing = threading.Event()
        self._wake = threading.Event()
        # listener thread appends, worker drains
        self._tasks: collections.deque = collections.deque()  # guarded-by: self._lock
        self._pressure_raised = False   # this controller raised the
        #                                 ladder hint; close() lowers it
        self._worker: threading.Thread | None = None
        if self.enabled:
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True,
                name="tts-remediation")
            self._worker.start()
        health = getattr(server, "health", None)
        if health is not None:
            health.add_listener(self._on_alert)
        tracelog.event("remediation.start", enabled=self.enabled,
                       window_s=self.window_s,
                       max_per_rule=self.max_per_rule,
                       quarantine_fails=self.quarantine_fails,
                       deadletter_submeshes=self.deadletter_submeshes,
                       probe_s=self.probe_s)

    # ------------------------------------------------------------ feed

    def _on_alert(self, rule: str, transition: str, alert: dict) -> None:
        """HealthMonitor listener (runs on the monitor thread, outside
        the monitor's lock)."""
        action = POLICY.get(rule)
        if action is None:
            return
        if transition == "firing":
            self._submit(rule, action, alert)
        elif transition == "resolved" and action in _REVERSALS:
            self._submit(rule, _REVERSALS[action], alert)

    def _submit(self, rule: str, action: str, alert: dict) -> None:
        if not self.enabled:
            # observe-only: journal the action the controller WOULD
            # take, inline (no worker thread exists in this mode)
            if action in _REVERSALS.values():
                return        # nothing was done, nothing to reverse
            self._journal(rule, action, "observed",
                          detail=alert.get("detail") or {})
            return
        with self._lock:
            self._tasks.append(("alert", rule, action, alert))
        self._wake.set()

    # ---------------------------------------------------------- worker

    def _worker_loop(self) -> None:
        while not self._closing.is_set():
            # sleep until woken (a task or a fresh quarantine) or the
            # next canary comes due — an idle controller costs nothing
            with self._lock:
                due = list(self._probes_due.values())
                if self._pause_check_due is not None:
                    due.append(self._pause_check_due)
            timeout = (max(0.05, min(due) - time.monotonic())
                       if due else None)
            self._wake.wait(timeout=timeout)
            self._wake.clear()
            while True:
                with self._lock:
                    task = (self._tasks.popleft()
                            if self._tasks else None)
                if task is None:
                    break
                try:
                    _, rule, action, alert = task
                    self.handle(rule, action, alert)
                except Exception as e:  # noqa: BLE001 — a broken action
                    # is a journal entry, never a dead controller
                    self._journal(rule, action, "error",
                                  detail={"error": repr(e)})
            try:
                self._run_due_canaries()
            except Exception as e:  # noqa: BLE001 — same stance
                self._journal("quarantine", "canary_probe", "error",
                              detail={"error": repr(e)})
            try:
                self._check_restored_pause()
            except Exception as e:  # noqa: BLE001 — same stance
                self._journal("compile_storm", "resume_admission",
                              "error", detail={"error": repr(e)})

    def close(self) -> None:
        self._closing.set()
        self._wake.set()
        if self._worker is not None:
            self._worker.join(timeout=5)
        if self._pressure_raised:
            # the hint is PROCESS-global (engine/ladder): a server
            # closing mid-incident must not leave later servers in
            # this process silently demoted
            from ..engine import ladder
            ladder.set_memory_pressure(False)
            self._pressure_raised = False

    # ---------------------------------------------------------- actions

    def handle(self, rule: str, action: str, alert: dict) -> str:
        """Execute one policy action (the worker's body; public so tests
        and drills can drive the table synchronously). Returns the
        journaled outcome."""
        detail = dict(alert.get("detail") or {})
        limited = action not in _REVERSALS.values()
        if limited and self._over_limit(rule):
            return self._journal(rule, action, "rate_limited",
                                 detail=detail)
        fn = getattr(self, f"_act_{action}", None)
        if fn is None:
            return self._journal(rule, action, "error",
                                 detail={"error": f"unknown action "
                                                  f"{action!r}"})
        outcome, extra = fn(detail)
        if limited and outcome == "applied":
            # only EXECUTED actions consume the window budget: a run of
            # stale noops (the alerted request finished before the
            # worker got there) must not rate-limit the remediation a
            # genuinely wedged request needs next
            self._note_action(rule)
        return self._journal(rule, action, outcome,
                             detail={**detail, **extra})

    def _over_limit(self, rule: str) -> bool:
        """Sliding-window rate valve: at most `max_per_rule` APPLIED
        actions per rule per `window_s` (see _note_action)."""
        now = time.monotonic()
        with self._lock:
            times = self._rule_actions.setdefault(rule, [])
            times[:] = [t for t in times if now - t < self.window_s]
            return len(times) >= self.max_per_rule

    def _note_action(self, rule: str) -> None:
        with self._lock:
            self._rule_actions.setdefault(rule, []).append(
                time.monotonic())

    def _act_preempt_requeue(self, detail: dict) -> tuple[str, dict]:
        rid = detail.get("request_id")
        if rid is None:
            return "noop", {"why": "alert names no request"}
        # act only if the request is still on the submesh the stall
        # was OBSERVED on: a delayed action on a request the retry
        # tier already moved would exclude a HEALTHY submesh and leave
        # the wedged one eligible
        ok, submesh = self.server.remediate_preempt(
            rid, expected_submesh=detail.get("submesh"))
        if not ok:
            return "noop", {"why": f"{rid} not RUNNING on the "
                                   "observed submesh anymore"}
        return "applied", {"request_id": rid,
                           "excluded_submesh": submesh}

    def _act_shed_memory(self, detail: dict) -> tuple[str, dict]:
        from ..engine import ladder
        self._pressure_raised = True
        ladder.set_memory_pressure(True)
        victim = self.server.lowest_priority_running()
        if victim is None:
            return "applied", {"why": "ladder pressure only; nothing "
                                      "running to shed"}
        ok, _ = self.server.remediate_preempt(victim,
                                              exclude_submesh=False)
        return ("applied" if ok else "noop"), {"request_id": victim}

    def _act_clear_memory_pressure(self, detail: dict
                                   ) -> tuple[str, dict]:
        from ..engine import ladder
        self._pressure_raised = False
        ladder.set_memory_pressure(False)
        return "applied", {}

    def _act_pause_admission(self, detail: dict) -> tuple[str, dict]:
        reason = ("compile storm: executable reuse broken "
                  f"({detail.get('compiles_in_interval', '?')} fresh "
                  "compiles in the last health interval)")
        self.server.pause_admission(reason)
        self._g_paused.set(1.0)
        return "applied", {"reason": reason}

    def _act_resume_admission(self, detail: dict) -> tuple[str, dict]:
        self.server.resume_admission()
        self._g_paused.set(0.0)
        return "applied", {}

    def _act_quarantine_checkpoint(self, detail: dict
                                   ) -> tuple[str, dict]:
        """A failed checkpoint_roundtrip invariant names the bad
        snapshot: quarantine it `*.corrupt` so the next load rolls back
        to the rotating `.prev` last-good (engine/checkpoint's
        load_resilient order)."""
        inner = detail.get("detail") or {}
        if detail.get("invariant") != "checkpoint_roundtrip":
            return "noop", {"why": "audit finding names no checkpoint"}
        path = inner.get("path")
        if not path or not os.path.exists(path):
            return "noop", {"why": f"no snapshot at {path!r}"}
        try:
            os.replace(path, path + ".corrupt")
        except OSError as e:
            return "error", {"error": repr(e), "path": path}
        return "applied", {"path": path,
                           "quarantined_to": path + ".corrupt"}

    # ------------------------------------------------- failure verdicts

    def on_dispatch_failure(self, rec, submesh: int,
                            error: str) -> str:
        """The retry tier's consult, called WITH the server lock held
        (takes only self._lock, never calls back into the server):
        returns ``"requeue"`` or ``"deadletter"`` and, when enabled,
        applies the exclusion / quarantine bookkeeping."""
        now = time.monotonic()
        distinct = {f["submesh"] for f in rec.failure_log}
        # the threshold is clamped to the PARTITION SIZE: on a
        # 2-submesh server a request that failed on both submeshes has
        # followed its fault everywhere it can — demanding 3 distinct
        # submeshes there would make dead-letter unreachable and burn
        # the whole retry budget ping-ponging. A single-submesh server
        # cannot attribute fault (request vs hardware) by geometry at
        # all, so dead-letter never engages and the retry cap governs.
        n_slots = len(self.server.slots)
        threshold = min(self.deadletter_submeshes, n_slots)
        deadletter = n_slots > 1 and len(distinct) >= threshold
        with self._lock:
            fails = self._submesh_fails.setdefault(int(submesh), [])
            fails[:] = [t for t in fails
                        if now - t < self.window_s][-_FAILURE_WINDOW_CAP:]
            fails.append(now)
            quarantine_due = len(fails) >= self.quarantine_fails
        if not self.enabled:
            # observe-only journals EVERY decision it would take —
            # dead-letter, exclusion AND quarantine — so a dry run
            # shows the full would-be containment, not a subset
            if deadletter:
                self._journal("retry", "deadletter", "observed",
                              detail={"request_id": rec.id,
                                      "distinct_submeshes":
                                          sorted(distinct)})
            self._journal("retry", "exclude_submesh", "observed",
                          detail={"request_id": rec.id,
                                  "submesh": int(submesh)})
            if quarantine_due:
                self._journal("quarantine", "quarantine_submesh",
                              "observed",
                              detail={"submesh": int(submesh)})
            return "requeue"
        if deadletter:
            # the submesh's localized-failure evidence stands on its
            # own: a quarantine that came due on THIS failure must not
            # be skipped just because the request also dead-letters
            if quarantine_due:
                self._quarantine(int(submesh))
            self._journal("retry", "deadletter", "applied",
                          detail={"request_id": rec.id,
                                  "distinct_submeshes": sorted(distinct),
                                  "threshold": threshold})
            return "deadletter"
        self.server.add_exclusion(rec, int(submesh))
        self._journal("retry", "exclude_submesh", "applied",
                      detail={"request_id": rec.id,
                              "submesh": int(submesh),
                              "excluded":
                                  sorted(rec.excluded_submeshes)})
        if quarantine_due:
            self._quarantine(int(submesh))
        return "requeue"

    # ------------------------------------------------------- quarantine

    def _quarantine(self, submesh: int) -> None:
        """Hold a submesh out of the partition (caller holds the server
        lock — this is only reached from on_dispatch_failure) and
        schedule its canary probe."""
        slots = self.server.slots
        slot = slots[submesh]
        healthy = sum(1 for s in slots
                      if not s.quarantined and s.index != submesh)
        if slot.quarantined:
            return
        if healthy == 0:
            self._journal("quarantine", "quarantine_submesh",
                          "skipped",
                          detail={"submesh": submesh,
                                  "why": "last healthy submesh — a "
                                         "server with zero capacity "
                                         "is worse than a degraded "
                                         "one"})
            return
        # the server executes (and ledger-journals) the hold: a crash
        # after this point restarts with the submesh still quarantined
        self.server.quarantine_submesh(
            submesh,
            f"{self.quarantine_fails} failures inside "
            f"{self.window_s:g}s localized to this submesh")
        # the drain is implicit: this is only reached from
        # on_dispatch_failure, so the slot's sole occupant is the very
        # request whose failure tripped the threshold — the caller is
        # already requeuing it with this submesh excluded, and a
        # quarantined slot accepts no new dispatches
        with self._lock:
            self._probes_due[submesh] = time.monotonic() + self.probe_s
        self._g_quar.set(float(sum(1 for s in slots if s.quarantined)))
        self._journal("quarantine", "quarantine_submesh", "applied",
                      detail={"submesh": submesh,
                              "probe_in_s": self.probe_s})
        self._wake.set()

    def restore_pause(self, reason: str) -> None:
        """A ledger replay restored an admission pause. The valve holds
        (a crash is not a resume); an ENABLED controller revalidates it
        on a cooldown — the causing alert died with the old process, so
        waiting for its firing->resolved reversal would strand the
        valve shut forever. Observe mode leaves it to the operator."""
        with self._lock:
            if self.enabled:
                self._pause_check_due = time.monotonic() + self.probe_s
        self._journal("compile_storm", "pause_admission", "restored",
                      detail={"reason": reason,
                              "revalidate": self.enabled})
        self._wake.set()

    def _check_restored_pause(self) -> None:
        """Worker tick: resume a restored pause once the compile_storm
        rule is demonstrably quiet (no pending/firing alert); re-arm
        the cooldown while it is not (or while we cannot tell)."""
        with self._lock:
            due = self._pause_check_due
        if due is None or time.monotonic() < due:
            return
        if self.server.admission_paused() is None:
            with self._lock:
                self._pause_check_due = None
            return
        active = True
        mon = getattr(self.server, "health", None)
        if mon is not None:
            try:
                active = any(
                    a.get("rule") == "compile_storm"
                    and a.get("state") in ("pending", "firing")
                    for a in mon.alerts_snapshot().get("alerts", []))
            except Exception:  # noqa: BLE001 — cannot tell: stay shut
                active = True
        with self._lock:
            if active:
                self._pause_check_due = time.monotonic() + self.probe_s
                return
            self._pause_check_due = None
        self._act_resume_admission({})
        self._journal("compile_storm", "resume_admission", "applied",
                      detail={"why": "ledger-restored pause "
                                     "revalidated: compile_storm "
                                     "quiet"})

    def restore_quarantine(self, submesh: int) -> None:
        """A ledger replay restored this slot's quarantine (the slot
        flags are already set by the server's boot pass): re-arm the
        canary probe so an enabled controller can readmit it the same
        way it would have without the crash. In observe mode the
        quarantine stands until an operator readmits — a restart must
        not be a backdoor readmission."""
        with self._lock:
            if self.enabled:
                self._probes_due[int(submesh)] = (time.monotonic()
                                                  + self.probe_s)
        self._g_quar.set(float(sum(
            1 for s in self.server.slots if s.quarantined)))
        self._journal("quarantine", "quarantine_submesh", "restored",
                      detail={"submesh": int(submesh),
                              "why": "replayed from the request ledger",
                              "probe_armed": self.enabled})
        self._wake.set()

    def _run_due_canaries(self) -> None:
        now = time.monotonic()
        with self._lock:
            due = [sm for sm, t in self._probes_due.items()
                   if t <= now and not (
                       (th := self._probe_threads.get(sm)) is not None
                       and th.is_alive())]
        for submesh in due:
            self._canary_probe(submesh)

    def _canary_probe(self, submesh: int) -> None:
        """Synthetic micro-request on the quarantined submesh; a clean
        complete readmits it, a failure re-arms the cooldown.

        The probe runs on its OWN bounded daemon thread: a genuinely
        hung submesh (the very failure quarantine exists for) would
        otherwise block the controller's single worker forever and
        kill self-healing server-wide. A probe that outlives its
        timeout is treated as failed (the thread leaks until the
        runtime returns — the quarantine already isolates the
        hardware) and the cooldown re-arms; no new probe starts for a
        submesh whose previous probe is still in flight."""
        from ..engine import distributed
        from ..problems.pfsp import PFSPInstance
        slot = self.server.slots[submesh]
        with self._lock:
            self._canaries += 1
            n = self._canaries
        p = PFSPInstance.synthetic(jobs=6, machines=3, seed=0).p_times
        box: dict = {}

        def probe():
            # the ambient context makes the probe attributable in the
            # flight recorder AND visible to @submesh-filtered fault
            # plans (a drill's injected fault hits the canary exactly
            # like it would hit a real request on this submesh)
            with tracelog.context(request_id=f"canary-{n}",
                                  submesh=submesh):
                try:
                    res = distributed.search(
                        p, lb_kind=1, init_ub=None, mesh=slot.mesh,
                        chunk=8, capacity=1 << 12, min_seed=4,
                        # bounded: a runaway probe must truncate
                        # (complete=False -> failed probe), not spin
                        max_rounds=4096,
                        loop_cache=self.server.cache)
                    box["ok"] = bool(res.complete)
                except Exception as e:  # noqa: BLE001 — a failed probe
                    box["err"] = repr(e)  # is the expected outcome on
                    #                       a still-broken submesh

        th = threading.Thread(target=probe, daemon=True,
                              name=f"tts-canary-{submesh}")
        with self._lock:
            self._probe_threads[submesh] = th
        th.start()
        th.join(timeout=max(30.0, self.probe_s))
        ok = bool(box.get("ok"))
        err = box.get("err")
        if th.is_alive():
            err = (f"probe still running after "
                   f"{max(30.0, self.probe_s):g}s (hung submesh)")
        if ok:
            self.server.readmit_submesh(submesh)
            with self._lock:
                self._probes_due.pop(submesh, None)
                # the slate is clean: stale failure history must not
                # instantly re-quarantine the readmitted submesh
                self._submesh_fails.pop(submesh, None)
            self._g_quar.set(float(sum(
                1 for s in self.server.slots if s.quarantined)))
            self._journal("quarantine", "readmit_submesh", "applied",
                          detail={"submesh": submesh, "canary": n})
        else:
            with self._lock:
                self._probes_due[submesh] = (time.monotonic()
                                             + self.probe_s)
            self._journal("quarantine", "canary_probe", "failed",
                          detail={"submesh": submesh, "canary": n,
                                  "error": err,
                                  "retry_in_s": self.probe_s})

    # ---------------------------------------------------------- surface

    def _journal(self, rule: str, action: str, outcome: str,
                 detail: dict | None = None) -> str:
        entry = {"t": time.time(), "rule": rule, "action": action,
                 "outcome": outcome, "detail": detail or {}}
        with self._lock:
            self.journal.append(entry)
        self._m_actions.inc(rule=rule, action=action, outcome=outcome)
        tracelog.event(f"remediation.{outcome}", rule=rule,
                       action=action, **(detail or {}))
        return outcome

    def snapshot(self) -> dict:
        """JSON-safe view for status_snapshot()'s `remediation` key
        (callers may hold the server lock; only self._lock is taken)."""
        slots = self.server.slots
        quarantined = [
            {"submesh": s.index, "since": s.quarantined_since,
             "reason": s.quarantine_reason}
            for s in slots if s.quarantined]
        with self._lock:
            actions = list(self.journal)[-32:]
            probes = dict(self._probes_due)
            counts: dict[str, int] = {}
            for e in self.journal:
                k = f"{e['action']}:{e['outcome']}"
                counts[k] = counts.get(k, 0) + 1
        return {"enabled": self.enabled,
                "mode": "act" if self.enabled else "observe",
                "quarantined": quarantined,
                "probes_pending": len(probes),
                "admission_paused": self.server.admission_paused(),
                "counts": counts,
                "actions": actions}
