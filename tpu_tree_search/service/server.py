"""In-process asynchronous search server.

The serving layer the reference architecture never had: its engine (and
the repo's campaign driver until this PR) burns one process — one MPI
world, one trace + compile — per instance. `SearchServer` is the
tree-search analogue of a continuous-batching inference server: a
long-lived process that multiplexes many concurrent solve requests onto
the device mesh.

Architecture::

    submit() --admission--> RequestQueue --scheduler--> submesh slots
                                              |             |
                                        preempt/deadline    executor thread
                                              |             per dispatch:
                                        stop_event ----> distributed.search
                                                          (segmented, ckpt)

- The global mesh is partitioned into equal SUBMESHES
  (parallel/mesh.partition_submeshes); each submesh serves one request
  at a time with the unmodified SPMD engine, so a served request's node
  counts are bit-identical to a standalone `distributed.search` run at
  the same worker count.
- The scheduler (one daemon thread) assigns the highest-priority queued
  request to a free submesh, stops over-deadline requests, and PREEMPTS
  a running lower-priority request when a higher-priority one waits with
  no free submesh. Stops land at segment boundaries via the engine's
  stop_event hook; the stopped state is checkpointed first, so a
  preempted request later RESUMES — on whatever submesh is free, even a
  different-sized one (checkpoint.reshard_state's elastic resume).
- Compiled executables are shared across requests through an
  ExecutorCache keyed by shape/bound/submesh — all instances of a
  Taillard class share one compile (serve many, compile once).
- A submesh failure (transient runtime/IO error escaping the engine's
  own retry tier) re-dispatches the request with exponential backoff
  (utils/retry); `service_retry_attempts` failures turn it FAILED.

Everything is observable through `status_snapshot()` — a JSON-safe dict
with queue depth, per-submesh occupancy, executor-cache hit rates and
per-request counters — and per-request `status()` / `result()`. Since
the obs layer landed, the snapshot's counters are a VIEW over the
server's metrics registry (`self.metrics`, an obs/metrics.Registry —
the same numbers `/metrics` exposes as Prometheus text), every
lifecycle transition is flight-recorded (obs/tracelog: admit /
dispatch / resume / preempt / terminal events, one `request.execute`
span per dispatch), and each executor thread runs inside an ambient
`tracelog.context(request_id=..., submesh=...)` so the engine-level
spans it drives (segments, checkpoint saves, retries, faults) are
attributable to the request without threading ids through engine APIs.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import pathlib
import shutil
import socket
import tempfile
import threading
import time

import numpy as np

from ..obs import capacity as obs_capacity
from ..obs import health as obs_health
from ..obs import metrics as obs_metrics
from ..obs import resource as obs_resource
from ..obs import store as obs_store_mod
from ..obs import tracelog
from ..utils import config as cfg
from ..utils import faults
from ..utils.retry import backoff_delay
from .executors import ExecutorCache
from .lease import LeaseLost
from .queueing import AdmissionError, AdmissionPaused, RequestQueue
from .request import (CANCELLED, DEADLINE, DONE, FAILED, FAILURE_LOG_CAP,
                      PREEMPTED, QUEUED, RUNNING, TERMINAL_STATES,
                      RequestRecord, SearchRequest)

__all__ = ["SearchServer", "AdmissionError", "SearchRequest"]


def _prior_spent_s(checkpoint_path: str) -> float:
    """Accumulated execution seconds recorded in an existing checkpoint
    under this tag (the `spent_s` meta key both the service and the
    legacy campaign worker write), or 0.0 when there is none / it is
    unreadable — budget continuity must never block a submission."""
    for cand in (checkpoint_path, checkpoint_path + ".prev"):
        try:
            with np.load(cand) as z:
                return float(z["meta_spent_s"])
        except Exception:  # noqa: BLE001 — missing/torn/legacy file
            continue
    return 0.0


def _prior_progress_est(checkpoint_path: str) -> list | None:
    """Progress-estimator state vector (obs/estimate's to_list) riding
    an existing checkpoint under this tag, or None when there is none /
    it predates the estimator — like spent_s, estimate continuity must
    never block a submission."""
    for cand in (checkpoint_path, checkpoint_path + ".prev"):
        try:
            with np.load(cand) as z:
                return [float(x) for x in z["meta_progress_est"]]
        except Exception:  # noqa: BLE001 — missing/torn/pre-estimator
            continue
    return None


class _Slot:
    """One submesh and the request currently running on it."""

    def __init__(self, index: int, mesh):
        self.index = index
        self.mesh = mesh
        self.record: RequestRecord | None = None
        # megabatch occupancy: the full member list of a batched
        # dispatch (record stays the first member so single-request
        # readers keep working); None for a solo dispatch
        self.batch: list | None = None
        self.thread: threading.Thread | None = None
        self.stop_event: threading.Event | None = None
        # submesh quarantine (service/remediate): a quarantined slot is
        # held out of the partition — the scheduler never dispatches to
        # it — until the controller's canary probe readmits it
        self.quarantined: bool = False
        self.quarantined_since: float | None = None
        self.quarantine_reason: str | None = None

    @property
    def device_ids(self) -> list[int]:
        return [int(d.id) for d in self.mesh.devices.flat]

    @property
    def records(self) -> list:
        """Every request occupying this slot — the batch member list
        under a batched dispatch, the single record solo, [] free.
        THE slot-occupancy enumeration (close/deadline/heartbeat paths
        all iterate it; hand-rolled copies drift)."""
        if self.batch is not None:
            return self.batch
        return [self.record] if self.record is not None else []


class SearchServer:
    """Async search-as-a-service over a partitioned device mesh.

    Lifecycle: construct (optionally inside a ``with`` block), `submit()`
    requests, `status()`/`result()` them, `close()`. The scheduler
    thread starts immediately unless ``autostart=False`` (submissions
    then queue up until `start()` — useful for admission-control tests
    and for pre-loading a batch before serving begins).
    """

    def __init__(self, n_submeshes: int = 1, devices=None,
                 workdir: str | None = None,
                 max_queue_depth: int = cfg.SERVICE_QUEUE_DEPTH_DEFAULT,
                 segment_iters: int = cfg.SERVICE_SEGMENT_ITERS_DEFAULT,
                 checkpoint_every: int = cfg.SERVICE_CHECKPOINT_EVERY_DEFAULT,
                 poll_s: float = cfg.SERVICE_POLL_S_DEFAULT,
                 service_retry_attempts: int =
                 cfg.SERVICE_RETRY_ATTEMPTS_DEFAULT,
                 service_retry_base_s: float =
                 cfg.SERVICE_RETRY_BASE_S_DEFAULT,
                 autostart: bool = True,
                 phase_profile=None,
                 resource_sample_s: float | None = None,
                 health_interval_s: float | None = None,
                 overlap: bool | None = None,
                 share_incumbent: bool | None = None,
                 aot_cache_dir: str | None = None,
                 tune_cache_dir: str | None = None,
                 tune_at_boot: bool | None = None,
                 remediate: bool | None = None,
                 ledger_dir: str | None = None,
                 fleet_dir: str | None = None,
                 failover: bool | None = None,
                 megabatch: bool | None = None,
                 batch_max: int | None = None,
                 batch_age_s: float | None = None):
        from ..parallel.mesh import partition_submeshes

        self.slots = [_Slot(i, m) for i, m in
                      enumerate(partition_submeshes(n_submeshes,
                                                    devices=devices))]
        # resolved EARLY (construction happens later, it needs the
        # metrics registry) because the workdir default depends on it:
        # durability needs checkpoints that survive the restart, so a
        # ledger server without an explicit workdir keeps them UNDER
        # the ledger dir — a fresh temp dir per lifetime would replay
        # budgets but restart every search from its root
        if ledger_dir is None:
            ledger_dir = cfg.env_str(cfg.LEDGER_ENV)
        if workdir is None and ledger_dir:
            workdir = os.path.join(ledger_dir, "workdir")
        self.workdir = pathlib.Path(
            workdir if workdir is not None
            else tempfile.mkdtemp(prefix="tts_service_"))
        self.workdir.mkdir(parents=True, exist_ok=True)
        # Per-SERVER metrics registry (obs/metrics): request/queue/cache
        # metrics must not bleed between servers in one process (the
        # test suite runs many); engine-level metrics (checkpoints,
        # retries, faults) stay in the process-global default registry
        # and the HTTP front-end exposes both.
        self.metrics = obs_metrics.Registry("tts_service")
        self._m_submitted = self.metrics.counter(
            "tts_requests_submitted_total", "requests admitted")
        self._m_terminal = self.metrics.counter(
            "tts_requests_total", "requests by terminal state")
        self._m_preempt = self.metrics.counter(
            "tts_preemptions_total",
            "running requests stopped and checkpointed for requeue")
        self._m_redispatch = self.metrics.counter(
            "tts_redispatches_total",
            "submesh-failure re-dispatches (retry tier)")
        self._m_spent = self.metrics.histogram(
            "tts_request_spent_seconds",
            "accumulated execution time of terminal requests")
        self._m_queue_wait = self.metrics.histogram(
            "tts_queue_wait_seconds",
            "admit/requeue -> dispatch wait by accounting tenant (the "
            "health layer's queue_wait SLO reads its windowed "
            "all-tenants p99)")
        self._m_drain_idle = self.metrics.histogram(
            "tts_batch_drain_idle_seconds",
            "per closed megabatch: lane-seconds members sat frozen "
            "waiting for batchmates to drain (the continuous-batching "
            "motivation number)")
        # under megabatching, requests waiting in the batch-former are
        # still WAITING — the depth gauge (and the admission bound in
        # submit()) must count them, or an overloaded megabatch server
        # would read as idle while its former grows without bound
        self.metrics.gauge(
            "tts_queue_depth", "requests waiting for a submesh"
            ).set_fn(lambda: len(self.queue)
                     + (len(self.former)
                        if getattr(self, "former", None) is not None
                        else 0))
        # a gauge (callback over queue.rejected), so no `_total` suffix:
        # the counter convention would promise rate()-safe reset
        # detection this scrape-time mirror cannot give
        self.metrics.gauge(
            "tts_queue_rejected",
            "admission-control rejections (validation/overflow/closed)"
            ).set_fn(lambda: self.queue.rejected)
        self.metrics.gauge(
            "tts_queue_peak_depth",
            "high-water queue depth since server start"
            ).set_fn(lambda: self.queue.peak_depth)
        self.metrics.gauge(
            "tts_submeshes", "submesh slots partitioned at startup"
            ).set_fn(lambda: len(self.slots))
        self.metrics.gauge(
            "tts_submeshes_busy", "submeshes currently running a request"
            ).set_fn(lambda: sum(1 for s in self.slots
                                 if s.record is not None))
        self.queue = RequestQueue(max_queue_depth)
        # disk-persistent AOT executable tier (service/aot_cache): a
        # restarted server replays previously-compiled loops from disk
        # instead of re-tracing+compiling. None -> the TTS_AOT_CACHE
        # env path; unset/empty -> in-memory executor cache only. The
        # capability probe gates construction: a pin that cannot
        # round-trip a program degrades to the pre-cache behavior, it
        # never serves maybe-wrong bytes.
        if aot_cache_dir is None:
            aot_cache_dir = cfg.env_str(cfg.AOT_CACHE_ENV)
        self.aot = None
        if aot_cache_dir:
            from . import aot_cache as aot_mod
            if aot_mod.probe():
                try:
                    self.aot = aot_mod.AOTCache(aot_cache_dir,
                                                registry=self.metrics)
                except OSError as e:
                    # an uncreatable/unwritable cache dir (read-only
                    # mount, fleet misconfig) degrades to in-memory-
                    # only like every other documented failure mode —
                    # it must not take the server down
                    tracelog.event(
                        "aot_cache.disabled", dir=str(aot_cache_dir),
                        reason=f"cache dir unusable: {e!r}; executor "
                               "cache stays in-memory-only")
            else:
                tracelog.event(
                    "aot_cache.disabled", dir=str(aot_cache_dir),
                    reason="probe failed: this jax/backend pin cannot "
                           "round-trip a serialized executable; "
                           "executor cache stays in-memory-only")
        self.cache = ExecutorCache(registry=self.metrics, aot=self.aot)
        # adaptive dispatch (tune/): the Autotuner resolves a request's
        # OPEN knobs (chunk=None / balance_period=None) from the
        # persistent tuning cache, falling back to the measured-
        # defaults table — never probing on the request path. Probing
        # happens at boot (prewarm_boot with tune_at_boot / TTS_TUNE);
        # a warm cache dir replays with zero probes.
        if tune_cache_dir is None:
            tune_cache_dir = cfg.env_str(cfg.TUNE_CACHE_ENV)
        self.tune_at_boot = (cfg.env_flag(cfg.TUNE_ENV)
                             if tune_at_boot is None
                             else bool(tune_at_boot))
        self.tuner = None
        if tune_cache_dir or self.tune_at_boot:
            from ..tune import Autotuner
            try:
                self.tuner = Autotuner(cache_dir=tune_cache_dir,
                                       registry=self.metrics)
            except OSError as e:
                # an unusable cache dir degrades to an IN-MEMORY tuner
                # (boot probes still work, they just don't persist) —
                # the AOT cache's degrade-don't-die stance
                tracelog.event(
                    "tuner.cache_disabled", dir=str(tune_cache_dir),
                    reason=f"tune cache dir unusable: {e!r}; tuned "
                           "optima live in-process only this lifetime")
                self.tuner = Autotuner(registry=self.metrics)
            if not tune_cache_dir:
                # --tune without --tune-cache must still probe at boot
                # (in-process memo only) — a documented flag that
                # silently did nothing would be a dead kill-switch
                tracelog.event(
                    "tuner.memory_only",
                    reason="tune_at_boot without a tune cache dir: "
                           "probed optima are not persisted")
        # resource observability: per-device bytes-in-use/peak + host
        # RSS gauges on THIS server's registry (so /metrics carries
        # them) plus memory counter lanes in the trace log; the daemon
        # thread samples on its own cadence, close() retires the series
        if resource_sample_s is None:
            resource_sample_s = cfg.env_float("TTS_RESOURCE_SAMPLE_S")
        self.resources = obs_resource.ResourceSampler(
            registry=self.metrics, period_s=resource_sample_s)
        if resource_sample_s > 0:
            # one sweep up front: the gauges must exist from the first
            # scrape, not only after the first period elapses
            try:
                self.resources.sample()
            except Exception:  # noqa: BLE001 — observability extra
                pass
        # Raw-speed knobs (None = the TTS_OVERLAP / TTS_SHARE_INCUMBENT
        # env flags). `overlap` pipelines every served request's
        # segments (async counter fetch + writer-thread checkpoints —
        # engine/checkpoint's overlapped driver); `share_incumbent`
        # builds the process-wide best-bound board so concurrent
        # same-instance requests tighten each other's pruning
        # (engine/incumbent.py — the reference's MPI best-makespan
        # exchange, served-form).
        self.overlap = (cfg.env_flag(cfg.OVERLAP_FLAG)
                        if overlap is None else bool(overlap))
        if share_incumbent is None:
            share_incumbent = cfg.env_flag(cfg.SHARE_INCUMBENT_FLAG)
        self.incumbents = None
        if share_incumbent:
            from ..engine.incumbent import IncumbentBoard
            self.incumbents = IncumbentBoard()
        # Request megabatching (engine/megabatch + service/batching):
        # the admission queue becomes a batch-former — same-shape-class
        # requests stack into ONE vmapped compiled loop per submesh.
        # Default off (TTS_MEGABATCH) = the solo scheduler exactly;
        # every batched request is bit-identical to its solo run.
        self.megabatch = (cfg.env_flag(cfg.MEGABATCH_FLAG)
                          if megabatch is None else bool(megabatch))
        self.former = None
        if self.megabatch:
            from .batching import BatchFormer
            self.former = BatchFormer(
                batch_max if batch_max is not None
                else cfg.env_int("TTS_BATCH_MAX"),
                batch_age_s if batch_age_s is not None
                else cfg.env_float("TTS_BATCH_AGE_S"))
        self._batch_seq = itertools.count()
        self._m_batches = self.metrics.counter(
            "tts_batches_formed_total",
            "batches closed by the former (reason=size|age)")
        self._m_batch_size = self.metrics.histogram(
            "tts_batch_size", "requests per closed batch",
            # integer-size buckets: the latency default (0.001..300 s)
            # would fold every size 3..8 batch into one le=10 bucket
            buckets=(1, 2, 4, 8, 16, 32, 64, 128))
        self._m_batch_req = self.metrics.counter(
            "tts_batch_requests_total",
            "requests dispatched through a multi-request batch")
        self.segment_iters = segment_iters
        self.checkpoint_every = checkpoint_every
        self.poll_s = poll_s
        self.service_retry_attempts = service_retry_attempts
        self.service_retry_base_s = service_retry_base_s
        # live per-worker phase attribution (utils/phase_timing): None
        # = off; a {"bound","step","compact","per_eval"} unit-cost dict
        # = attribute every heartbeat with it; True = MEASURE unit costs
        # once per (shape, lb, chunk) on first dispatch (adds seconds of
        # profiling to that dispatch — an opt-in production knob)
        self.phase_profile = phase_profile
        self._prof_cache: dict[tuple, dict] = {}
        # online progress/ETA estimation (obs/estimate; static, read
        # once): off = NO estimator objects, gauges, snapshot keys,
        # checkpoint-meta keys or predictive rules — bit-identical to
        # the pre-estimator server
        self.progress_enabled = cfg.env_flag("TTS_PROGRESS")
        # fleet capacity & utilization (obs/capacity; static, read
        # once): off = NO lane ledger, capacity model, lane events/
        # counters, capacity gauges, snapshot key or saturation rule —
        # bit-identical to the pre-capacity server. Constructed after
        # the obs store resume below so a restarted server seeds lane
        # history from the replayed counters.
        self.capacity_enabled = cfg.env_flag("TTS_CAPACITY")
        self.lane_ledger = None
        self.capacity = None
        self.records: dict[str, RequestRecord] = {}  # guarded-by: self._lock
        self._lock = threading.RLock()
        self._seq = itertools.count()
        self._t0 = time.monotonic()
        self._closing = threading.Event()
        self._scheduler: threading.Thread | None = None
        # the operational judge (obs/health): SLO/anomaly rules over
        # this server's registries + snapshot on a daemon interval,
        # surfaced as /alerts, tts_alerts gauges and alert.* events.
        # interval None resolves to TTS_HEALTH_INTERVAL_S inside the
        # monitor; <= 0 disables the daemon (evaluate_now() still
        # works for tests and the doctor path).
        self.health = obs_health.HealthMonitor(
            server=self, registry=self.metrics,
            interval_s=health_interval_s)
        # admission pause valve (the remediation controller's
        # compile_storm action; None = admitting). A paused server
        # REJECTS submit() with the reason — HTTP clients see 429 —
        # while the file spool holds its backlog unserved instead
        self._paused_reason: str | None = None  # guarded-by: self._lock
        # self-healing (service/remediate): subscribes to the monitor
        # above, so it must construct after it. remediate=None resolves
        # TTS_REMEDIATE; the default (off) is OBSERVE-ONLY — detection
        # and journaling run, zero actions are taken, behavior is
        # bit-identical to the pre-remediation server
        from .remediate import RemediationController
        self.remediation = RemediationController(
            self, enabled=remediate, registry=self.metrics)
        # bound-portfolio racing (service/portfolio): always
        # constructed (a pure coordination object; zero cost when no
        # request carries `portfolio`). Must exist BEFORE the ledger
        # replays — replayed races reconcile through it.
        from .portfolio import PortfolioCoordinator
        self.portfolio = PortfolioCoordinator(self)
        # crash-safe serving (service/ledger): a write-ahead journal of
        # every request state transition, replayed here at boot so a
        # hard-killed server's queued/active requests re-admit with
        # budgets/exclusions/failure logs intact, terminal results
        # re-serve idempotently, and standing quarantines/admission
        # pauses survive. None -> the TTS_LEDGER env path; unset/empty
        # -> off, and every ledger code path below is vacuous — the
        # server is bit-identical to the pre-ledger one (test-pinned).
        # An unusable ledger dir RAISES instead of degrading: the
        # operator asked for durability, and serving without it would
        # turn the HTTP 200 durability promise into a lie.
        # (ledger_dir itself was resolved at the top of __init__ — the
        # workdir default depends on it.)
        self.ledger = None
        self.replayed_spool: dict[str, str] = {}
        self._recovered = {"queued": 0, "active": 0, "held": 0,
                           "terminal": 0}
        # fleet failover (service/lease + service/failover): inside a
        # shared fleet root this server's ledger is owned through a
        # fenced LEASE — acquired BEFORE the ledger replays, so a boot
        # against a ledger a live adopter is serving comes up FENCED
        # (serves nothing, commits nothing, exits clean) instead of
        # split-braining it. Unset fleet dir -> every lease/watcher
        # path below is vacuous — bit-identical PR-12 behavior.
        if fleet_dir is None:
            fleet_dir = cfg.env_str(cfg.FLEET_DIR_ENV)
        self.lease = None
        self.watcher = None
        self.fenced = False
        self._fence_reason: str | None = None
        self._adopted: list = []    # LeaseKeepers of adopted ledgers
        #                             (kept renewing: a restarted stale
        #                             owner must find a LIVE lease)
        if ledger_dir:
            from .ledger import RequestLedger
            if fleet_dir:
                from .lease import LeaseKeeper
                keeper = LeaseKeeper(ledger_dir, registry=self.metrics,
                                     on_lost=self._self_fence)
                try:
                    keeper.acquire()
                    self.lease = keeper
                except LeaseLost as e:
                    self.fenced = True
                    self._fence_reason = str(e)
                    tracelog.event("failover.boot_fenced",
                                   dir=str(ledger_dir), reason=str(e))
            if not self.fenced:
                self.ledger = RequestLedger(ledger_dir,
                                            registry=self.metrics,
                                            lease=self.lease,
                                            on_fenced=self._self_fence)
                self._replay_boot()
                self.ledger.journal("boot", pid=os.getpid(),
                                   submeshes=len(self.slots))
        # set BEFORE the watcher starts: its takeover thread journals
        # our ledger-dir name as the `adopter` forward pointer
        self._ledger_dir = ledger_dir or None
        self._fleet_dir = fleet_dir or None
        if fleet_dir and not self.fenced:
            from .failover import FailoverWatcher
            self.watcher = FailoverWatcher(
                self, fleet_dir, own_root=ledger_dir,
                act=failover, registry=self.metrics)
            self.watcher.start()
        # fleet flight recorder (obs/store): a durable metric/event
        # store in the fleet/ledger dir, replayed here so dashboards,
        # health history and whitelisted tts_* counters RESUME across
        # restarts/takeovers, and the slo_* burn rules window over
        # history older than this process. Unset TTS_OBS_STORE -> every
        # store code path below is vacuous — bit-identical (test-pinned)
        self.obs_store = None
        store_dir = cfg.env_str(cfg.OBS_STORE_ENV)
        if store_dir and not self.fenced:
            # the writer id must be STABLE across restarts (counter
            # resume keys on it) and DISTINCT across fleet peers: the
            # host plus the ledger family when there is one
            writer = socket.gethostname()
            if ledger_dir:
                writer += f"-{pathlib.Path(ledger_dir).name}"
            else:
                writer += f"-{os.getpid()}"
            try:
                self.obs_store = obs_store_mod.ObsStore(
                    store_dir, writer, registry=self.metrics,
                    segment_records=cfg.env_int(
                        "TTS_OBS_STORE_SEGMENT_RECORDS"),
                    retain_s=cfg.env_float("TTS_OBS_STORE_RETAIN_S"),
                    queue_depth=cfg.env_int("TTS_OBS_STORE_QUEUE"))
            except OSError as e:
                # an unwritable store degrades to store-less serving —
                # observability must not take the server down (the
                # ledger's opposite stance is about DATA durability)
                tracelog.event("obs_store.disabled", dir=store_dir,
                               error=repr(e))
            if self.obs_store is not None:
                replayed = self.obs_store.records_replayed()
                seeded = obs_store_mod.resume_counters(
                    self.metrics, replayed, self.obs_store.writer)
                self.health.store = self.obs_store
                self.health.seed_history(
                    [r for r in replayed if r.get("k") == "sample"
                     and r.get("w") == self.obs_store.writer])
                tracelog.get().add_listener(self.obs_store.on_trace_event)
                interval = (resource_sample_s
                            if resource_sample_s is not None
                            else cfg.env_float("TTS_RESOURCE_SAMPLE_S"))
                if interval > 0:
                    self.obs_store.start_sampling(self._obs_sample,
                                                  interval)
                tracelog.event(
                    "obs_store.open", dir=store_dir,
                    writer=self.obs_store.writer,
                    replayed=self.obs_store.replayed,
                    truncated=self.obs_store.truncated,
                    counters_seeded=seeded)
        if self.capacity_enabled:
            # AFTER the obs-store resume above: the lane ledger seeds
            # its per-state accumulators from the replayed
            # tts_lane_seconds_total series (store unset/fenced = a
            # fresh ledger, same construction)
            self.lane_ledger = obs_capacity.LaneLedger(
                self.metrics, [s.index for s in self.slots])
            for _, key, val in self.metrics.counter(
                    obs_capacity.LANE_SECONDS_METRIC,
                    obs_capacity.LANE_SECONDS_DOC).samples():
                labels = dict(key)
                if "lane" in labels and "state" in labels:
                    try:
                        self.lane_ledger.seed(int(labels["lane"]),
                                              labels["state"],
                                              float(val))
                    except (TypeError, ValueError):
                        pass    # a foreign writer's malformed series
            self.capacity = obs_capacity.CapacityModel(self.metrics)
        tracelog.event("server.start", submeshes=len(self.slots),
                       devices_per_submesh=self.slots[0].mesh.devices.size,
                       workdir=str(self.workdir),
                       megabatch=self.megabatch,
                       overlap=self.overlap,
                       share_incumbent=self.incumbents is not None,
                       remediate=self.remediation.enabled,
                       ledger=ledger_dir or None,
                       fleet_dir=fleet_dir or None,
                       fenced=self.fenced)
        if autostart:
            self.start()

    @property
    def counters(self) -> dict:
        """Lifecycle counters, now a VIEW over the metrics registry (the
        pre-obs hand-rolled dict, kept as the JSON snapshot schema and
        for callers that read e.g. ``srv.counters["preemptions"]``)."""
        t = self._m_terminal
        # value_matching, not value: terminal series carry a tenant
        # label, so the lifecycle view sums across tenants
        return {"submitted": int(self._m_submitted.value()),
                "done": int(t.value_matching(state="done")),
                "cancelled": int(t.value_matching(state="cancelled")),
                "deadline": int(t.value_matching(state="deadline")),
                "failed": int(t.value_matching(state="failed")),
                "preemptions": int(self._m_preempt.value()),
                "redispatches": int(self._m_redispatch.value())}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._lock:
            if self._scheduler is None and not self._closing.is_set():
                self._scheduler = threading.Thread(
                    target=self._scheduler_loop, daemon=True,
                    name="tts-service-scheduler")
                self._scheduler.start()

    def close(self, wait: bool = True) -> None:
        """Stop serving: running requests are stopped at their next
        segment boundary and left PREEMPTED with a fresh checkpoint (a
        new server with the same workdir + tags resumes them); queued
        requests are CANCELLED — except under a ledger, where they
        stay QUEUED: a ledger server's shutdown is a DRAIN, and its
        backlog re-admits on the next boot instead of being forgotten.
        Unblocks every `result()` waiter either way."""
        if not self._closing.is_set():
            tracelog.event("server.close")
        self._closing.set()
        with self._lock:
            for slot in self.slots:
                for rec in slot.records:
                    if rec.stop_reason is None:
                        rec.stop_reason = "shutdown"
                if slot.records and slot.stop_event is not None:
                    slot.stop_event.set()
            if self.former is not None:
                # held batch members are live admitted requests: hand
                # them back to the record loop below (CANCELLED without
                # a ledger, kept QUEUED for replay with one)
                self.former.drain()
        if wait:
            if self._scheduler is not None:
                self._scheduler.join()
            for slot in self.slots:
                th = slot.thread
                if th is not None:
                    th.join()
        with self._lock:
            for rec in self.records.values():
                if rec.state == QUEUED and self.ledger is None:
                    self._finalize(rec, CANCELLED, error="server shutdown")
                rec.done_event.set()
        # the failover watcher stops scanning before the lease goes
        if self.watcher is not None:
            self.watcher.close()
        # stop the resource sampler and retire its gauge series — a
        # closed server must not keep publishing (or holding) them
        self.resources.close()
        # same valve for the health daemon and its tts_alerts series
        self.health.close()
        # close the lane ledger's final open intervals into the counter
        # (BEFORE the obs store's last sample below, so the persisted
        # lane seconds include them) and retire the capacity gauges
        if self.lane_ledger is not None:
            for slot in self.slots:
                self._lane_sync(slot)
            self.lane_ledger.flush()
        if self.capacity is not None:
            self.capacity.close()
        # and the remediation worker (its journal stays readable)
        self.remediation.close()
        # flush the AOT-cache writer so every compile paid this
        # lifetime is on disk for the next one (store() after this
        # point is a silent no-op — late executor threads on
        # wait=False close paths lose only the persistence)
        if self.aot is not None:
            self.aot.close()
        # the ledger closes LAST, after every executor thread's final
        # preempt/terminal record landed: a `drain` marker stamps the
        # shutdown as graceful (its absence at replay = a hard kill)
        if self.ledger is not None:
            self.ledger.journal("drain", pid=os.getpid())
            self.ledger.close()
        # release leases LAST: our own (marked `released` so peers do
        # not adopt a cleanly drained ledger; a fenced keeper leaves
        # the file to its adopter) and every adopted orphan's
        if self.lease is not None:
            self.lease.release()
        for keeper in self._adopted:
            keeper.release()
        # the obs store drains LAST so the close-path events above
        # (server.close, lease.released) are on disk for the next
        # lifetime's replay
        if self.obs_store is not None:
            if self.lane_ledger is not None:
                # one final sample so the just-flushed lane counters
                # land on disk for the next lifetime's ledger seed (a
                # kill -9 keeps the last periodic sample instead —
                # conservation then counts the lost tail as replayed
                # time it never saw, which is exactly the truth)
                self.obs_store.sample_now(self._obs_sample)
            tracelog.get().remove_listener(self.obs_store.on_trace_event)
            self.obs_store.flush()
            self.obs_store.close()

    def _obs_sample(self) -> dict:
        """One durable metrics snapshot (obs/store `sample` record):
        whitelisted counters (the resume set), the history-ring gauge
        signals, and the health rings' latest values."""
        counters, gauges = [], []
        if self.lane_ledger is not None:
            # close open lane intervals into the counter first, so the
            # persisted lane seconds are current as of this sample
            self.lane_ledger.flush()
        for m in self.metrics.metrics():
            if m.kind == "counter" \
                    and m.name in obs_store_mod.RESUME_COUNTERS:
                counters.extend([n, dict(k), v]
                                for n, k, v in m.samples())
        for reg in (self.metrics, obs_metrics.default()):
            for m in reg.metrics():
                if m.kind == "gauge" \
                        and m.name in obs_store_mod.SAMPLE_GAUGES:
                    gauges.extend([n, dict(k), v]
                                  for n, k, v in m.samples())
        return {"counters": counters, "gauges": gauges,
                "history": self.health.history_sample()}

    def journeys(self, tag: str | None = None) -> list[dict]:
        """Stitched request journeys (obs/journey) over this server's
        ledger, every fleet peer's ledger, and the durable store —
        the GET /journey payload."""
        from ..obs import journey as journey_mod
        store_dir = (str(self.obs_store.root)
                     if self.obs_store is not None else None)
        return journey_mod.find_journeys(
            ledger_dirs=[self._ledger_dir] if self._ledger_dir else [],
            fleet_dir=self._fleet_dir, store=store_dir, tag=tag)

    def __enter__(self) -> "SearchServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ client API

    def submit(self, request: SearchRequest, *,
               spool_id: str | None = None,
               _portfolio_member: bool = False) -> str:
        """Admit a request; returns its id. Raises AdmissionError (with
        `.reason`) when the queue is full, the request is invalid, or
        the server is closed — rejection is immediate and explicit, the
        client never learns about overload from a timeout.

        With a ledger, admission is a DURABILITY promise: the admit
        record is journaled (fsync'd) before this returns, so a request
        acknowledged here — including over ``POST /submit`` — survives
        an immediate hard kill. A tag whose recorded terminal is DONE
        re-serves idempotently: the original request id is returned
        with its recorded result instead of re-solving. `spool_id`
        (the file-spool front-end's id) rides the admit record so a
        restarted serve loop can reconnect result-file delivery."""
        if self._closing.is_set():
            self.queue.rejected += 1
            tracelog.event("request.reject", reason="server closed")
            raise AdmissionError("server closed")
        if self.fenced:
            # a fenced server owns nothing: its ledger belongs to an
            # adopter, so an admission here could never be durable —
            # the typed refusal tells the client to resubmit to the
            # peer that holds the lease
            self.queue.rejected += 1
            tracelog.event("request.reject",
                           reason=f"fenced: {self._fence_reason}")
            raise LeaseLost(f"server fenced: {self._fence_reason}")
        paused = self.admission_paused()
        if paused is not None:
            # the remediation controller's compile_storm valve: an
            # explicit retry-later rejection (HTTP 429 through
            # obs/httpd; the typed subclass tells the spool to HOLD),
            # cleared when the alert resolves
            self.queue.rejected += 1
            tracelog.event("request.reject",
                           reason=f"admission paused: {paused}")
            raise AdmissionPaused(f"admission paused: {paused}")
        reason = request.validate()
        if reason is not None:
            self.queue.rejected += 1
            tracelog.event("request.reject",
                           reason=f"invalid request: {reason}")
            raise AdmissionError(f"invalid request: {reason}")
        if not _portfolio_member:
            # bound-portfolio racing: an explicit `portfolio: K` (or
            # the TTS_PORTFOLIO server default, capped at the
            # admission bound) fans out instead of queueing. Members
            # resubmit through this method with the guard flag — the
            # env default must not fan a member out recursively
            k = request.portfolio
            if k is None:
                k = cfg.env_int(cfg.PORTFOLIO_ENV, 0)
                k = min(k, cfg.env_int("TTS_PORTFOLIO_MAX",
                                       cfg.PORTFOLIO_MAX_DEFAULT))
            if k and k >= 2:
                return self._submit_portfolio(request, int(k),
                                              spool_id=spool_id)
        with self._lock:
            if self.ledger is not None and request.tag:
                # idempotent re-serve: a duplicate tag whose recorded
                # terminal is DONE returns the recorded result instead
                # of re-solving (crash-duplicated submissions and
                # client retries are absorbed; DEADLINE/FAILED tags
                # still resubmit-to-extend through the normal path).
                # Only a SAME-PROBLEM duplicate qualifies: a reused
                # tag carrying a different instance/bound must solve,
                # not silently receive the old answer
                done = next(
                    (r for r in self.records.values()
                     if r.state == DONE
                     and (r.request.tag or r.id) == request.tag), None)
                if done is not None:
                    prior = done.request
                    if (prior.problem == request.problem
                            and np.array_equal(
                                np.asarray(prior.p_times),
                                np.asarray(request.p_times))
                            and prior.lb_kind == request.lb_kind
                            and prior.init_ub == request.init_ub):
                        tracelog.event("request.reserved_terminal",
                                       request_id=done.id,
                                       tag=request.tag)
                        return done.id
                    tracelog.event(
                        "request.tag_reused_different_problem",
                        request_id=done.id, tag=request.tag)
            seq = next(self._seq)
            rid = f"req-{seq:04d}"
            tag = request.tag or rid
            path = str(self.workdir / f"{tag}.ckpt.npz")
            holder = next(
                (r for r in self.records.values()
                 if r.checkpoint_path == path
                 and r.state not in TERMINAL_STATES), None)
            if holder is not None:
                # two live requests sharing one checkpoint family would
                # interleave snapshot writes and retire each other's
                # files; resubmit-to-extend is only meaningful once the
                # prior request is terminal
                self.queue.rejected += 1
                tracelog.event("request.reject", tag=tag,
                               reason=f"tag active on {holder.id}")
                raise AdmissionError(
                    f"tag {tag!r} is already active on request "
                    f"{holder.id} ({holder.state}); wait for it to "
                    "finish or cancel it first")
            if self.former is not None:
                # the admission bound covers the WHOLE wait line: heap
                # + former-held members (the scheduler drains the heap
                # into the former every tick, so the heap alone would
                # never fill and backpressure would silently vanish)
                held = len(self.former)
                if held + len(self.queue) >= self.queue.max_depth:
                    self.queue.rejected += 1
                    reason = (f"queue full: {held} batching + "
                              f"{len(self.queue)} queued at the "
                              f"admission bound {self.queue.max_depth};"
                              " retry later or raise the bound")
                    tracelog.event("request.reject", reason=reason)
                    raise AdmissionError(reason)
            rec = RequestRecord(
                id=rid, request=request, submitted_t=time.monotonic(),
                seq=seq, checkpoint_path=path,
                # a pre-existing checkpoint under this tag carries its
                # accumulated execution clock (the meta both this
                # service and the legacy campaign worker write): the
                # compute deadline is CUMULATIVE across resumes, so a
                # resubmitted tag gets the remainder of a larger
                # budget, not a fresh one
                spent_prev_s=_prior_spent_s(path))
            self._progress_seed(rec)
            try:
                self.queue.admit(rec)      # raises AdmissionError if full
            except AdmissionError as e:
                tracelog.event("request.reject", reason=str(e))
                raise
            self.records[rid] = rec
            self._m_submitted.inc()
            if self.ledger is not None:
                # journaled BEFORE the id is returned: once the caller
                # (or the HTTP 200 built on it) sees this admission,
                # the request survives a hard kill
                from .spool import payload_from_request
                self.ledger.journal(
                    "admit", rid=rid, tag=tag, seq=seq,
                    payload=payload_from_request(request),
                    spool_id=spool_id,
                    tenant=request.tenant,
                    spent_s=round(rec.spent_prev_s, 3))
            tracelog.event("request.admit", request_id=rid, tag=tag,
                           priority=request.priority,
                           deadline_s=request.deadline_s,
                           tenant=request.tenant,
                           resumable=rec.spent_prev_s > 0)
            if self.capacity is not None:
                self.capacity.on_admit(self._shape_class(request),
                                       request.tenant)
            return rid

    def _submit_portfolio(self, request: SearchRequest, k: int, *,
                          spool_id: str | None) -> str:
        """Admit a ``portfolio: K`` request: create the (never-queued,
        never-dispatched) PARENT record, fan out K member sub-requests
        over distinct configurations (service/portfolio.plan_members),
        journal the parent->member linkage, and arm the race. The
        parent id is what the client polls/awaits; it finalizes DONE
        with the first member to complete a proof (losers cancel), or
        inherits the least-bad outcome when none does."""
        import dataclasses as _dc

        from .. import problems
        from . import portfolio as portfolio_mod
        prob = problems.get(request.problem)
        # pin the resolved K on the parent request (it may have come
        # from the TTS_PORTFOLIO server default): the journaled admit
        # payload must replay the same race width on the next boot
        request = _dc.replace(request, portfolio=int(k))
        with self._lock:
            if self.ledger is not None and request.tag:
                # same idempotent re-serve rule as the solo path: a
                # duplicate tag whose recorded terminal is DONE
                # returns the recorded result instead of re-racing
                done = next(
                    (r for r in self.records.values()
                     if r.state == DONE
                     and (r.request.tag or r.id) == request.tag), None)
                if done is not None:
                    prior = done.request
                    if (prior.problem == request.problem
                            and np.array_equal(
                                np.asarray(prior.p_times),
                                np.asarray(request.p_times))
                            and prior.lb_kind == request.lb_kind
                            and prior.init_ub == request.init_ub):
                        tracelog.event("request.reserved_terminal",
                                       request_id=done.id,
                                       tag=request.tag)
                        return done.id
            seq = next(self._seq)
            rid = f"req-{seq:04d}"
            tag = request.tag or rid
            path = str(self.workdir / f"{tag}.ckpt.npz")
            holder = next(
                (r for r in self.records.values()
                 if r.checkpoint_path == path
                 and r.state not in TERMINAL_STATES), None)
            if holder is not None:
                self.queue.rejected += 1
                tracelog.event("request.reject", tag=tag,
                               reason=f"tag active on {holder.id}")
                raise AdmissionError(
                    f"tag {tag!r} is already active on request "
                    f"{holder.id} ({holder.state}); wait for it to "
                    "finish or cancel it first")
            parent = RequestRecord(
                id=rid, request=request,
                submitted_t=time.monotonic(), seq=seq,
                checkpoint_path=path,
                spent_prev_s=_prior_spent_s(path))
            self.records[rid] = parent
            self._m_submitted.inc()
            if self.ledger is not None:
                from .spool import payload_from_request
                self.ledger.journal(
                    "admit", rid=rid, tag=tag, seq=seq,
                    payload=payload_from_request(request),
                    spool_id=spool_id,
                    spent_s=round(parent.spent_prev_s, 3))
            tracelog.event("request.admit", request_id=rid, tag=tag,
                           priority=request.priority,
                           deadline_s=request.deadline_s,
                           portfolio=k,
                           resumable=parent.spent_prev_s > 0)
            plan = portfolio_mod.plan_members(
                request, prob, k, parent_tag=tag, tuner=self.tuner,
                n_workers=self.slots[0].mesh.devices.size)
            members: list = []
            try:
                for mreq, config in plan:
                    mrid = self.submit(mreq, _portfolio_member=True)
                    mrec = self.records[mrid]
                    mrec.portfolio_parent = rid
                    mrec.portfolio_config = dict(config)
                    members.append((mrid, config))
            except AdmissionError as e:
                # partial fan-out (queue filled mid-race): a half
                # portfolio is not the race the client asked for —
                # unwind the admitted members and refuse the parent
                for mrid, _ in members:
                    mrec = self.records.get(mrid)
                    if mrec is not None \
                            and mrec.state not in TERMINAL_STATES:
                        self._finalize(mrec, CANCELLED,
                                       error="portfolio fan-out aborted")
                self._finalize(
                    parent, FAILED,
                    error=f"portfolio fan-out failed at member "
                          f"{len(members)} of {k}: {e}")
                raise
            if self.ledger is not None:
                self.ledger.journal(
                    "portfolio", rid=rid,
                    members=[{"rid": m, "config": c}
                             for m, c in members])
            self.portfolio.register(parent, members)
            return rid

    def status(self, request_id: str) -> dict:
        """JSON-safe lifecycle/progress snapshot of one request."""
        return self._rec(request_id).snapshot()

    # --------------------------------------------------------- pre-warm

    def prewarm_boot(self, spec: str | None = None,
                     spool_dir: str | None = None,
                     concurrency: int | None = None) -> dict:
        """Boot pre-warm: ready compiled loops for the expected traffic
        BEFORE the first request, so warm capacity exists from second
        zero (with a warm AOT cache dir this is a burst of disk
        deserializes; on a cold dir it pays the compiles once and
        persists them for every later boot).

        `spec` is a comma-separated list of tokens: ``taillard`` (the
        standard Taillard shape families, config.
        PREWARM_TAILLARD_FAMILIES), ``spool`` (every shape found in the
        spool backlog — requests already waiting get their executables
        first), and/or explicit ``JxM`` (jobs x machines) entries.
        None/empty resolves to ``"spool,taillard"`` — the backlog's
        shapes are warmed FIRST (that traffic is already committed;
        an aborted mid-warm boot must not have spent its time on
        speculative families while waiting requests got nothing).
        Each shape is
        warmed per SUBMESH (distinct device sets are distinct executor
        keys) in the server's overlap mode (donated-pool variant when
        the pipelined driver will run). Bounded concurrency
        (TTS_PREWARM_CONCURRENCY) and idempotent — an already-warm key
        reports "warm" and costs a dict lookup.

        Returns a JSON-safe summary {shapes, warms, by: {disk, compile,
        warm, skipped}, seconds, errors}."""
        import concurrent.futures as cf

        from ..engine import distributed
        from ..problems.pfsp import PFSPInstance
        from .request import SearchRequest

        spec = (spec or "").strip() or "spool,taillard"
        chunk_default = SearchRequest.__dataclass_fields__[
            "chunk"].default
        shapes: list[dict] = []
        seen: set[tuple] = set()

        def add(jobs, machines, lb=1, chunk=chunk_default,
                capacity=None, p_times=None, balance_period=4,
                min_seed=32, problem="pfsp", rung_profile=None):
            k = (problem, jobs, machines, lb, chunk, capacity,
                 balance_period)
            if k in seen:
                return
            seen.add(k)
            shapes.append({"jobs": jobs, "machines": machines,
                           "lb": lb, "chunk": chunk,
                           "capacity": capacity, "p_times": p_times,
                           "balance_period": balance_period,
                           "min_seed": min_seed, "problem": problem,
                           "rung_profile": rung_profile})

        for token in (t.strip().lower() for t in spec.split(",")):
            if not token:
                continue
            if token == "taillard":
                for jobs, machines in cfg.PREWARM_TAILLARD_FAMILIES:
                    add(jobs, machines, **self._tuned_kwargs(jobs,
                                                             machines))
            elif token == "spool":
                from ..tune import defaults as tune_defaults
                for req in self._spool_backlog(spool_dir):
                    p = np.asarray(req.p_times)
                    bchunk, bperiod = req.chunk, req.balance_period
                    bprofile = None
                    if bchunk is None or bperiod is None:
                        # a {"tuned": true} backlog request leaves its
                        # knobs open; warm the values DISPATCH will
                        # resolve to — the tuner (probing now when
                        # tune_at_boot, so the dispatch-time cache
                        # lookup replays this boot's winner) else the
                        # serving defaults tier
                        tk = self._tuned_kwargs(p.shape[1], p.shape[0],
                                                lb=req.lb_kind,
                                                problem=req.problem)
                        dflt = tune_defaults.params_for(
                            "serving", p.shape[1], p.shape[0],
                            problem=req.problem)
                        # dispatch (distributed.search) enters its
                        # tuner-resolve block whenever EITHER knob is
                        # open and attaches rung_modes from that same
                        # cache lookup unconditionally — mirror it
                        # exactly, or an explicit-chunk request with
                        # an open balance_period warms profile-less
                        # keys dispatch never asks for
                        bprofile = tk.get("rung_profile")
                        if bchunk is None:
                            bchunk = tk.get("chunk", dflt.chunk)
                        if bperiod is None:
                            bperiod = tk.get("balance_period",
                                             dflt.balance_period)
                    add(p.shape[1], p.shape[0], lb=req.lb_kind,
                        chunk=bchunk, capacity=req.capacity,
                        p_times=p, balance_period=bperiod,
                        min_seed=req.min_seed, problem=req.problem,
                        rung_profile=bprofile)
            elif "x" in token:
                jobs, _, machines = token.partition("x")
                add(int(jobs), int(machines))
            else:
                raise ValueError(
                    f"unknown prewarm token {token!r} (want 'taillard',"
                    " 'spool' or 'JxM')")

        if concurrency is None:
            concurrency = cfg.env_int("TTS_PREWARM_CONCURRENCY")
        concurrency = max(1, concurrency)

        def warm_one(shape, mesh):
            p = shape["p_times"]
            if p is None:
                # only the SHAPE and value range matter (the tables are
                # runtime args): a synthetic Taillard-range instance
                # warms the executable every real instance of the
                # class reuses
                p = PFSPInstance.synthetic(shape["jobs"],
                                           shape["machines"],
                                           seed=0).p_times
            return distributed.prewarm(
                p, lb_kind=shape["lb"], chunk=shape["chunk"],
                capacity=shape["capacity"],
                balance_period=shape["balance_period"],
                min_seed=shape["min_seed"], mesh=mesh,
                loop_cache=self.cache,
                problem=shape.get("problem", "pfsp"),
                # a tuned entry's rung_modes mask changes the ladder's
                # rung set and per-rung fused key suffixes — the warm
                # must build the exact keys a tuned dispatch resolves
                rung_profile=shape.get("rung_profile"),
                # the pipelined driver dispatches the donated-pool
                # variant; warm the one this server will actually run
                donate=self.overlap)

        t0 = time.monotonic()
        by = {"disk": 0, "compile": 0, "warm": 0, "skipped": 0}
        errors = 0
        with cf.ThreadPoolExecutor(
                max_workers=concurrency,
                thread_name_prefix="tts-prewarm") as pool:
            futs = [pool.submit(warm_one, shape, slot.mesh)
                    for shape in shapes for slot in self.slots]
            for fut in cf.as_completed(futs):
                try:
                    by[fut.result()] += 1
                except Exception as e:  # noqa: BLE001 — warming is an
                    # optimization: one failed shape must not abort the
                    # boot (the first real request pays its compile)
                    errors += 1
                    tracelog.event("aot_cache.prewarm_failed",
                                   error=repr(e))
        if self.aot is not None:
            self.aot.drain()    # warm capacity AND a warm disk for the
            # next lifetime — the prewarm promise is both
        summary = {"shapes": len(shapes), "warms": len(shapes)
                   * len(self.slots), "by": by, "errors": errors,
                   "seconds": round(time.monotonic() - t0, 3)}
        tracelog.event("server.prewarm", shapes=summary["shapes"],
                       warms=summary["warms"], errors=errors,
                       seconds=summary["seconds"],
                       **{f"n_{k}": v for k, v in by.items()})
        return summary

    def _tuned_kwargs(self, jobs: int, machines: int,
                      lb: int = 1, problem: str = "pfsp") -> dict:
        """Tuned dispatch knobs for a pre-warm family shape: the
        tuning cache when warm, a PROBE at boot when `tune_at_boot`
        (persisted — the next boot replays it with zero probes), else
        nothing (the family keeps the serving default). Never raises —
        a failed probe must not abort the boot."""
        if self.tuner is None:
            return {}
        try:
            n_workers = self.slots[0].mesh.devices.size
            params = self.tuner.resolve(jobs, machines, lb,
                                        n_workers=n_workers,
                                        allow_probe=self.tune_at_boot,
                                        problem=problem)
        except Exception as e:  # noqa: BLE001 — tuning is an
            # optimization; the default-knob warm still happens
            tracelog.event("tuner.boot_failed", jobs=jobs,
                           machines=machines, error=repr(e))
            return {}
        if params.source == "default":
            return {}
        return {"chunk": params.chunk,
                "balance_period": params.balance_period,
                "rung_profile": params.rung_modes}

    def _spool_backlog(self, spool_dir: str | None) -> list:
        """Parse the unserved request files waiting in the spool (their
        shapes are the most certain pre-warm targets: that traffic is
        already committed). The which-requests-are-waiting rule is
        spool.unserved_requests — shared with the serve loop so the
        two can never drift."""
        import json as _json

        from . import spool as spool_mod
        if not spool_dir:
            return []
        out = []
        for _sid, req_file in spool_mod.unserved_requests(spool_dir):
            try:
                out.append(spool_mod.request_from_payload(
                    _json.loads(req_file.read_text())))
            except Exception:  # noqa: BLE001 — a malformed backlog file
                continue       # is the serve loop's problem (it writes
                #                the REJECTED result), not warm's
        return out

    def result(self, request_id: str,
               timeout: float | None = None) -> RequestRecord:
        """Block until the request is terminal (or the server closes);
        returns its record. Raises TimeoutError if `timeout` expires
        first — the record is NOT terminal in that case."""
        rec = self._rec(request_id)
        if not rec.done_event.wait(timeout):
            raise TimeoutError(
                f"request {request_id} still {rec.state} after "
                f"{timeout}s")
        return rec

    def cancel(self, request_id: str) -> bool:
        """Cancel a request. Queued: terminal immediately. Running:
        stopped at the next segment boundary. Returns False if it was
        already terminal."""
        with self._lock:
            rec = self._rec(request_id)
            if rec.state in TERMINAL_STATES:
                return False
            if rec.state in (QUEUED, PREEMPTED):
                self._finalize(rec, CANCELLED)
                return True
            rec.stop_reason = "cancel"
            self._stop_slot_of(rec)
            return True

    def preempt(self, request_id: str, hold: bool = False) -> bool:
        """Operator preemption: stop a RUNNING request at its next
        segment boundary, checkpoint it, and requeue it — or park it
        (``hold=True``) until `release()`, e.g. to drain a request
        before maintenance. Returns False unless it was running."""
        with self._lock:
            rec = self._rec(request_id)
            if rec.state != RUNNING:
                return False
            rec.hold = hold
            if rec.stop_reason is None:
                rec.stop_reason = "preempt"
            self._stop_slot_of(rec)
            return True

    def release(self, request_id: str) -> bool:
        """Requeue a held PREEMPTED request (see `preempt(hold=True)`)."""
        with self._lock:
            rec = self._rec(request_id)
            if rec.state != PREEMPTED or not rec.hold:
                return False
            rec.hold = False
            if self.ledger is not None:
                # journaled like every other transition: a crash after
                # an operator released the request must not replay it
                # back into the parked state
                self.ledger.journal("release", rid=rec.id)
            self.queue.requeue(rec)
            return True

    # ----------------------------------------- remediation support API
    # (service/remediate.RemediationController's actuation surface; the
    # controller never reaches into server internals directly, and none
    # of these run unless an action executes — TTS_REMEDIATE=1)

    def pause_admission(self, reason: str) -> None:
        """Reject new submissions with `reason` until resumed (the
        spool front-end holds its backlog instead). Ledger-journaled:
        a crash while paused restarts PAUSED — a degraded valve must
        not be laundered open by a reboot."""
        with self._lock:
            self._paused_reason = reason
            if self.ledger is not None:
                self.ledger.journal("pause", reason=reason)
        tracelog.event("server.admission_paused", reason=reason)

    def resume_admission(self) -> None:
        with self._lock:
            was, self._paused_reason = self._paused_reason, None
            if was is not None and self.ledger is not None:
                self.ledger.journal("resume")
        if was is not None:
            tracelog.event("server.admission_resumed")

    def admission_paused(self) -> str | None:
        """The pause reason, or None while admitting."""
        with self._lock:
            return self._paused_reason

    def remediate_preempt(self, request_id: str,
                          exclude_submesh: bool = True,
                          expected_submesh: int | None = None
                          ) -> tuple[bool, int | None]:
        """Controller preemption: stop a RUNNING request at its next
        segment boundary (checkpoint + requeue, like `preempt`) and —
        by default — append its current submesh to the request's
        excluded set so the resume lands elsewhere.
        `expected_submesh` (when not None) must match the request's
        CURRENT submesh — a stall observed on one submesh must not
        preempt (and exclude!) a later dispatch that already moved to
        a healthy one. Returns (preempted, excluded_submesh)."""
        with self._lock:
            rec = self.records.get(request_id)
            if rec is None or rec.state != RUNNING:
                return False, None
            if expected_submesh is not None \
                    and rec.submesh != expected_submesh:
                return False, None
            submesh = rec.submesh
            if exclude_submesh and submesh is not None:
                self.add_exclusion(rec, submesh)
            rec.hold = False
            if rec.stop_reason is None:
                rec.stop_reason = "preempt"
            for slot in self.slots:
                if slot.batch is not None and rec in slot.batch:
                    # a REMEDIATION preempt of a batched member stops
                    # the WHOLE batch: memory shedding frees nothing
                    # until the shared (D,B,...) pools release, and a
                    # stalled batch executor has stalled every member
                    # alike — all members checkpoint at the boundary
                    # and requeue (member-level stops stay the rule
                    # for cancel/deadline, see _stop_slot_of)
                    if slot.stop_event is not None:
                        slot.stop_event.set()
                    break
            else:
                self._stop_slot_of(rec)
            return True, (submesh if exclude_submesh else None)

    def add_exclusion(self, rec: RequestRecord, submesh: int) -> None:
        """Exclude `submesh` for `rec` (caller may hold the lock — it
        is an RLock). If the exclusions would cover the whole
        partition, only the newest offender is kept (on a
        single-submesh server: none at all) — a request must always
        have somewhere left to run; one that genuinely fails
        everywhere dead-letters through the failure path instead."""
        with self._lock:
            rec.excluded_submeshes.add(int(submesh))
            if len(rec.excluded_submeshes) >= len(self.slots):
                rec.excluded_submeshes = (
                    {int(submesh)} if len(self.slots) > 1 else set())
            if self.ledger is not None:
                # journaled in ABSOLUTE form: the cap above can RESET
                # the set, which a relative append would replay wrong
                self.ledger.journal(
                    "exclude", rid=rec.id,
                    excluded=sorted(rec.excluded_submeshes))

    def lowest_priority_running(self) -> str | None:
        """The shed_memory action's victim: the lowest-priority,
        youngest RUNNING request not already stopping."""
        with self._lock:
            cands = [rec for s in self.slots for rec in s.records
                     if rec.state == RUNNING
                     and rec.stop_reason is None]
            if not cands:
                return None
            return min(cands,
                       key=lambda r: (r.request.priority,
                                      -(r.started_t or 0.0))).id

    def quarantine_submesh(self, index: int, reason: str) -> None:
        """Hold a slot out of the partition (the remediation
        controller's containment decision executes here — and is
        ledger-journaled, so a crash cannot launder a quarantined
        submesh back into rotation)."""
        with self._lock:
            slot = self.slots[index]
            slot.quarantined = True
            slot.quarantined_since = time.time()
            slot.quarantine_reason = reason
            if self.ledger is not None:
                self.ledger.journal("quarantine", submesh=int(index),
                                   reason=reason)
            self._lane_sync(slot)

    def readmit_submesh(self, index: int) -> None:
        """Clear a slot's quarantine (the canary probe passed)."""
        with self._lock:
            slot = self.slots[index]
            slot.quarantined = False
            slot.quarantine_reason = None
            if self.ledger is not None:
                self.ledger.journal("readmit", submesh=int(index))
            self._lane_sync(slot)

    def heartbeat_ages(self) -> dict:
        """Seconds since each RUNNING request's last engine heartbeat —
        the health layer's `stall` rule input (a wedged submesh stops
        heartbeating long before it stops holding its slot)."""
        now = time.monotonic()
        with self._lock:
            return {rec.id: now - rec.last_heartbeat_t
                    for slot in self.slots
                    for rec in slot.records
                    if rec.state == RUNNING
                    and rec.last_heartbeat_t is not None}

    # --------------------------------------------- capacity (TTS_CAPACITY)

    def _lane_state(self, slot: _Slot) -> str:
        """Resolve a slot's lane state from existing scheduler state —
        no new bookkeeping, so the resolver cannot drift from the
        transitions it observes. Priority order matters: a quarantined
        lane is quarantined whatever it still runs, a stop in flight is
        draining even if some member already froze."""
        if slot.quarantined:
            return "quarantined"
        recs = slot.records
        if not recs:
            return "idle"
        if all(r.dispatch_heartbeats == 0 for r in recs):
            return "compiling"      # dispatched, no heartbeat yet:
            #                         the XLA trace+compile window
        if ((slot.stop_event is not None and slot.stop_event.is_set())
                or any(r.stop_reason is not None
                       and r.state not in TERMINAL_STATES
                       for r in recs)):
            return "draining"   # a stop is in flight only until the
            #                     stopped member finalizes
        if slot.batch is not None \
                and any(r.state != RUNNING for r in recs):
            return "batch-frozen"   # a member finished; the rest run
            #                         the batch out (ROADMAP item 2)
        return "executing"

    def _lane_sync(self, slot: _Slot) -> None:
        """Fold `slot`'s current resolved state into the lane ledger (a
        no-op when unchanged, and entirely absent with TTS_CAPACITY=0).
        Callable with OR without the server lock: the ledger locks
        itself, and a racing resolve can at worst label a sliver of
        time with the neighboring state — conservation is untouched."""
        if self.lane_ledger is not None:
            self.lane_ledger.transition(slot.index,
                                        self._lane_state(slot))

    def _shape_class(self, request: SearchRequest) -> str:
        """The tune/defaults shape-class label of a request — the key
        the capacity model's demand and service-rate tables join on."""
        from .. import problems
        from ..tune import defaults as tune_defaults
        p = np.asarray(request.p_times)
        return tune_defaults.shape_class(
            problems.get(request.problem).slots(p), p.shape[0],
            problem=request.problem)

    def _capacity_seed(self, shape: str, p: np.ndarray,
                       lb_kind: int) -> None:
        """Seed the capacity model's service rate for `shape` from the
        same tuning tier the dispatch itself resolves through (cached
        eval's evals/s when present, the defaults table otherwise) —
        the model corrects it with observed throughput as heartbeats
        arrive, but a fresh class gets a non-degenerate E[S] from the
        very first admit."""
        if self.capacity is None:
            return
        params = None
        if self.tuner is not None:
            try:
                params = self.tuner.resolve(
                    p.shape[1], p.shape[0], lb_kind,
                    n_workers=self.slots[0].mesh.devices.size)
            except Exception:   # noqa: BLE001 — seeding is best-effort
                params = None
        if params is None:
            from ..tune import defaults as tune_defaults
            try:
                params = tune_defaults.params_for(
                    "serving", p.shape[1], p.shape[0])
            except Exception:   # noqa: BLE001
                return
        rate = getattr(params, "evals_per_s", None)
        if rate:
            self.capacity.seed_rate(shape, float(rate))

    def capacity_snapshot(self) -> dict | None:
        """The ``GET /capacity`` document (and status_snapshot's
        ``capacity`` key): lane-state ledger detail + the shape-class
        demand/capacity model with its what-if partition table. None
        with the capacity layer off."""
        if self.capacity is None or self.lane_ledger is None:
            return None
        healthy = sum(1 for s in self.slots if not s.quarantined)
        devices = sum(len(s.device_ids) for s in self.slots)
        doc = self.capacity.snapshot(healthy, len(self.slots), devices)
        doc["lanes_detail"] = self.lane_ledger.snapshot()
        return doc

    def status_snapshot(self) -> dict:
        """One JSON-safe dict describing the whole server: queue depth
        and order, per-submesh occupancy, executor-cache hit/miss
        counters, lifecycle counters, and every request's snapshot.
        The counters and the `metrics` view are both read from the
        server's metrics registry (the same numbers `/metrics` exposes
        as Prometheus text) — the snapshot is a rendering of the
        registry, not a parallel bookkeeping path."""
        with self._lock:
            return {
                "t": time.time(),
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "queue": {"depth": len(self.queue),
                          "waiting": self.queue.waiting_ids(),
                          "max_depth": self.queue.max_depth,
                          "peak_depth": self.queue.peak_depth,
                          "rejected": self.queue.rejected},
                "submeshes": [
                    {"index": s.index, "devices": s.device_ids,
                     "running": s.record.id if s.record else None,
                     "batch": ([r.id for r in s.batch]
                               if s.batch is not None else None),
                     "quarantined": s.quarantined}
                    for s in self.slots],
                "megabatch": ({"enabled": True,
                               "held": self.former.waiting_ids(),
                               "max": self.former.max_size,
                               "age_s": self.former.age_s}
                              if self.former is not None else None),
                "remediation": self.remediation.snapshot(),
                "ledger": ({**self.ledger.snapshot(),
                            "recovered": dict(self._recovered)}
                           if self.ledger is not None else None),
                "failover": self._failover_snapshot(),
                "executor_cache": self.cache.snapshot(),
                "aot_cache": (self.aot.snapshot()
                              if self.aot is not None else None),
                "compile_ledger": self.cache.ledger_snapshot(),
                "incumbents": (self.incumbents.snapshot()
                               if self.incumbents is not None else None),
                "tuner": (self.tuner.snapshot()
                          if self.tuner is not None else None),
                "portfolio": self._portfolio_snapshot(),
                "counters": self.counters,
                "metrics": self.metrics.to_json(),
                "requests": {rid: rec.snapshot()
                             for rid, rec in self.records.items()},
                # ABSENT (not None) with the capacity layer off: the
                # off-path snapshot is bit-identical, test-pinned
                **({"capacity": self.capacity_snapshot()}
                   if self.capacity is not None else {}),
            }

    def _portfolio_snapshot(self) -> dict | None:
        """status_snapshot()'s `portfolio` key: None when no request
        ever raced (snapshot parity with the pre-portfolio server),
        else the race totals the doctor's column reads — per-race
        detail (siblings, winner config, cancelled counts) lives on
        each parent's request snapshot `portfolio` block."""
        parents = [r for r in self.records.values()
                   if r.portfolio_members is not None]
        if not parents:
            return None
        return {"parents": len(parents),
                "active": sum(1 for r in parents
                              if r.state not in TERMINAL_STATES),
                "won": sum(1 for r in parents if r.state == DONE),
                "cancelled_members": sum(r.portfolio_cancelled
                                         for r in parents)}

    def _failover_snapshot(self) -> dict | None:
        """status_snapshot()'s `failover` key: None outside fleet mode
        (snapshot parity with the PR-12 server), else lease + watcher
        state — the doctor/dashboard columns and the health layer's
        `peer_down` rule both read it."""
        if (self.lease is None and self.watcher is None
                and not self.fenced):
            return None
        out: dict = {"fenced": self.fenced,
                     "fence_reason": self._fence_reason,
                     "adopted": len(self._adopted)}
        if self.lease is not None:
            out["lease"] = self.lease.snapshot()
        if self.watcher is not None:
            out.update(self.watcher.snapshot())
        return out

    # ------------------------------------------------------ crash recovery
    # (service/ledger: replaying the write-ahead journal at boot)

    def _replay_boot(self) -> None:
        """Rebuild serving state from the replayed ledger: standing
        admission pause + submesh quarantines first (a crash must not
        launder a degraded configuration back to healthy), then every
        journaled request — queued/active re-admitted with budgets,
        exclusions and failure logs intact (their checkpoints make the
        resume lossless), terminal snapshots kept for idempotent
        re-serve."""
        from . import spool as spool_mod
        st = self.ledger.state
        if st.boots:
            # a monotone restart count fed from the ledger itself, so
            # the doctor's column survives the registry reset a restart
            # is
            self.metrics.counter(
                "tts_server_restarts_total",
                "server boots that replayed prior ledger state"
                ).inc(st.boots)
        if st.paused:
            with self._lock:
                self._paused_reason = st.paused
            self.remediation.restore_pause(st.paused)
            tracelog.event("ledger.pause_restored", reason=st.paused)
        for idx, reason in sorted(st.quarantined.items()):
            if not 0 <= idx < len(self.slots):
                continue        # journaled on a larger partition
            if sum(1 for s in self.slots if not s.quarantined) <= 1:
                # the last healthy slot stays in rotation — the same
                # never-zero-capacity guard remediate._quarantine
                # applies live; a shrunk partition must not replay
                # itself into a server that can never dispatch
                tracelog.event("ledger.quarantine_not_restored",
                               submesh=idx,
                               reason="last healthy submesh")
                continue
            slot = self.slots[idx]
            slot.quarantined = True
            slot.quarantined_since = time.time()
            slot.quarantine_reason = reason or "restored from ledger"
            self.remediation.restore_quarantine(idx)
        max_seq = -1
        for entry in sorted(st.requests.values(),
                            key=lambda e: e.get("seq", 0)):
            max_seq = max(max_seq, int(entry.get("seq", 0)))
            try:
                self._readmit_replayed(entry, spool_mod)
            except Exception as e:  # noqa: BLE001 — one unparseable
                # entry (schema drift, a hand-edited ledger) must not
                # strand the rest of the recovery
                tracelog.event("ledger.readmit_failed",
                               request_id=entry.get("rid"),
                               error=repr(e))
        if max_seq >= 0:
            self._seq = itertools.count(max_seq + 1)
        # re-arm replayed portfolio races AFTER every entry landed
        # (members replay after their lower-seq parent): a race the
        # crash interrupted mid-decision resolves right here — a
        # pre-kill winner decides, members of an already-terminal
        # parent cancel instead of re-running a finished race
        self.portfolio.reconcile()
        if st.requests:
            tracelog.event("ledger.recovered", restarts=st.boots,
                           **self._recovered)

    def _readmit_replayed(self, entry: dict, spool_mod) -> None:
        rid = entry["rid"]
        req = spool_mod.request_from_payload(entry.get("payload") or {})
        tag = entry.get("tag") or rid
        req.tag = tag
        if entry.get("tenant"):
            req.tenant = str(entry["tenant"])
        path = str(self.workdir / f"{tag}.ckpt.npz")
        rec = RequestRecord(
            id=rid, request=req, submitted_t=time.monotonic(),
            seq=int(entry.get("seq", 0)), checkpoint_path=path,
            # the budget clock is CUMULATIVE across the crash: the
            # journaled spent_s (heartbeat-fresh) and the checkpoint's
            # own meta both survive; trust whichever saw more
            spent_prev_s=max(float(entry.get("spent_s") or 0.0),
                             _prior_spent_s(path)),
            dispatches=int(entry.get("dispatches") or 0),
            preemptions=int(entry.get("preemptions") or 0),
            failures=int(entry.get("failures") or 0))
        self._progress_seed(rec)
        # adoption lineage survives the adopter's own restart: the
        # replayed admit record carried it (see _adopt_entry)
        rec.origin_rid = entry.get("origin_rid")
        rec.origin_owner = entry.get("origin_owner")
        rec.failure_log = [dict(f) for f in
                           entry.get("failure_log") or []]
        # restored exclusions are re-capped against THIS lifetime's
        # partition (it may be smaller than the one that journaled
        # them): indices past the partition drop, and a set that would
        # cover every slot clears — the add_exclusion invariant that a
        # request must always have somewhere left to run
        excluded = {int(s) for s in entry.get("excluded") or []
                    if 0 <= int(s) < len(self.slots)}
        if len(excluded) >= len(self.slots):
            excluded = set()
        rec.excluded_submeshes = excluded
        rec.error = entry.get("error")
        # portfolio linkage (the `portfolio` journal record stamped it
        # on the entries; _apply_restore carries it through compaction
        # verbatim) — restored BEFORE the state branch so a parent is
        # recognized and never requeued
        pf_members = entry.get("portfolio_members")
        if pf_members:
            rec.portfolio_members = [m.get("rid") for m in pf_members]
        if entry.get("portfolio_parent"):
            rec.portfolio_parent = str(entry["portfolio_parent"])
            rec.portfolio_config = entry.get("portfolio_config")
        state = entry.get("state")
        if state in TERMINAL_STATES:
            rec.state = state
            snap = entry.get("terminal") or {}
            if snap.get("result") is not None:
                rec.result = _ReplayedResult(snap["result"])
            rec.error = snap.get("error", rec.error)
            if rec.portfolio_members is not None:
                pf = snap.get("portfolio") or {}
                rec.portfolio_winner = pf.get("winner")
                rec.portfolio_config = (pf.get("winner_config")
                                        or rec.portfolio_config)
                rec.portfolio_cancelled = int(pf.get("cancelled") or 0)
            rec.done_event.set()
            self._recovered["terminal"] += 1
        elif state == PREEMPTED and entry.get("hold"):
            # an operator parked it (preempt(hold=True)); stay parked
            # until release() — a restart is not a release
            rec.state = PREEMPTED
            rec.hold = True
            self._recovered["held"] += 1
        else:
            rec.state = QUEUED
            self._recovered["active" if state == RUNNING
                            else "queued"] += 1
            if rec.portfolio_members is None:
                # a portfolio PARENT is a coordination object: it waits
                # on its members' terminals, it never queues — the
                # post-replay reconcile() re-arms its race instead
                self.queue.requeue(rec)
        with self._lock:
            self.records[rid] = rec
        if entry.get("spool_id"):
            self.replayed_spool[str(entry["spool_id"])] = rid
        tracelog.event("request.recovered", request_id=rid,
                       state=rec.state, tag=tag,
                       spent_s=round(rec.spent_prev_s, 3),
                       dispatches=rec.dispatches,
                       excluded=sorted(rec.excluded_submeshes))

    # ------------------------------------------------------ fleet failover
    # (service/lease + service/failover: fenced ownership and takeover)

    def _self_fence(self, reason: str) -> None:
        """This process no longer owns its ledger (epoch bumped by an
        adopter). Stop committing: admission refuses with LeaseLost,
        the scheduler tick exits cleanly, running requests stop at
        their next segment boundary (their preempt journals no-op on
        the fenced ledger — zero commits by construction). Idempotent;
        fired by the lease keeper's renewal daemon or the ledger's
        append-path check, whichever notices first."""
        with self._lock:
            if self.fenced:
                return
            self.fenced = True
            self._fence_reason = reason
            for slot in self.slots:
                for rec in slot.records:
                    if rec.stop_reason is None:
                        rec.stop_reason = "fenced"
                if slot.records and slot.stop_event is not None:
                    slot.stop_event.set()
        tracelog.event("server.fenced", reason=reason)

    def _ckpt_fence_meta(self) -> dict:
        """Fencing stamp for checkpoint meta. Raises LeaseLost before a
        stale owner's save can even serialize; the epoch stamp it
        returns makes engine/checkpoint refuse an epoch-stale overwrite
        on top (the fence is in the data, not just the timing).
        Vacuous ({}) outside fleet mode."""
        if self.lease is None:
            return {}
        self.lease.check()
        return {"lease_epoch": self.lease.epoch}

    def adopt_ledger(self, orphan_dir: str,
                     current_epoch: int | None = None) -> dict:
        """Take over a dead peer's ledger (the FailoverWatcher's act
        path; callable directly for drills). Protocol:

        1. CAS the fencing epoch to ``current_epoch + 1`` through the
           claim file — exactly one adopter; losing returns
           ``{"outcome": "lost_race"}`` without touching the orphan.
        2. Replay the orphan through the PR-12 boot path (the ledger
           constructor truncates any torn tail to last-good) and
           journal a ``takeover`` record at the NEW epoch — any stale
           append the dead owner slips in afterwards is discarded on
           every future replay.
        3. Re-admit its QUEUED/ACTIVE requests HERE under fresh ids
           (the orphan's ``req-NNNN`` ids collide with ours) with
           budgets, exclusions, failure logs, spool ids and checkpoint
           files intact; journal each into OUR ledger (a crash here
           re-replays the adoption) and a ``forget`` tombstone into
           the orphan (a rebooted original owner replays an empty live
           set). DONE terminals register for idempotent tag re-serve.
           The orphan's standing submesh quarantines are deliberately
           NOT imported — they described the dead host's hardware.
        4. Keep renewing the orphan's lease: a restarted stale owner
           must find a LIVE foreign lease and boot fenced, and no
           second peer may re-adopt. Released at close().
        """
        from . import lease as lease_mod
        from . import spool as spool_mod
        from .lease import LeaseKeeper
        from .ledger import RequestLedger

        orphan_dir = str(orphan_dir)
        if current_epoch is None:
            info = lease_mod.read_lease(orphan_dir)
            current_epoch = info.epoch if info is not None else 0
        keeper = LeaseKeeper(orphan_dir)
        if not keeper.takeover(current_epoch):
            tracelog.event("failover.lost_race", dir=orphan_dir,
                           epoch=current_epoch + 1)
            return {"outcome": "lost_race", "dir": orphan_dir}
        moved = reserved = failed = 0
        orphan = RequestLedger(orphan_dir, lease=keeper)
        try:
            # `adopter` names OUR ledger directory: the forward pointer
            # a journey reconstructor reading the orphan needs to know
            # where the live requests went (origin_rid on our admits is
            # the matching back pointer)
            orphan.journal("takeover", owner=keeper.owner,
                           from_epoch=current_epoch, pid=os.getpid(),
                           adopter=(pathlib.Path(self._ledger_dir).name
                                    if self._ledger_dir else None))
            entries = sorted(orphan.state.requests.values(),
                             key=lambda e: e.get("seq", 0))
            for entry in entries:
                try:
                    if entry.get("state") in TERMINAL_STATES:
                        if entry.get("state") == DONE \
                                and self._adopt_terminal(entry,
                                                         spool_mod):
                            reserved += 1
                        continue
                    self._adopt_entry(entry, orphan_dir, spool_mod)
                    orphan.journal("forget", rid=entry.get("rid"))
                    moved += 1
                except Exception as e:  # noqa: BLE001 — one
                    # unparseable entry must not strand the rest of
                    # the takeover (the _replay_boot stance)
                    failed += 1
                    tracelog.event("failover.adopt_entry_failed",
                                   request_id=entry.get("rid"),
                                   error=repr(e))
        finally:
            orphan.close()
        self._adopted.append(keeper)
        result = {"outcome": "adopted", "dir": orphan_dir,
                  "epoch": keeper.epoch, "moved": moved,
                  "reserved": reserved, "failed": failed}
        tracelog.event("failover.adopted", **result)
        return result

    def _adopt_entry(self, entry: dict, orphan_dir: str,
                     spool_mod) -> str:
        """Re-admit one live orphan entry on THIS server — the
        _readmit_replayed recipe under a fresh id, journaled into our
        own ledger. The orphan's checkpoint family is copied into our
        workdir first (never clobbering an existing one) so the resume
        is lossless and budget-continuous."""
        rid_old = entry["rid"]
        req = spool_mod.request_from_payload(entry.get("payload") or {})
        tag = entry.get("tag") or rid_old
        req.tag = tag
        if entry.get("tenant"):
            req.tenant = str(entry["tenant"])
        src_dir = pathlib.Path(orphan_dir) / "workdir"
        path = str(self.workdir / f"{tag}.ckpt.npz")
        for suffix in ("", ".prev"):
            src = src_dir / f"{tag}.ckpt.npz{suffix}"
            dst = pathlib.Path(path + suffix)
            if not src.exists() or dst.exists() or src == dst:
                continue
            try:
                # copy to a unique temp then rename: our own executor
                # must never read a half-copied snapshot
                tmp = dst.with_name(f".{dst.name}.{os.getpid()}.tmp")
                shutil.copy2(src, tmp)
                os.replace(tmp, dst)
            except OSError as e:
                tracelog.event("failover.checkpoint_copy_failed",
                               src=str(src), error=repr(e))
        with self._lock:
            seq = next(self._seq)
            rid = f"req-{seq:04d}"
            rec = RequestRecord(
                id=rid, request=req, submitted_t=time.monotonic(),
                seq=seq, checkpoint_path=path,
                spent_prev_s=max(float(entry.get("spent_s") or 0.0),
                                 _prior_spent_s(path)),
                dispatches=int(entry.get("dispatches") or 0),
                preemptions=int(entry.get("preemptions") or 0),
                failures=int(entry.get("failures") or 0))
            # the copied checkpoint's meta seeds the estimate warm, so
            # an adopted request's progress continues across the
            # takeover like its budget clock does
            self._progress_seed(rec)
            # id lineage: the fresh rid continues the orphan's rid —
            # stamped on the record, its admit journal and the adopted
            # event, so the flight recorder's journey reconstructor
            # chains ONE logical request across the takeover. If the
            # entry itself was already an adoption (a second hop), the
            # ORIGINAL lineage wins: chains stay one link deep to the
            # first admit.
            rec.origin_rid = entry.get("origin_rid") or rid_old
            rec.origin_owner = (entry.get("origin_owner")
                                or pathlib.Path(orphan_dir).name)
            rec.failure_log = [dict(f) for f in
                               entry.get("failure_log") or []]
            excluded = {int(s) for s in entry.get("excluded") or []
                        if 0 <= int(s) < len(self.slots)}
            if len(excluded) >= len(self.slots):
                excluded = set()
            rec.excluded_submeshes = excluded
            rec.error = entry.get("error")
            if entry.get("state") == PREEMPTED and entry.get("hold"):
                rec.state = PREEMPTED
                rec.hold = True
            else:
                rec.state = QUEUED
            self.records[rid] = rec
            self._m_submitted.inc()
            if self.ledger is not None:
                self.ledger.journal(
                    "admit", rid=rid, tag=tag, seq=seq,
                    payload=spool_mod.payload_from_request(req),
                    spool_id=entry.get("spool_id"),
                    spent_s=round(rec.spent_prev_s, 3),
                    tenant=req.tenant,
                    origin_rid=rec.origin_rid,
                    origin_owner=rec.origin_owner)
                if rec.excluded_submeshes:
                    self.ledger.journal(
                        "exclude", rid=rid,
                        excluded=sorted(rec.excluded_submeshes))
            if rec.state == QUEUED:
                self.queue.requeue(rec)
        if entry.get("spool_id"):
            self.replayed_spool[str(entry["spool_id"])] = rid
        tracelog.event("request.adopted", request_id=rid,
                       orphan_id=rid_old, tag=tag, state=rec.state,
                       tenant=req.tenant,
                       origin_rid=rec.origin_rid,
                       origin_owner=rec.origin_owner,
                       spent_s=round(rec.spent_prev_s, 3),
                       spool_id=entry.get("spool_id"))
        return rid

    def _adopt_terminal(self, entry: dict, spool_mod) -> bool:
        """Register a DONE orphan entry for idempotent re-serve: a
        duplicate-tag submission (a crash-retried client) gets the
        recorded result instead of a re-solve, exactly as it would
        have from the dead owner. In-memory only — the orphan ledger
        keeps the durable copy."""
        tag = entry.get("tag") or entry.get("rid")
        snap = entry.get("terminal") or {}
        if snap.get("result") is None:
            return False
        with self._lock:
            if any((r.request.tag or r.id) == tag
                   for r in self.records.values()):
                return False    # the tag already lives here
            seq = next(self._seq)
            rid = f"req-{seq:04d}"
            req = spool_mod.request_from_payload(
                entry.get("payload") or {})
            req.tag = tag
            rec = RequestRecord(
                id=rid, request=req, submitted_t=time.monotonic(),
                seq=seq,
                checkpoint_path=str(self.workdir / f"{tag}.ckpt.npz"),
                spent_prev_s=float(entry.get("spent_s") or 0.0))
            rec.state = DONE
            rec.result = _ReplayedResult(snap["result"])
            rec.done_event.set()
            self.records[rid] = rec
        if entry.get("spool_id"):
            self.replayed_spool[str(entry["spool_id"])] = rid
        tracelog.event("request.adopted_terminal", request_id=rid,
                       tag=tag, spool_id=entry.get("spool_id"))
        return True

    def _ledger_budget(self, rec: RequestRecord) -> None:
        """Journal the request's cumulative execution clock, throttled
        to LEDGER_BUDGET_EVERY_S (every heartbeat would fsync at
        heartbeat rate; this bounds what a hard kill can lose to a few
        seconds of budget, never the request)."""
        if self.ledger is None:
            return
        now = time.monotonic()
        if now - rec.ledger_budget_t < cfg.LEDGER_BUDGET_EVERY_S_DEFAULT:
            return
        rec.ledger_budget_t = now
        extra = {}
        est = rec.progress.get("estimate") or {}
        if est.get("progress_ratio") is not None:
            # the journey timeline's per-lifetime progress marks ride
            # the same throttled budget record (obs/journey reads them
            # back; absent when TTS_PROGRESS=0 — record bit-identity)
            extra["progress"] = est["progress_ratio"]
        self.ledger.journal("budget", rid=rec.id,
                           spent_s=round(rec.spent_s(), 3), **extra)

    # ------------------------------------------------- progress estimation

    def _progress_seed(self, rec: RequestRecord) -> None:
        """Attach a ProgressEstimator (TTS_PROGRESS on), warm from any
        existing checkpoint's meta vector so a resumed / resharded /
        adopted request continues its estimate instead of restarting
        cold (the spent_s continuity rule, estimator-shaped)."""
        if not self.progress_enabled:
            return
        from ..obs import estimate as est_mod
        # depth hint = the instance's first shape axis (jobs / cities /
        # items): it bounds the estimator's cascade horizon so the
        # early no-pruning expansion phase cannot inflate the estimate
        # past the finite-depth tree
        depth = int(np.asarray(rec.request.p_times).shape[0])
        prior = _prior_progress_est(rec.checkpoint_path)
        est = (est_mod.ProgressEstimator.from_list(prior,
                                                   depth_hint=depth)
               if prior is not None else None)
        rec.estimator = est or est_mod.ProgressEstimator(
            depth_hint=depth)

    def _progress_rate(self, rec: RequestRecord) -> float | None:
        """ETA fallback rate before the first live window: the tuner's
        measured per-shape evals/s (memo/cache/defaults only — never a
        probe on the heartbeat path); None when unknown."""
        if self.tuner is None:
            return None
        try:
            from .. import problems
            p = np.asarray(rec.request.p_times)
            prob = problems.get(rec.request.problem)
            params = self.tuner.resolve(
                prob.slots(p), p.shape[0], lb_kind=rec.request.lb_kind,
                problem=rec.request.problem)
            return params.evals_per_s
        except Exception:  # noqa: BLE001 — a fallback must never break hb
            return None

    def _progress_update(self, rec: RequestRecord, rep) -> None:
        """Heartbeat hook: fold one segment report into the request's
        estimator, surface the estimate in the progress snapshot, and
        publish the per-request gauges once past the warmup gate."""
        est = rec.estimator
        if est is None:
            return
        est.update(tree=rep.tree, pool=rep.pool_size,
                   elapsed=rep.elapsed, telemetry=rep.telemetry)
        snap = est.snapshot(self._progress_rate(rec))
        rec.progress["estimate"] = snap
        self._progress_publish(rec, snap)
        self._portfolio_progress(rec)

    def _progress_publish(self, rec: RequestRecord, snap: dict) -> None:
        if snap.get("progress_ratio") is None:
            return
        labels = dict(request=rec.id, tag=rec.request.tag or rec.id,
                      tenant=rec.request.tenant)
        self.metrics.gauge(
            "tts_progress_ratio",
            "estimated fraction of the search tree explored").set(
            snap["progress_ratio"], **labels)
        self.metrics.gauge(
            "tts_est_tree_size",
            "estimated total search-tree size in nodes").set(
            snap["est_tree_size"], **labels)
        if snap.get("eta_s") is not None:
            self.metrics.gauge(
                "tts_eta_seconds",
                "estimated execution seconds remaining").set(
                snap["eta_s"], **labels)

    def _portfolio_progress(self, rec: RequestRecord) -> None:
        """A racing member's estimate rolls up to its parent: the race
        resolves at the FIRST finisher, so the parent reports the best
        member's view (furthest progress, its ETA)."""
        pid = rec.portfolio_parent
        if pid is None:
            return
        parent = self.records.get(pid)
        if parent is None or parent.portfolio_members is None:
            return
        best = None
        for mid in parent.portfolio_members:
            m = self.records.get(mid)
            est = (m.progress.get("estimate") or {}) if m else {}
            p = est.get("progress_ratio")
            if p is not None and (best is None
                                  or p > best["progress_ratio"]):
                best = {**est, "member": mid}
        if best is not None:
            parent.progress = {**parent.progress, "estimate": best}

    # ------------------------------------------------------------ internals

    def _rec(self, request_id: str) -> RequestRecord:
        try:
            return self.records[request_id]
        except KeyError:
            raise KeyError(f"unknown request id {request_id!r}") from None

    def _stop_slot_of(self, rec: RequestRecord) -> None:
        for slot in self.slots:
            if slot.batch is not None:
                # member-level stop: the batched engine honors the
                # record's stop_reason at the next segment boundary;
                # setting the slot event would stop the WHOLE batch
                if rec in slot.batch:
                    return
            elif slot.record is rec and slot.stop_event is not None:
                slot.stop_event.set()

    def _handle_dispatch_failure(self, rec: RequestRecord, submesh: int,
                                 error: str,
                                 no_retry: bool = False) -> bool:
        """Dispatch-failure bookkeeping shared by the solo and batched
        finish paths (failure log/journal/event, remediation verdict,
        requeue-vs-deadletter-vs-FAILED arbitration — two hand-rolled
        copies would drift, the _record_preempt lesson). Returns True
        when the caller should requeue the record with backoff;
        otherwise it was finalized FAILED here. Caller holds the lock
        and has rolled `spent_prev_s` forward."""
        if no_retry:
            rec.failures = self.service_retry_attempts + 1
        rec.failures += 1
        rec.error = error
        rec.failure_log.append(
            {"t": time.time(), "submesh": submesh,
             "attempt": rec.dispatches, "error": error})
        del rec.failure_log[:-FAILURE_LOG_CAP]
        tracelog.event("request.dispatch_failure", request_id=rec.id,
                       submesh=submesh, attempt=rec.dispatches,
                       error=error)
        if self.ledger is not None:
            self.ledger.journal(
                "failure", rid=rec.id, submesh=submesh,
                attempt=rec.dispatches, error=error,
                failures=rec.failures,
                spent_s=round(rec.spent_prev_s, 3))
        verdict = self.remediation.on_dispatch_failure(rec, submesh,
                                                       error)
        if (verdict == "requeue"
                and rec.failures <= self.service_retry_attempts
                and not self._closing.is_set()):
            rec.state = QUEUED
            self._m_redispatch.inc()
            tracelog.event("request.redispatch", request_id=rec.id,
                           failures=rec.failures, error=error)
            return True
        if verdict == "deadletter":
            self._finalize(
                rec, FAILED,
                error=f"dead-lettered: failed on "
                      f"{len({f['submesh'] for f in rec.failure_log})} "
                      f"distinct submeshes (the fault follows the "
                      f"request); last: {error}")
        else:
            self._finalize(rec, FAILED, error=error)
        return False

    def _record_preempt(self, rec: RequestRecord,
                        reason: str | None) -> bool:
        """PREEMPTED bookkeeping — state, counter, ledger journal,
        trace event — shared by the solo executor, the batched
        mid-batch stop handler and the batched finish path (three
        hand-rolled copies had already started to drift). Returns
        whether the caller should requeue the record (not on
        shutdown, not while parked, not while closing). Caller holds
        the lock and has already rolled `spent_prev_s` forward."""
        rec.state = PREEMPTED
        rec.preemptions += 1
        self._m_preempt.inc()
        if self.ledger is not None:
            self.ledger.journal("preempt", rid=rec.id,
                               preemptions=rec.preemptions,
                               spent_s=round(rec.spent_prev_s, 3),
                               hold=rec.hold)
        tracelog.event("request.preempt", request_id=rec.id,
                       reason=reason or "stop",
                       preemptions=rec.preemptions, hold=rec.hold)
        return (reason != "shutdown" and not rec.hold
                and not self._closing.is_set())

    def _finalize(self, rec: RequestRecord, state: str,
                  error: str | None = None) -> None:
        """Move a record to a terminal state (caller holds the lock)."""
        rec.state = state
        rec.error = error if error is not None else rec.error
        rec.finished_t = time.monotonic()
        key = {DONE: "done", CANCELLED: "cancelled",
               DEADLINE: "deadline", FAILED: "failed"}[state]
        if rec.estimator is not None and state == DONE:
            # DONE makes the estimate exact: pin progress to 1.0 / ETA
            # to 0 in the terminal snapshot (the other terminals keep
            # the last honest estimate — an abandoned tree has no
            # truthful "fraction complete")
            rec.estimator.finalize()
            rec.progress["estimate"] = rec.estimator.snapshot()
        if self.ledger is not None:
            # the full snapshot rides the terminal record: it is the
            # idempotent re-serve source for a duplicate tag after a
            # restart (and the forensic record of HOW it ended)
            self.ledger.journal("terminal", rid=rec.id, state=state,
                               snapshot=rec.snapshot())
        self._m_terminal.inc(state=key, tenant=rec.request.tenant)
        self._m_spent.observe(rec.spent_s())
        # live-attribution series are per-request labeled; retire them
        # with the request or a long-serving process grows gauge
        # cardinality without bound. Unconditional: remove_matching on
        # a metric that was never created is a free no-op, and gating
        # it on phase_profile left series behind when the knob was
        # flipped off mid-lifetime
        self.metrics.remove_matching("tts_phase_seconds",
                                     request=rec.id)
        # same cardinality valve for the search-telemetry series
        # (engine/telemetry.publish, fed by the heartbeat below)
        from ..engine import telemetry as tele_mod
        for name in tele_mod.SERIES:
            self.metrics.remove_matching(name, request=rec.id)
        # ...and for the progress/ETA estimate family (obs/estimate):
        # the estimate lives on in the terminal snapshot, never as a
        # live series
        for name in ("tts_progress_ratio", "tts_eta_seconds",
                     "tts_est_tree_size"):
            self.metrics.remove_matching(name, request=rec.id)
        tracelog.event(f"request.{key}", request_id=rec.id,
                       tag=rec.request.tag or rec.id,
                       tenant=rec.request.tenant,
                       spent_s=round(rec.spent_s(), 3),
                       dispatches=rec.dispatches,
                       preemptions=rec.preemptions, error=rec.error)
        if self.capacity is not None and rec.result is not None:
            # a finished tree is a measured service demand: explored
            # nodes feed the shape class's evals-per-request EWMA
            self.capacity.on_terminal(
                self._shape_class(rec.request),
                getattr(rec.result, "explored_tree", None),
                service_s=rec.spent_s())
        if state == DONE:
            # retire the checkpoint family: a DONE snapshot left behind
            # would make a tag-reusing resubmission instantly "resume"
            # these counters as a fresh result (the campaign driver's
            # retire-on-done rule). Every other terminal state KEEPS
            # the files: DEADLINE so a larger-deadline resubmission of
            # the tag extends the work, and CANCELLED/FAILED because
            # the tag may name PRE-EXISTING progress this request never
            # touched (a cancelled queued request must not destroy a
            # prior run's partial checkpoint).
            self._unlink_checkpoints(rec)
        rec.done_event.set()
        # bound-portfolio racing hooks (service/portfolio; the lock is
        # an RLock, so the resolution's nested _finalize calls — a
        # member's DONE finalizing the parent, a parent's terminal
        # cancelling queued losers — re-enter here safely)
        if rec.portfolio_parent is not None:
            self.portfolio.on_member_terminal(rec)
        if rec.portfolio_members is not None:
            self.portfolio.on_parent_terminal(rec)

    def _unlink_checkpoints(self, rec: RequestRecord) -> None:
        if not rec.checkpoint_path:
            return
        for suffix in ("", ".prev", ".corrupt"):
            with contextlib.suppress(OSError):
                os.unlink(rec.checkpoint_path + suffix)

    # ---------------------------------------------------------- scheduler

    def _scheduler_loop(self) -> None:
        while not self._closing.is_set():
            self._tick()
            time.sleep(self.poll_s)

    def _tick(self) -> None:
        with self._lock:
            if self._closing.is_set():
                # close() may win the lock between our loop-condition
                # check and here; dispatching now would start a search
                # whose stop_event close() has already swept past —
                # close(wait=True) would then block on the full solve
                return
            if self.fenced:
                # a fenced scheduler tick exits cleanly: nothing may
                # dispatch (every dispatch would journal, and a fenced
                # ledger commits nothing) — the adopter serves instead
                return
            now = time.monotonic()
            # 1. deadline enforcement on running requests. A batched
            # member stops ALONE (the engine honors its stop_reason at
            # the next boundary; the slot event would stop the batch)
            for slot in self.slots:
                for rec in slot.records:
                    if (rec.state == RUNNING
                            and rec.stop_reason is None
                            and rec.over_deadline(now)):
                        rec.stop_reason = "deadline"
                        if slot.batch is None:
                            slot.stop_event.set()
                # the lane ledger's periodic sweep: catches transitions
                # with no dedicated sync site (deadline/cancel stops
                # turning a lane draining, a canceled queue emptying a
                # lane) at scheduler-tick resolution
                self._lane_sync(slot)
            if self.megabatch:
                self._tick_megabatch(now)
                return
            # 2. dispatch to free submeshes. Quarantined slots are held
            # out of the partition; each pop honors the request's
            # excluded-submesh set FOR THIS SLOT (skipped entries stay
            # in line at their position). A request whose exclusions
            # cover EVERY healthy (non-quarantined) slot is eligible
            # anywhere again — trying the least-bad submesh beats
            # stranding it QUEUED forever (exclusions can come to
            # cover the partition later, when a quarantine shrinks it
            # after the add_exclusion cap was applied). With
            # remediation off both filters are vacuous and this is the
            # pre-remediation scheduler exactly.
            healthy = [s.index for s in self.slots
                       if not s.quarantined]

            def eligible_for(idx):
                def ok(r):
                    excl = r.excluded_submeshes
                    return idx not in excl \
                        or all(h in excl for h in healthy)
                return ok

            for slot in self.slots:
                if slot.record is not None or slot.quarantined:
                    continue
                idx = slot.index
                rec = self.queue.pop_best(eligible=eligible_for(idx))
                while (rec is not None and rec.over_deadline(now)
                       and rec.dispatches > 0):
                    # a preempted request can exhaust its compute budget
                    # while waiting in line; its partial result stands.
                    # A NEVER-dispatched request over budget (a resumed
                    # tag whose checkpoint already spent more than the
                    # new deadline) still gets ONE dispatch — it stops
                    # at its first segment boundary with a fresh partial
                    # result, like the legacy campaign worker, instead
                    # of finalizing with no result at all
                    self._finalize(rec, DEADLINE)
                    rec = self.queue.pop_best(
                        eligible=eligible_for(idx))
                if rec is None:
                    continue
                self._dispatch(slot, rec)
            # 3. preemption: highest waiting priority vs running
            # requests. Judged against the actual HEAD RECORD, not just
            # its priority: a free slot only suppresses preemption if
            # the head can USE it (a slot it is excluded from does not
            # help — suppressing on it would priority-invert), and a
            # victim is only worth stopping if its slot is one the head
            # can run on.
            head = self.queue.peek_best()
            if head is None:
                return
            best = head.request.priority
            running = [s.record for s in self.slots
                       if s.record is not None
                       and s.record.state == RUNNING]
            if not running or any(
                    s.record is None and not s.quarantined
                    and eligible_for(s.index)(head)
                    for s in self.slots):
                return
            candidates = [r for r in running
                          if r.stop_reason is None
                          and r.submesh is not None
                          and eligible_for(r.submesh)(head)]
            if not candidates:
                return
            victim = min(candidates,
                         key=lambda r: (r.request.priority,
                                        -(r.started_t or 0.0)))
            if best <= victim.request.priority:
                return
            # don't over-preempt: stops already in flight will free slots
            pending = sum(1 for r in running
                          if r.stop_reason in ("preempt", "deadline",
                                               "cancel"))
            waiting_higher = self.queue.count_priority_above(
                victim.request.priority)
            if waiting_higher <= pending:
                return
            victim.stop_reason = "preempt"
            self._stop_slot_of(victim)

    # ------------------------------------------------------- megabatch
    # (TTS_MEGABATCH: the admission queue becomes a batch-former and a
    # closed batch dispatches to one submesh as ONE vmapped compiled
    # loop — engine/megabatch. The strict-priority preemption pass is
    # a solo-mode feature; megabatch is the throughput mode.)

    def _batch_key(self, rec: RequestRecord) -> tuple:
        """Everything the batched compiled loop specializes on (and the
        segment geometry that must agree for lockstep boundaries) —
        two requests batch together iff these match. Fault-injected
        requests never batch: their injection is scoped to one
        request's executor, and a batch shares one."""
        req = rec.request
        if req.faults is not None or rec.solo_only:
            return ("solo", rec.id)
        return (req.problem, np.asarray(req.p_times).shape,
                req.lb_kind, req.chunk, req.capacity,
                req.balance_period, req.min_seed,
                req.segment_iters or self.segment_iters,
                req.checkpoint_every or self.checkpoint_every)

    def _tick_megabatch(self, now: float) -> None:
        """Steps 2+ of the scheduler tick in megabatch mode (lock
        held): drain the wait line into the former, close ready
        batches onto free healthy submeshes. Submesh exclusions are a
        remediation refinement the batched dispatcher does not honor
        per-slot (a batch of one — the age-closed lone request — goes
        through the ordinary solo path and keeps every solo
        semantic)."""
        while True:
            rec = self.queue.pop_best()
            if rec is None:
                break
            self.former.offer(self._batch_key(rec), rec)
        # the peak-depth high-water must see the former-held wait line
        # (the heap is drained every tick, so it alone would record ~0)
        self.queue.observe_backlog(len(self.former))
        for slot in self.slots:
            if slot.record is not None or slot.quarantined:
                continue
            batch = reason = None
            while batch is None:
                ready = self.former.pop_ready(now)
                if ready is None:
                    break
                cand, reason = ready
                live = []
                for r in cand:
                    if r.over_deadline(now) and r.dispatches > 0:
                        # the solo pop rule: budget exhausted in line,
                        # the partial result stands
                        self._finalize(r, DEADLINE)
                    else:
                        live.append(r)
                batch = live or None
            if batch is None:
                break
            close_t = time.monotonic()
            for r in batch:
                # the queue-wait SLO observes at BATCH-CLOSE: a member
                # held waiting for batchmates (or a free slot) is
                # waiting, and the health engine's queue_wait p99 must
                # see it (the per-request dispatch wait stays visible
                # in snapshots as dispatch_wait_s)
                r.batch_closed_t = close_t
                if r.queued_t:
                    wait = close_t - r.queued_t
                    self._m_queue_wait.observe(
                        wait, tenant=r.request.tenant)
                    if self.capacity is not None:
                        self.capacity.on_queue_wait(r.request.tenant,
                                                    wait)
            self._m_batches.inc(reason=reason)
            self._m_batch_size.observe(len(batch))
            if self.ledger is not None:
                self.ledger.journal("batch", members=[r.id for r in batch],
                                   reason=reason, submesh=slot.index)
            tracelog.event("batch.close", size=len(batch),
                           reason=reason, submesh=slot.index,
                           members=[r.id for r in batch])
            if len(batch) == 1:
                # a lone age-closed request runs the ordinary solo
                # path: exact solo semantics, no batched compile
                self._dispatch(slot, batch[0])
            else:
                self._m_batch_req.inc(len(batch))
                self._dispatch_batch(slot, batch)

    def _dispatch_batch(self, slot: _Slot, recs: list) -> None:
        """Start one executor thread for a closed multi-request batch
        on `slot` (lock held)."""
        bid = f"batch-{next(self._batch_seq):04d}"
        for rec in recs:
            rec.state = RUNNING
            rec.submesh = slot.index
            rec.dispatches += 1
            rec.stop_reason = None
            rec.started_t = time.monotonic()
            rec.last_heartbeat_t = rec.started_t
            rec.dispatch_heartbeats = 0
            rec.batch_id = bid
            if self.ledger is not None:
                self.ledger.journal("dispatch", rid=rec.id,
                                   submesh=slot.index,
                                   dispatch=rec.dispatches,
                                   batch=bid, batch_size=len(recs))
            tracelog.event("request.dispatch", request_id=rec.id,
                           submesh=slot.index, dispatch=rec.dispatches,
                           batch=bid, batch_size=len(recs),
                           queue_depth=len(self.queue))
            if rec.dispatches > 1:
                tracelog.event("request.resume", request_id=rec.id,
                               submesh=slot.index,
                               dispatch=rec.dispatches,
                               preemptions=rec.preemptions,
                               failures=rec.failures)
        slot.record = recs[0]
        slot.batch = list(recs)
        slot.stop_event = threading.Event()
        slot.thread = threading.Thread(
            target=self._execute_batch, args=(slot, list(recs)),
            daemon=True, name=f"tts-service-exec-{slot.index}")
        slot.thread.start()
        self._lane_sync(slot)       # -> compiling

    def _execute_batch(self, slot: _Slot, recs: list) -> None:
        from ..engine import checkpoint, megabatch
        from .. import problems

        req0 = recs[0].request
        p0 = np.asarray(req0.p_times)
        prob = problems.get(req0.problem)
        capacity = req0.capacity or prob.default_capacity(p0)
        evt = slot.stop_event
        bid = recs[0].batch_id
        # the batch key guarantees one shape class for every member
        cap_shape = (self._shape_class(req0)
                     if self.capacity is not None else None)
        if cap_shape is not None:
            self._capacity_seed(cap_shape, p0, req0.lb_kind)

        def hb(b, rep):
            rec = recs[b]
            rec.last_heartbeat_t = time.monotonic()
            rec.dispatch_heartbeats += 1
            if rec.dispatch_heartbeats == 1:
                self._lane_sync(slot)       # compiling -> executing
            if self.capacity is not None and rep.elapsed > 0:
                self.capacity.on_progress(cap_shape,
                                          rep.tree / rep.elapsed)
            self._ledger_budget(rec)
            rec.progress = {
                "segment": rep.segment, "iters": rep.iters,
                "tree": rep.tree, "sol": rep.sol, "best": rep.best,
                "pool": rep.pool_size,
                "elapsed_s": round(rep.elapsed, 3)}
            if rep.telemetry is not None:
                from ..engine import telemetry as tele_mod
                tele_mod.publish(rep.telemetry, self.metrics,
                                 request=rec.id,
                                 tag=rec.request.tag or rec.id,
                                 tenant=rec.request.tenant)
                rec.progress["telemetry"] = {
                    k: rep.telemetry[k] for k in
                    ("pruning_rate", "frontier_depth",
                     "pool_highwater", "steal_sent", "steal_recv",
                     "improvements")}
            self._progress_update(rec, rep)

        def member_stop(b, rep):
            rec = recs[b]
            if rec.stop_reason is not None:
                return True
            if rec.over_deadline():
                rec.stop_reason = "deadline"
                return True
            return False

        handled: set = set()
        # member -> monotonic stamp of its mid-batch freeze: the time
        # from here to batch return is lane time the member's slice of
        # the submesh sat idle waiting for batchmates to drain —
        # tts_batch_drain_idle_seconds, ROADMAP item 2's motivation
        frozen: dict[int, float] = {}

        def on_member_done(b, res):
            # a drained member turns DONE the moment the engine sees
            # its pool empty — its terminal state (and result()) never
            # waits for slower batchmates
            rec = recs[b]
            with self._lock:
                handled.add(b)
                frozen[b] = time.monotonic()
                rec.spent_prev_s = rec.spent_s()
                rec.started_t = None
                rec.result = res
                rec.error = None
                self._finalize(rec, DONE)
            self._lane_sync(slot)           # -> batch-frozen

        def on_member_stopped(b, res):
            # a stopped member (cancel / deadline / member preempt)
            # finalizes AT the boundary its lanes froze, like a solo
            # request would: its result() unblocks, its spent clock
            # stops accruing batch wall time, and it leaves RUNNING so
            # the health stall rule cannot misread frozen lanes as a
            # wedged submesh while batchmates keep exploring
            rec = recs[b]
            requeue = False
            with self._lock:
                if rec.state in TERMINAL_STATES:
                    return
                handled.add(b)
                frozen[b] = time.monotonic()
                rec.spent_prev_s = rec.spent_s()
                rec.started_t = None
                reason = rec.stop_reason
                rec.result = res
                rec.error = None
                if reason == "deadline" or rec.over_deadline():
                    self._finalize(rec, DEADLINE)
                elif reason == "cancel":
                    self._finalize(rec, CANCELLED)
                else:          # preempt / shutdown / whole-batch stop
                    requeue = self._record_preempt(rec, reason)
            self._lane_sync(slot)   # -> batch-frozen (or draining)
            if requeue:
                self.queue.requeue(rec)

        specs = []
        inc_keys = [None] * len(recs)
        if self.incumbents is not None:
            from ..engine import incumbent as inc_mod
            inc_keys = [inc_mod.share_key(
                np.asarray(r.request.p_times),
                problem=r.request.problem,
                group=r.request.share_group) for r in recs]
        for rec, ikey in zip(recs, inc_keys):
            specs.append(megabatch.MemberSpec(
                table=np.asarray(rec.request.p_times),
                init_ub=rec.request.init_ub,
                checkpoint_path=rec.checkpoint_path,
                checkpoint_meta_extra=(lambda rec=rec: {
                    **(rec.request.checkpoint_meta or {}),
                    **self._ckpt_fence_meta(),
                    **({"progress_est": rec.estimator.to_list()}
                       if rec.estimator is not None else {}),
                    "spent_s": round(rec.spent_s(), 2)}),
                incumbent_key=ikey))

        results = error = None
        no_retry = False
        with tracelog.context(request_id=bid, submesh=slot.index):
            try:
                with tracelog.span(
                        "batch.dispatch", batch=len(recs),
                        problem=req0.problem, jobs=int(p0.shape[1]),
                        lb_kind=req0.lb_kind) as sp:
                    results = megabatch.serve_batch(
                        specs, problem=req0.problem,
                        lb_kind=req0.lb_kind, mesh=slot.mesh,
                        chunk=req0.chunk, capacity=capacity,
                        balance_period=req0.balance_period,
                        min_seed=req0.min_seed,
                        segment_iters=(req0.segment_iters
                                       or self.segment_iters),
                        checkpoint_every=(req0.checkpoint_every
                                          or self.checkpoint_every),
                        heartbeat=hb, member_stop=member_stop,
                        on_member_done=on_member_done,
                        on_member_stopped=on_member_stopped,
                        stop_event=evt, loop_cache=self.cache,
                        incumbent_board=self.incumbents,
                        tuner=self.tuner)
                    sp.set(done=sum(1 for r in results
                                    if r is not None and r.complete))
            except megabatch.MemberIncompatible as e:
                # ONE member's resume state cannot batch (legacy
                # checkpoint dtype/telemetry width, cross-problem tag
                # — invisible to the batch key): demote THAT member to
                # the solo path and requeue every batchmate untouched
                # — nobody ran, nobody earned a failure, and a
                # batch-wide FAILED would dead-letter innocents
                tracelog.event("batch.member_incompatible",
                               request_id=recs[e.member].id,
                               batch=bid, reason=str(e))
                with self._lock:
                    recs[e.member].solo_only = True
                    for rec in recs:
                        if rec.state in TERMINAL_STATES:
                            continue
                        rec.spent_prev_s = rec.spent_s()
                        rec.started_t = None
                        rec.state = QUEUED
                        handled.add(recs.index(rec))
                if not self._closing.is_set():
                    for rec in recs:
                        if rec.state == QUEUED:
                            self.queue.requeue(rec)
            except (LeaseLost, checkpoint.StaleCheckpointError) as e:
                # fenced mid-batch: every unhandled member preempts
                # cleanly at this boundary (journals no-op on the
                # fenced ledger) — the solo executor's fence path,
                # batch-wide
                with self._lock:
                    for b, rec in enumerate(recs):
                        if b in handled or rec.state in TERMINAL_STATES:
                            continue
                        rec.spent_prev_s = rec.spent_s()
                        rec.started_t = None
                        self._record_preempt(rec, "fenced")
                        handled.add(b)
                    slot.record = None
                    slot.batch = None
                    slot.stop_event = None
                    slot.thread = None
                    self._lane_sync(slot)   # -> idle
                self._self_fence(f"{type(e).__name__}: {e}")
                return
            except checkpoint.TRANSIENT_ERRORS as e:
                error = f"transient: {e!r}"      # retryable: no_retry
                #                                  stays False
            except Exception as e:  # noqa: BLE001 — FAILED terminal
                error = f"{type(e).__name__}: {e}"
                no_retry = True
            # the measured cost of run-to-drain batching: every
            # mid-batch freeze pays (batch return − freeze) seconds of
            # idle lane share. Observed once per closed batch, before
            # the per-member bookkeeping releases the slot.
            end_t = time.monotonic()
            idle = sum(end_t - t for t in frozen.values())
            if idle > 0:
                self._m_drain_idle.observe(idle)
            self._on_batch_finished(slot, recs, results, error,
                                    handled, no_retry)

    def _on_batch_finished(self, slot: _Slot, recs: list, results,
                           error: str | None, handled: set,
                           no_retry: bool = False) -> None:
        """Per-member terminal/requeue bookkeeping after a batch
        dispatch returns — the batched mirror of `_on_finished`.
        Members the engine already finalized mid-batch (DONE on drain,
        stopped at their boundary — `handled`) are skipped, so a later
        batch-wide error can never smear failure counts onto requests
        that already succeeded or were requeued."""
        requeues = []
        backoff = None
        with self._lock:
            for b, rec in enumerate(recs):
                if b in handled or rec.state in TERMINAL_STATES:
                    continue
                rec.spent_prev_s = rec.spent_s()
                rec.started_t = None
                reason = rec.stop_reason
                if error is not None:
                    if self._handle_dispatch_failure(rec, slot.index,
                                                     error,
                                                     no_retry=no_retry):
                        backoff = backoff_delay(rec.failures - 1,
                                                self.service_retry_base_s)
                        requeues.append(rec)
                    continue
                res = results[b] if results is not None else None
                rec.result = res if res is not None else rec.result
                rec.error = None
                if res is not None and res.complete:
                    self._finalize(rec, DONE)
                elif reason == "deadline" or rec.over_deadline():
                    self._finalize(rec, DEADLINE)
                elif reason == "cancel":
                    self._finalize(rec, CANCELLED)
                elif reason in ("preempt", "shutdown") or evt_set(slot):
                    if self._record_preempt(rec, reason):
                        requeues.append(rec)
                else:
                    self._finalize(
                        rec, FAILED,
                        error="batch member stopped incomplete without "
                              "a stop request (engine bug?)")
        if backoff:
            time.sleep(backoff)
        for rec in requeues:
            self.queue.requeue(rec)
        with self._lock:
            slot.record = None
            slot.batch = None
            slot.stop_event = None
            slot.thread = None
            self._lane_sync(slot)   # -> idle

    def _dispatch(self, slot: _Slot, rec: RequestRecord) -> None:
        """Start one executor thread for `rec` on `slot` (lock held)."""
        rec.state = RUNNING
        rec.submesh = slot.index
        rec.dispatches += 1
        rec.stop_reason = None
        rec.started_t = time.monotonic()
        # the queue-wait SLO observation (admit/requeue -> here) and
        # the stall rule's liveness baseline until the first heartbeat.
        # A batch-of-one dispatch already observed its wait at
        # batch-close (batch_closed_t set) — observing again would
        # double-count the member
        if rec.queued_t and rec.batch_closed_t is None:
            wait = rec.started_t - rec.queued_t
            self._m_queue_wait.observe(wait, tenant=rec.request.tenant)
            if self.capacity is not None:
                self.capacity.on_queue_wait(rec.request.tenant, wait)
        rec.last_heartbeat_t = rec.started_t
        rec.dispatch_heartbeats = 0     # this dispatch warms afresh
        # (stall judges it against the warmup threshold until the
        # engine heartbeats — a resume on a cold submesh pays a compile)
        rec.batch_id = None             # THIS dispatch is solo; a
        # stale id from an earlier batched dispatch would contradict
        # the slot's own (null) batch field in snapshots
        if self.ledger is not None:
            self.ledger.journal("dispatch", rid=rec.id,
                               submesh=slot.index,
                               dispatch=rec.dispatches)
        tracelog.event("request.dispatch", request_id=rec.id,
                       submesh=slot.index, dispatch=rec.dispatches,
                       queue_depth=len(self.queue))
        if rec.dispatches > 1:
            # re-dispatch of preempted/failed work — the flight
            # recorder's "resume" marker the span-sequence tests assert
            tracelog.event("request.resume", request_id=rec.id,
                           submesh=slot.index, dispatch=rec.dispatches,
                           preemptions=rec.preemptions,
                           failures=rec.failures)
        slot.record = rec
        slot.stop_event = threading.Event()
        slot.thread = threading.Thread(
            target=self._execute, args=(slot, rec), daemon=True,
            name=f"tts-service-exec-{slot.index}")
        slot.thread.start()
        self._lane_sync(slot)       # -> compiling

    # ----------------------------------------------------------- executor

    def _execute(self, slot: _Slot, rec: RequestRecord) -> None:
        from ..engine import checkpoint, distributed

        req = rec.request
        p = np.asarray(req.p_times)
        from .. import problems
        prob = problems.get(req.problem)
        jobs, machines = prob.slots(p), p.shape[0]
        capacity = req.capacity or prob.default_capacity(p)
        evt = slot.stop_event
        # phase attribution prices the PFSP kernels; other problems
        # skip it rather than publish numbers measured on the wrong
        # pipeline
        unit_costs = (self._unit_costs(req)
                      if self.phase_profile is not None
                      and req.problem == "pfsp" else None)
        cap_shape = None
        if self.capacity is not None:
            cap_shape = self._shape_class(req)
            self._capacity_seed(cap_shape, p, req.lb_kind)

        def hb(rep):
            rec.last_heartbeat_t = time.monotonic()
            rec.dispatch_heartbeats += 1
            if rec.dispatch_heartbeats == 1:
                self._lane_sync(slot)   # compiling -> executing
            if self.capacity is not None and rep.elapsed > 0:
                self.capacity.on_progress(cap_shape,
                                          rep.tree / rep.elapsed)
            # durable budget clock: throttled inside (a hard kill loses
            # at most LEDGER_BUDGET_EVERY_S of spent_s, never the
            # request — the checkpoint meta is the second witness)
            self._ledger_budget(rec)
            rec.progress = {
                "segment": rep.segment, "iters": rep.iters,
                "tree": rep.tree, "sol": rep.sol, "best": rep.best,
                "pool": rep.pool_size,
                "elapsed_s": round(rep.elapsed, 3)}
            if rep.telemetry is not None:
                # on-device search telemetry (TTS_SEARCH_TELEMETRY):
                # per-request labeled gauges in the server registry —
                # pruning efficiency scrapeable from /metrics without
                # opening the trace (series retire with the request,
                # see _finalize) — and the compact rates in the
                # progress snapshot
                from ..engine import telemetry as tele_mod
                tele_mod.publish(rep.telemetry, self.metrics,
                                 request=rec.id, tag=req.tag or rec.id,
                                 tenant=req.tenant)
                rec.progress["telemetry"] = {
                    k: rep.telemetry[k] for k in
                    ("pruning_rate", "frontier_depth",
                     "pool_highwater", "steal_sent", "steal_recv",
                     "improvements")}
            self._progress_update(rec, rep)
            if unit_costs is not None and rep.per_worker is not None:
                self._publish_phases(rec, rep, unit_costs)

        # per-request fault injection stays thread-scoped: it must not
        # leak into requests concurrently served on other submeshes.
        # The plan object is parsed ONCE per request and reused across
        # redispatches so its injection budgets span the request's
        # lifetime (see RequestRecord.fault_plan)
        if req.faults is not None and rec.fault_plan is None:
            rec.fault_plan = faults.FaultPlan.parse(req.faults)
        scope = (faults.scoped(rec.fault_plan)
                 if req.faults is not None
                 else contextlib.nullcontext())
        res = error = None
        # every record the engine emits from this thread (segment spans,
        # checkpoint saves, retries, injected faults) carries the
        # request/submesh identity via the recorder's ambient context
        with tracelog.context(request_id=rec.id, submesh=slot.index):
            try:
                with scope, tracelog.span(
                        "request.execute", dispatch=rec.dispatches,
                        problem=req.problem,
                        jobs=jobs, machines=machines,
                        lb_kind=req.lb_kind) as ex_span:
                    inc_key = None
                    if self.incumbents is not None:
                        from ..engine import incumbent as inc_mod
                        # problem-aware namespacing lives in ONE place
                        # (incumbent.share_key): two problems with
                        # bit-identical tables never exchange bounds
                        inc_key = inc_mod.share_key(
                            p, problem=req.problem,
                            group=req.share_group)
                    res = distributed.search(
                        p, problem=req.problem,
                        lb_kind=req.lb_kind, init_ub=req.init_ub,
                        mesh=slot.mesh, chunk=req.chunk,
                        capacity=capacity,
                        balance_period=req.balance_period,
                        min_seed=req.min_seed,
                        segment_iters=(req.segment_iters
                                       or self.segment_iters),
                        checkpoint_path=rec.checkpoint_path,
                        checkpoint_every=(req.checkpoint_every
                                          or self.checkpoint_every),
                        heartbeat=hb, stop_event=evt,
                        loop_cache=self.cache,
                        overlap=self.overlap,
                        # adaptive dispatch: open knobs (chunk=None /
                        # balance_period=None) resolve via the tuning
                        # cache or the defaults table inside search()
                        tuner=self.tuner,
                        incumbent_board=self.incumbents,
                        incumbent_key=inc_key,
                        # cumulative execution clock rides every
                        # checkpoint (the legacy campaign worker's
                        # spent_s key), so budgets survive preemption,
                        # server restarts and legacy<->serve handoffs
                        checkpoint_meta_extra=lambda: {
                            **(req.checkpoint_meta or {}),
                            # fencing: raises LeaseLost / stamps the
                            # epoch so a stale owner's save can never
                            # land over the adopter's (vacuous outside
                            # fleet mode)
                            **self._ckpt_fence_meta(),
                            # estimator continuity: the same rule as
                            # spent_s — a resume seeds from this vector
                            **({"progress_est":
                                rec.estimator.to_list()}
                               if rec.estimator is not None else {}),
                            "spent_s": round(rec.spent_s(), 2)})
                    ex_span.set(tree=res.explored_tree, best=res.best,
                                complete=res.complete)
            except (LeaseLost, checkpoint.StaleCheckpointError) as e:
                # fenced mid-dispatch (an adopter bumped our epoch):
                # stop cleanly at this boundary — PREEMPTED with the
                # journal no-op'ing on the fenced ledger, never FAILED.
                # The adopter re-admitted the request from the ledger;
                # our copy is a husk the operator restarts around.
                with self._lock:
                    rec.spent_prev_s = rec.spent_s()
                    rec.started_t = None
                    if rec.state not in TERMINAL_STATES:
                        self._record_preempt(rec, "fenced")
                    slot.record = None
                    slot.stop_event = None
                    slot.thread = None
                    self._lane_sync(slot)   # -> idle
                self._self_fence(f"{type(e).__name__}: {e}")
                return
            except checkpoint.TRANSIENT_ERRORS as e:
                error = f"transient: {e!r}"
            except Exception as e:  # noqa: BLE001 — FAILED terminal below
                error = f"{type(e).__name__}: {e}"
                rec.failures = self.service_retry_attempts + 1  # no retry
            self._on_finished(slot, rec, res, error)

    def _unit_costs(self, req) -> dict | None:
        """Resolve the phase-attribution unit costs for `req` (see the
        `phase_profile` constructor knob): a shared dict is used as-is;
        True measures utils/phase_timing.profile_phases once per
        (shape, lb, chunk) and caches it for every later request.
        Open-knob (tuned) requests profile at the chunk dispatch will
        actually resolve — never at None."""
        if isinstance(self.phase_profile, dict):
            return self.phase_profile
        p = np.asarray(req.p_times)
        chunk = req.chunk
        if chunk is None:
            chunk = self._resolved_chunk(p, req.lb_kind)
        key = (p.shape, req.lb_kind, chunk)
        with self._lock:
            prof = self._prof_cache.get(key)
        if prof is not None:
            return prof
        from ..engine import device
        from ..ops import batched
        from ..utils import phase_timing
        try:
            with tracelog.span("phase_profile", jobs=p.shape[1],
                               lb_kind=req.lb_kind, chunk=chunk):
                tables = batched.make_tables(p)
                state = device.init_state(
                    p.shape[1], max(1 << 12, 4 * chunk * p.shape[1]),
                    req.init_ub, p_times=p)
                prof = phase_timing.profile_phases(
                    tables, state, req.lb_kind, chunk, warm_iters=4)
        except Exception as e:  # noqa: BLE001 — attribution is an
            # observability extra; its failure must never fail a request
            tracelog.event("phase_profile.failed", error=repr(e))
            prof = None
        with self._lock:
            self._prof_cache[key] = prof
        return prof

    def _resolved_chunk(self, p: np.ndarray, lb_kind: int) -> int:
        """The chunk an open-knob request resolves to at dispatch —
        the tuner's cache-or-defaults tier, mirrored here so anything
        that needs the concrete value BEFORE dispatch (phase
        profiling) sees the same number the engine will run."""
        if self.tuner is not None:
            try:
                return self.tuner.resolve(
                    p.shape[1], p.shape[0], lb_kind,
                    n_workers=self.slots[0].mesh.devices.size).chunk
            except Exception:  # noqa: BLE001 — fall to the table
                pass
        from ..tune import defaults as tune_defaults
        return tune_defaults.params_for("serving", p.shape[1],
                                        p.shape[0]).chunk

    def _publish_phases(self, rec: RequestRecord, rep, prof: dict) -> None:
        """Heartbeat hook: attribute the request's CUMULATIVE execution
        clock across kernel/genchild/balance/idle from its per-worker
        counters and publish tts_phase_seconds gauges — the live view of
        the attribution that used to exist only in end-of-run CSVs."""
        from ..utils import phase_timing
        att = phase_timing.attribute(
            prof, elapsed=rec.spent_s(),
            evals=rep.per_worker["evals"], iters=rep.per_worker["iters"])
        phase_timing.publish_attribution(att, registry=self.metrics,
                                         request=rec.id,
                                         tenant=rec.request.tenant)

    def _on_finished(self, slot: _Slot, rec: RequestRecord,
                     res, error: str | None) -> None:
        requeue = backoff = None
        with self._lock:
            rec.spent_prev_s = rec.spent_s()
            rec.started_t = None
            reason = rec.stop_reason
            if error is not None:
                # failure_log append, journal, trace event, remediation
                # verdict and requeue/deadletter/FAILED arbitration all
                # live in _handle_dispatch_failure (shared with the
                # batched finish path). On requeue the slot cools down
                # for the backoff, then the scheduler may re-dispatch
                # to a DIFFERENT submesh (the checkpoint, when one was
                # written, reshards elastically)
                if self._handle_dispatch_failure(rec, slot.index,
                                                 error):
                    backoff = backoff_delay(rec.failures - 1,
                                            self.service_retry_base_s)
                    requeue = rec
            else:
                rec.result = res
                rec.error = None     # a recovered transient is not an error
                if res.complete:
                    self._finalize(rec, DONE)
                elif reason == "deadline" or rec.over_deadline():
                    self._finalize(rec, DEADLINE)
                elif reason == "cancel":
                    self._finalize(rec, CANCELLED)
                elif reason in ("preempt", "shutdown") or evt_set(slot):
                    if self._record_preempt(rec, reason):
                        requeue = rec
                else:
                    self._finalize(
                        rec, FAILED,
                        error="search stopped incomplete without a stop "
                              "request (engine bug?)")
        if backoff:
            time.sleep(backoff)
        if requeue is not None:
            self.queue.requeue(requeue)
        with self._lock:
            slot.record = None
            slot.stop_event = None
            slot.thread = None
            self._lane_sync(slot)   # -> idle


class _ReplayedResult:
    """Duck-typed stand-in for a DistResult, rebuilt from a ledger
    terminal snapshot — enough surface for RequestRecord.snapshot()
    and in-process `result()` readers (per-worker spreads are not
    journaled; `per_device` replays empty)."""

    def __init__(self, d: dict):
        self.best = int(d.get("best") or 0)
        self.explored_tree = int(d.get("explored_tree") or 0)
        self.explored_sol = int(d.get("explored_sol") or 0)
        self.complete = bool(d.get("complete"))
        self.per_device: dict = {}


def evt_set(slot: _Slot) -> bool:
    evt = slot.stop_event
    return evt is not None and evt.is_set()
