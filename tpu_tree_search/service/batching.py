"""The batch-former: the admission queue's megabatch front.

Under ``TTS_MEGABATCH`` the scheduler stops popping one request per
free submesh and instead drains the wait line into this former, which
groups requests by their BATCH KEY — problem, instance-table shape,
lb_kind and every engine knob the compiled batched loop specializes on
(chunk, capacity, balance/segment geometry). A group CLOSES (becomes a
dispatchable batch) when it reaches ``TTS_BATCH_MAX`` members or its
oldest member has waited ``TTS_BATCH_AGE_S`` seconds — the classic
size-or-age continuous-batching rule, so a burst of same-class traffic
fills batches immediately while a lone request is delayed by at most
the age bound (and then runs the ordinary solo path as a batch of
one).

The former holds RequestRecords that are already admitted (the queue
popped them); cancellation/deadline while held is handled lazily at
close time, exactly like the queue's stale-head pruning. Priority
ordering is preserved within a group (members keep their heap order)
and across groups (the oldest-member clock breaks ties); the
strict-priority PREEMPTION pass stays a solo-mode feature — megabatch
is the throughput mode, and a batch is not preemptible member-by-member
mid-segment anyway (stops land at segment boundaries for every member
alike).
"""

from __future__ import annotations

import time

from .request import PREEMPTED, QUEUED, RequestRecord


class BatchFormer:
    """Groups admitted requests into closeable batches. NOT thread-safe
    on its own — the server drives it under its scheduler lock, the
    same discipline as every other scheduler structure."""

    def __init__(self, max_size: int, age_s: float):
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        self.max_size = int(max_size)
        self.age_s = float(age_s)
        # key -> list of (enter_t, RequestRecord), oldest first
        self._groups: dict[tuple, list] = {}

    def __len__(self) -> int:
        # list() snapshot: the depth gauge reads this at scrape time
        # without the scheduler lock; an approximate count during a
        # concurrent offer/close is fine, a RuntimeError is not
        return sum(len(g) for g in list(self._groups.values()))

    def offer(self, key: tuple, rec: RequestRecord) -> None:
        """Hold one popped request under its batch key."""
        self._groups.setdefault(key, []).append((time.monotonic(), rec))

    def _prune(self, group: list) -> list:
        """Drop members that went stale while held (cancelled in line,
        deadline-expired handling is the server's at close time)."""
        return [(t, r) for t, r in group
                if r.state in (QUEUED, PREEMPTED)]

    def _take(self, key: tuple, reason: str
              ) -> tuple[list[RequestRecord], str]:
        """Close up to max_size members off a group (oldest first);
        the remainder stays in line with its entry times."""
        group = self._groups[key]
        batch, rest = group[:self.max_size], group[self.max_size:]
        if rest:
            self._groups[key] = rest
        else:
            del self._groups[key]
        return [r for _, r in batch], reason

    def pop_ready(self, now: float | None = None
                  ) -> tuple[list[RequestRecord], str] | None:
        """The next closeable batch as ``(members, reason)`` — reason
        ``"age"`` (the group's oldest member waited past age_s) or
        ``"size"`` (it hit max_size) — or None when nothing closes
        yet. AGE-ready groups outrank size-ready ones, oldest member
        first: the age bound is a latency promise, size-closure only a
        throughput optimization — sustained traffic in one shape class
        must not starve an aged group of another class indefinitely
        (a size-first rule would, and the starved member's queue-wait
        observation only lands at close, so the SLO could not even see
        it). Every closure trims to max_size (an age-closed group may
        have grown past it between calls)."""
        if now is None:
            now = time.monotonic()
        aged = aged_t = None
        sized = None
        for key in list(self._groups):
            group = self._prune(self._groups[key])
            if not group:
                del self._groups[key]
                continue
            self._groups[key] = group
            oldest = group[0][0]
            if now - oldest >= self.age_s and (
                    aged_t is None or oldest < aged_t):
                aged, aged_t = key, oldest
            elif sized is None and len(group) >= self.max_size:
                sized = key
        if aged is not None:
            return self._take(aged, "age")
        if sized is not None:
            return self._take(sized, "size")
        return None

    def waiting_ids(self) -> list[str]:
        """Held request ids (status snapshots)."""
        return [r.id for g in self._groups.values() for _, r in g]

    def drain(self) -> list[RequestRecord]:
        """Every held live request, surrendered (server shutdown: held
        members must be cancelled or re-queued, never forgotten)."""
        out = [r for g in self._groups.values()
               for _, r in self._prune(g)]
        self._groups.clear()
        return out
