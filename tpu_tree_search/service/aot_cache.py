"""Disk-persistent AOT executable cache: zero-compile cold start.

The ExecutorCache (service/executors.py) makes compiles a once-per-key
cost *within* a server lifetime; this module makes them a once-per-key
cost *across* lifetimes. A restarted or freshly autoscaled SearchServer
deserializes the compiled SPMD loop from disk (~0.2 s on the CPU test
mesh) instead of re-tracing and re-compiling it (seconds to minutes) —
the same shape-of-win a serving stack gets from a persistent compilation
cache, and the jit-world equivalent of the reference engine paying its
CUDA kernel load once per binary. The compile-storm a redeploy used to
be becomes a directory of file reads.

Serialization rides the jit AOT path: the executor's first compile goes
through ``fn.lower(...).compile()`` already (the PR-5 ledger), and the
resulting ``jax.stages.Compiled`` round-trips through
``jax.experimental.serialize_executable`` (the pickle form of
``jax.export``'s executable serialization on this pin — the loaded
program performs ZERO ``lower()``/``compile()`` calls). Not every
backend/pin can round-trip a program, so :func:`probe` compiles and
reloads a trivial jitted function ONCE per process; when it fails, the
cache degrades to in-memory-only (the pre-PR-8 behavior) instead of
serving maybe-wrong bytes.

Safety model — a stale entry can never load into the wrong runtime:

- **Key**: the file name is a digest of the FULL ExecutorCache key
  (problem kind, shape, bound, chunk, aux dtype, submesh device ids,
  capacity, balance knobs, row limit, donation variant) — everything
  the trace specializes on.
- **Fingerprint**: each entry's header embeds :func:`runtime_fingerprint`
  (jax/jaxlib versions, platform, device topology/kind, process count,
  telemetry block width) and is IGNORED on mismatch — the telemetry
  flag changes the traced state shapes without changing the key, and a
  jaxlib bump invalidates the serialized executable wholesale.
- **Integrity**: entries are written with the checkpoint layer's
  discipline — temp file + fsync + atomic rename, a CRC32 stamp over
  the payload — and a corrupt/truncated entry is QUARANTINED (renamed
  ``*.corrupt``, never loaded, counted) and recompiled, mirroring
  ``checkpoint.load_resilient``.
- **Hot path**: persistence happens on a single bounded-queue writer
  thread (the ``AsyncCheckpointWriter`` pattern from PR 7) — the
  serving thread never waits on serialize + fsync; ``drain()`` exists
  for tests and shutdown.

Observability: ``tts_aot_cache_{hits,misses,errors}_total`` counters and
a ``tts_deserialize_seconds`` histogram when a registry is supplied;
``snapshot()`` rides ``status_snapshot()``'s ``aot_cache`` key (the
``doctor`` CLI surfaces it); the executor ledger records per-entry
``source=disk|compile`` and ``deserialize_s``
(tools/compile_report.py renders both).
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import pickle
import queue
import struct
import threading
import time
import zlib

from ..obs import tracelog
from ..utils import config as cfg

__all__ = ["AOTCache", "probe", "runtime_fingerprint"]

MAGIC = b"TTSAOT1\n"
_HDR_LEN = struct.Struct("<Q")
QUARANTINE_SUFFIX = ".corrupt"

_probe_lock = threading.Lock()
_probe_result: bool | None = None


def runtime_fingerprint(extra: dict | None = None) -> dict:
    """Everything OUTSIDE the ExecutorCache key that a serialized
    executable depends on. Two processes whose fingerprints differ must
    never exchange entries: the bytes encode the XLA version's program
    format, the device assignment, and state shapes the static
    telemetry flag bakes in."""
    import jax
    import jaxlib

    from ..engine import telemetry as tele

    devices = jax.devices()
    fp = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device_count": len(devices),
        "device_kinds": sorted({d.device_kind for d in devices}),
        "process_count": jax.process_count(),
        # static compile-in flags: they change the traced state
        # SHAPES/dtypes without appearing in the executor key —
        # telemetry width (zero-width leaf when off) and x64 (the
        # counter block and max_iters are int64-or-int32 with it)
        "telemetry_width": tele.enabled_width(),
        "x64": bool(jax.config.jax_enable_x64),
    }
    if extra:
        fp.update(extra)
    return fp


def probe() -> bool:
    """ONE per-process capability check: can this jax/backend pin
    round-trip a compiled program through serialize + deserialize and
    still execute it? False => the cache must stay in-memory-only
    (callers construct no AOTCache); never raises."""
    global _probe_result
    with _probe_lock:
        if _probe_result is None:
            _probe_result = _probe_impl()
        return _probe_result


def _probe_impl() -> bool:
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import serialize_executable as se

        fn = jax.jit(lambda x: x * 2 + 1)
        x = jnp.arange(4, dtype=jnp.int32)
        compiled = fn.lower(x).compile()
        payload, in_tree, out_tree = se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
        loaded = se.deserialize_and_load(*pickle.loads(blob))
        ok = bool((loaded(x) == compiled(x)).all())
    except Exception as e:  # noqa: BLE001 — any failure means "cannot"
        tracelog.event("aot_cache.probe", supported=False, error=repr(e))
        return False
    tracelog.event("aot_cache.probe", supported=ok)
    return ok


def _key_digest(key: tuple) -> str:
    """Stable digest of an ExecutorCache key (tuples of scalars by
    construction). The FINGERPRINT deliberately stays out of the name:
    the header check is what rejects a wrong-runtime entry, so a runtime
    upgrade OVERWRITES stale entries at the same path instead of
    stranding them forever."""
    raw = json.dumps([str(k) for k in key]).encode()
    return hashlib.sha256(raw).hexdigest()[:32]


class AOTCache:
    """Disk tier under the ExecutorCache. ``load(key)`` returns a ready
    ``jax.stages.Compiled`` (or None); ``store(key, compiled)`` queues
    persistence on the writer thread. Construct only when :func:`probe`
    says the pin can round-trip (the server does this gating)."""

    ENTRIES_TTL_S = 5.0   # entries() rescans the dir at most this often

    def __init__(self, root: str | os.PathLike, registry=None,
                 fingerprint_extra: dict | None = None,
                 max_pending: int | None = None):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.fingerprint = runtime_fingerprint(fingerprint_extra)
        self.hits = 0            # guarded-by: self._lock
        self.misses = 0          # guarded-by: self._lock
        #                          (no entry on disk for the key)
        self.mismatches = 0      # guarded-by: self._lock
        #                          (entry present, wrong-runtime header)
        self.errors = 0          # guarded-by: self._lock
        #                          (corrupt/unreadable/unserializable)
        self.quarantined = 0     # guarded-by: self._lock
        self.writes = 0          # guarded-by: self._lock
        # deliberately UNguarded (atomic tuple swap, staleness is fine
        # for a stats field): see entries()
        self._entries_cache: tuple | None = None
        self._lock = threading.Lock()
        self._hits_c = self._misses_c = self._errors_c = None
        self._deser_h = None
        if registry is not None:
            self._hits_c = registry.counter(
                "tts_aot_cache_hits_total",
                "executables deserialized from the disk AOT cache "
                "(zero compiles paid)")
            self._misses_c = registry.counter(
                "tts_aot_cache_misses_total",
                "disk AOT cache lookups with no loadable entry "
                "(absent or wrong-runtime fingerprint)")
            self._errors_c = registry.counter(
                "tts_aot_cache_errors_total",
                "corrupt/unreadable/unserializable AOT cache entries "
                "(corrupt ones are quarantined, never loaded)")
            self._deser_h = registry.histogram(
                "tts_deserialize_seconds",
                "disk AOT cache deserialize+load wall seconds per hit")
        # single FIFO writer thread, bounded queue: persistence stays
        # off the serving thread; a serve burst outrunning the disk
        # blocks in store() rather than buffering unbounded payloads
        # (the AsyncCheckpointWriter discipline — writes are one per
        # fresh compile, so the bound is essentially never felt)
        self._q: queue.Queue = queue.Queue(
            maxsize=max_pending or cfg.AOT_WRITER_QUEUE_DEPTH)
        self._closed = False     # guarded-by: self._close_lock
        # makes store()'s closed-check + enqueue atomic against
        # close(): without it a racing store() could enqueue AFTER the
        # shutdown sentinel — its task_done never runs, so a later
        # drain() (q.join) would hang forever. The writer thread never
        # takes this lock, so a store() blocked on the bounded queue
        # while holding it still drains (close() just waits its turn).
        self._close_lock = threading.Lock()
        self._thread = threading.Thread(target=self._writer_loop,
                                        daemon=True,
                                        name="tts-aot-writer")
        self._thread.start()

    # ---------------------------------------------------------- paths

    def path_for(self, key: tuple) -> pathlib.Path:
        return self.root / f"{_key_digest(key)}.aot"

    # ----------------------------------------------------------- load

    def load(self, key: tuple):
        """Deserialize the entry for `key`, or None. Returns
        ``(compiled, deserialize_s)`` on a hit. Never raises: a corrupt
        entry is quarantined + counted, a wrong-fingerprint entry is
        ignored + counted, and the caller compiles as if the cache
        were empty."""
        path = self.path_for(key)
        t0 = time.perf_counter()
        try:
            blob = path.read_bytes()
        except FileNotFoundError:
            self._count("_misses_c", "misses")
            return None
        except OSError as e:
            # an entry that EXISTS but cannot be read (EACCES, EIO on
            # a failing mount) is an ERROR, not a miss: booking it as
            # a miss would leave an operator staring at a dir full of
            # entries, misses incrementing, and zero error signal
            self._count("_errors_c", "errors")
            tracelog.event("aot_cache.read_error", path=path.name,
                           error=repr(e))
            return None
        # timer spans the WHOLE hit cost — on fleet/network storage the
        # read of a multi-MB entry can dominate validate+load, and an
        # operator debugging a slow warm restart needs the real number
        payload = self._validate(path, blob)
        if payload is None:
            return None
        try:
            from jax.experimental import serialize_executable as se
            compiled = se.deserialize_and_load(*pickle.loads(payload))
        except Exception as e:  # noqa: BLE001 — bytes are CRC-clean but
            # the runtime rejects them (a drift the fingerprint missed):
            # this entry will never load better, quarantine it
            self._quarantine(path, f"deserialize failed: {e!r}")
            return None
        dt = time.perf_counter() - t0
        self._count("_hits_c", "hits")
        if self._deser_h is not None:
            self._deser_h.observe(dt)
        tracelog.event("aot_cache.hit", path=path.name,
                       deserialize_s=round(dt, 6))
        return compiled, dt

    def _validate(self, path: pathlib.Path, blob: bytes) -> bytes | None:
        """Header + CRC discipline; returns the payload or None (counted
        and, for corruption, quarantined)."""
        try:
            if blob[:len(MAGIC)] != MAGIC:
                raise ValueError("bad magic")
            off = len(MAGIC)
            (hdr_len,) = _HDR_LEN.unpack_from(blob, off)
            off += _HDR_LEN.size
            header = json.loads(blob[off:off + hdr_len].decode())
            off += hdr_len
            payload = blob[off:]
            if len(payload) != int(header["payload_len"]):
                raise ValueError("truncated payload")
            if zlib.crc32(payload) != int(header["payload_crc32"]):
                raise ValueError("payload CRC mismatch")
        except Exception as e:  # noqa: BLE001 — torn/truncated/garbled
            self._quarantine(path, repr(e))
            return None
        if header.get("fingerprint") != self.fingerprint:
            # a DIFFERENT runtime's entry (jax bump, topology change,
            # telemetry flag flip): valid bytes, wrong world — ignore
            # it (this runtime's compile will overwrite it) but never
            # load it
            with self._lock:
                self.mismatches += 1
            self._count("_misses_c", "misses")
            tracelog.event("aot_cache.mismatch", path=path.name,
                           theirs=header.get("fingerprint"),
                           ours=self.fingerprint)
            return None
        return payload

    def _quarantine(self, path: pathlib.Path, error: str) -> None:
        self._count("_errors_c", "errors")
        # per-writer unique target (same discipline as store()'s temp
        # name): N processes quarantining corrupt incarnations of the
        # SAME entry must not os.replace over each other's forensic
        # copy — the suffix stays last so sweeps/tests keep matching.
        # The existence loop is raceless: only THIS thread mints names
        # under this pid-tid prefix
        base = f"{path.name}.{os.getpid()}-{threading.get_ident()}"
        qpath = str(path.with_name(base + QUARANTINE_SUFFIX))
        n = 0
        while os.path.exists(qpath):
            n += 1
            qpath = str(path.with_name(f"{base}.{n}{QUARANTINE_SUFFIX}"))
        try:
            os.replace(path, qpath)
            with self._lock:
                self.quarantined += 1
            self._entries_cache = None   # one fewer .aot on disk
        except OSError:
            qpath = None
        tracelog.event("aot_cache.quarantine", path=path.name,
                       quarantined_to=qpath, error=error)

    # ---------------------------------------------------------- store

    def store(self, key: tuple, compiled, key_repr: str = "") -> None:
        """Queue persistence of a freshly compiled executable (writer
        thread does serialize + CRC + atomic write). Serialization
        failures are counted, never raised — a program the pin cannot
        serialize still serves from memory."""
        with self._close_lock:
            if self._closed:
                return
            self._q.put({"path": self.path_for(key),
                         "compiled": compiled, "key_repr": key_repr})

    def drain(self) -> None:
        """Block until every queued entry is on disk (tests/shutdown)."""
        self._q.join()

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)
        self._thread.join()

    def _writer_loop(self) -> None:
        while True:
            task = self._q.get()
            try:
                if task is None:
                    return
                self._write(task)
            except Exception as e:  # noqa: BLE001 — persistence is an
                # optimization; its failure must never kill the writer
                self._count("_errors_c", "errors")
                tracelog.event("aot_cache.store_failed", error=repr(e))
            finally:
                self._q.task_done()

    def _write(self, task: dict) -> None:
        from jax.experimental import serialize_executable as se
        path: pathlib.Path = task["path"]
        try:
            payload = pickle.dumps(se.serialize(task["compiled"]))
        except Exception as e:  # noqa: BLE001 — per-program capability:
            # the probe passing does not guarantee EVERY program
            # round-trips on this pin; fall back to in-memory-only for
            # this entry
            self._count("_errors_c", "errors")
            tracelog.event("aot_cache.serialize_unsupported",
                           key=task["key_repr"], error=repr(e))
            return
        header = json.dumps({
            "v": 1, "fingerprint": self.fingerprint,
            "key": task["key_repr"], "created_unix": time.time(),
            "payload_len": len(payload),
            "payload_crc32": zlib.crc32(payload),
        }).encode()
        # unique per-writer temp name: two processes sharing one cache
        # dir (the autoscale fleet scenario) both compiling this key
        # must not interleave bytes in a shared temp file — each
        # renames its OWN complete entry; last replace wins, both valid
        tmp = path.with_name(
            f".{path.name}.{os.getpid()}-{threading.get_ident()}.tmp")
        try:
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                f.write(_HDR_LEN.pack(len(header)))
                f.write(header)
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers see old bytes
            #                        or new, never a torn mix
            self._entries_cache = None   # count may have changed
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            self.writes += 1
        tracelog.event("aot_cache.store", path=path.name,
                       bytes=len(payload), key=task["key_repr"])

    # ----------------------------------------------------------- read

    def _count(self, counter_attr: str, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)
        c = getattr(self, counter_attr)
        if c is not None:
            c.inc()

    def entries(self) -> int:
        """Entry-file count, rescanned at most every ENTRIES_TTL_S:
        /status polls at 1 Hz must not pay a directory scan each time
        on slow fleet storage (the count only moves on writes, plus
        other processes sharing the dir — a few seconds stale is fine
        for a stats field)."""
        now = time.monotonic()
        cached = self._entries_cache
        if cached is not None and now - cached[0] < self.ENTRIES_TTL_S:
            return cached[1]
        try:
            n = sum(1 for p in self.root.iterdir()
                    if p.suffix == ".aot")
        except OSError:
            n = 0
        self._entries_cache = (now, n)
        return n

    def snapshot(self) -> dict:
        """JSON-safe stats — status_snapshot()'s `aot_cache` key (the
        doctor CLI surfaces it per server)."""
        # the directory listing can be slow on fleet/network storage:
        # keep it OUTSIDE the stats lock the load/store paths need
        n_entries = self.entries()
        with self._lock:
            return {"dir": str(self.root), "entries": n_entries,
                    "hits": self.hits, "misses": self.misses,
                    "mismatches": self.mismatches,
                    "errors": self.errors,
                    "quarantined": self.quarantined,
                    "writes": self.writes}
