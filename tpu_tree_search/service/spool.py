"""File-spool front-end for the search service.

The transport-free way to talk to a `SearchServer` from another process:
clients drop ``<id>.req.json`` files into a spool directory, the serving
process ingests them and writes ``<id>.res.json`` when the request turns
terminal. No sockets, no wire protocol to version — the same pattern as
the campaign driver's status files, and it composes with any batch
system that can touch a shared filesystem. (A real HTTP front-end is a
ROADMAP follow-on; it would sit exactly where this module sits.)

Request JSON::

    {"inst": 21,                 # Taillard id — OR "p_times": [[...]]
     "problem": "pfsp",          # workload plugin (problems/base.py):
                                 # pfsp | nqueens | tsp | knapsack;
                                 # p_times is that problem's table
     "lb": 1, "ub": "opt",       # ub: "opt" | integer | null
     "priority": 0, "deadline_s": null,
     "chunk": 64, "capacity": null, "tag": null,
     "tuned": false}             # true: leave chunk/balance_period to
                                 # the server's tuner (tune/tuner.py)

Result JSON: the request's final `RequestRecord.snapshot()` plus the
spool id. Writes on both sides are atomic (tmp + rename) so a reader
never sees a torn file.
"""

from __future__ import annotations

import itertools
import json
import os
import pathlib
import time

import numpy as np

from .request import SearchRequest

REQ_SUFFIX = ".req.json"
RES_SUFFIX = ".res.json"

# default spool ids: timestamp + pid + per-process counter — two
# submissions in the same millisecond must not collide (the second
# would overwrite the first's request file and be silently dropped)
_spool_seq = itertools.count()


def _atomic_write_json(path: pathlib.Path, payload: dict) -> None:
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps(payload, indent=1))
    os.replace(tmp, path)


def request_from_payload(payload: dict) -> SearchRequest:
    """Build a SearchRequest from a spool request dict. `problem`
    (default "pfsp") names the workload plugin; `p_times` is that
    problem's 2-D instance table (problems/base.py documents the
    per-problem format). `inst` (a Taillard id) is PFSP-only."""
    problem = str(payload.get("problem") or "pfsp")
    if "p_times" in payload:
        p = np.asarray(payload["p_times"], np.int32)
    elif "inst" in payload:
        if problem != "pfsp":
            raise ValueError("'inst' (a Taillard id) is PFSP-only; "
                             f"problem {problem!r} needs 'p_times'")
        from ..problems import taillard
        p = taillard.processing_times(int(payload["inst"]))
    else:
        raise ValueError("request needs 'inst' or 'p_times'")
    ub = payload.get("ub")
    if ub == "opt":
        if "inst" not in payload:
            raise ValueError("'ub': 'opt' needs a Taillard 'inst'")
        from ..problems import taillard
        ub = taillard.optimal_makespan(int(payload["inst"]))
    kwargs = {}
    for k in ("priority", "chunk", "balance_period", "min_seed",
              "segment_iters", "checkpoint_every"):
        if payload.get(k) is not None:
            kwargs[k] = int(payload[k])
    if payload.get("capacity") is not None:
        kwargs["capacity"] = int(payload["capacity"])
    if payload.get("deadline_s") is not None:
        kwargs["deadline_s"] = float(payload["deadline_s"])
    if payload.get("share_group") is not None:
        kwargs["share_group"] = str(payload["share_group"])
    if payload.get("tenant") is not None:
        kwargs["tenant"] = str(payload["tenant"])
    if payload.get("portfolio") is not None:
        kwargs["portfolio"] = int(payload["portfolio"])
    if payload.get("checkpoint_meta") is not None:
        kwargs["checkpoint_meta"] = dict(payload["checkpoint_meta"])
    if payload.get("tuned"):
        # adaptive dispatch: leave the knobs OPEN (chunk=None /
        # balance_period=None) so the server resolves them from its
        # tuning cache / defaults table; explicit chunk/balance_period
        # keys in the same payload win (they were set above)
        kwargs.setdefault("chunk", None)
        kwargs.setdefault("balance_period", None)
    from .. import problems
    try:
        default_lb = problems.get(problem).default_lb
    except KeyError:
        default_lb = 1        # validate() rejects with the real reason
    return SearchRequest(
        p_times=p, problem=problem,
        lb_kind=int(payload.get("lb", default_lb)),
        init_ub=None if ub is None else int(ub),
        tag=payload.get("tag"), faults=payload.get("faults"), **kwargs)


def payload_from_request(req: SearchRequest) -> dict:
    """The inverse of :func:`request_from_payload`: serialize a
    SearchRequest back into the spool payload schema (the request
    ledger's admit-record body — `request_from_payload(
    payload_from_request(r))` must rebuild an equivalent request).
    Open tuned knobs (chunk/balance_period None) round-trip as
    ``{"tuned": true}``; per-request ``faults`` specs are deliberately
    NOT serialized (a drill fault must not follow a request across the
    crash-restart it exists to prove); non-JSON-safe ``checkpoint_meta``
    (the campaign driver stamps numpy arrays) is dropped with a trace
    event rather than failing the admit."""
    p = np.asarray(req.p_times)
    payload: dict = {"p_times": p.tolist(), "lb": int(req.lb_kind),
                     "problem": str(req.problem),
                     "ub": None if req.init_ub is None
                     else int(req.init_ub),
                     "priority": int(req.priority), "tag": req.tag}
    if req.deadline_s is not None:
        payload["deadline_s"] = float(req.deadline_s)
    if req.chunk is None or req.balance_period is None:
        payload["tuned"] = True
    if req.chunk is not None:
        payload["chunk"] = int(req.chunk)
    if req.balance_period is not None:
        payload["balance_period"] = int(req.balance_period)
    for k in ("capacity", "min_seed", "segment_iters",
              "checkpoint_every"):
        v = getattr(req, k)
        if v is not None:
            payload[k] = int(v)
    if req.share_group is not None:
        payload["share_group"] = str(req.share_group)
    if req.tenant != "-":
        # "-" is the unattributed default; omitted so an unattributed
        # request's admit record is byte-identical to pre-tenant ones
        payload["tenant"] = str(req.tenant)
    if req.portfolio is not None:
        payload["portfolio"] = int(req.portfolio)
    if req.checkpoint_meta:
        try:
            json.dumps(req.checkpoint_meta)
            payload["checkpoint_meta"] = req.checkpoint_meta
        except (TypeError, ValueError):
            from ..obs import tracelog
            tracelog.event("ledger.meta_dropped", tag=req.tag,
                           reason="checkpoint_meta is not JSON-safe; "
                                  "not journaled")
    return payload


def submit_file(spool: str | pathlib.Path, payload: dict,
                spool_id: str | None = None) -> str:
    """Client side: atomically drop a request file; returns the spool id."""
    spool = pathlib.Path(spool)
    spool.mkdir(parents=True, exist_ok=True)
    spool_id = spool_id or (f"{int(time.time() * 1000):x}-{os.getpid()}"
                            f"-{next(_spool_seq)}")
    _atomic_write_json(spool / f"{spool_id}{REQ_SUFFIX}", payload)
    return spool_id


def wait_result(spool: str | pathlib.Path, spool_id: str,
                timeout: float | None = None,
                poll_s: float = 0.2) -> dict:
    """Client side: poll for the result file; returns its dict."""
    path = pathlib.Path(spool) / f"{spool_id}{RES_SUFFIX}"
    t0 = time.monotonic()
    while True:
        if path.exists():
            return json.loads(path.read_text())
        if timeout is not None and time.monotonic() - t0 > timeout:
            raise TimeoutError(f"no result for {spool_id} after {timeout}s")
        time.sleep(poll_s)


def unserved_requests(spool: str | pathlib.Path, skip=None):
    """Yield ``(spool_id, request_file_path)`` for every request file
    with no result file yet — THE definition of the backlog, shared by
    the serve loop and the server's boot pre-warm so the two can never
    drift on which requests count as waiting. `skip` is an optional set
    of already-handled spool ids; ids discovered to be already SERVED
    are added to it, so a long-polling caller (the serve loop) stats
    each historical result file once, not once per poll tick."""
    spool = pathlib.Path(spool)
    for req_file in sorted(spool.glob(f"*{REQ_SUFFIX}")):
        sid = req_file.name[:-len(REQ_SUFFIX)]
        if skip is not None and sid in skip:
            continue
        if (spool / f"{sid}{RES_SUFFIX}").exists():
            # already served (by this process or a previous server
            # lifetime): a restart must not re-execute history or
            # clobber a result file a client may be reading
            if skip is not None:
                skip.add(sid)
            continue
        yield sid, req_file


def serve_spool(server, spool: str | pathlib.Path,
                idle_exit_s: float | None = None,
                status_every_s: float | None = None,
                poll_s: float = 0.2, emit=print,
                should_exit=None) -> int:
    """Server side: ingest request files into `server`, write result
    files as requests turn terminal. Returns the number of requests
    served. Exits when `idle_exit_s` elapses with nothing queued,
    running or pending (None = run until `should_exit()`), printing a
    JSON status snapshot every `status_every_s` seconds.

    A malformed or rejected request file still gets a result file (with
    an ``"error"``) — a client polling for it must not hang forever on
    a bad submission.
    """
    from .queueing import AdmissionError, AdmissionPaused
    from .request import TERMINAL_STATES

    spool = pathlib.Path(spool)
    spool.mkdir(parents=True, exist_ok=True)
    pending: dict[str, str] = {}        # spool id -> request id
    seen: set[str] = set()
    # crash recovery (service/ledger): requests this server REPLAYED at
    # boot that originally arrived through a spool reconnect to their
    # request files here — re-submitting them would either duplicate
    # the work or bounce off their own still-active tag, and their
    # clients are still polling for the result file
    replayed = dict(getattr(server, "replayed_spool", None) or {})
    if replayed:
        pending.update(replayed)
        seen.update(replayed)
        emit(json.dumps({"spool_reconnected": len(replayed)}))
    served = 0
    last_work = time.monotonic()
    last_status = 0.0
    while True:
        # while the remediation tier holds admission paused
        # (compile_storm), the backlog WAITS in the spool instead of
        # being turned into permanent REJECTED results — the pause is a
        # temporary valve, and a spooled file carries its own retry
        paused = getattr(server, "admission_paused",
                         lambda: None)()
        for sid, req_file in ([] if paused is not None
                              else unserved_requests(spool, skip=seen)):
            seen.add(sid)
            try:
                payload = json.loads(req_file.read_text())
                # spool_id rides the ledger's admit record so a
                # restarted serve loop can reconnect result delivery
                rid = server.submit(request_from_payload(payload),
                                    spool_id=sid)
            except AdmissionPaused:
                # the pause engaged between this loop's paused check
                # and the submit: HOLD the file (back out of `seen` so
                # the next poll retries it) — a temporary valve must
                # never turn backlog into permanent REJECTED results
                seen.discard(sid)
                break
            except AdmissionError as e:
                _atomic_write_json(
                    spool / f"{sid}{RES_SUFFIX}",
                    {"spool_id": sid, "state": "REJECTED",
                     "error": str(e)})
                continue
            except (ValueError, KeyError, json.JSONDecodeError) as e:
                _atomic_write_json(
                    spool / f"{sid}{RES_SUFFIX}",
                    {"spool_id": sid, "state": "REJECTED",
                     "error": str(e)})
                continue
            pending[sid] = rid
        for sid, rid in list(pending.items()):
            snap = server.status(rid)
            if snap["state"] in TERMINAL_STATES:
                _atomic_write_json(spool / f"{sid}{RES_SUFFIX}",
                                   {"spool_id": sid, **snap})
                del pending[sid]
                served += 1
        # a paused server is mid-incident, not idle: the idle-exit
        # clock must not shut it down on top of a held backlog.
        # Megabatch: requests the scheduler drained into the batch-
        # former are admitted work WAITING to batch — idle-exit must
        # not cancel them mid-hold (the queue reads empty the moment
        # the former holds them)
        former = getattr(server, "former", None)
        busy = bool(pending) or paused is not None \
            or len(server.queue) > 0 \
            or (former is not None and len(former) > 0) \
            or any(s.record is not None for s in server.slots)
        now = time.monotonic()
        if busy:
            last_work = now
        if status_every_s and now - last_status > status_every_s:
            emit(json.dumps(server.status_snapshot()))
            last_status = now
        if should_exit is not None and should_exit():
            return served
        if idle_exit_s is not None and now - last_work > idle_exit_s:
            return served
        time.sleep(poll_s)
