"""Bounded priority queue with admission control.

The wait line in front of the scheduler: higher `priority` pops first,
FIFO within a priority level (submission sequence breaks ties, and a
preempted request keeps its original sequence number so preemption does
not send it to the back of its class). Depth is bounded — a full queue
REJECTS new work with a reason (`AdmissionError`) instead of buffering
unboundedly, which is what separates a server under load from a server
that falls over: the client learns immediately and can back off,
re-prioritize, or go elsewhere.

Requeued (preempted) entries do not count against the admission bound —
they were already admitted; bouncing them on re-entry would turn
preemption into silent request loss.
"""

from __future__ import annotations

import heapq
import threading
import time

from .request import PREEMPTED, QUEUED, RequestRecord


class AdmissionError(RuntimeError):
    """Request rejected at the door; `.reason` says why."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class AdmissionPaused(AdmissionError):
    """Rejected because the remediation tier is holding admission
    paused (a TEMPORARY valve, e.g. a compile storm). Typed, not a
    string protocol: the spool front-end must HOLD its backlog on this
    and only this rejection — matching on the message wording would
    turn a future rewording into silent backlog loss."""


class RequestQueue:
    """Thread-safe bounded max-priority queue of RequestRecords.

    Entries whose state is no longer QUEUED/PREEMPTED (cancelled while
    waiting, deadline-expired in line) are dropped lazily at pop time —
    cancellation never has to hunt through the heap.
    """

    def __init__(self, max_depth: int):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        self._lock = threading.Lock()
        self._heap: list[tuple[int, int, RequestRecord]] = []
        # guarded-by: self._lock
        self.rejected = 0          # admission-control rejections (stats)
        self.peak_depth = 0        # high-water mark since construction —
                                   # the capacity-planning number a
                                   # point-in-time depth gauge misses

    def _prune(self) -> None:
        # drop stale heads (cancelled/expired while queued)
        while self._heap and self._heap[0][2].state not in (QUEUED,
                                                            PREEMPTED):
            heapq.heappop(self._heap)

    def _depth(self) -> int:
        """Waiting entries (caller holds the lock) — THE definition of
        queue depth, shared by __len__/admit/requeue so the admission
        bound and the peak-depth stat cannot diverge."""
        return sum(1 for _, _, r in self._heap
                   if r.state in (QUEUED, PREEMPTED))

    def __len__(self) -> int:
        with self._lock:
            self._prune()
            return self._depth()

    def admit(self, rec: RequestRecord) -> None:
        """Admit a NEW request; raises AdmissionError when full."""
        with self._lock:
            self._prune()
            depth = self._depth()
            if depth >= self.max_depth:
                self.rejected += 1
                raise AdmissionError(
                    f"queue full: depth {depth} at the admission bound "
                    f"{self.max_depth}; retry later or raise the bound")
            rec.queued_t = time.monotonic()
            heapq.heappush(self._heap,
                           (-rec.request.priority, rec.seq, rec))
            self.peak_depth = max(self.peak_depth, depth + 1)

    def requeue(self, rec: RequestRecord) -> None:
        """Put a preempted/re-dispatched request back in line.
        Bypasses the admission bound (the request was already admitted)."""
        with self._lock:
            rec.queued_t = time.monotonic()
            heapq.heappush(self._heap,
                           (-rec.request.priority, rec.seq, rec))
            self.peak_depth = max(self.peak_depth, self._depth())

    def observe_backlog(self, held: int) -> None:
        """Fold externally-held waiting work into the peak-depth
        high-water mark — the megabatch scheduler drains the heap into
        its batch-former every tick, so the heap alone would record a
        near-zero peak while the real wait line lives in the former."""
        with self._lock:
            self._prune()
            self.peak_depth = max(self.peak_depth,
                                  self._depth() + int(held))

    def pop_best(self, eligible=None) -> RequestRecord | None:
        """Highest-priority waiting request, or None if empty.

        `eligible` (optional predicate over the record) lets the
        scheduler pop per SLOT: the best request whose excluded-submesh
        set allows the slot in hand, with every skipped (higher-
        priority but ineligible) entry left in line at its original
        position. With no predicate — or all-empty exclusion sets, the
        TTS_REMEDIATE=0 default — this is exactly the old
        highest-priority pop."""
        with self._lock:
            self._prune()
            if eligible is None:
                if not self._heap:
                    return None
                return heapq.heappop(self._heap)[2]
            skipped = []
            found = None
            while self._heap:
                entry = heapq.heappop(self._heap)
                if entry[2].state not in (QUEUED, PREEMPTED):
                    continue        # stale (cancelled/expired in line)
                if eligible(entry[2]):
                    found = entry[2]
                    break
                skipped.append(entry)
            for entry in skipped:
                heapq.heappush(self._heap, entry)
            return found

    def best_priority(self) -> int | None:
        """Priority of the head of the line (None if empty) — the
        scheduler's preemption trigger."""
        with self._lock:
            self._prune()
            return (self._heap[0][2].request.priority
                    if self._heap else None)

    def peek_best(self) -> RequestRecord | None:
        """The head of the line WITHOUT popping it — the scheduler's
        preemption pass needs the record itself (its excluded-submesh
        set decides whether a free slot actually helps it)."""
        with self._lock:
            self._prune()
            return self._heap[0][2] if self._heap else None

    def count_priority_above(self, priority: int) -> int:
        """How many waiting requests outrank `priority` — the
        scheduler's bound on how many preemptions are justified."""
        with self._lock:
            self._prune()
            return sum(1 for _, _, r in self._heap
                       if r.state in (QUEUED, PREEMPTED)
                       and r.request.priority > priority)

    def waiting_ids(self) -> list[str]:
        """Queued request ids in pop order (status snapshots)."""
        with self._lock:
            self._prune()
            return [r.id for _, _, r in sorted(self._heap)
                    if r.state in (QUEUED, PREEMPTED)]
