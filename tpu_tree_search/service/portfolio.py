"""Bound-portfolio racing: K sibling configs, one incumbent board,
first proof wins.

A request submitted with ``portfolio: K`` (K >= 2) does not dispatch
itself. It fans out as K sibling SUB-REQUESTS over DISTINCT
configurations — the problem's bound tiers (``lb_kinds``) first, then
per-tier tuned chunk/balance plans resolved from the Autotuner's cache
(never a probe on the admission path), then chunk variants when tiers
run out — all naming ONE ``share_group``, so on a server with the
incumbent board enabled every sibling's improvements tighten every
other sibling's pruning (engine/incumbent.py). The race ends at the
FIRST sibling that terminates DONE with a complete proof: the parent
finalizes DONE with the winner's result, and every losing sibling is
cancelled through the ordinary member-level stop path (queued losers
finalize CANCELLED synchronously under the scheduler lock — zero
post-proof dispatches by construction; running losers get
``stop_reason="cancel"`` and stop at their next segment boundary,
exactly like a user ``cancel()``).

Why racing beats picking: which bound tier wins is instance-dependent
(a tight lb2/1-tree prunes more but costs more per node; lb1 streams),
and the shared board makes the race POSITIVE-SUM — the losers' early
incumbents shrink the winner's tree, so the race typically finishes
in fewer total bound evaluations than the K solo runs it replaces
(bench.py's ``pfsp_portfolio_speedup`` row measures exactly this).

Substrate: members flow through the ordinary scheduler. Under
megabatching, same-config siblings stack into one vmapped serve batch
via the batch key; heterogeneous-config siblings age-close as batches
of one onto the solo dispatch path — either way the member-level stop
path is what cancellation rides. With megabatch off every member
dispatches solo. The parent record is never queued or dispatched; it
is a pure coordination object that finalizes from its members'
terminals.

Durability: the parent's admit record carries ``portfolio: K`` in its
payload, and a ``portfolio`` ledger record links parent -> member rids
(+ raced configs). Replay rebuilds the race: the parent re-admits
UNQUEUED, members requeue like any interrupted request, and
``reconcile()`` re-arms the coordinator — resolving immediately when a
member's replayed terminal already decides the race (a winner DONE
before the crash re-serves its recorded result; the restarted race
converges to the bit-identical optimum either way, since a complete
proof pins ``best`` to the instance's optimum).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..obs import tracelog
from . import request as request_mod
from .request import (CANCELLED, DEADLINE, DONE, FAILED,
                      TERMINAL_STATES, SearchRequest)

__all__ = ["plan_members", "PortfolioCoordinator"]


def plan_members(request: SearchRequest, prob, k: int, *,
                 parent_tag: str, tuner=None, n_workers: int = 1
                 ) -> list[tuple[SearchRequest, dict]]:
    """The K raced configurations for one portfolio request.

    Deterministic fan-out order (the fan-out journal and the doctor's
    member columns rely on it):

    - member 0 is the request's OWN configuration verbatim (its
      ``lb_kind``/``chunk``/``balance_period`` untouched) — the race
      always contains the run the client would have gotten solo, so
      racing can only add information, never lose the baseline;
    - members 1.. cycle the problem's remaining bound tiers
      (``prob.lb_kinds``, plugin order, the request's own tier last in
      the cycle), each resolved through the Autotuner's PER-TIER cache
      entry when one is warm (``allow_probe=False`` — admission never
      probes);
    - when K exceeds the tier count, repeats race chunk variants
      (halved per lap) so no two members share an exact
      ``(lb_kind, chunk, balance_period)`` config.

    Returns ``[(member_request, config_dict), ...]`` where the config
    dict is the JSON-safe description journaled with the race and shown
    by doctor/status.
    """
    p = np.asarray(request.p_times)
    tiers = [request.lb_kind] + [lb for lb in prob.lb_kinds
                                 if lb != request.lb_kind]
    share = request.share_group or f"pf:{parent_tag}"
    out: list[tuple[SearchRequest, dict]] = []
    seen: set = set()
    for i in range(k):
        lb = tiers[i % len(tiers)]
        if i == 0:
            chunk, period, source = request.chunk, \
                request.balance_period, "request"
        else:
            chunk, period, source = request.chunk, \
                request.balance_period, "request"
            if tuner is not None:
                try:
                    params = tuner.resolve(
                        int(p.shape[1]), int(p.shape[0]), lb,
                        n_workers=n_workers, allow_probe=False,
                        problem=request.problem)
                    chunk, period = params.chunk, params.balance_period
                    source = params.source
                except Exception as e:  # noqa: BLE001 — tuning is an
                    # optimization; the member races the request knobs
                    tracelog.event("portfolio.tune_failed",
                                   lb_kind=lb, error=repr(e))
        # distinct-config guarantee: a duplicate (lb, chunk, period)
        # would race itself — vary the chunk (halved) until unique
        key, bump = (lb, chunk, period), 0
        while key in seen and bump < 16:
            bump += 1
            base = chunk if chunk else 1 << 15
            chunk = max(1, base // 2)
            key = (lb, chunk, period)
        seen.add(key)
        mreq = dataclasses.replace(
            request, lb_kind=lb, chunk=chunk, balance_period=period,
            portfolio=None, share_group=share,
            tag=f"{parent_tag}.pf{i}")
        out.append((mreq, {"lb_kind": int(lb),
                           "chunk": None if chunk is None else int(chunk),
                           "balance_period": None if period is None
                           else int(period),
                           "source": source,
                           "tag": mreq.tag}))
    return out


class _Race:
    __slots__ = ("parent_rid", "member_rids")

    def __init__(self, parent_rid: str, member_rids: list):
        self.parent_rid = parent_rid
        self.member_rids = list(member_rids)


class PortfolioCoordinator:
    """Parent/member race bookkeeping for one SearchServer.

    Every method is called WITH the server's scheduler lock held (it is
    an RLock, so the reentrant ``_finalize`` -> hook -> ``_finalize``
    chains a race resolution produces are safe). The coordinator never
    touches slots or the queue directly — losers cancel through the
    server's own terminal/stop machinery, so the member lifecycle stays
    byte-for-byte the ordinary request lifecycle.
    """

    def __init__(self, server):
        self.server = server
        self.races: dict[str, _Race] = {}   # parent rid -> race
        self._m_races = server.metrics.counter(
            "tts_portfolio_races_total",
            "portfolio races by outcome (won/deadline/cancelled/failed)")
        self._m_members = server.metrics.counter(
            "tts_portfolio_members_total",
            "portfolio members by terminal role")
        server.metrics.gauge(
            "tts_portfolio_active",
            "portfolio races currently unresolved"
            ).set_fn(lambda: sum(
                1 for rid in self.races
                if (r := server.records.get(rid)) is not None
                and r.state not in TERMINAL_STATES))

    # ----------------------------------------------------------- fan-out

    def register(self, parent_rec, members: list) -> None:
        """Arm the race after fan-out (``members`` =
        ``[(rid, config), ...]`` in fan-out order), then resolve
        immediately if it is already decided — an idempotently
        re-served DONE member (a resubmitted tag family) wins on the
        spot."""
        parent_rec.portfolio_members = [rid for rid, _ in members]
        self.races[parent_rec.id] = _Race(parent_rec.id,
                                          parent_rec.portfolio_members)
        tracelog.event("portfolio.fanout", request_id=parent_rec.id,
                       k=len(members),
                       members=[{"rid": rid, **cfg}
                                for rid, cfg in members])
        self._try_resolve(parent_rec)

    # ------------------------------------------------------ terminal hooks
    # (called from SearchServer._finalize, lock held)

    def on_member_terminal(self, rec) -> None:
        parent = self.server.records.get(rec.portfolio_parent or "")
        if parent is None or parent.portfolio_members is None:
            return
        if rec.state == CANCELLED:
            parent.portfolio_cancelled += 1
        self._m_members.inc(role=self._role(parent, rec))
        self._try_resolve(parent)

    def on_parent_terminal(self, parent_rec) -> None:
        """The parent just finalized (a won race, a user ``cancel()``,
        a no-ledger ``close()`` sweep, an all-members-terminal
        resolution): any still-live member is a loser — cancel it
        through the ordinary member-level stop path."""
        cancelled = self._cancel_live_members(
            parent_rec, but=parent_rec.portfolio_winner)
        if parent_rec.state == DONE:
            tracelog.event(
                "portfolio.win", request_id=parent_rec.id,
                winner=parent_rec.portfolio_winner,
                config=parent_rec.portfolio_config,
                cancelled=cancelled,
                best=(int(parent_rec.result.best)
                      if parent_rec.result is not None else None))
        self._m_races.inc(outcome={
            DONE: "won", DEADLINE: "deadline",
            CANCELLED: "cancelled"}.get(parent_rec.state, "failed"))

    # ---------------------------------------------------------- recovery

    def reconcile(self) -> None:
        """Post-replay sweep (ledger boot): re-arm every replayed race
        and resolve the ones the crash interrupted mid-decision — a
        winner whose DONE landed before the kill decides now; members
        of an already-terminal parent (their cancel never landed)
        cancel now instead of re-running a finished race."""
        for rec in list(self.server.records.values()):
            if rec.portfolio_members is None:
                continue
            self.races.setdefault(
                rec.id, _Race(rec.id, rec.portfolio_members))
            if rec.state in TERMINAL_STATES:
                n = self._cancel_live_members(
                    rec, but=rec.portfolio_winner)
                if n:
                    tracelog.event("portfolio.reconciled",
                                   request_id=rec.id, cancelled=n)
            else:
                self._try_resolve(rec)

    # ---------------------------------------------------------- internals

    def _members(self, parent_rec):
        return [self.server.records[rid]
                for rid in parent_rec.portfolio_members or []
                if rid in self.server.records]

    def _role(self, parent, rec) -> str:
        if rec.id == parent.portfolio_winner:
            return "winner"
        return {DONE: "lost_done", CANCELLED: "lost_cancelled",
                DEADLINE: "lost_deadline"}.get(rec.state, "lost_failed")

    def _cancel_live_members(self, parent_rec, but: str | None) -> int:
        n = 0
        for mrec in self._members(parent_rec):
            if mrec.id == but or mrec.state in TERMINAL_STATES:
                continue
            n += 1
            if mrec.state == request_mod.RUNNING:
                if mrec.stop_reason is None:
                    mrec.stop_reason = "cancel"
                self.server._stop_slot_of(mrec)
            else:
                # QUEUED/PREEMPTED: terminal right here, under the
                # scheduler lock — it can never dispatch post-proof
                self.server._finalize(
                    mrec, CANCELLED,
                    error=f"portfolio: lost race {parent_rec.id}")
        return n

    def _try_resolve(self, parent_rec) -> None:
        """Decide the race if it is decidable (lock held). First DONE
        member wins; with every member terminal and none DONE the
        parent inherits the least-bad outcome (DEADLINE beats
        CANCELLED beats FAILED) and the best partial result."""
        if parent_rec.state in TERMINAL_STATES:
            return
        members = self._members(parent_rec)
        winner = next((m for m in members if m.state == DONE), None)
        if winner is not None:
            parent_rec.portfolio_winner = winner.id
            parent_rec.portfolio_config = winner.portfolio_config
            parent_rec.result = winner.result
            # _finalize fires on_parent_terminal -> losers cancel
            self.server._finalize(parent_rec, DONE)
            return
        if any(m.state not in TERMINAL_STATES for m in members) \
                or not members:
            return
        with_result = [m for m in members if m.result is not None]
        if with_result:
            best = min(with_result, key=lambda m: int(m.result.best))
            parent_rec.result = best.result
            parent_rec.portfolio_config = best.portfolio_config
        if any(m.state == DEADLINE for m in members):
            state, err = DEADLINE, None
        elif all(m.state == CANCELLED for m in members):
            state, err = CANCELLED, None
        else:
            state = FAILED
            err = ("portfolio: no member completed ("
                   + ", ".join(f"{m.id}={m.state}" for m in members)
                   + ")")
        self.server._finalize(parent_rec, state, error=err)
