"""Heterogeneous CPU+TPU co-processing for the single-device engine.

The reference's `-C 1` mode runs CPU worker threads next to each GPU
manager and finishes with a serial CPU drain (pfsp_multigpu_cuda.c:61-69,
236-263, 487-495; its device loop only pops full chunks while
`pool.size >= m`, PFSP_lib.c:175/Pool_atom.c:154-178). The TPU analogue:

1. the native C++ runtime grows the warm-up frontier (step 1),
2. the compiled device loop explores while the pool can still feed full
   chunks (`size >= m`, the reference's `-m` threshold),
3. the residual pool is handed to native host threads which finish it
   with a multi-threaded DFS sharing the incumbent through an atomic
   (`tts_search_from` — checkBest semantics).

With the UB fixed the explored set is traversal-order independent, so the
combined counters equal the pure-device run exactly (the same invariant
the golden-parity tests rely on).
"""

from __future__ import annotations

import numpy as np

from ..ops import batched
from . import device, distributed


class HybridResult(distributed.DistResult):
    pass


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           chunk: int = 1024, capacity: int = 1 << 20,
           drain_min: int | None = None, host_threads: int = 0,
           tile: int = 1024):
    """Single-chip search with host warm-up and host drain (`-C 1`).

    `drain_min` (default: the chunk size) is the reference's `-m`: the
    device loop runs while the pool can feed at least that many parents;
    the leftovers go to the native host runtime.
    """
    from .. import native

    jobs = p_times.shape[1]
    tables = batched.make_tables(p_times)
    drain_min = chunk if drain_min is None else max(1, drain_min)

    # step 1: native warm-up so the device starts with full chunks
    fr = distributed.bfs_warmup(p_times, lb_kind, init_ub,
                                target=max(4 * chunk, 2 * drain_min))
    best0 = fr.best if init_ub is None else min(fr.best, int(init_ub))

    # step 2: compiled device loop while chunks stay full
    while True:
        state = device.init_state(jobs, capacity, best0,
                                  prmu0=fr.prmu, depth0=fr.depth,
                                  p_times=p_times)
        out = device.run(tables, state, lb_kind, chunk, tile=tile,
                         drain_min=drain_min)
        if not bool(out.overflow):
            break
        capacity *= 2

    # step 3: native drain of the residual pool (host threads)
    n_left = int(out.size)
    d_tree, d_sol = int(out.tree), int(out.sol)
    best = int(out.best)
    drained = 0
    if n_left > 0:
        res_prmu = np.asarray(out.prmu[:, :n_left]).T
        res_depth = np.asarray(out.depth[:n_left])
        h_tree, h_sol, best, drained = native.search_from(
            p_times, res_prmu, res_depth, lb_kind=lb_kind,
            init_ub=best, n_threads=host_threads)
        d_tree += h_tree
        d_sol += h_sol

    return HybridResult(
        explored_tree=d_tree + fr.tree,
        explored_sol=d_sol + fr.sol,
        best=best,
        per_device={"tree": [d_tree], "sol": [d_sol],
                    "evals": [int(out.evals)],
                    "steals": [0], "recv": [0],
                    "host_drained": [drained]},
        warmup_tree=fr.tree, warmup_sol=fr.sol,
        complete=True,
    )
