"""Heterogeneous CPU+TPU co-processing: CONCURRENT host + device search.

The reference's `-C 1` mode runs CPU worker threads concurrently with the
GPU managers, all sharing the incumbent through the `checkBest` CAS
(pfsp_multigpu_cuda.c:61-69, 159-263), and finishes with a serial CPU
drain (:487-495). The TPU analogue here:

1. the native C++ runtime grows the warm-up frontier (step 1),
2. the frontier is stride-split (roundRobin_distribution semantics):
   the host share seeds a native multi-threaded ASYNC search session
   (native.async_start) that runs in the background,
3. the compiled device loop explores its share in bounded segments;
   every segment boundary merges incumbents BOTH ways with the session
   (native.async_best / async_offer) — a bound found by either side
   prunes the other while both are still running (round 1 ran these
   phases sequentially, so with ub=inf the device never saw host
   incumbents),
4. the device residue (pool below the `-m` threshold, PFSP_lib.c:175)
   drains on host threads with the freshest merged bound, then the
   async session is joined.

With a FIXED ub the explored set is traversal-order independent, so the
combined counters still equal the pure-device run exactly (the invariant
the golden-parity tests rely on); with a live incumbent the exchanges
are what keep both sides' trees near the oracle's.
"""

from __future__ import annotations

import numpy as np

from ..ops import batched
from . import device, distributed


class HybridResult(distributed.DistResult):
    pass


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           chunk: int = 1024, capacity: int = 1 << 20,
           drain_min: int | None = None, host_threads: int = 0,
           host_fraction: int = 8, segment_iters: int = 64,
           tile: int = 1024):
    """Single-chip search with a concurrent native host tier (`-C 1`).

    `drain_min` (default: the chunk size) is the reference's `-m`: the
    device loop runs while the pool can feed at least that many parents;
    the leftovers go to the host runtime. `host_fraction`: the host
    session seeds with every host_fraction-th warm-up node (0 disables
    the concurrent tier, leaving warm-up + device + drain).
    `segment_iters` sets the incumbent-exchange cadence in device loop
    iterations."""
    import jax.numpy as jnp

    from .. import native
    from . import checkpoint

    jobs = p_times.shape[1]
    tables = batched.make_tables(p_times)
    drain_min = chunk if drain_min is None else max(1, drain_min)

    # step 1: native warm-up so both tiers start with real work
    fr = distributed.bfs_warmup(p_times, lb_kind, init_ub,
                                target=max(4 * chunk, 2 * drain_min))
    best0 = fr.best if init_ub is None else min(fr.best, int(init_ub))

    # step 2: stride-split the frontier; host share starts NOW, async
    n = len(fr.depth)
    handle = None
    d_prmu, d_depth = fr.prmu, fr.depth
    if host_fraction > 0 and n >= host_fraction:
        hmask = np.zeros(n, bool)
        hmask[::host_fraction] = True
        handle = native.async_start(
            p_times, fr.prmu[hmask], fr.depth[hmask], lb_kind=lb_kind,
            init_ub=best0, n_threads=host_threads)
        d_prmu, d_depth = fr.prmu[~hmask], fr.depth[~hmask]

    # step 3: segmented device loop with incumbent exchange per segment
    state = device.init_state(jobs, capacity, best0, prmu0=d_prmu,
                              depth0=d_depth, p_times=p_times)
    exchanges = host_improved = dev_improved = 0
    target = 0
    while True:
        target += segment_iters
        state = device.run(tables, state, lb_kind, chunk, max_iters=target,
                           tile=tile, drain_min=drain_min)
        if bool(state.overflow):
            capacity *= 2
            state = checkpoint.grow(state, capacity)
            continue
        if handle is not None:
            dev_best = int(state.best)
            host_best = native.async_best(handle)
            merged = min(dev_best, host_best)
            exchanges += 1
            if host_best < dev_best:
                host_improved += 1
                state = state._replace(
                    best=jnp.asarray(merged, state.best.dtype))
            elif dev_best < host_best:
                dev_improved += 1
                native.async_offer(handle, merged)
        if int(state.size) < drain_min:
            break

    # step 4: host drain of the device residue with the freshest bound
    n_left = int(state.size)
    d_tree, d_sol = int(state.tree), int(state.sol)
    best = int(state.best)
    if handle is not None:
        best = min(best, native.async_best(handle))
    drained = 0
    if n_left > 0:
        res_prmu = np.asarray(state.prmu[:, :n_left]).T
        res_depth = np.asarray(state.depth[:n_left])
        r_tree, r_sol, best, drained = native.search_from(
            p_times, res_prmu, res_depth, lb_kind=lb_kind,
            init_ub=best, n_threads=host_threads)
        d_tree += r_tree
        d_sol += r_sol

    # join the concurrent host session
    h_tree = h_sol = h_expanded = 0
    if handle is not None:
        h_tree, h_sol, h_best, h_expanded = native.async_join(handle)
        best = min(best, h_best)

    return HybridResult(
        explored_tree=d_tree + h_tree + fr.tree,
        explored_sol=d_sol + h_sol + fr.sol,
        best=best,
        per_device={"tree": [d_tree], "sol": [d_sol],
                    "evals": [int(state.evals)],
                    "iters": [int(state.iters)],
                    "steals": [0], "recv": [0],
                    "host_tree": [h_tree], "host_sol": [h_sol],
                    "host_expanded": [h_expanded],
                    "host_drained": [drained],
                    "exchanges": [exchanges],
                    "host_improved": [host_improved],
                    "dev_improved": [dev_improved]},
        warmup_tree=fr.tree, warmup_sol=fr.sol,
        complete=True,
    )
