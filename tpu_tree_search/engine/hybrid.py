"""Heterogeneous CPU+TPU co-processing: CONCURRENT host + device search.

The reference's `-C 1` mode runs CPU worker threads concurrently with the
GPU managers, all sharing the incumbent through the `checkBest` CAS
(pfsp_multigpu_cuda.c:61-69, 159-263), and finishes with a serial CPU
drain (:487-495). The TPU analogue here:

1. the native C++ runtime grows the warm-up frontier (step 1),
2. the frontier is stride-split (roundRobin_distribution semantics):
   the host share seeds a native multi-threaded ASYNC search session
   (native.async_start) that runs in the background,
3. the compiled device loop explores its share in bounded segments;
   every segment boundary merges incumbents BOTH ways with the session
   (native.async_best / async_offer) — a bound found by either side
   prunes the other while both are still running (round 1 ran these
   phases sequentially, so with ub=inf the device never saw host
   incumbents),
4. the device residue (pool below the `-m` threshold, PFSP_lib.c:175)
   drains on host threads with the freshest merged bound, then the
   async session is joined.

With a FIXED ub the explored set is traversal-order independent, so the
combined counters still equal the pure-device run exactly (the invariant
the golden-parity tests rely on); with a live incumbent the exchanges
are what keep both sides' trees near the oracle's.
"""

from __future__ import annotations

import numpy as np

from ..ops import batched
from . import device, distributed


class HybridResult(distributed.DistResult):
    pass


class HostSession:
    """The native concurrent host tier of `-C`: owns the async session
    lifecycle, the two-way incumbent merge applied at exchange points,
    and the final join. Driver-agnostic — the single-chip hybrid loop,
    the single-device segmented driver, and the distributed _DistDriver
    all plug it in (the reference runs its CPU workers beside the
    multi-GPU managers AND inside the distributed flagship:
    pfsp_multigpu_cuda.c:61-69, pfsp_dist_multigpu_cuda.c:471-741)."""

    def __init__(self, p_times, prmu, depth, lb_kind: int, init_ub: int,
                 n_threads: int = 0):
        from .. import native

        self._native = native
        self.handle = native.async_start(
            np.asarray(p_times), np.asarray(prmu), np.asarray(depth),
            lb_kind=lb_kind, init_ub=int(init_ub), n_threads=n_threads)
        self.seeded = int(len(depth))
        self.exchanges = self.host_improved = self.dev_improved = 0
        self.joined = None

    def merge(self, dev_best: int) -> int:
        """Two-way exchange: returns min(device, host) incumbent and
        offers the device's bound to the session when it is the tighter
        one (checkBest semantics, multigpu:61-69)."""
        host_best = self._native.async_best(self.handle)
        merged = min(int(dev_best), host_best)
        self.exchanges += 1
        if host_best < dev_best:
            self.host_improved += 1
        elif dev_best < host_best:
            self.dev_improved += 1
            self._native.async_offer(self.handle, merged)
        return merged

    def offer(self, best: int) -> None:
        self._native.async_offer(self.handle, int(best))

    def join(self):
        """(tree, sol, best, expanded) of the session; idempotent."""
        if self.joined is None:
            self.joined = self._native.async_join(self.handle)
        return self.joined

    def post_segment(self, state):
        """checkpoint.run_segmented hook: merge incumbents between the
        device state (single-device scalar best or stacked per-worker
        bests) and the session at every segment boundary."""
        import jax.numpy as jnp

        from . import checkpoint

        dev_best = int(checkpoint._to_np(state.best).min())
        merged = self.merge(dev_best)
        if merged < dev_best:
            state = state._replace(
                best=jnp.minimum(state.best,
                                 jnp.asarray(merged, state.best.dtype)))
        return state


class PyHostSession:
    """Generic host tier: the same concurrent-session API as
    :class:`HostSession`, but a Python DFS thread over the problem
    plugin's `host_children` oracle instead of the native PFSP
    runtime. Any plugin that sets `supports_host_tier` and implements
    `host_children` gets `-C` for free (TSP, knapsack); PFSP keeps the
    native session (this one would be ~100x slower on its kernels).
    `n_threads` is accepted for signature parity and ignored — a GIL
    DFS gains nothing from more threads, and exactly-once accounting
    stays trivial with one."""

    def __init__(self, problem, table, prmu, depth, lb_kind: int,
                 init_ub: int, n_threads: int = 0):
        import threading

        del n_threads
        self._prob = problem
        self._table = np.asarray(table)
        self._lb_kind = int(lb_kind)
        self._lock = threading.Lock()
        self._best = int(init_ub)
        self._stack = [(np.asarray(p, np.int16), int(d))
                       for p, d in zip(np.asarray(prmu),
                                       np.asarray(depth))]
        self.seeded = int(len(depth))
        self.exchanges = self.host_improved = self.dev_improved = 0
        self.joined = None
        self._tree = self._sol = self._expanded = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        prob, table, lb = self._prob, self._table, self._lb_kind
        slots = prob.slots(table)
        stack, leaf_in_evals = self._stack, prob.leaf_in_evals
        while stack:
            node, depth = stack.pop()
            self._expanded += 1
            if not leaf_in_evals and depth == slots:
                self._sol += 1
                continue
            best = self._best      # one snapshot per expansion
            for child, cdepth, bound, is_leaf in prob.host_children(
                    table, node, depth, best, lb_kind=lb):
                if leaf_in_evals and is_leaf:
                    self._sol += 1
                    if bound < best:
                        with self._lock:
                            if bound < self._best:
                                self._best = bound
                        best = min(best, bound)
                elif bound < best:
                    stack.append((child, cdepth))
                    self._tree += 1

    def merge(self, dev_best: int) -> int:
        """Two-way exchange, same contract as the native session."""
        with self._lock:
            host_best = self._best
            merged = min(int(dev_best), host_best)
            self._best = merged
        self.exchanges += 1
        if host_best < dev_best:
            self.host_improved += 1
        elif dev_best < host_best:
            self.dev_improved += 1
        return merged

    def offer(self, best: int) -> None:
        with self._lock:
            self._best = min(self._best, int(best))

    def join(self):
        """(tree, sol, best, expanded); idempotent, blocks until the
        DFS thread drains its subtree."""
        if self.joined is None:
            self._thread.join()
            self.joined = (self._tree, self._sol, self._best,
                           self._expanded)
        return self.joined

    post_segment = HostSession.post_segment


def make_session(problem, table, prmu, depth, lb_kind: int,
                 init_ub: int, n_threads: int = 0):
    """The `-C` session factory: native runtime for PFSP, the generic
    Python session for any other opted-in plugin, a typed refusal
    otherwise (problems/base.HostTierUnsupported — callers surface it
    as a rejection, not a crash)."""
    from ..problems import base as problems_base

    if not problem.supports_host_tier:
        raise problems_base.HostTierUnsupported(problem.name)
    if problem.name == "pfsp":
        return HostSession(table, prmu, depth, lb_kind, init_ub,
                           n_threads=n_threads)
    return PyHostSession(problem, table, prmu, depth, lb_kind, init_ub,
                         n_threads=n_threads)


def split_host_share(prmu, depth, host_fraction: int):
    """Stride-split a frontier (roundRobin_distribution semantics,
    multigpu:159-263): every host_fraction-th node goes to the host
    tier. Returns (dev_mask, host_prmu, host_depth); host share is empty
    when the frontier is too small to split."""
    n = len(depth)
    if host_fraction <= 0 or n < host_fraction:
        return np.ones(n, bool), prmu[:0], depth[:0]
    hmask = np.zeros(n, bool)
    hmask[::host_fraction] = True
    return ~hmask, prmu[hmask], depth[hmask]


def restore_host_share(host_state, h_prmu, h_depth, p_times,
                       problem=None):
    """Resume WITHOUT `-C` of a checkpoint whose host tier held carved
    nodes (they ride the checkpoint meta — see the search drivers): push
    them back into the least-loaded pool so no subtree is lost. The aux
    rows are recomputed from the permutations via the problem plugin's
    `seed_aux` (default PFSP for pre-plugin callers)."""
    import jax.numpy as jnp

    n = len(h_depth)
    if n == 0:
        return host_state
    if problem is None:
        from ..problems import get as _get_problem
        problem = _get_problem("pfsp")
    prmu = np.asarray(host_state.prmu).copy()
    depth = np.asarray(host_state.depth).copy()
    aux = np.asarray(host_state.aux).copy()
    size = np.atleast_1d(np.asarray(host_state.size)).copy()
    stacked = prmu.ndim == 3
    M = aux.shape[-2]
    rows = np.asarray(problem.seed_aux(
        np.asarray(p_times), np.asarray(h_prmu),
        np.asarray(h_depth)))[:, :M]
    w = int(size.argmin())
    s = int(size[w])
    if s + n > prmu.shape[-1]:
        raise RuntimeError(
            f"no room to restore the {n}-node host share into pool {w} "
            f"(size {s}, capacity {prmu.shape[-1]}); resume with "
            "--grow-capacity")
    sl = (w,) if stacked else ()
    prmu[sl + (slice(None), slice(s, s + n))] = np.asarray(h_prmu).T
    depth[sl + (slice(s, s + n),)] = np.asarray(h_depth)
    aux[sl + (slice(None), slice(s, s + n))] = rows.T
    size[w] = s + n
    new_size = (jnp.asarray(size) if stacked
                else jnp.asarray(np.asarray(size[0],
                                            np.asarray(host_state.size).dtype)))
    return host_state._replace(
        prmu=jnp.asarray(prmu), depth=jnp.asarray(depth),
        aux=jnp.asarray(aux), size=new_size)


def pop_host_share(host_state, host_fraction: int, cap: int = 4096):
    """Resume path: no warm-up frontier exists, so carve the host tier's
    seed off the TOP of the checkpointed pools (host-side numpy, before
    the state is committed to devices — lossless: the session explores
    exactly the carved rows). Works on the single-device layout
    (jobs, capacity) and the stacked one (n_dev, jobs, capacity).
    Returns (new_state, host_prmu (n, jobs), host_depth (n,))."""
    prmu = np.asarray(host_state.prmu)
    depth = np.asarray(host_state.depth)
    size = np.asarray(host_state.size)
    stacked = prmu.ndim == 3
    sizes = size.reshape(-1) if stacked else size.reshape(1)
    pools_p = prmu if stacked else prmu[None]
    pools_d = depth if stacked else depth[None]
    take = [min(int(s) // max(host_fraction, 1), cap // len(sizes))
            for s in sizes]
    hp, hd = [], []
    new_sizes = []
    for w, k in enumerate(take):
        s = int(sizes[w])
        if k > 0:
            hp.append(pools_p[w][:, s - k:s].T.copy())
            hd.append(pools_d[w][s - k:s].copy())
        new_sizes.append(s - k)
    if not hp:
        return host_state, prmu[:0].reshape(0, prmu.shape[-2]), depth[:0]
    import jax.numpy as jnp

    new_size = (jnp.asarray(np.asarray(new_sizes, size.dtype))
                if stacked else
                jnp.asarray(np.asarray(new_sizes[0], size.dtype)))
    state = host_state._replace(size=new_size)
    return state, np.concatenate(hp, axis=0), np.concatenate(hd)


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           chunk: int = 1024, capacity: int = 1 << 20,
           drain_min: int | None = None, host_threads: int = 0,
           host_fraction: int = 8, segment_iters: int = 64,
           tile: int = 1024):
    """Single-chip search with a concurrent native host tier (`-C 1`).

    `drain_min` (default: the chunk size) is the reference's `-m`: the
    device loop runs while the pool can feed at least that many parents;
    the leftovers go to the host runtime. `host_fraction`: the host
    session seeds with every host_fraction-th warm-up node (0 disables
    the concurrent tier, leaving warm-up + device + drain).
    `segment_iters` sets the incumbent-exchange cadence in device loop
    iterations."""
    from .. import native
    from . import checkpoint

    jobs = p_times.shape[1]
    tables = batched.make_tables(p_times)
    drain_min = chunk if drain_min is None else max(1, drain_min)

    # step 1: native warm-up so both tiers start with real work
    fr = distributed.bfs_warmup(p_times, lb_kind, init_ub,
                                target=max(4 * chunk, 2 * drain_min))
    best0 = fr.best if init_ub is None else min(fr.best, int(init_ub))

    # step 2: stride-split the frontier; host share starts NOW, async
    dmask, h_prmu, h_depth = split_host_share(fr.prmu, fr.depth,
                                              host_fraction)
    session = None
    d_prmu, d_depth = fr.prmu[dmask], fr.depth[dmask]
    if len(h_depth):
        session = HostSession(p_times, h_prmu, h_depth, lb_kind, best0,
                              n_threads=host_threads)

    # step 3: segmented device loop with incumbent exchange per segment
    state = device.init_state(jobs, capacity, best0, prmu0=d_prmu,
                              depth0=d_depth, p_times=p_times)
    target = 0
    while True:
        target += segment_iters
        state = device.run(tables, state, lb_kind, chunk, max_iters=target,
                           tile=tile, drain_min=drain_min)
        if bool(state.overflow):
            capacity *= 2
            state = checkpoint.grow(state, capacity)
            continue
        if session is not None:
            state = session.post_segment(state)
        if int(state.size) < drain_min:
            break

    # step 4: host drain of the device residue with the freshest bound
    n_left = int(state.size)
    d_tree, d_sol = int(state.tree), int(state.sol)
    best = int(state.best)
    if session is not None:
        best = session.merge(best)
    drained = 0
    if n_left > 0:
        res_prmu = np.asarray(state.prmu[:, :n_left]).T
        res_depth = np.asarray(state.depth[:n_left])
        r_tree, r_sol, best, drained = native.search_from(
            p_times, res_prmu, res_depth, lb_kind=lb_kind,
            init_ub=best, n_threads=host_threads)
        d_tree += r_tree
        d_sol += r_sol
        if session is not None:
            # a bound improved by the drain must reach the session while
            # it is still searching — otherwise it keeps pruning with a
            # stale (higher) incumbent until join (wasted host work)
            session.offer(best)

    # join the concurrent host session
    h_tree = h_sol = h_expanded = 0
    exchanges = host_improved = dev_improved = 0
    if session is not None:
        h_tree, h_sol, h_best, h_expanded = session.join()
        best = min(best, h_best)
        exchanges = session.exchanges
        host_improved = session.host_improved
        dev_improved = session.dev_improved

    return HybridResult(
        explored_tree=d_tree + h_tree + fr.tree,
        explored_sol=d_sol + h_sol + fr.sol,
        best=best,
        per_device={"tree": [d_tree], "sol": [d_sol],
                    "evals": [int(state.evals)],
                    "iters": [int(state.iters)],
                    "steals": [0], "recv": [0],
                    "host_tree": [h_tree], "host_sol": [h_sol],
                    "host_expanded": [h_expanded],
                    "host_drained": [drained],
                    "exchanges": [exchanges],
                    "host_improved": [host_improved],
                    "dev_improved": [dev_improved]},
        warmup_tree=fr.tree, warmup_sol=fr.sol,
        complete=True,
    )
