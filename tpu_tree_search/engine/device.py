"""Single-device PFSP B&B engine: HBM-resident pool + compiled search loop.

This replaces the reference's host-managed architecture — CPU deque
(Pool_atom.c), chunked H2D/D2H offload with `-m/-M` thresholds, CUDA bound
kernel, host-side prune+branch (`generate_children`, PFSP_lib.h:51-95) —
with a design where the node pool never leaves the device: the whole
pop -> bound -> prune -> branch cycle is one `lax.while_loop` inside `jit`
(reference hot loop: pfsp_multigpu_cuda.c:221-320 round-trips the host
every iteration; here the host only sees the final counters).

Pool layout (struct-of-arrays in HBM, replacing the reference's
array-of-struct deque, Pool_atom.h:23-30):
    prmu  int16[capacity, jobs]   permutations
    depth int16[capacity]         scheduled-prefix length
    size  int32                   stack cursor (rows [0, size) are live)

Each step pops a chunk of up to `chunk` parents off the top of the stack
(deepest-first => depth-first, preserving the pruning locality the
reference gets from popBackBulk, Pool_atom.c:154-178), evaluates the dense
(chunk, jobs) grid of child bounds with the batched kernels, and pushes
surviving children back with a masked compacting scatter — the on-device
equivalent of `generate_children` + `pushBackBulk`.

Unlike the reference's growable deque (realloc-on-push, Pool_atom.c:47-51),
the pool has static capacity; an `overflow` flag aborts the search cleanly
if it would be exceeded (callers then retry with a larger pool). DFS order
keeps the live size near (tree depth x branching x chunk), far below
capacity in practice.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import batched
from ..ops.batched import BoundTables

I32_MAX = jnp.int32(2**31 - 1)


class SearchState(NamedTuple):
    """Carried through the `lax.while_loop`; all arrays device-resident."""

    prmu: jax.Array      # (capacity, jobs) int16
    depth: jax.Array     # (capacity,) int16
    size: jax.Array      # int32 live-row cursor
    best: jax.Array      # int32 incumbent makespan
    tree: jax.Array      # int64 explored (= pushed) internal nodes
    sol: jax.Array       # int64 evaluated leaf children
    iters: jax.Array     # int64 loop iterations (stats)
    evals: jax.Array     # int64 child bound evaluations (the bench metric)
    sent: jax.Array      # int64 nodes donated via balance exchanges
    recv: jax.Array      # int64 nodes received via balance exchanges
    steals: jax.Array    # int64 balance rounds that received > 0 nodes
    overflow: jax.Array  # bool: capacity would have been exceeded


def init_state(jobs: int, capacity: int, init_ub: int | None,
               prmu0: np.ndarray | None = None,
               depth0: np.ndarray | None = None) -> SearchState:
    """Pool with the given seed nodes (default: the root at depth 0)."""
    if prmu0 is None:
        prmu0 = np.arange(jobs, dtype=np.int16)[None, :]
        depth0 = np.zeros(1, dtype=np.int16)
    prmu0 = np.asarray(prmu0, dtype=np.int16).reshape(-1, jobs)
    depth0 = np.asarray(depth0, dtype=np.int16).reshape(-1)
    n = prmu0.shape[0]
    assert n <= capacity

    prmu = np.zeros((capacity, jobs), dtype=np.int16)
    depth = np.zeros(capacity, dtype=np.int16)
    prmu[:n] = prmu0
    depth[:n] = depth0
    best = 2**31 - 1 if init_ub is None else int(init_ub)
    return SearchState(
        prmu=jnp.asarray(prmu),
        depth=jnp.asarray(depth),
        size=jnp.int32(n),
        best=jnp.int32(best),
        tree=jnp.int64(0),
        sol=jnp.int64(0),
        iters=jnp.int64(0),
        evals=jnp.int64(0),
        sent=jnp.int64(0),
        recv=jnp.int64(0),
        steals=jnp.int64(0),
        overflow=jnp.asarray(False),
    )


def make_children(prmu: jax.Array, depth: jax.Array) -> jax.Array:
    """Dense (B, J, J) child permutations: slot i swaps positions depth<->i
    (the prefix-swap branching of decompose, reference: PFSP_lib.c:13-16)."""
    B, J = prmu.shape
    pos = jnp.arange(J, dtype=jnp.int32)[None, None, :]     # permutation index
    slot = jnp.arange(J, dtype=jnp.int32)[None, :, None]    # which child
    d = depth[:, None, None].astype(jnp.int32)
    at_depth = jnp.take_along_axis(
        prmu, depth[:, None].astype(jnp.int32), axis=1
    )                                                        # (B, 1) job at prmu[depth]
    base = prmu[:, None, :]                                  # (B, 1, J)
    swapped_in = jnp.take_along_axis(
        prmu, jnp.broadcast_to(slot[..., 0], (B, J)).astype(jnp.int32), axis=1
    )[:, :, None]                                            # (B, J, 1) prmu[i]
    child = jnp.where(pos == d, swapped_in,
                      jnp.where(pos == slot, at_depth[:, :, None], base))
    return child.astype(jnp.int16)


def step(tables: BoundTables, lb_kind: int, chunk: int,
         state: SearchState) -> SearchState:
    """One pop->bound->prune->branch cycle (the compiled analogue of the
    reference per-thread hot loop, pfsp_multigpu_cuda.c:221-320)."""
    capacity, J = state.prmu.shape
    B = chunk

    # --- pop up to B parents off the top (popBackBulk analogue)
    n = jnp.minimum(state.size, B)
    start = state.size - n
    rows = start + jnp.arange(B, dtype=jnp.int32)
    valid = jnp.arange(B) < n
    rows = jnp.clip(rows, 0, capacity - 1)
    p_prmu = state.prmu[rows]                        # (B, J)
    p_depth = state.depth[rows].astype(jnp.int32)
    p_depth = jnp.where(valid, p_depth, 0)

    # --- bound the dense child grid
    bounds = batched.children_bounds(lb_kind)(tables, p_prmu, p_depth, valid)
    mask = batched.child_mask(p_prmu, p_depth, valid)

    # --- leaves: complete schedules; count + tighten incumbent
    # (reference: the depth==jobs branch of decompose, PFSP_lib.c:24-32)
    is_leaf = ((p_depth + 1) == J)[:, None] & mask
    sol = state.sol + is_leaf.sum(dtype=jnp.int64)
    leaf_best = jnp.where(is_leaf, bounds, I32_MAX).min()
    best = jnp.minimum(state.best, leaf_best)

    # --- prune + push surviving internal children
    push = mask & ~is_leaf & (bounds < best)
    flat_push = push.reshape(-1)
    n_push = flat_push.sum(dtype=jnp.int32)
    tree = state.tree + n_push.astype(jnp.int64)

    children = make_children(p_prmu, p_depth).reshape(B * J, J)
    child_depth = jnp.broadcast_to(
        (p_depth + 1)[:, None], (B, J)
    ).reshape(-1).astype(jnp.int16)

    # compacting scatter: k-th surviving child -> row start + k
    dest = jnp.where(flat_push,
                     start + jnp.cumsum(flat_push, dtype=jnp.int32) - 1,
                     capacity)                       # capacity => dropped
    new_size = start + n_push

    # An overflowing step must NOT commit: children past capacity are
    # dropped by the scatter, so advancing the cursor would silently lose
    # subtrees (and make the overflow checkpoint unrecoverable). Instead
    # the state is left exactly as before the step with only the flag
    # set, so grow-capacity + resume continues the search losslessly.
    # Pool arrays stay untouched by routing the whole scatter to the
    # drop row (O(chunk), no capacity-sized select on the hot loop);
    # the remaining guards are scalar selects.
    overflow = new_size > capacity
    dest = jnp.where(overflow, capacity, dest)
    prmu = state.prmu.at[dest].set(children, mode="drop")
    depth = state.depth.at[dest].set(child_depth, mode="drop")
    keep = lambda new, old: jnp.where(overflow, old, new)  # noqa: E731
    return state._replace(
        prmu=prmu,
        depth=depth,
        size=keep(new_size, state.size),
        best=keep(best, state.best),
        tree=keep(tree, state.tree),
        sol=keep(sol, state.sol),
        iters=state.iters + 1,
        evals=keep(state.evals + mask.sum(dtype=jnp.int64), state.evals),
        overflow=state.overflow | overflow)


@functools.partial(jax.jit, static_argnames=("lb_kind", "chunk"))
def _run(tables: BoundTables, state: SearchState, lb_kind: int, chunk: int,
         max_iters: jax.Array) -> SearchState:
    def cond(s: SearchState):
        return (s.size > 0) & ~s.overflow & (s.iters < max_iters)

    return jax.lax.while_loop(cond, functools.partial(step, tables, lb_kind, chunk),
                              state)


def run(tables: BoundTables, state: SearchState, lb_kind: int, chunk: int,
        max_iters: int | None = None) -> SearchState:
    """Run the search to exhaustion (or up to a cumulative `max_iters`) in
    one compiled loop (the analogue of pfsp_c.c:55-63's while(1)
    pop+decompose). `max_iters` is a traced scalar, NOT a static argument:
    segmented drivers pass a new ceiling every segment and must hit the
    compile cache."""
    limit = (jnp.iinfo(state.iters.dtype).max if max_iters is None
             else max_iters)
    return _run(tables, state, lb_kind, chunk,
                jnp.asarray(limit, dtype=state.iters.dtype))


class SearchResult(NamedTuple):
    explored_tree: int
    explored_sol: int
    best: int
    iters: int
    evals: int
    overflow: bool
    complete: bool = True  # pool drained (False: max_iters truncation)


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           chunk: int = 64, capacity: int = 1 << 18,
           max_iters: int | None = None,
           tables: BoundTables | None = None) -> SearchResult:
    """Host entry point: build tables, run, fetch counters.

    Retries with doubled capacity on overflow rather than failing — the
    static-shape replacement for the reference's realloc-on-push.
    """
    if tables is None:
        tables = batched.make_tables(p_times)
    jobs = p_times.shape[1]
    while True:
        state = init_state(jobs, capacity, init_ub)
        out = run(tables, state, lb_kind, chunk, max_iters)
        if not bool(out.overflow):
            return SearchResult(
                explored_tree=int(out.tree), explored_sol=int(out.sol),
                best=int(out.best), iters=int(out.iters),
                evals=int(out.evals), overflow=False,
                complete=int(out.size) == 0,
            )
        capacity *= 2
