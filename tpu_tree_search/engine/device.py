"""Single-device PFSP B&B engine: HBM-resident pool + compiled search loop.

This replaces the reference's host-managed architecture — CPU deque
(Pool_atom.c), chunked H2D/D2H offload with `-m/-M` thresholds, CUDA bound
kernel, host-side prune+branch (`generate_children`, PFSP_lib.h:51-95) —
with a design where the node pool never leaves the device: the whole
pop -> bound -> prune -> branch cycle is one `lax.while_loop` inside `jit`
(reference hot loop: pfsp_multigpu_cuda.c:221-320 round-trips the host
every iteration; here the host only sees the final counters).

Pool layout (struct-of-arrays in HBM, replacing the reference's
array-of-struct deque, Pool_atom.h:23-30):
    prmu  int16[capacity, jobs]   permutations
    depth int16[capacity]         scheduled-prefix length
    size  int32                   stack cursor (rows [0, size) are live)

Each step pops a chunk of up to `chunk` parents off the top of the stack
(deepest-first => depth-first, preserving the pruning locality the
reference gets from popBackBulk, Pool_atom.c:154-178), evaluates the dense
(chunk, jobs) grid of child bounds with the batched kernels, and pushes
surviving children back with a masked compacting scatter — the on-device
equivalent of `generate_children` + `pushBackBulk`.

Unlike the reference's growable deque (realloc-on-push, Pool_atom.c:47-51),
the pool has static capacity; an `overflow` flag aborts the search cleanly
if it would be exceeded (callers then retry with a larger pool). DFS order
keeps the live size near (tree depth x branching x chunk), far below
capacity in practice.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import batched, pallas_expand, pallas_fused, reference as ref
from ..ops.batched import BoundTables
from ..utils import config as _cfg
from . import telemetry as tele

I32_MAX = jnp.int32(2**31 - 1)

# read ONCE at import, never inside the traced step: an env read at
# trace time is a silent retrace/stale-value hazard (tts-lint
# trace_safety) — the executable keeps whatever the first trace saw
_DEBUG_STEP = _cfg.env_flag("TTS_DEBUG_STEP")

# default telemetry leaf for keyword-constructed states (numpy, not jnp:
# a module-import-time jnp array would force backend selection before
# the CLI's --platform override can run)
_NO_TELEMETRY = np.zeros(0, np.int64)


def aux_dtype(p_times: np.ndarray | None) -> np.dtype:
    """Narrowest safe dtype for the pool's per-node tables (front vectors)
    and their compaction traffic. Every value stored there is a machine
    completion time of some partial schedule, bounded by the critical-path
    bound: any C[k][i] in the flow-shop recurrence is a sum over one
    monotone lattice path from (0,0) to (k,i), at most (J + M - 1) cells
    of at most max(p) each. When that bound fits int16, halving the aux
    bytes roughly halves the byte-bound compaction gathers and block
    writes that dominate the step (BENCHMARKS.md round-3 profile:
    gathers 38% of the LB2 step). Every Taillard class through 200x20
    fits; 500-job instances fall back to int32 automatically.
    """
    if p_times is None:
        return np.dtype(np.int32)
    m, j = p_times.shape
    bound = (j + m - 1) * int(np.max(p_times))
    if bound <= int(np.iinfo(np.int16).max):
        return np.dtype(np.int16)
    return np.dtype(np.int32)


def row_limit(capacity: int, chunk: int, jobs: int) -> int:
    """Usable pool rows. The top `chunk*jobs` rows are a scratch margin:
    the push block-write always writes a full chunk*jobs block, and an
    overflowing step routes it there so the live region stays untouched.
    Every commit point (step, balance, seeding) must keep
    `size <= row_limit` — that invariant is what keeps the block write in
    bounds and overflow recovery lossless."""
    return max(capacity - chunk * jobs, 0)


class SearchState(NamedTuple):
    """Carried through the `lax.while_loop`; all arrays device-resident.

    Pool arrays are FEATURE-MAJOR — the row (node) axis is last, so it
    rides the 128-wide vector lanes. Row-major `(capacity, jobs)` pools
    put jobs~20 on the lanes (84% waste) and force layout conversions
    around every push/pop; feature-major matches the expand kernel's
    native layout (ops/pallas_expand.py) end to end."""

    prmu: jax.Array      # (jobs, capacity) int16
    depth: jax.Array     # (capacity,) int16
    aux: jax.Array       # (A, capacity) int32 per-node tables; PFSP stores
                         # the node's machine-completion vector `front`
                         # (A = machines) so bounds never rescan the
                         # prefix; problems without per-node tables
                         # (N-Queens) use A = 0
    size: jax.Array      # int32 live-row cursor
    best: jax.Array      # int32 incumbent makespan
    tree: jax.Array      # int64 explored (= pushed) internal nodes
    sol: jax.Array       # int64 evaluated leaf children
    iters: jax.Array     # int64 loop iterations (stats)
    evals: jax.Array     # int64 child bound evaluations (the bench metric)
    sent: jax.Array      # int64 nodes donated via balance exchanges
    recv: jax.Array      # int64 nodes received via balance exchanges
    steals: jax.Array    # int64 balance rounds that received > 0 nodes
    overflow: jax.Array  # bool: capacity would have been exceeded
    telemetry: jax.Array = _NO_TELEMETRY
                         # int64 (telemetry.WIDTH,) on-device search
                         # telemetry block (engine/telemetry.py layout);
                         # width 0 when TTS_SEARCH_TELEMETRY is off —
                         # the step then traces ZERO telemetry ops


@functools.partial(jax.jit, donate_argnums=0)
def _seed_update(buf, rows):
    """In-place (donated) write of the seed rows into the fresh pool
    buffer; module-level so the jit cache persists across init_state
    calls (a per-call wrapper would retrace every instance/segment)."""
    return jax.lax.dynamic_update_slice(buf, rows, (0,) * buf.ndim)


def init_state(jobs: int, capacity: int, init_ub: int | None,
               prmu0: np.ndarray | None = None,
               depth0: np.ndarray | None = None,
               p_times: np.ndarray | None = None,
               telemetry: bool | None = None,
               aux0: np.ndarray | None = None) -> SearchState:
    """Pool with the given seed nodes (default: the root at depth 0).

    `p_times` (PFSP) sizes and fills the per-node aux tables; `aux0`
    ((n, A) host rows, any problem) seeds them directly — the problem-
    plugin path (problems/base.Problem.seed_aux). Without either the
    aux width is 0 (problems like N-Queens that carry no per-node
    tables). `telemetry` compiles the on-device search-telemetry block
    into the state (None: the TTS_SEARCH_TELEMETRY env flag,
    engine/telemetry.py).
    """
    if prmu0 is None:
        prmu0 = np.arange(jobs, dtype=np.int16)[None, :]
        depth0 = np.zeros(1, dtype=np.int16)
    prmu0 = np.asarray(prmu0, dtype=np.int16).reshape(-1, jobs)
    depth0 = np.asarray(depth0, dtype=np.int16).reshape(-1)
    n = prmu0.shape[0]
    assert n <= capacity

    # Allocate the pool ON the device and ship only the seed rows: the
    # host-side np.zeros variant uploaded the full capacity through the
    # runtime (~350 MB at capacity 2^22 for 20x20 — seconds per call on
    # a remote-TPU tunnel, paid per instance by campaign drivers). The
    # seeding update runs jitted with the zeros buffer DONATED so the
    # write is in place — eager dynamic_update_slice holds both the
    # zeros and the result at once, ~2x peak HBM per pool array at init
    # (enough to OOM capacities that fit once running).
    def seeded(shape, dtype, rows):
        return _seed_update(jnp.zeros(shape, dtype),
                            jnp.asarray(rows, dtype))

    prmu = seeded((jobs, capacity), jnp.int16, prmu0.T)
    depth = seeded((capacity,), jnp.int16, depth0)
    if p_times is not None:
        m = p_times.shape[0]
        aux = seeded((m, capacity), aux_dtype(p_times),
                     ref.prefix_front_remain(p_times, prmu0,
                                             depth0)[:, :m].T)
    elif aux0 is not None and aux0.shape[-1] > 0:
        aux0 = np.asarray(aux0).reshape(len(depth0), -1)
        aux = seeded((aux0.shape[1], capacity), aux0.dtype, aux0.T)
    else:
        aux = jnp.zeros((0, capacity), jnp.int32)
    best = 2**31 - 1 if init_ub is None else int(init_ub)
    return SearchState(
        prmu=prmu,
        depth=depth,
        aux=aux,
        size=jnp.int32(n),
        best=jnp.int32(best),
        tree=jnp.int64(0),
        sol=jnp.int64(0),
        iters=jnp.int64(0),
        evals=jnp.int64(0),
        sent=jnp.int64(0),
        recv=jnp.int64(0),
        steals=jnp.int64(0),
        overflow=jnp.asarray(False),
        telemetry=jnp.zeros(
            (tele.WIDTH if (tele.enabled() if telemetry is None
                            else telemetry) else 0,), jnp.int64),
    )


def make_children(prmu: jax.Array, depth: jax.Array) -> jax.Array:
    """Dense (B, J, J) child permutations: slot i swaps positions depth<->i
    (the prefix-swap branching of decompose, reference: PFSP_lib.c:13-16).

    Gather-free: the value swapped into position `depth` is just `prmu[b, i]`
    (= `prmu` itself along the slot axis), and the job swapped out to
    position i is extracted with a masked sum — per-element dynamic
    gathers cost ~ms at this batch size on TPU, pure vector ops don't."""
    B, J = prmu.shape
    pos = jnp.arange(J, dtype=jnp.int32)[None, None, :]     # permutation index
    slot = jnp.arange(J, dtype=jnp.int32)[None, :, None]    # which child
    d = depth[:, None, None].astype(jnp.int32)
    at_depth = jnp.sum(
        jnp.where(jnp.arange(J)[None, :] == depth[:, None].astype(jnp.int32),
                  prmu.astype(jnp.int32), 0),
        axis=1)                                              # (B,) prmu[b, depth]
    base = prmu[:, None, :]                                  # (B, 1, J)
    swapped_in = prmu[:, :, None]                            # (B, J, 1) prmu[b, i]
    child = jnp.where(pos == d, swapped_in,
                      jnp.where(pos == slot, at_depth[:, None, None], base))
    return child.astype(jnp.int16)


def _col_major(x, G: int, J: int, TB: int):
    """(1, B) per-parent row -> (1, N) per-child-slot row in the expand
    kernel's column order (c = (g*J + i)*TB + b)."""
    return jnp.broadcast_to(x.reshape(G, 1, TB), (G, J, TB)).reshape(1, -1)


def _child_masks(p_depth, valid, G: int, J: int, TB: int):
    """The (1, N) child-slot mask family in the expand kernel's column
    order — ONE construction shared by step()'s dense routes and the
    fused spill branch, so the two can never drift (the spill cond's
    bit-parity with the kernel path depends on it). Returns (depth_c,
    mask); leaves are ``(depth_c + 1) == J`` within mask."""
    depth_c = _col_major(p_depth, G, J, TB)
    valid_c = _col_major(valid[None, :], G, J, TB)
    slot_c = jnp.broadcast_to(
        jnp.arange(J, dtype=jnp.int32)[None, :, None], (G, J, TB)
    ).reshape(1, G * J * TB)
    return depth_c, (slot_c >= depth_c) & valid_c


def _partition(push: jax.Array) -> jax.Array:
    """Stable-partition permutation: indices of all True columns first (in
    order), then the False ones. One single-operand unstable sort of a
    packed u32 key — the flag rides bit 31, the column index the low bits,
    so every key is unique and the unstable sort is deterministic. ~4x
    cheaper than argsort on TPU (no hidden payload operands)."""
    n = push.shape[0]
    assert n < 2**31
    key = (jnp.where(push, jnp.uint32(0), jnp.uint32(1) << 31)
           | jnp.arange(n, dtype=jnp.uint32))
    return (jax.lax.sort(key, is_stable=False)
            & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)


def _regather(tables: BoundTables, p_prmu, p_depth2, p_aux, idx,
              TB: int, with_sched: bool = False):
    """Rebuild the first `t` compacted children directly from the popped
    parent arrays (sources are only `chunk` wide, so these gathers move a
    fraction of what gathering the dense (features, chunk*jobs) child
    block would; the children's permutations and front chains are
    recomputed — O(jobs + machines) vector ops per survivor, far cheaper
    on TPU than the avoided HBM traffic).

    `idx` (t,) are child-column indices in expand()'s slot-major order
    (c = (g*J + i)*TB + b). Returns (child (J,t) int16,
    caux (M+1,t) = [child front | depth+1] in the POOL's aux dtype
    (int16 when the instance's completion times fit it, see aux_dtype)
    [, sched (W,t) int32 multi-word scheduled-set bitmask,
    W = ceil(J/32)]). Keeping the child block int16 and SEPARATE from
    the wider aux rows measures faster than one combined i32 block
    (tried: +60% gather time per step — these gathers are byte-bound at
    40+ i32 rows; the narrow aux dtype attacks the same wall)."""
    J, B = p_prmu.shape
    M = p_aux.shape[0]
    adt = p_aux.dtype
    t = idx.shape[0]
    JTB = J * TB
    g = idx // JTB
    r = idx - g * JTB
    slot = r // TB
    b = r - slot * TB
    pcol = g * TB + b                               # parent column in [0, B)
    # barriers: without them XLA fuses the index arithmetic into the
    # gathers and the fused kernels run ~5x slower (measured on v5e)
    pcol, slot = jax.lax.optimization_barrier((pcol, slot))
    src = jnp.concatenate([p_aux, p_depth2.astype(adt)], axis=0)  # (M+1, B)
    pp = jnp.take(p_prmu, pcol, axis=1)                   # (J, t) int16
    pfd = jnp.take(src, pcol, axis=1)                     # (M+1, t) adt
    pp, pfd = jax.lax.optimization_barrier((pp, pfd))
    pfd = pfd.astype(jnp.int32)   # chain math in i32; stores back in adt
    pf = pfd[:M]
    pd = pfd[M:]                                          # (1, t) depth

    ppi = pp.astype(jnp.int32)
    rows = jax.lax.broadcasted_iota(jnp.int32, (J, t), 0)
    appended = jnp.sum(jnp.where(rows == slot[None, :], ppi, 0),
                       axis=0, dtype=jnp.int32)[None, :]  # prmu[slot]
    at_depth = jnp.sum(jnp.where(rows == pd, ppi, 0),
                       axis=0, dtype=jnp.int32)[None, :]  # prmu[depth]
    child = jnp.where(rows == pd, appended,
                      jnp.where(rows == slot[None, :], at_depth,
                                ppi)).astype(jnp.int16)

    # child_p[k] = p[k, appended] (J-step select: dynamic column gathers
    # of the tiny (M, J) table serialize on TPU, selects vectorize)
    cp = jnp.zeros((M, t), jnp.int32)
    for j in range(J):
        cp = jnp.where(appended == j, tables.p[:, j:j + 1], cp)

    # add_forward chain (c_bound_simple.c:31-38) from the parent front
    cf = pf[0:1] + cp[0:1]
    cf_rows = [cf]
    for k in range(1, M):
        cf = jnp.maximum(cf, pf[k:k + 1]) + cp[k:k + 1]
        cf_rows.append(cf)
    caux = jnp.concatenate(cf_rows + [pd + 1], axis=0).astype(adt)  # (M+1,t)

    if not with_sched:
        return child, caux
    one = jnp.int32(1)
    words = []
    for w in range(pallas_expand.sched_words(J)):
        inw = (ppi >= 32 * w) & (ppi < 32 * (w + 1))
        bit = one << jnp.where(inw, ppi - 32 * w, 0)
        pmask = jnp.sum(jnp.where((rows < pd) & inw, bit, 0),
                        axis=0, dtype=jnp.int32)[None, :]
        ainw = (appended >= 32 * w) & (appended < 32 * (w + 1))
        abit = jnp.where(
            ainw, one << jnp.where(ainw, appended - 32 * w, 0), 0)
        words.append(pmask | abit)
    return child, caux, jnp.concatenate(words, axis=0)


def _compact_tiers(N: int, two_phase: bool = False,
                   cap: int | None = None) -> list[int]:
    """Compaction tier widths. Few and carefully placed: every extra
    lax.switch branch costs a copy of the (rows, N) output blocks
    (measured: a 9-rung ladder cost LB1 14% of its step rate). The LB1
    ladder holds its two steady-state occupancies (final push in N//16,
    candidates in N//4); the two-phase LB2 ladder adds 3N//32 for the
    post-prefilter survivors, which sit just above N//16 — a pow2-only
    ladder would round them to N//4, 4x the gather+pad width (measured
    on ta021: ncand~152k -> N//4, nkeep~43k -> 3N//32).

    `cap` truncates the ladder AND the frame: every block is padded to
    `cap` instead of N (the steady branch of the two-phase route runs
    its whole post-LB1 pipeline in N//4-wide frames — see step())."""
    steps = ((N // 16, 3 * N // 32, N // 4) if two_phase
             else (N // 16, N // 4))
    cap = N if cap is None else cap
    return [t for t in steps if 128 <= t < cap] + [cap]


def _tier_switch(tiers: list[int], count, make_branch):
    """Dispatch to the smallest tier covering `count` via ONE lax.switch
    (a nested cond ladder copies its result at every level).
    `make_branch(width) -> (_ -> result)` builds each branch; the last
    tier must cover every possible count."""
    if len(tiers) == 1:
        return make_branch(tiers[0])(0)
    sel = sum((count > t).astype(jnp.int32) for t in tiers[:-1])
    return jax.lax.switch(sel, [make_branch(t) for t in tiers], 0)


def _partition_prefix(push: jax.Array, live, N: int,
                      two_phase: bool = False,
                      cap: int | None = None) -> jax.Array:
    """_partition when every True column is known to sit below `live`
    (a traced count): sort only the smallest compaction tier covering
    `live` instead of all N keys (~3x of the two-phase step's sort cost
    was full-width sorts whose tails were all-False). Entries past the
    sorted prefix are filled with their own index — valid garbage that
    downstream tier gathers may read into pad columns, which land above
    the pool cursor and are never read (the consuming compact's tier is
    chosen by n_push <= live, so its prefix always lies inside the
    sorted region)."""
    tiers = _compact_tiers(N, two_phase, cap)
    frame = push.shape[0]

    def branch(t):
        def f(_):
            srt = _partition(push[:t])
            if t < frame:
                srt = jnp.concatenate(
                    [srt, jnp.arange(t, frame, dtype=jnp.int32)])
            return srt
        return f

    return _tier_switch(tiers, live, branch)


def _tiered_compact(gather, perm, n_keep, N: int, two_phase: bool = False,
                    cap: int | None = None):
    """Frame-width compacted block (frame = `cap` or N), built by the
    smallest tier that covers the `n_keep` survivors: a switch branch
    gathers only its tier's prefix via `gather(idx) -> tuple of
    (rows, len(idx)) blocks` and zero-pads the rest (a cheap sequential
    write; the garbage columns land above the pool cursor and are never
    read). The switch carries only these blocks — threading the HBM
    pools through conditional branches copies them (measured: ~4x step
    cost), which is why the caller writes the block into the pool
    outside."""
    tiers = _compact_tiers(N, two_phase, cap)
    frame = tiers[-1]

    def branch(t):
        def f(_):
            out = gather(jax.lax.slice(perm, (0,), (t,)))
            if t < frame:
                out = tuple(jnp.concatenate(
                    [o, jnp.zeros(o.shape[:-1] + (frame - t,), o.dtype)],
                    axis=-1) for o in out)
            return out
        return f

    return _tier_switch(tiers, n_keep, branch)


def _compact_from_parents(tables: BoundTables, p_prmu, p_depth2, p_aux,
                          perm, n_keep, TB: int, N: int,
                          with_sched: bool = False,
                          two_phase: bool = False,
                          cap: int | None = None):
    """Compacted child block rebuilt from the popped parents (see
    _regather), tiered by survivor count (see _tiered_compact)."""
    def gather(idx):
        return _regather(tables, p_prmu, p_depth2, p_aux, idx, TB,
                         with_sched)
    return _tiered_compact(gather, perm, n_keep, N, two_phase, cap)


def lb2_route(jobs: int, machines: int, pairs: int, chunk: int,
              tile: int = 1024) -> tuple[str, int, bool]:
    """THE LB2 routing decision at these shapes: returns
    (route, TB, pair_kernel_ok), route in {'dense', 'prefilter'} —
    pair_kernel_ok says whether the small-J register pair-sweep kernel
    runs (the prefilter route sweeps via it when True, else via the
    streaming big-J kernel or the XLA scan, lb2_sweep_tile). Shared by
    step() and the phase-attribution profiler (utils/phase_timing) so
    the attribution can never price a path or an implementation the
    engine does not use.

    - 'dense': one-shot dense pair sweep — needs the pallas pair kernel
      (lb2_kernel_fits) at the LB2-capped tile AND a few-pair class.
    - 'prefilter': LB1 pre-prune + pair sweeps over survivor tiers.
      Every stage degrades independently to its XLA fallback (the LB1
      bounds via expand_bounds' own dispatch, the sweeps via
      lb2_bounds'/sweep_tiers'), so this route covers EVERY class —
      including the 200/500-job classes whose expand kernel misses the
      scoped-VMEM cap: sweeping only survivor tiers beats the dense
      all-children XLA sweep ~10x there (the pair scan is the dominant
      cost and LB1 removes most of the grid first). When the pair
      kernel cannot run anyway, the LB2 tile cap's halving is moot and
      the tile retries at the LB1 cap (the 100-job classes).
    """
    TB = pallas_expand.effective_tile(jobs, chunk, tile, 2,
                                      machines=machines)
    pair_ok = (pallas_expand.kernel_ok(jobs, TB, 2, machines=machines)
               and pallas_expand.lb2_kernel_fits(jobs, pairs))
    if not pair_ok:
        TB1 = pallas_expand.effective_tile(jobs, chunk, tile, 1,
                                           machines=machines)
        if pallas_expand.kernel_ok(jobs, TB1, 1, machines=machines):
            TB = TB1
    if pair_ok and pairs <= 2 * batched.PAIR_PREFILTER:
        return "dense", TB, pair_ok
    return "prefilter", TB, pair_ok


def pop_chunk(state: SearchState, B: int, M: int):
    """Pop window of up to B parents off the stack top (no commit; the
    caller owns the cursor): the popBackBulk analogue. The window
    [start, start+B) is contiguous, so dynamic_slice beats a gather.
    Returns (p_prmu (J,B) i16, p_depth (1,B) i32, p_aux (M,B) in the
    POOL's aux dtype (aux_dtype — int16 on most classes; widen to i32
    before doing chain arithmetic on it), n, start, valid)."""
    J, capacity = state.prmu.shape
    n = jnp.minimum(state.size, B)
    start = state.size - n
    valid = jnp.arange(B) < n
    zero = jnp.zeros((), start.dtype)
    p_prmu = jax.lax.dynamic_slice(state.prmu, (zero, start), (J, B))
    p_depth = jax.lax.dynamic_slice(state.depth, (start,), (B,)) \
        .astype(jnp.int32)
    p_depth = jnp.where(valid, p_depth, 0)[None, :]            # (1, B)
    p_aux = jax.lax.dynamic_slice(state.aux, (zero, start), (M, B))
    return p_prmu, p_depth, p_aux, n, start, valid


def _write_block(state: SearchState, children, child_depth, child_aux,
                 start, n_push, limit):
    """Write the compacted child block at the cursor — or, when the step
    overflows, into the scratch margin at `limit` (rows
    [limit, limit + B*J) hold no live data by the size <= limit
    invariant), so an overflowing step's pool is untouched in its live
    region. Uses the same `start + n_push > limit` predicate as
    _commit's scalar guards — keep via this one helper."""
    M = child_aux.shape[0] - 1
    zero = jnp.zeros((), start.dtype)
    write_at = jnp.where(start + n_push > limit,
                         jnp.asarray(limit, start.dtype), start)
    prmu = jax.lax.dynamic_update_slice(state.prmu, children,
                                        (zero, write_at))
    depth = jax.lax.dynamic_update_slice(state.depth, child_depth,
                                         (write_at,))
    aux = jax.lax.dynamic_update_slice(
        state.aux, child_aux[:M].astype(state.aux.dtype), (zero, write_at))
    return prmu, depth, aux


def _commit(state: SearchState, prmu, depth, aux, n_push, best, sol, mask,
            limit, start, tele_delta=None) -> SearchState:
    """THE no-commit overflow contract, shared by every route: an
    overflowing step must NOT commit — advancing the cursor past the
    limit would lose subtrees (and make the overflow checkpoint
    unrecoverable). The state is left exactly as before the step with
    only the flag set: the caller routes the block write to the scratch
    margin (rows [limit, limit + B*J) hold no live data by the
    size <= limit invariant — `write_at` at the call sites uses this
    same `start + n_push > limit` condition) and the scalars here are
    guarded with selects, so grow-capacity + resume continues the
    search losslessly.

    `tele_delta` (telemetry.step_delta, or None when telemetry is off)
    folds the step's masked telemetry counts in under the SAME guard,
    plus the non-additive slots owned here: pool high-water max and the
    incumbent-improvement ring (telemetry.commit)."""
    new_size = start + n_push
    overflow = new_size > limit
    keep = lambda new, old: jnp.where(overflow, old, new)  # noqa: E731
    telem = state.telemetry
    if tele_delta is not None:
        telem = keep(tele.commit(telem, tele_delta, new_size, best,
                                 state.best, state.iters), telem)
    return state._replace(
        prmu=prmu,
        depth=depth,
        aux=aux,
        size=keep(new_size, state.size),
        best=keep(best, state.best),
        tree=keep(state.tree + n_push.astype(jnp.int64), state.tree),
        sol=keep(sol, state.sol),
        iters=state.iters + 1,
        evals=keep(state.evals + mask.sum(dtype=jnp.int64), state.evals),
        overflow=state.overflow | overflow,
        telemetry=telem)


def _sweep_tiers(tbl, cf_cols, sched_cols, count, N: int, J: int,
                 M: int):
    """Pair sweep over the smallest prefix tier covering `count` live
    columns; columns past the tier read I32_MAX. Finer ladder than the
    compaction's (its branches carry only a (1, frame) row, so extra
    rungs are nearly free) with 3/2^k rungs for the same occupancy
    reason (_compact_tiers). When the sweep runs as the pallas kernel,
    each rung must satisfy its tile rule (lb2_tile — lane alignment
    AND the scoped-VMEM model) or lb2_bounds would silently take its
    XLA fallback there; when the class is outside the pair kernel
    anyway (lb2_kernel_fits false — the J>64 classes), the XLA scan
    has no tile constraint and every rung is admitted, keeping the
    swept prefix snug around small survivor sets."""
    PT = int(tbl.ma0.shape[0])
    frame = cf_cols.shape[1]
    on_tpu = jax.default_backend() == "tpu"

    def rung_ok(t):
        # a rung is admitted when the sweep at that width runs a
        # pallas kernel — lb2_sweep_tile is THE shared dispatch
        # predicate (register kernel or streaming big-J), so admission
        # cannot diverge from lb2_bounds. On CPU every rung is fine
        # (the XLA scan has no tile rule).
        return (not on_tpu
                or pallas_expand.lb2_sweep_tile(J, PT, M, t) > 0)

    # finer than the compaction ladder (rungs here carry only a
    # (1, frame) row): the tail sweep's survivor count sits wherever
    # the head prune left it, and a coarse ladder over-sweeps it by up
    # to 50% (nkeep~43k rode the 61440 rung — measured, 166 pairs x
    # 18k wasted columns/step)
    tiers = [t for t in (k * N // 64 for k in
                         (1, 2, 3, 4, 5, 6, 8, 10, 12, 14, 16,
                          20, 24, 32))
             if 0 < t < frame and rung_ok(t)]
    if on_tpu and not rung_ok(frame):
        # the frame rung is appended unconditionally (it must cover
        # every count), but if it misses the tile rule lb2_bounds
        # takes its XLA fallback there — on the WIDEST (most
        # expensive) rung. Loud, not silent.
        import warnings
        warnings.warn(
            f"lb2 sweep frame rung {frame} (J={J}, P={PT}) fails "
            "the pallas tile rule; the widest sweep tier will run "
            "the XLA scan fallback", stacklevel=2)
    tiers.append(frame)

    def prefix(width):
        def f(_):
            b = pallas_expand.lb2_bounds(
                tbl, cf_cols[:, :width], sched_cols[:, :width])
            if width < frame:
                b = jnp.concatenate(
                    [b, jnp.full((1, frame - width), I32_MAX,
                                 jnp.int32)], axis=1)
            return b
        return f

    return _tier_switch(tiers, count, prefix)


def _take_block(*rows_arrays):
    """prefix-gather closure over the given (rows, frame) arrays."""
    def take(idx):
        idx = jax.lax.optimization_barrier(idx)
        out = tuple(jnp.take(a, idx, axis=1) for a in rows_arrays)
        return jax.lax.optimization_barrier(out)
    return take


def _lb2_tail(tables: BoundTables, state: SearchState, children, caux,
              sched, ncand, W_: int, N: int, best, start, limit,
              debug_tap: bool, TELE: bool):
    """Everything after the LB1 prune of the two-phase LB2 route, in
    W_-wide frames: the strong-pair head sweep, the mid prune+compact,
    the tail sweep, the final prune+compact and the pool block write.
    Extracted to module level so the UNFUSED prefilter branches (which
    regather survivors from their parents) and the FUSED route (whose
    kernel emits the compacted survivor block directly,
    ops/pallas_fused) run the exact same ops on the compacted block —
    the two can never drift. Inputs: children (J, W_) i16, caux
    (M+1, W_) i32, sched (SW, W_) i32, `ncand` live survivors in the
    leading columns (the rest unread garbage — the scratch-margin
    contract covers the pool write). Returns
    (prmu, depth, aux, n_push, hsum, tsum[, tele_tail])."""
    J = children.shape[0]
    M = tables.p.shape[0]
    P = int(tables.ma0.shape[0])
    KH = batched.PAIR_PREFILTER

    if P <= KH:
        # Few pairs but outside the dense route (the wide few-pair
        # classes, e.g. 100x5: the pallas pair kernel is gated off
        # past J=64): no prefilter tail exists — pair_split would
        # return an empty tail table whose (0, frame) pair-max has no
        # identity — so ONE full sweep over the LB1 survivors is the
        # whole LB2.
        lb2b = _sweep_tiers(tables, caux[:M], sched, ncand, N, J, M)
        live = ncand
        if TELE:
            head_hp = jnp.zeros(tele.BOUND_BINS, jnp.int64)
    else:
        # Strong-pair prefilter (the reference's unimplemented
        # LB2_LEARN, c_bound_johnson.h:29): sweep only the
        # PAIR_PREFILTER strongest pairs (tables store pairs
        # strongest-first), prune on that partial max (partial max <=
        # LB2, so pruning on it is sound), and pay for the remaining
        # pairs only on the children the prefix failed to prune (<10%
        # on the 20x20 class). The total bound stays exactly
        # max(head, tail) = full LB2, so explored trees are
        # bit-identical to the single-sweep path.
        SW = pallas_expand.sched_words(J)
        head_t, tail_t = batched.pair_split(tables, KH)
        lb2h = _sweep_tiers(head_t, caux[:M], sched, ncand, N, J, M)
        keep = ((jnp.arange(W_) < ncand)
                & (lb2h.reshape(-1) < best))
        if TELE:
            # pruned by the strong-pair head sweep: binned at the
            # partial bound that pruned them (a sound lower bound —
            # partial max <= LB2)
            head_hp = tele.bound_hist(
                lb2h, (jnp.arange(W_) < ncand) & ~keep, best)
        nkeep = keep.sum(dtype=jnp.int32)
        permh = _partition_prefix(keep, ncand, N, two_phase=True,
                                  cap=W_)
        # the partial bound rides the compaction as an extra row
        # (three structural variants were tried and measured WORSE: an
        # index-composed final gather that skips re-gathering children
        # — the composing (N,) take lowers to a ~4.7 ms serialized
        # gather; one combined i32 block per compaction — +60% gather
        # time, byte-bound at 40+ rows; and gathering these blocks in
        # the pool's int16 aux dtype — TPU column gathers are
        # element/latency-bound, i16 made them SLOWER (+18%), so the
        # narrow dtype lives only at the pool boundary, see step())
        aux_plus = jnp.concatenate([caux, sched, lb2h], axis=0)
        children, aux_plus = _tiered_compact(
            _take_block(children, aux_plus), permh, nkeep, N,
            two_phase=True, cap=W_)
        # barrier: the tail sweep's pallas call must see the
        # mid-compaction's switch outputs materialized — without this,
        # XLA's fusion of the slice chain miscompiles the compiled
        # (jitted) step on TPU and the tail sweep reads stale columns,
        # silently over-pruning (eager and debug-tapped traces are
        # correct — caught by test_prefilter_branch_matches_oracle on
        # hardware)
        aux_plus = jax.lax.optimization_barrier(aux_plus)
        caux = aux_plus[:M + 1]
        sched = aux_plus[M + 1:M + 1 + SW]
        lb2h_c = aux_plus[M + 1 + SW:M + 2 + SW]
        lb2t = _sweep_tiers(tail_t, caux[:M], sched, nkeep, N, J, M)
        lb2b = jnp.maximum(lb2h_c, lb2t)
        live = nkeep

    push = ((jnp.arange(W_) < live)
            & (lb2b.reshape(-1) < best))
    n_push = push.sum(dtype=jnp.int32)
    if TELE:
        # branched buckets + bound histograms, computed while caux
        # still aligns column-for-column with push/lb2b (the final
        # compaction reorders)
        pb = tele.depth_bucket(
            caux[M].astype(jnp.int32).reshape(-1) - 1, J)
        live_m = jnp.arange(W_) < live
        tele_tail = jnp.concatenate([
            tele.bucket_counts(pb, push),
            head_hp + tele.bound_hist(lb2b, live_m & ~push, best),
            tele.bound_hist(lb2b, push, best)])
    if debug_tap:
        # smuggle intermediates out via the balance counters
        lv = jnp.arange(W_) < live
        hsum = jnp.where(lv, lb2h_c.reshape(-1),
                         0).sum(dtype=jnp.int64)
        tsum = jnp.where(lv, lb2t.reshape(-1),
                         0).sum(dtype=jnp.int64)
    else:
        hsum = tsum = jnp.int64(0)

    # final compaction: direct prefix gather of the already-built
    # block (sources are the compacted (features, W_) arrays)
    perm2 = _partition_prefix(push, live, N, two_phase=True, cap=W_)
    children, child_aux = _tiered_compact(
        _take_block(children, caux), perm2, n_push, N,
        two_phase=True, cap=W_)
    child_depth = child_aux[M].astype(jnp.int16)

    # pool write inside the branch: the written block is W_-wide, so
    # the steady branch moves a quarter of the bytes (_write_block
    # owns the overflow scratch-margin routing, shared with the common
    # path)
    prmu, depth, aux = _write_block(
        state, children, child_depth, child_aux, start, n_push, limit)
    out = (prmu, depth, aux, n_push, hsum, tsum)
    if TELE:
        out += (tele_tail,)
    return out


def _leaf_scan(tables: BoundTables, p_prmu, p_depth, p_aux, valid):
    """Parent-level leaf/eval statistics of one popped chunk — the
    dense-grid quantities the unfused routes read off the (1, N) child
    masks, computed in O(M*B) without materializing them (the fused
    route's whole point is that the dense grid never exists in HBM).

    A parent at depth J-1 has exactly ONE valid child (slot J-1), a
    complete schedule; its LB1 as the kernels compute it is the chain
    max_k(tmp_k + min_tails[k]) with every child-remain term zero —
    replicated here term for term so `leaf_best` is bit-identical to
    the dense route's masked min over leaf columns. Parents below J-1
    contribute J - depth evaluated (all non-leaf) children; a parent
    at J-1 contributes its one leaf. Returns
    (leaf_best i32, n_leaf i64, evals i64)."""
    J, B = p_prmu.shape
    M = p_aux.shape[0]
    d = p_depth.reshape(-1)                        # (B,) i32
    leafp = (d == J - 1) & valid
    # the lone unscheduled job of a depth-(J-1) parent sits at
    # position J-1; its processing column via the J-step select
    # (_regather's gather-free idiom)
    a = p_prmu[J - 1:J, :].astype(jnp.int32)       # (1, B)
    cp = jnp.zeros((M, B), jnp.int32)
    for j in range(J):
        cp = jnp.where(a == j, tables.p[:, j:j + 1], cp)
    cf = p_aux[0:1] + cp[0:1]
    tmp = cf
    lb = tmp + tables.min_tails[0]
    for k in range(1, M):
        cf = jnp.maximum(cf, p_aux[k:k + 1]) + cp[k:k + 1]
        tmp = jnp.maximum(tmp, cf)
        lb = jnp.maximum(lb, tmp + tables.min_tails[k])
    leaf_best = jnp.where(leafp, lb.reshape(-1), I32_MAX).min()
    n_leaf = leafp.sum(dtype=jnp.int64)
    evals = jnp.where(valid, (J - d).astype(jnp.int64), 0).sum()
    return leaf_best, n_leaf, evals


def _fused_step(tables: BoundTables, lb_kind: int, route, chunk: int,
                TB: int, state: SearchState, p_prmu, p_depth, p_aux,
                n, start, valid, limit, mode: str) -> SearchState:
    """The fused bound+prune+compact route (ops/pallas_fused): the
    dense child grid, its (1, N) bound row, the (N,) prune mask and
    the (N,) partition keys never exist in HBM. The kernel emits the
    compacted survivors (capped at the steady W = N/4 frame) plus a
    count; leaves and eval totals come from the parent-level O(M*B)
    scan (_leaf_scan); a rare survivor-overflow step (count > W) takes
    the unfused pipeline via ONE lax.cond on bit-identical bound math,
    so the explored set cannot depend on which branch ran. For LB2 the
    kernel is the fused LB1 prefilter (also emitting the survivors'
    scheduled-set bitmask) and the shared _lb2_tail runs the pair
    sweeps over the compacted block — op-identical to the unfused
    two-phase route. Telemetry: popped/evaluated buckets are
    parent-level, branched buckets and the surviving-bound histogram
    come off the compacted block, and the PRUNED-bound histogram is
    the kernel's per-tile masked-add output — bound_hist_exact holds
    without the pruned bounds ever touching HBM."""
    J, capacity = state.prmu.shape
    M = tables.p.shape[0]
    B = chunk
    G = B // TB
    N = B * J
    TELE = state.telemetry.shape[-1] > 0

    leaf_best, n_leaf, evals_cnt = _leaf_scan(tables, p_prmu, p_depth,
                                              p_aux, valid)
    best = jnp.minimum(state.best, leaf_best)
    sol = state.sol + n_leaf
    if TELE:
        d = p_depth.reshape(-1)
        wb = tele.depth_bucket(d, J)
        popped_b = tele.bucket_counts(wb, valid)
        # evaluated non-leaf children bucket by PARENT depth: J - d of
        # them per valid parent below J-1, none at J-1 (its one child
        # is the leaf) — the dense route's bucket_counts(child_b,
        # mask & ~leaf) collapsed to parent-level weighted sums
        w = jnp.where(valid & (d < J - 1), (J - d).astype(jnp.int64), 0)
        evalnl_b = jnp.stack([jnp.sum(jnp.where(wb == k, w, 0))
                              for k in range(tele.DEPTH_BUCKETS)])

    # Survivor-cap width: the LB2 route caps at the steady N/4 frame
    # (matching the unfused tail's steady branch; the rare overflow
    # takes the spill cond below). The LB1 route runs uncapped — its
    # unfused pipeline block-writes a full-N frame anyway, so a narrow
    # cap would buy no frame bytes while costing a whole duplicated
    # spill pipeline in the compiled program (MEASURED: capping LB1 at
    # N/4 was a net LOSS, -8% vs +17% step-temp — the spill branch's
    # dense pipeline and the kernel outputs are live across the cond
    # boundary, so buffer assignment cannot overlay them).
    if lb_kind == 2:
        W = max(N // 4, 128)
        narrow = W < N
        if not narrow:
            W = N
    else:
        W = N
        narrow = False
    # survivors-only frames as narrow as their consumers allow: the
    # bound row only feeds the LB1 telemetry histogram (the LB2 tail
    # re-bounds survivors with the pair sweeps), and the LB1 caux
    # block can ride the pool's own narrow aux dtype — every output
    # byte of the kernel is the fused route's whole HBM footprint
    kch, kaux, kbnd, ksched, n_surv, khist = pallas_fused.fused_expand(
        tables, p_prmu, p_depth, p_aux, n, best, lb_kind=1, tile=TB,
        cap_width=W, with_sched=(route == "prefilter"),
        tele_bins=tele.BOUND_BINS if TELE else 0,
        with_bounds=(lb_kind != 2 and TELE),
        aux_i16=(lb_kind != 2 and state.aux.dtype == jnp.int16),
        interpret=(mode == "interpret"))
    if limit is None:
        limit = row_limit(capacity, B, J)

    def dense_masks():
        """The unfused routes' mask family (_child_masks — the same
        ops step() traces) — built ONLY inside the rare spill
        branches."""
        depth_c, mask = _child_masks(p_depth, valid, G, J, TB)
        is_leaf = ((depth_c + 1) == J) & mask
        return depth_c, mask, is_leaf

    def narrow_to_W(a, rows):
        """The kernel block at frame width W. The kernel's frame is
        always WPAD = W + store_sub(J*tile): the count-gated tail
        stores carry one sub-block of slack past the survivor cap, so
        every fused step pays this slice — a copy of each output at
        width W. That cost is priced in (the measured HBM wins
        include it); store_sub exists precisely to keep the slack —
        and therefore this copy's source frame — one ~N/8 sub-block
        instead of a whole tile. Clamping the kernel's final stores
        to land the frame at exactly W would retire the copy; that is
        hardware-round work (the cursor stores are being relowered
        through Mosaic anyway, ROADMAP item 4)."""
        if a.shape[1] == W:
            return a
        return jax.lax.slice(a, (0, 0), (rows, W))

    if lb_kind != 2:
        def fused_fit(_):
            children = narrow_to_W(kch, J)
            caux = narrow_to_W(kaux, M + 1)
            child_depth = caux[M].astype(jnp.int16)
            prmu, depth, aux = _write_block(
                state, children, child_depth, caux, start, n_surv,
                limit)
            out = (prmu, depth, aux, n_surv)
            if TELE:
                bnd = narrow_to_W(kbnd, 1)
                livem = jnp.arange(W) < n_surv
                pb = tele.depth_bucket(
                    caux[M].astype(jnp.int32).reshape(-1) - 1, J)
                out += (jnp.concatenate(
                    [tele.bucket_counts(pb, livem),
                     tele.bound_hist(bnd, livem, best)]),)
            return out

        # LB1 runs uncapped (W == N, see the cap comment above):
        # n_surv can never exceed the frame, so there is no spill
        # branch to trace — only the LB2 route carries one
        outs = fused_fit(0)
        prmu, depth, aux, n_push = outs[:4]
        delta = None
        if TELE:
            DB = tele.DEPTH_BUCKETS
            bh = outs[4]
            delta = tele.step_delta(popped_b, bh[:DB],
                                    evalnl_b - bh[:DB],
                                    khist, bh[DB:])
        return _commit(state, prmu, depth, aux, n_push, best, sol,
                       jnp.asarray(evals_cnt), limit, start,
                       tele_delta=delta)

    # --- route == "prefilter": the kernel was the fused LB1 prefilter
    P = int(tables.ma0.shape[0])
    KH = batched.PAIR_PREFILTER
    SW = pallas_expand.sched_words(J)
    debug_tap = bool(__debug__ and P > KH and _DEBUG_STEP)
    ncand = n_surv

    def fused_tail(_):
        children = narrow_to_W(kch, J)
        caux = narrow_to_W(kaux, M + 1)
        sched = narrow_to_W(ksched, SW)
        return _lb2_tail(tables, state, children, caux, sched, ncand,
                         W, N, best, start, limit, debug_tap, TELE)

    def spill_tail(_):
        lb1b = pallas_expand.expand_bounds(
            tables, p_prmu, p_depth, p_aux, lb_kind=1, tile=TB)
        _, mask, is_leaf = dense_masks()
        cand = (mask & ~is_leaf & (lb1b < best)).reshape(-1)
        perm1 = _partition(cand)
        children, caux, sched = _compact_from_parents(
            tables, p_prmu, p_depth, p_aux, perm1, ncand, TB, N,
            with_sched=True, two_phase=True, cap=N)
        return _lb2_tail(tables, state, children, caux, sched, ncand,
                         N, N, best, start, limit, debug_tap, TELE)

    if narrow:
        outs = jax.lax.cond(ncand <= W, fused_tail, spill_tail, 0)
    else:
        outs = fused_tail(0)
    prmu, depth, aux, n_push, hsum, tsum = outs[:6]
    if debug_tap:
        state = state._replace(sent=hsum, recv=tsum,
                               steals=n_push.astype(jnp.int64))
    delta = None
    if TELE:
        DB, BB = tele.DEPTH_BUCKETS, tele.BOUND_BINS
        branched_b = outs[6][:DB]
        delta = tele.step_delta(
            popped_b, branched_b, evalnl_b - branched_b,
            khist + outs[6][DB:DB + BB], outs[6][DB + BB:])
    return _commit(state, prmu, depth, aux, n_push, best, sol,
                   jnp.asarray(evals_cnt), limit, start,
                   tele_delta=delta)


def step(tables: BoundTables, lb_kind: int, chunk: int,
         state: SearchState, tile: int = 1024,
         limit: int | None = None, fused: str = "off") -> SearchState:
    """One pop->bound->prune->branch cycle (the compiled analogue of the
    reference per-thread hot loop, pfsp_multigpu_cuda.c:221-320).

    `limit` tightens the usable-row bound below the default
    row_limit(capacity, chunk, jobs) — the distributed loop reserves
    extra headroom above it so balance-round block writes stay in bounds
    (engine/distributed._balance_round)."""
    J, capacity = state.prmu.shape
    B = chunk
    assert capacity >= B, f"pool capacity {capacity} < chunk {B}"
    M = tables.p.shape[0]
    assert state.aux.shape[0] == M, (
        f"pool aux width {state.aux.shape[0]} != machines {M}: "
        "seed the state with init_state(..., p_times=...) so it carries "
        "the per-node front tables")
    # the tile ALSO defines the expand outputs' column order — derived
    # through the same single functions expand() uses; lb2_route owns
    # the LB2 route/tile choice (dense vs prefilter, including the
    # LB1-tile retry for the 100-job classes whose register pair kernel
    # is gated off — measured on ta071/ta081, BENCHMARKS.md)
    if lb_kind == 2:
        route, TB, _ = lb2_route(J, M, int(tables.ma0.shape[0]), B, tile)
    else:
        route = None
        TB = pallas_expand.effective_tile(J, B, tile, lb_kind, machines=M)
    G = B // TB
    N = B * J

    p_prmu, p_depth, p_aux, n, start, valid = pop_chunk(state, B, M)
    # The pool stores aux in the narrow per-instance dtype (aux_dtype:
    # int16 for every class whose completion times fit); intra-step
    # blocks are all i32 — measured on v5e: TPU column gathers are
    # element/latency-bound, so narrow GATHERS buy nothing (+18% step
    # time when tried), while the sequential push block-write IS
    # byte-bound and pays half, and the balance all_to_all + checkpoint
    # + pool HBM footprint halve too. The cast back happens at the
    # write below.
    p_aux = p_aux.astype(jnp.int32)

    # --- fused bound+prune+compact route (ops/pallas_fused): STATIC
    # gate — `fused` is a static argument threaded from the host-side
    # mode resolution (never an env read at trace time), and fused_ok
    # applies the same expand-kernel shape rule as the unfused
    # dispatch. LB2's dense (few-pair) route and LB1_d stay unfused.
    if (fused != "off"
            and pallas_fused.fused_ok(fused, J, TB, lb_kind, M)
            and (lb_kind == 1 or route == "prefilter")):
        return _fused_step(tables, lb_kind, route, B, TB, state,
                           p_prmu, p_depth, p_aux, n, start, valid,
                           limit, fused)

    # --- masks in the kernel's child-slot column order (shared with
    # the fused spill branches — _child_masks)
    depth_c, mask = _child_masks(p_depth, valid, G, J, TB)     # (1, N)

    # --- search telemetry (STATIC Python branch: with the block off the
    # traced program contains zero telemetry ops). Common inputs shared
    # by every route: popped parents and evaluated non-leaf children by
    # relative-depth bucket; each route supplies its branched buckets
    # and bound histograms, pruned = evaluated - branched by exactness
    # of the per-route accounting (tests pin the bucket sums).
    TELE = state.telemetry.shape[-1] > 0
    if TELE:
        is_leaf_c = ((depth_c + 1) == J) & mask
        child_b = tele.depth_bucket(depth_c.reshape(-1), J)
        popped_b = tele.bucket_counts(
            tele.depth_bucket(p_depth.reshape(-1), J), valid)
        evalnl_b = tele.bucket_counts(
            child_b, (mask & ~is_leaf_c).reshape(-1))

    P = int(tables.ma0.shape[0]) if lb_kind == 2 else 0
    KH = batched.PAIR_PREFILTER
    if route == "dense":
        # One-shot dense LB2 for the FEW-PAIR classes (P <= 2*KH — no
        # prefilter tier exists): sweep all P pairs over the dense child
        # grid and compact ONCE. The two-phase detour assumes the LB1
        # pre-prune removes most of the grid; in the weak-bound regimes
        # these classes live in (ta031: 50x5, LB1 removes only ~27%) it
        # removed almost nothing while its full-width regather+sort ran
        # anyway — measured 10x slower per pushed node than ta021. With
        # P this small the dense sweep costs less than the detour even
        # when LB1 WOULD have pruned well (20x5: a wash), so the route
        # is static. The explored set is identical either way (the final
        # prune uses the same exact LB2 values), matching the
        # reference's single code path (bounds_gpu.cu:252-316).
        _, _, lb2b = pallas_expand.expand(
            tables, p_prmu, p_depth, p_aux, lb_kind=2, tile=TB)

        is_leaf = ((depth_c + 1) == J) & mask
        sol = state.sol + is_leaf.sum(dtype=jnp.int64)
        # a complete schedule's LB2 == its makespan
        leaf_best = jnp.where(is_leaf, lb2b, I32_MAX).min()
        best = jnp.minimum(state.best, leaf_best)

        push = (mask & ~is_leaf & (lb2b.reshape(1, -1) < best)).reshape(-1)
        n_push = push.sum(dtype=jnp.int32)
        if TELE:
            branched_b = tele.bucket_counts(child_b, push)
            hist_surv = tele.bound_hist(lb2b, push, best)
            hist_pruned = tele.bound_hist(
                lb2b, (mask & ~is_leaf).reshape(-1) & ~push, best)

        # Compaction rebuilds survivors from the CHUNK-WIDE parents
        # (_compact_from_parents) rather than gathering the dense
        # (rows, N) child blocks the kernel materialized: at the wide
        # classes this route serves (50x5: N = 1.64M at chunk 32768)
        # the dense frame sits far past the v5e source-width gather
        # cliff (tools/bench_gather.py), while the parent sources stay
        # 32k wide. The expand kernel's children/aux outputs are dead
        # here (lb2 sweeps run on the kernel's internal fronts) — their
        # materialization is cheap relative to the cliff-priced dense
        # gathers this replaces (measured: ta033 1.21M -> 1.65M
        # pushed/s).
        perm = _partition(push)
        children, child_aux = _compact_from_parents(
            tables, p_prmu, p_depth, p_aux, perm, n_push, TB, N,
            two_phase=True)
        child_depth = child_aux[M].astype(jnp.int16)
    elif route == "prefilter":
        # Two-phase LB2 (TPU): bound every child with the near-free LB1
        # first (LB1 <= LB2, so LB1-pruning is sound and the explored
        # set stays the exact LB2 set), rebuild only the survivors from
        # their parents (regather), and run the expensive pair-sweep
        # kernel only over the smallest prefix tier that covers them. At
        # UB=opt LB1 removes ~85% of the child grid. The reference gets
        # its version of this saving from the per-child early exit the
        # vector unit cannot take (c_bound_johnson.c:231-233).
        lb1b = pallas_expand.expand_bounds(
            tables, p_prmu, p_depth, p_aux, lb_kind=1, tile=TB)

        is_leaf = ((depth_c + 1) == J) & mask
        sol = state.sol + is_leaf.sum(dtype=jnp.int64)
        # a complete schedule's LB1 == LB2 == its makespan
        leaf_best = jnp.where(is_leaf, lb1b, I32_MAX).min()
        best = jnp.minimum(state.best, leaf_best)

        cand = (mask & ~is_leaf & (lb1b < best)).reshape(-1)
        ncand = cand.sum(dtype=jnp.int32)
        if TELE:
            # children the LB1 prefilter pruned, binned at the bound
            # that pruned them (the tail sweep's prunes bin at their
            # exact LB2 inside the pipeline)
            hist_lb1_pruned = tele.bound_hist(
                lb1b, (mask & ~is_leaf).reshape(-1) & ~cand, best)

        perm1 = _partition(cand)
        debug_tap = bool(__debug__ and P > KH and _DEBUG_STEP)
        if limit is None:
            limit = row_limit(capacity, B, J)

        def tail_pipeline(W_):
            """Everything after the LB1 prune, in W_-wide frames
            (_lb2_tail — shared with the fused route so the two cannot
            drift).

            Run twice as the two branches of ONE lax.cond: the steady
            branch at W_ = N//4 (taken whenever ncand fits, ~93% of
            ta021 steady-state iterations) and the safe branch at
            W_ = N. On v5e the gather cost cliff sits on the SOURCE
            width (tools/bench_gather.py: t=61440 costs 0.69 ms from a
            164k-wide source vs 4.0 ms from a 655k-wide one), so the
            steady branch's blocks are BORN narrow — its compaction
            gathers read N//4-wide sources, its pads/copies and the
            final pool block write shrink 4x. Slicing the sources of a
            full-width pipeline instead was measured WORSE than the
            round-3 baseline (the slice ops break XLA's gather+pad
            fusions and re-materialize every block: 43.6M -> 34.0M
            evals/s), which is why the narrow width is threaded through
            the whole pipeline rather than applied at the gathers."""
            def f(_):
                children, caux, sched = _compact_from_parents(
                    tables, p_prmu, p_depth, p_aux, perm1, ncand, TB, N,
                    with_sched=True, two_phase=True, cap=W_)
                return _lb2_tail(tables, state, children, caux, sched,
                                 ncand, W_, N, best, start, limit,
                                 debug_tap, TELE)
            return f

        # N/4 cap: ncand hovers just under it on the 20x20 class
        # (~0.93 N/4 steady state; ~7% of iterations exceed it and take
        # a wider branch). A 5N/16 cap was measured very slightly
        # WORSE (47.4M vs 47.9M): widening every steady-branch frame
        # costs more than the rare safe branch saves. Instead the
        # overflow iterations get a MIDDLE 3N/8 frame (a lax.switch
        # rung): they ran the full-N pipeline at ~2x the steady cost,
        # and nearly all of them fit 3N/8 — the steady branch stays
        # untouched (measured on ta021: 48.7 -> 51.0M evals/s).
        W = max(N // 4, 128)
        W2 = 3 * N // 8
        if W >= N:  # toy shapes: no narrow branch exists
            outs = tail_pipeline(N)(0)
        elif W2 <= W or W2 >= N or W2 % 128 != 0:
            outs = jax.lax.cond(
                ncand <= W, tail_pipeline(W), tail_pipeline(N), 0)
        else:
            sel = ((ncand > W).astype(jnp.int32)
                   + (ncand > W2).astype(jnp.int32))
            outs = jax.lax.switch(
                sel, [tail_pipeline(W), tail_pipeline(W2),
                      tail_pipeline(N)], 0)
        prmu, depth, aux, n_push, hsum, tsum = outs[:6]

        if debug_tap:
            state = state._replace(sent=hsum, recv=tsum,
                                   steals=n_push.astype(jnp.int64))
        delta = None
        if TELE:
            DB, BB = tele.DEPTH_BUCKETS, tele.BOUND_BINS
            branched_b = outs[6][:DB]
            delta = tele.step_delta(
                popped_b, branched_b, evalnl_b - branched_b,
                hist_lb1_pruned + outs[6][DB:DB + BB],
                outs[6][DB + BB:])
        return _commit(state, prmu, depth, aux, n_push, best, sol, mask,
                       limit, start, tele_delta=delta)
    else:
        # --- bounds of the dense child grid (Pallas on TPU; the children
        # themselves are never materialized — survivors are rebuilt from
        # their parents below)
        bounds = pallas_expand.expand_bounds(
            tables, p_prmu, p_depth, p_aux, lb_kind=lb_kind, tile=TB)

        # --- leaves: complete schedules; count + tighten incumbent
        # (reference: the depth==jobs branch of decompose, PFSP_lib.c:24-32)
        is_leaf = ((depth_c + 1) == J) & mask
        sol = state.sol + is_leaf.sum(dtype=jnp.int64)
        leaf_best = jnp.where(is_leaf, bounds, I32_MAX).min()
        best = jnp.minimum(state.best, leaf_best)

        # --- prune + push surviving internal children
        push = (mask & ~is_leaf & (bounds < best)).reshape(-1)
        n_push = push.sum(dtype=jnp.int32)
        if TELE:
            branched_b = tele.bucket_counts(child_b, push)
            hist_surv = tele.bound_hist(bounds, push, best)
            hist_pruned = tele.bound_hist(
                bounds, (mask & ~is_leaf).reshape(-1) & ~push, best)

        # Compaction: stable-partition the surviving column indices to
        # the front (_partition), rebuild those children from their
        # parents (_compact_from_parents), then write the whole block
        # contiguously at `start`. A per-node compacting scatter costs
        # ~100x more on TPU (it serializes row updates); the garbage
        # columns past n_push land above the cursor and are never read.
        # The top chunk*J rows of the pool are a scratch margin (see
        # row_limit) so the block write stays in bounds even when the
        # live region is full.
        perm = _partition(push)
        children, child_aux = _compact_from_parents(
            tables, p_prmu, p_depth, p_aux, perm, n_push, TB, N)
        child_depth = child_aux[M].astype(jnp.int16)

    if limit is None:
        limit = row_limit(capacity, B, J)
    prmu, depth, aux = _write_block(state, children, child_depth,
                                    child_aux, start, n_push, limit)
    delta = (tele.step_delta(popped_b, branched_b,
                             evalnl_b - branched_b,
                             hist_pruned, hist_surv)
             if TELE else None)
    return _commit(state, prmu, depth, aux, n_push, best, sol, mask,
                   limit, start, tele_delta=delta)


@functools.partial(jax.jit,
                   static_argnames=("lb_kind", "chunk", "tile", "fused"))
def _run(tables: BoundTables, state: SearchState, lb_kind: int, chunk: int,
         max_iters: jax.Array, drain_min: jax.Array,
         tile: int = 1024, fused: str = "off") -> SearchState:
    def cond(s: SearchState):
        return (s.size >= drain_min) & ~s.overflow & (s.iters < max_iters)

    body = functools.partial(step, tables, lb_kind, chunk, tile=tile,
                             fused=fused)
    return jax.lax.while_loop(cond, lambda s: body(state=s), state)


def run(tables: BoundTables, state: SearchState, lb_kind: int, chunk: int,
        max_iters: int | None = None, tile: int = 1024,
        drain_min: int = 1, fused=None) -> SearchState:
    """Run the search to exhaustion (or up to a cumulative `max_iters`) in
    one compiled loop (the analogue of pfsp_c.c:55-63's while(1)
    pop+decompose). `max_iters` is a traced scalar, NOT a static argument:
    segmented drivers pass a new ceiling every segment and must hit the
    compile cache. `fused` (None = the TTS_FUSED env resolution,
    ops/pallas_fused.resolve_mode) is resolved HERE, host-side, and rides
    the jit key as a static mode string — flipping the knob retraces
    instead of reusing a stale executable."""
    jobs, capacity = state.prmu.shape[-2:]
    if int(np.asarray(state.size).max()) > row_limit(capacity, chunk, jobs):
        # Pool already fuller than the usable limit (e.g. capacity < the
        # chunk*jobs scratch margin): report overflow without touching
        # anything — the caller grows the pool and resumes losslessly.
        return state._replace(overflow=jnp.asarray(True))
    ceiling = (jnp.iinfo(state.iters.dtype).max if max_iters is None
               else max_iters)
    return _run(tables, state, lb_kind, chunk,
                jnp.asarray(ceiling, dtype=state.iters.dtype),
                jnp.asarray(max(drain_min, 1), dtype=jnp.int32), tile=tile,
                fused=pallas_fused.resolve_mode(fused))


def generic_step(problem, tables, lb_kind: int, chunk: int,
                 state: SearchState, tile: int = 1024,
                 limit: int | None = None) -> SearchState:
    """One problem-generic pop -> branch -> bound -> prune -> compact
    cycle, parameterized by the plugin protocol (problems/base.Problem):
    the plugin supplies the dense child grid (`branch`) and the child
    bound values (`bound`); everything else — pool pop, incumbent and
    solution accounting, stable-partition compaction, the scratch-margin
    overflow contract and the telemetry block — is shared engine code.

    This is the default `Problem.make_step` pipeline (N-Queens, TSP,
    knapsack); PFSP overrides the hook with the specialized two-phase
    Pallas pipeline above (`step`). The N-Queens instantiation is
    op-for-op the pipeline the deleted `engine/nqueens_device.nq_step`
    ran (same pop, same stable argsort partition, same block write and
    overflow guard), so node/sol/evals counts are bit-identical to the
    pre-refactor fork — pinned by the parity suite.

    `tile` is accepted for signature parity with the fast-path hook and
    ignored (the generic pipeline has no kernel tiling)."""
    del tile
    J, capacity = state.prmu.shape
    A = state.aux.shape[0]
    B = chunk

    n_pop = jnp.minimum(state.size, B)
    start = state.size - n_pop
    valid = jnp.arange(B) < n_pop
    zero = jnp.zeros((), start.dtype)
    p_prmu = jax.lax.dynamic_slice(state.prmu, (zero, start), (J, B))
    depth = jnp.where(
        valid,
        jax.lax.dynamic_slice(state.depth, (start,), (B,)).astype(jnp.int32),
        0)
    p_aux = jax.lax.dynamic_slice(state.aux, (zero, start), (A, B)) \
        .astype(jnp.int32)

    sol = state.sol
    if not problem.leaf_in_evals:
        # N-Queens-style accounting: a popped complete node is a
        # solution (reference: nqueens_c.c:104-106); children at full
        # depth are pushed like any survivor
        sol = sol + ((depth == J) & valid).sum(dtype=jnp.int64)

    br = problem.branch(tables, p_prmu, depth, p_aux, valid)
    C = br.children.shape[1]
    assert C <= B * (problem.branch_factor or J), (
        f"branch grid {C} wider than the chunk*branching scratch "
        f"margin {B * (problem.branch_factor or J)}: the overflow "
        "block write would run out of bounds")
    bounds = problem.bound(tables, lb_kind, br, state.best).reshape(-1)
    evaluated = br.evaluated.reshape(-1)
    if problem.leaf_in_evals:
        # PFSP-style: every evaluated leaf child counts, the incumbent
        # tightens from leaf bounds (bound == objective at leaves), and
        # leaves are never pushed
        is_leaf = evaluated & problem.is_leaf_cols(tables, br).reshape(-1)
        sol = sol + is_leaf.sum(dtype=jnp.int64)
        leaf_best = jnp.where(is_leaf, bounds, I32_MAX).min()
        best = jnp.minimum(state.best, leaf_best)
        push = evaluated & ~is_leaf & (bounds < best)
    else:
        is_leaf = jnp.zeros_like(evaluated)
        best = state.best
        push = evaluated & (bounds < best)
    n_push = push.sum(dtype=jnp.int32)
    tree = state.tree + n_push.astype(jnp.int64)

    # stable-partition survivors to the front, block-write at the
    # cursor (scatter-free push; the same scheme as step/nq_step)
    order = jnp.argsort(~push, stable=True)
    children = jnp.take(br.children, order, axis=1)
    child_depth = jnp.take(br.child_depth, order)
    child_aux = jnp.take(br.child_aux, order, axis=1)

    if limit is None:
        limit = problem.usable_rows(capacity, B, J)
    new_size = start + n_push
    overflow = new_size > limit
    write_at = jnp.where(overflow, jnp.asarray(limit, start.dtype), start)
    keep = lambda new, old: jnp.where(overflow, old, new)  # noqa: E731
    evals = state.evals + evaluated.sum(dtype=jnp.int64)
    telem = state.telemetry
    if telem.shape[-1] > 0:
        # child buckets bin by PARENT depth (= child_depth - 1), the
        # same convention as step()/the deleted nq_step; the bound
        # histograms bin every pruned/surviving child so the audit's
        # bound_hist_exact invariant holds for every problem (unbounded
        # problems' 0 / I32_MAX sentinel bounds land in fixed bins)
        cb = tele.depth_bucket(br.child_depth.astype(jnp.int32) - 1, J)
        pruned_m = evaluated & ~is_leaf & ~push
        delta = tele.step_delta(
            tele.bucket_counts(tele.depth_bucket(depth, J), valid),
            tele.bucket_counts(cb, push),
            tele.bucket_counts(cb, pruned_m),
            tele.bound_hist(bounds, pruned_m, best),
            tele.bound_hist(bounds, push, best))
        telem = keep(tele.commit(telem, delta, new_size, best,
                                 state.best, state.iters), telem)
    return state._replace(
        prmu=jax.lax.dynamic_update_slice(state.prmu, children,
                                          (zero, write_at)),
        depth=jax.lax.dynamic_update_slice(state.depth, child_depth,
                                           (write_at,)),
        aux=jax.lax.dynamic_update_slice(
            state.aux, child_aux.astype(state.aux.dtype),
            (zero, write_at)),
        size=keep(new_size, state.size),
        best=keep(best, state.best),
        tree=keep(tree, state.tree),
        sol=keep(sol, state.sol),
        iters=state.iters + 1,
        evals=keep(evals, state.evals),
        overflow=state.overflow | overflow,
        telemetry=telem,
    )


@functools.partial(jax.jit,
                   static_argnames=("problem", "lb_kind", "chunk", "tile",
                                    "fused"))
def _run_problem(tables, state: SearchState, problem, lb_kind: int,
                 chunk: int, max_iters: jax.Array, drain_min: jax.Array,
                 tile: int = 1024, fused: str = "off") -> SearchState:
    def cond(s: SearchState):
        return (s.size >= drain_min) & ~s.overflow & (s.iters < max_iters)

    body = problem.make_step(tables, lb_kind, chunk, tile, None,
                             fused=fused)
    return jax.lax.while_loop(cond, lambda s: body(s), state)


def run_problem(problem, tables, state: SearchState, lb_kind: int,
                chunk: int, max_iters: int | None = None,
                tile: int = 1024, drain_min: int = 1,
                fused=None) -> SearchState:
    """Problem-generic `run`: the plugin's step (fast-path hook or
    generic_step) to exhaustion in one compiled loop. `max_iters` is a
    traced scalar like run()'s — segmented drivers hit the compile
    cache across ceilings. `fused` resolves like run()'s (host-side,
    static on the jit key); plugins without a fused fast path ignore
    it."""
    jobs, capacity = state.prmu.shape[-2:]
    if int(np.asarray(state.size).max()) > \
            problem.usable_rows(capacity, chunk, jobs):
        # as in run(): flag overflow without touching anything — the
        # caller grows the pool and resumes losslessly (same margin
        # rule as generic_step's default limit: the two must agree, or
        # a seeded state could sit past the scratch rows a step writes)
        return state._replace(overflow=jnp.asarray(True))
    ceiling = (jnp.iinfo(state.iters.dtype).max if max_iters is None
               else max_iters)
    return _run_problem(tables, state, problem, lb_kind, chunk,
                        jnp.asarray(ceiling, dtype=state.iters.dtype),
                        jnp.asarray(max(drain_min, 1), dtype=jnp.int32),
                        tile=tile, fused=pallas_fused.resolve_mode(fused))


def solve(problem, table: np.ndarray, lb_kind: int | None = None,
          init_ub: int | None = None, chunk: int = 64,
          capacity: int | None = None, max_iters: int | None = None,
          tile: int = 1024) -> SearchResult:
    """Single-device host entry for ANY registered problem: build the
    plugin's tables, seed the pool from its root, run to exhaustion
    with lossless grow-on-overflow (checkpoint.grow — the same recovery
    path search() uses). `problem` is a plugin object or a registry
    name."""
    from . import checkpoint

    if isinstance(problem, str):
        from .. import problems as problems_pkg
        problem = problems_pkg.get(problem)
    table = np.asarray(table)
    if lb_kind is None:
        lb_kind = problem.default_lb
    tables = problem.make_tables(table)
    jobs = problem.slots(table)
    if capacity is None:
        capacity = problem.default_capacity(table)
    prmu0, depth0 = problem.root(table)
    state = init_state(jobs, capacity, init_ub, prmu0=prmu0,
                       depth0=depth0,
                       aux0=problem.seed_aux(table, prmu0, depth0))
    while True:
        out = run_problem(problem, tables, state, lb_kind, chunk,
                          max_iters, tile=tile)
        if not bool(out.overflow):
            return SearchResult(
                explored_tree=int(out.tree), explored_sol=int(out.sol),
                best=int(out.best), iters=int(out.iters),
                evals=int(out.evals), overflow=False,
                complete=int(out.size) == 0,
            )
        capacity *= 2
        state = checkpoint.grow(out, capacity)


def default_capacity(jobs: int, machines: int, floor: int = 1 << 18) -> int:
    """Pool-capacity pre-sizing by instance class. The weak-bound
    few-machine classes (ta031-class 50x5) hold ~11M live rows at their
    peak (measured, BENCHMARKS r2); starting at the generic default
    costs six doubling cycles, each a fetch + re-home + recompile.
    Large-but-strong classes get one free doubling step instead."""
    if jobs >= 40 and machines <= 8:
        return max(1 << 24, floor)
    if jobs >= 40 or machines <= 8:
        return max(1 << 20, floor)
    return floor


class SearchResult(NamedTuple):
    explored_tree: int
    explored_sol: int
    best: int
    iters: int
    evals: int
    overflow: bool
    complete: bool = True  # pool drained (False: max_iters truncation)


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           chunk: int = 64, capacity: int = 1 << 18,
           max_iters: int | None = None,
           tables: BoundTables | None = None,
           tile: int = 1024) -> SearchResult:
    """Host entry point: build tables, run, fetch counters.

    On overflow the pool is re-homed into double the capacity and the
    search RESUMES from exactly where it stopped (checkpoint.grow) — the
    lossless static-shape replacement for the reference's
    realloc-on-push (round 1 restarted from scratch here).
    """
    from . import checkpoint

    if tables is None:
        tables = batched.make_tables(p_times)
    jobs = p_times.shape[1]
    state = init_state(jobs, capacity, init_ub, p_times=p_times)
    while True:
        out = run(tables, state, lb_kind, chunk, max_iters, tile=tile)
        if not bool(out.overflow):
            return SearchResult(
                explored_tree=int(out.tree), explored_sol=int(out.sol),
                best=int(out.best), iters=int(out.iters),
                evals=int(out.evals), overflow=False,
                complete=int(out.size) == 0,
            )
        capacity *= 2
        state = checkpoint.grow(out, capacity)
