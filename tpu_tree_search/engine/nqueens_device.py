"""N-Queens device engines (single-device and distributed).

Same HBM-pool machinery as the PFSP engine (engine/device.py) with the
problem-specific differences of the reference's N-Queens programs
(reference: nqueens_c.c:99-148, nqueens_multigpu_cuda.cu:213-360):

- children are *safe* candidates, all of which are pushed — including
  complete boards (no bound, no incumbent);
- a popped node at depth N counts as a solution;
- `explored_tree` counts pushes, as in PFSP.

The reference's multi-GPU N-Queens has no work stealing (static split
only, SURVEY.md §2.2); the TPU version reuses the collective balancer
anyway — strictly more capable, same results.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import nqueens_ops
from ..parallel.mesh import worker_mesh
from . import distributed as dist
from . import telemetry as tele
from .device import SearchState, init_state, make_children, row_limit

I32_MAX = jnp.int32(2**31 - 1)


def nq_step(n: int, g: int, chunk: int, state: SearchState,
            limit: int | None = None) -> SearchState:
    """One pop -> safety-check -> branch cycle.

    The pool is feature-major (device.SearchState); the safety kernel is
    row-major, so the popped block is transposed in and the child block
    transposed out — at N-Queens batch sizes that cost is noise.
    `limit` tightens the usable-row bound (see device.step)."""
    N, capacity = state.prmu.shape
    B = chunk

    n_pop = jnp.minimum(state.size, B)
    start = state.size - n_pop
    valid = jnp.arange(B) < n_pop
    zero = jnp.zeros((), start.dtype)
    board = jax.lax.dynamic_slice(state.prmu, (zero, start), (N, B)).T
    depth = jnp.where(
        valid,
        jax.lax.dynamic_slice(state.depth, (start,), (B,)).astype(jnp.int32),
        0)

    # popped complete boards are solutions (reference: nqueens_c.c:104-106)
    sol = state.sol + ((depth == N) & valid).sum(dtype=jnp.int64)

    push = nqueens_ops.safe_children(board, depth, valid, g=g)
    flat_push = push.reshape(-1)
    n_push = flat_push.sum(dtype=jnp.int32)
    tree = state.tree + n_push.astype(jnp.int64)

    children = make_children(board, depth).reshape(B * N, N)
    child_depth = jnp.broadcast_to((depth + 1)[:, None], (B, N)) \
        .reshape(-1).astype(jnp.int16)

    # As in device.step: stable-partition survivors first, block-write at
    # `start` (scatter-free push), route an overflowing write to the
    # scratch margin so the state stays resumable.
    order = jnp.argsort(~flat_push, stable=True)
    children = jnp.take(children, order, axis=0).T        # (N, B*N)
    child_depth = jnp.take(child_depth, order)

    if limit is None:
        limit = row_limit(capacity, B, N)
    new_size = start + n_push
    overflow = new_size > limit
    write_at = jnp.where(overflow, jnp.asarray(limit, start.dtype), start)
    keep = lambda new, old: jnp.where(overflow, old, new)  # noqa: E731
    evaluated = ((jnp.arange(N)[None, :] >= depth[:, None])
                 & valid[:, None])                          # (B, N)
    evals = state.evals + evaluated.sum(dtype=jnp.int64)
    telem = state.telemetry
    if telem.shape[-1] > 0:
        # search telemetry, mirroring device.step: popped/branched/
        # pruned by relative-depth bucket ("pruned" = unsafe children —
        # N-Queens has no bound, so the histograms and the incumbent
        # ring stay zero; state.best never improves, telemetry.commit's
        # ring write is a no-op select)
        pb = tele.depth_bucket(depth, N)                    # (B,)
        pbc = jnp.broadcast_to(pb[:, None], (B, N)).reshape(-1)
        delta = tele.step_delta(
            tele.bucket_counts(pb, valid),
            tele.bucket_counts(pbc, flat_push),
            tele.bucket_counts(pbc, evaluated.reshape(-1) & ~flat_push))
        telem = keep(tele.commit(telem, delta, new_size, state.best,
                                 state.best, state.iters), telem)
    return state._replace(
        prmu=jax.lax.dynamic_update_slice(state.prmu, children,
                                          (zero, write_at)),
        depth=jax.lax.dynamic_update_slice(state.depth, child_depth,
                                           (write_at,)),
        size=keep(new_size, state.size),
        tree=keep(tree, state.tree),
        sol=keep(sol, state.sol),
        iters=state.iters + 1,
        evals=keep(evals, state.evals),
        overflow=state.overflow | overflow,
        telemetry=telem,
    )


@functools.partial(jax.jit, static_argnames=("n", "g", "chunk"))
def _run(state: SearchState, n: int, g: int, chunk: int,
         max_iters: jax.Array) -> SearchState:
    def cond(s):
        return (s.size > 0) & ~s.overflow & (s.iters < max_iters)

    return jax.lax.while_loop(cond, functools.partial(nq_step, n, g, chunk),
                              state)


def run(state: SearchState, n: int, g: int, chunk: int,
        max_iters: int | None = None) -> SearchState:
    """`max_iters` is a traced scalar (see device.run): segmented callers
    pass a new ceiling per segment without recompiling."""
    capacity = state.prmu.shape[-1]
    if int(np.asarray(state.size).max()) > row_limit(capacity, chunk, n):
        # as in device.run: overflow-flag, don't touch anything
        return state._replace(overflow=jnp.asarray(True))
    ceiling = (jnp.iinfo(state.iters.dtype).max if max_iters is None
               else max_iters)
    return _run(state, n, g, chunk,
                jnp.asarray(ceiling, dtype=state.iters.dtype))


class NQResult(NamedTuple):
    explored_tree: int
    explored_sol: int
    iters: int


def search(n: int, g: int = 1, chunk: int = 64, capacity: int = 1 << 18,
           max_iters: int | None = None) -> NQResult:
    """Single-device N-Queens search (reference: nqueens_gpu_cuda.cu)."""
    while True:
        state = init_state(n, capacity, None)
        out = run(state, n, g, chunk, max_iters)
        if not bool(out.overflow):
            return NQResult(explored_tree=int(out.tree),
                            explored_sol=int(out.sol),
                            iters=int(out.iters))
        capacity *= 2


def bfs_warmup(n: int, target: int):
    """Host BFS frontier for seeding the mesh (reference step 1,
    nqueens_multigpu_cuda.cu:232-238)."""
    from collections import deque

    from ..problems import nqueens as nq
    tree = sol = 0
    frontier = deque([(np.arange(n, dtype=np.int16), 0)])
    while frontier and len(frontier) < target:
        board, depth = frontier.popleft()
        if depth == n:
            sol += 1
            continue
        for j in range(depth, n):
            if nq.is_safe(board, depth, int(board[j])):
                child = board.copy()
                child[depth], child[j] = child[j], child[depth]
                frontier.append((child, depth + 1))
                tree += 1
    prmu = (np.stack([f[0] for f in frontier]).astype(np.int16)
            if frontier else np.zeros((0, n), np.int16))
    depths = np.array([f[1] for f in frontier], dtype=np.int16)
    return dist.Frontier(prmu=prmu, depth=depths, tree=tree, sol=sol,
                         best=2**31 - 1)


def search_distributed(n: int, g: int = 1, n_devices: int | None = None,
                       chunk: int = 64, capacity: int = 1 << 17,
                       balance_period: int = 4, min_seed: int = 32,
                       transfer_cap: int | None = None,
                       min_transfer: int | None = None,
                       mesh=None) -> NQResult:
    """Distributed N-Queens over the worker mesh
    (capability parity with nqueens_multigpu_cuda.cu, plus balancing)."""
    if mesh is None:
        mesh = worker_mesh(n_devices)
    n_dev = mesh.devices.size
    fr = bfs_warmup(n, target=min_seed * n_dev)

    def make_local_step(_tables, limit):
        return functools.partial(nq_step, n, g, chunk, limit=limit)

    out = dist.run_with_retry(
        mesh, (), make_local_step, fr, capacity, n,
        init_best=2**31 - 1, balance_period=balance_period,
        transfer_cap=transfer_cap or 4 * chunk,
        min_transfer=min_transfer or 2 * chunk, max_rounds=None,
        limit_fn=lambda cap: row_limit(cap, chunk, n))
    return NQResult(
        explored_tree=int(dist._fetch(out.tree).sum()) + fr.tree,
        explored_sol=int(dist._fetch(out.sol).sum()) + fr.sol,
        iters=int(dist._fetch(out.iters).max()),
    )
