"""Chunk-ladder execution: pool-aware rung selection for the
segmented distributed driver.

The tuned chunk is only optimal at STEADY STATE: bench.py documents
that ramp and drain phases "pop underfilled chunks for hundreds of
steps" at the fixed big chunk — every one of those steps pays the full
chunk-wide bound kernels for parents that are not there. The ladder
pre-compiles 2–3 chunk rungs per executor key (each its own
ExecutorCache/AOT entry, so switching never retraces) and switches
rungs ONLY at segment boundaries, driven by the live pool-occupancy
signal the per-segment counter fetch already carries: ramp-up and
drain run small-chunk steps, the filled middle runs the tuned chunk.

Correctness story:

- Every rung's compiled loop is built against ONE unified usable-row
  limit (the minimum over rungs of each rung's scratch-margin +
  balance-headroom bound — engine/distributed._ladder_plan), so a
  state committed by any rung is in-bounds for every other rung and a
  switch in either direction can never clamp a block write onto live
  rows.
- Rung choice only changes which compiled program runs a segment —
  pool contents, counters and the incumbent ride the same SearchState
  untouched, so node accounting is exact across every switch (the
  audit invariants hold; tests pin TTS_AUDIT_HARD across switches).
- `TTS_LADDER` is a STATIC flag: off (the default) takes the
  pre-ladder single-driver path bit-identically; on, a fixed-incumbent
  run (ub=opt) explores the identical node set — the explored tree is
  order-independent when the incumbent never moves.
- The live rung rides checkpoint meta (``ladder_rung``): resume starts
  on the recorded rung instead of re-deriving it from a pool snapshot
  that the warm-up/occupancy heuristic would misread.

Observability: ``tts_ladder_switches_total{direction=up|down}`` in the
process-global registry and ``ladder.start`` / ``ladder.switch``
flight-recorder events (segment, pool, from/to chunks).
"""

from __future__ import annotations

import threading

from ..obs import metrics as obs_metrics
from ..obs import tracelog

__all__ = ["RungController", "rungs_for", "min_rung_for",
           "rungs_from_profile", "fused_for",
           "set_memory_pressure", "memory_pressure",
           "LADDER_FACTOR", "LADDER_RUNGS", "LADDER_MIN_CHUNK",
           "LADDER_MIN_CHUNK_LB2"]

# process-wide memory-pressure hint (the remediation controller's
# mem_headroom action raises it, the alert's resolution clears it).
# Under pressure the controller holds the smallest COVERING rung —
# the ramp-momentum bump one rung above covering is suppressed, so the
# next segments run the narrowest per-iteration scratch that still
# pops exactly what the tuned chunk would. Covering-rung pops are
# pool-limited identically across rungs, so node accounting stays
# bit-identical with the hint on or off — it trades only adaptation
# latency for headroom. A threading.Event, not a flag under a lock:
# the readers are per-segment host callbacks.
_MEM_PRESSURE = threading.Event()


def set_memory_pressure(on: bool) -> None:
    """Raise/clear the demote-the-ladder hint (service/remediate)."""
    if on:
        _MEM_PRESSURE.set()
    else:
        _MEM_PRESSURE.clear()


def memory_pressure() -> bool:
    return _MEM_PRESSURE.is_set()

# rung geometry: LADDER_RUNGS rungs, each LADDER_FACTOR× the previous,
# topped by the tuned chunk (pow2 factor keeps every rung lane-aligned
# like the tuned chunk itself); rungs below the floor collapse into
# it. chunk <= floor * FACTOR yields a single rung and the ladder
# degrades to the plain driver.
#
# The floor is MEASURED, per bound: sub-lane chunks compile to
# programs whose per-iteration cost INVERTS the ladder's premise —
# on the 8-dev CPU mesh the LB2 pair-sweep loop costs 220 ms/iter at
# chunk 64 vs 15 ms/iter at 256 (the prefilter tail vectorizes below
# the lane width); LB1 at 64 stays cheap (9.6 ms/iter). A rung that
# is slower per iteration than the tuned chunk is a pure loss, so LB2
# never rungs below 256 and the cheap bounds never below 64.
LADDER_FACTOR = 4
LADDER_RUNGS = 3
LADDER_MIN_CHUNK = 64
LADDER_MIN_CHUNK_LB2 = 256


def min_rung_for(lb_kind: int) -> int:
    """The measured per-bound rung floor (see the note above)."""
    return LADDER_MIN_CHUNK_LB2 if lb_kind == 2 else LADDER_MIN_CHUNK


def rungs_for(chunk: int, n_rungs: int = LADDER_RUNGS,
              factor: int = LADDER_FACTOR,
              min_chunk: int = LADDER_MIN_CHUNK) -> tuple[int, ...]:
    """The ascending rung chunks under (and including) `chunk`."""
    chunk = int(chunk)
    rungs = {max(min_chunk, chunk // factor ** k)
             for k in range(n_rungs)}
    return tuple(sorted(min(r, chunk) for r in rungs))


def _profile_rows(profile) -> dict:
    """Normalize a per-rung tuning profile (tune/defaults
    Params.rung_modes — a tuple of {"chunk", "winner", "ms_per_iter",
    ...} dicts, JSON-roundtripped through the TuningCache) into a
    chunk-keyed dict. Malformed rows are dropped, not fatal — a stale
    cache entry must degrade to the static floors, never crash a
    boot."""
    rows = {}
    for r in (profile or ()):
        try:
            rows[int(r["chunk"])] = r
        except (TypeError, KeyError, ValueError):
            continue
    return rows


def _selected_ms(chunk: int, row: dict, profile, fused_mode: str):
    """The probed ms/iter of the pipeline THIS boot would actually run
    on the rung (fused_for's selection), not the winner's: a rung whose
    fused rate won the probe is still a pure loss on a TTS_FUSED=0
    boot that can only run its slower matmul rate. Per-pipeline fields
    (ms_per_iter_{unfused,fused}) fall back to the winner's
    ms_per_iter only for masks persisted before they existed; a
    present-but-None fused field means that rung's fused probe FAILED
    — the boot would run the rung fused (fused_for's never-measured
    guard), so returning the unfused rate here would admit the rung
    on a rate it won't run. None: the caller refuses the rung (or,
    for the top row, falls back to the static floors)."""
    if fused_for(chunk, profile, fused_mode) == "off":
        return row.get("ms_per_iter_unfused") or row.get("ms_per_iter")
    if "ms_per_iter_fused" in row:
        return row["ms_per_iter_fused"]
    return row.get("ms_per_iter")          # pre-field mask schema


def rungs_from_profile(chunk: int, profile,
                       n_rungs: int = LADDER_RUNGS,
                       factor: int = LADDER_FACTOR,
                       fused_mode: str = "off"
                       ) -> tuple[int, ...] | None:
    """MEASURED rung admission — the per-shape subsumption of the
    static per-bound floor (min_rung_for): when the tuner probed this
    shape's rung ladder (Params.rung_modes, tune/tuner), a candidate
    rung joins the ladder iff its measured ms/iter ON THE PIPELINE
    THIS BOOT WILL RUN (`fused_mode` + the mask through fused_for —
    _selected_ms) beats the tuned top rung's. A rung slower per
    iteration than the tuned chunk is a pure loss — the ladder's
    premise; the PR-9 LB2>=256 floor encoded that statically from one
    measurement, here it is per-shape data. Returns None (caller
    falls back to the static floors) when the profile does not cover
    the top rung."""
    rows = _profile_rows(profile)
    chunk = int(chunk)
    top = rows.get(chunk)
    if top is None:
        return None
    top_ms = _selected_ms(chunk, top, profile, fused_mode)
    if not top_ms:
        return None
    rungs = {chunk}
    for k in range(1, n_rungs):
        c = max(1, chunk // factor ** k)
        row = rows.get(c)
        if row is None:
            continue
        ms = _selected_ms(c, row, profile, fused_mode)
        if ms and ms < top_ms:
            rungs.add(c)
    return tuple(sorted(rungs))


def fused_for(chunk: int, profile, fused_mode: str) -> str:
    """Per-rung kernel-vs-matmul selection: the probed winner when the
    profile covers the rung, else the resolved env mode
    (ops/pallas_fused.resolve_mode). The env master switch gates
    everything — a profile row can only REFINE a fused-enabled run
    (send an unprofitable rung back to the matmul pipeline), never
    enable fused while TTS_FUSED is off; either way the node
    accounting is bit-identical, only the per-iteration cost moves.

    An "unfused" verdict counts only when the fused pipeline was
    actually MEASURED (evals_per_s_fused recorded): a mask probed
    under TTS_TUNE_RUNGS=1 on a matmul-only boot records "unfused"
    for every rung by construction, and honoring it here would let a
    never-measured mask silently disable a later TTS_FUSED=1 boot."""
    if fused_mode == "off":
        return "off"
    row = _profile_rows(profile).get(int(chunk))
    if (row is not None and row.get("winner") == "unfused"
            and row.get("evals_per_s_fused") is not None):
        return "off"
    return fused_mode


class RungController:
    """Owns the live rung index; the segmented driver's run_fn asks it
    for the current rung's driver and the heartbeat feeds it each
    segment's pool occupancy. Host-side only — nothing here is traced.

    Under overlap the next segment is dispatched before the previous
    segment's counters land, so the controller's signal lags one
    segment; a switch is therefore taken one boundary later than in
    sync mode — the accounting stays exact either way, only the
    adaptation latency differs.
    """

    def __init__(self, drivers: dict[int, object], n_workers: int):
        self.chunks = tuple(sorted(drivers))
        self.drivers = drivers
        self.n_workers = max(int(n_workers), 1)
        self.idx = len(self.chunks) - 1          # start on the tuned rung
        self.switches = {"up": 0, "down": 0}
        self._last_pool: int | None = None
        self._switch_c = obs_metrics.default().counter(
            "tts_ladder_switches_total",
            "chunk-ladder rung switches at segment boundaries")

    # ------------------------------------------------------------ state

    @property
    def current_chunk(self) -> int:
        return self.chunks[self.idx]

    def driver(self):
        return self.drivers[self.current_chunk]

    # ---------------------------------------------------------- control

    def start(self, pool_total: int, meta_rung: int | None = None) -> None:
        """Pick the initial rung: the checkpoint's recorded rung when
        resuming (`meta_rung`), else from the seed pool's occupancy."""
        if meta_rung is not None and int(meta_rung) in self.chunks:
            self.idx = self.chunks.index(int(meta_rung))
            source = "meta"
        else:
            self.idx = self._target(pool_total)
            source = "occupancy"
        self._last_pool = int(pool_total)
        tracelog.event("ladder.start", rung=self.current_chunk,
                       rungs=list(self.chunks), pool=int(pool_total),
                       source=source)

    def observe(self, pool_total: int, segment: int | None = None) -> None:
        """Feed one segment boundary's pool size; may switch the rung
        used for the NEXT dispatch."""
        target = self._target(pool_total)
        if (self._last_pool is not None
                and pool_total > 2 * max(self._last_pool, 1)
                and not memory_pressure()):
            # ramp momentum: the pool at least doubled inside the last
            # segment, so the boundary snapshot is already stale — go
            # one rung above covering to cut the chase (an explosive
            # warm-up otherwise costs one under-rung segment per
            # doubling). Suppressed under the remediation tier's
            # memory-pressure hint: covering is the demoted,
            # narrowest-scratch choice and pops identically
            target = min(target + 1, len(self.chunks) - 1)
        self._last_pool = int(pool_total)
        if target == self.idx:
            return
        direction = "up" if target > self.idx else "down"
        self.switches[direction] += 1
        tracelog.event("ladder.switch",
                       frm=self.current_chunk,
                       to=self.chunks[target],
                       direction=direction, segment=segment,
                       pool=int(pool_total))
        self._switch_c.inc(direction=direction)
        self.idx = target

    def _target(self, pool_total: int) -> int:
        """The SMALLEST rung that still covers the per-worker pool
        (the top rung when even it is outgrown). Covering means the
        rung pops exactly what the tuned chunk would have popped — a
        pool-limited pop either way — so the iteration count can NEVER
        inflate relative to the fixed-chunk driver; the ladder's win
        is purely the narrower per-iteration compute. (The earlier
        half-occupancy policy allowed pops smaller than the pool and
        measurably LOST on iteration inflation — 12 vs 8 iterations
        at 1024 on the small-instance drill.)"""
        per_worker = pool_total / self.n_workers
        for i, c in enumerate(self.chunks):
            if c >= per_worker:
                return i
        return len(self.chunks) - 1

    def snapshot(self) -> dict:
        return {"rungs": list(self.chunks),
                "current": self.current_chunk,
                "switches": dict(self.switches)}
